"""Persistent AOT executable cache — a new process serves in seconds.

The reference keeps long-lived operators hot inside one Flink job, so
compilation cost is paid once per cluster.  Our processes instead repaid
every XLA compile on every restart: serving ``warm_up()`` compiles each
``(op, schema, bucket)`` at startup, which at hundreds of tenants x
bucket ladders is minutes of cold-start.  This module makes compiled
executables a DURABLE artifact:

- **AOT compile**: the registry's dispatch surface (and the
  :func:`aot_jit`-wrapped training step builders) compile through
  ``jax.jit(...).lower().compile()`` so the resulting
  ``jax.stages.Compiled`` is a first-class object we can serialize
  (``jax.experimental.serialize_executable``) instead of an entry buried
  in the jit's in-process cache.
- **Persistent cache**: serialized executables live in a cache directory
  (``FLINK_ML_TPU_AOT_CACHE_PATH`` / ``FrameworkConfig.aot_cache_path``),
  one committed subdirectory per key under ``exec/``.  Every entry
  speaks the PR 5 durability contract (``robustness/durability.py``):
  payload files -> ``manifest.json`` CRCs -> ``COMMITTED`` marker, all
  written into a tmp dir that is ``os.replace``d into place — a crash
  mid-write never leaves a trusted half-entry.
- **Keying**: plan identity (module-qualified fn names + bytecode
  fingerprints + static config) + operand treedef/shapes/dtypes — the
  registry's existing in-memory cache key — EXTENDED with the
  environment fingerprint (jax/jaxlib versions, backend, device kind,
  cache format).  A new jaxlib or a different chip simply misses; it can
  never load an executable built for another world.
- **Fail-safe loads**: a corrupt entry (torn payload, flipped byte,
  missing manifest) or a version-skewed one (meta fingerprint not this
  process's environment) is QUARANTINED (``<key>.corrupt``) and the
  caller transparently falls back to a live compile — never a crash,
  never wrong bits (the executable's own arg validation rejects any
  shape/dtype drift the key missed).

The same cache root also stores the registry autotuner's measured
decisions (``kernels/autotune.py``, ``autotune/`` subdir), so one
directory is THE portable warm state of a process fleet.
"""

from __future__ import annotations

import hashlib
import inspect
import json
import logging
import os
import pickle
import shutil
import threading
import time

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

__all__ = [
    "ExecutableCache",
    "active_cache",
    "aot_jit",
    "env_fingerprint",
    "plan_token",
    "reset_cache",
    "set_cache",
    "stable_repr",
]

log = logging.getLogger("flink_ml_tpu.kernels")

#: bump when the entry layout / key recipe changes: old entries become
#: fingerprint-skewed (quarantined on contact), never misread
AOT_FORMAT = 1

_EXEC_DIR = "exec"
_TUNE_DIR = "autotune"
_PAYLOAD = "executable.bin"
_TREES = "trees.pkl"
_META = "meta.json"
_DECISION = "decision.json"


def env_fingerprint() -> Dict[str, Any]:
    """The environment a serialized executable is only valid in: jax +
    jaxlib versions (the PJRT serialization format owner), the backend,
    and the device kind (an executable for one chip generation is garbage
    on another).  Part of the key digest AND re-checked against the
    entry's meta on load, so a hand-copied or stale-keyed entry
    quarantines instead of deserializing garbage."""
    import jax
    import jaxlib

    try:
        device_kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — no devices: fingerprint still total
        device_kind = "unknown"
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "device_kind": device_kind,
        "format": AOT_FORMAT,
    }


def _code_fingerprint(fn: Callable) -> str:
    """Stable digest of a function's compiled bytecode — the
    invalidation handle for 'the kernel's code changed but its name did
    not'.  TRANSITIVE over module-level helpers: every global the
    bytecode references by name that is itself a Python function (or a
    dict of functions, the ``_HIST_IMPLS``-style dispatch-table idiom)
    folds its own bytecode in recursively, so editing a helper a kernel
    calls invalidates the kernel's cached executables too.  The closure
    stops at non-function globals (modules, classes, arrays): a key
    cannot see through those — the jax/jaxlib fingerprint and the
    ``AOT_FORMAT`` bump are the invalidation levers beyond it.
    Address-carrying reprs (code/object reprs embed ``0x...``) are
    never hashed."""
    h = hashlib.sha256()
    seen: set = set()

    def feed_code(code) -> None:
        h.update(code.co_code)
        for const in code.co_consts:
            if isinstance(const, (int, float, str, bytes, bool,
                                  type(None))):
                h.update(repr(const).encode())
            elif hasattr(const, "co_code"):
                feed_code(const)
        h.update(repr(code.co_names).encode())

    def feed_fn(f) -> None:
        wrapped = getattr(f, "__wrapped__", None)
        if wrapped is not None:       # aot_jit / functools wrappers
            feed_fn(wrapped)
            return
        code = getattr(f, "__code__", None)
        if code is None:
            h.update(repr(getattr(f, "__qualname__",
                                  type(f).__qualname__)).encode())
            return
        if id(code) in seen:
            return
        seen.add(id(code))
        feed_code(code)
        g = getattr(f, "__globals__", {})
        for name in code.co_names:
            ref = g.get(name)
            if ref is None:
                continue
            if isinstance(ref, dict):
                for val in ref.values():
                    if callable(val):
                        feed_fn(val)
            elif callable(ref) and (hasattr(ref, "__code__")
                                    or hasattr(ref, "__wrapped__")):
                feed_fn(ref)

    feed_fn(fn)
    return h.hexdigest()[:16]


def stable_repr(obj: Any, _depth: int = 0, _seen: Optional[set] = None
                ) -> str:
    """An address-free ``repr`` for cache keys: the default object repr
    embeds ``at 0x...``, which would give every process a different
    token for the same plan (KMeans statics carry the DistanceMeasure
    singleton).  Objects render as their qualified class plus the
    stable repr of their instance state, functions as qualified name +
    bytecode fingerprint; primitives/containers recurse.

    A value the renderer cannot stably see through (cyclic, or nested
    past the depth bound) is POISONED with its process-local ``id`` —
    the resulting key can never falsely match anything persisted by
    another process (or another object in this one), so an unkeyable
    static degrades to cache misses, never to loading the wrong
    executable."""
    if isinstance(obj, (int, float, complex, str, bytes, bool,
                        type(None))):
        return repr(obj)
    if _depth > 6:
        return f"<unkeyed:{type(obj).__qualname__}:{id(obj)}>"
    if _seen is None:
        _seen = set()
    if id(obj) in _seen:
        return f"<unkeyed:cycle:{id(obj)}>"
    _seen = _seen | {id(obj)}
    if isinstance(obj, tuple):
        return "(" + ",".join(stable_repr(x, _depth + 1, _seen)
                              for x in obj) + ")"
    if isinstance(obj, list):
        return "[" + ",".join(stable_repr(x, _depth + 1, _seen)
                              for x in obj) + "]"
    if isinstance(obj, dict):
        items = sorted((stable_repr(k, _depth + 1, _seen),
                        stable_repr(v, _depth + 1, _seen))
                       for k, v in obj.items())
        return "{" + ",".join(f"{k}:{v}" for k, v in items) + "}"
    if isinstance(obj, type):
        return f"<class {obj.__module__}.{obj.__qualname__}>"
    if callable(obj) and hasattr(obj, "__qualname__"):
        return (f"<fn {getattr(obj, '__module__', '?')}."
                f"{obj.__qualname__}:{_code_fingerprint(obj)}>")
    r = repr(obj)
    if " at 0x" not in r:
        return r
    state = getattr(obj, "__dict__", None)
    return (f"<{type(obj).__module__}.{type(obj).__qualname__} "
            f"{stable_repr(state, _depth + 1, _seen) if state else ''}>")


def plan_token(plan: tuple) -> str:
    """Cross-process identity of a dispatch plan: per stage, the
    module-qualified fn name, its bytecode fingerprint, and the static
    config tuple (address-free: :func:`stable_repr`).  Two processes
    running the same code build the same token; an edited kernel fn
    changes it."""
    parts = []
    for fn, static in plan:
        parts.append((f"{fn.__module__}.{fn.__qualname__}",
                      _code_fingerprint(fn), stable_repr(static)))
    return repr(parts)


def _digest(kind: str, token: str, shape_repr: str,
            fingerprint: Dict[str, Any]) -> str:
    blob = json.dumps({"kind": kind, "token": token, "shapes": shape_repr,
                       "env": fingerprint}, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()


class ExecutableCache:
    """One cache root: ``exec/<key>`` committed executable entries plus
    ``autotune/<key>`` committed decision entries, shared by every
    consumer in the process (and by every process pointed at the root).

    Loads are memoized per process (``_loaded``): a key deserializes
    once, steady-state dispatches call the held ``Compiled`` directly.
    """

    def __init__(self, root: str):
        self.root = root
        self._fingerprint = env_fingerprint()
        self._lock = threading.Lock()
        self._build_lock = threading.Lock()
        self._loaded: Dict[str, Any] = {}
        self._decisions: Optional[Dict[Tuple[str, str], Dict]] = None
        os.makedirs(os.path.join(root, _EXEC_DIR), exist_ok=True)
        os.makedirs(os.path.join(root, _TUNE_DIR), exist_ok=True)

    # -- keys ----------------------------------------------------------------
    @property
    def fingerprint(self) -> Dict[str, Any]:
        return dict(self._fingerprint)

    def key_for(self, kind: str, token: str, shape_repr: str) -> str:
        return _digest(kind, token, shape_repr, self._fingerprint)

    # -- the load-or-build protocol ------------------------------------------
    def load_or_build(self, key: str, build: Callable[[], Any], *,
                      label: str = "?") -> Tuple[Any, str]:
        """Resolve ``key`` to a callable executable: in-memory hit ->
        disk load (an *aot hit*) -> live ``build()`` (an *aot miss*,
        compile + store).  Returns ``(compiled, source)`` with source in
        ``("memory", "aot", "compile")``.  Disk failures of any kind
        degrade to the live compile; the event is accounted on
        ``kernel_stats``."""
        from .registry import kernel_stats

        with self._lock:
            compiled = self._loaded.get(key)
        if compiled is not None:
            return compiled, "memory"
        with self._build_lock:
            with self._lock:       # raced another thread's miss path
                compiled = self._loaded.get(key)
            if compiled is not None:
                return compiled, "memory"
            t0 = time.perf_counter()
            compiled = self._load_entry(key)
            if compiled is not None:
                kernel_stats.record_aot(label, event="hit",
                                        seconds=time.perf_counter() - t0)
                with self._lock:
                    self._loaded[key] = compiled
                return compiled, "aot"
            t0 = time.perf_counter()
            compiled = build()
            kernel_stats.record_aot(label, event="miss",
                                    seconds=time.perf_counter() - t0)
            self._store_entry(key, compiled, label)
            with self._lock:
                self._loaded[key] = compiled
            return compiled, "compile"

    def forget_loaded(self) -> None:
        """Drop the in-process executable memo (tests: force the next
        dispatch through the disk-load path, as a fresh process would)."""
        with self._lock:
            self._loaded.clear()

    # -- disk entries --------------------------------------------------------
    def _entry_dir(self, key: str) -> str:
        return os.path.join(self.root, _EXEC_DIR, key)

    def _load_entry(self, key: str):
        from jax.experimental import serialize_executable as se

        from ..robustness.durability import (CorruptStateError, quarantine,
                                             verify_dir)
        from .registry import kernel_stats

        entry = self._entry_dir(key)
        if not os.path.isdir(entry):
            return None
        try:
            verify_dir(entry, allow_legacy=False)
            with open(os.path.join(entry, _META)) as f:
                meta = json.load(f)
            if meta.get("fingerprint") != self._fingerprint:
                raise CorruptStateError(
                    f"{entry}: executable fingerprint "
                    f"{meta.get('fingerprint')!r} is not this process's "
                    f"{self._fingerprint!r} (version/backend skew)")
            with open(os.path.join(entry, _TREES), "rb") as f:
                in_tree, out_tree = pickle.load(f)
            with open(os.path.join(entry, _PAYLOAD), "rb") as f:
                payload = f.read()
            return se.deserialize_and_load(payload, in_tree, out_tree)
        except CorruptStateError as exc:
            log.warning("AOT cache entry failed validation (%s); "
                        "quarantining and recompiling live", exc)
            kernel_stats.record_aot(key, event="quarantine")
            self._quarantine_entry(entry)
            return None
        except Exception as exc:  # noqa: BLE001 — CRC-valid garbage, pickle
            # drift inside the payload, PJRT refusal: same degraded path
            log.warning("AOT cache entry %s failed to deserialize (%r); "
                        "quarantining and recompiling live", entry, exc)
            kernel_stats.record_aot(key, event="quarantine")
            self._quarantine_entry(entry)
            return None

    @staticmethod
    def _quarantine_entry(entry: str) -> None:
        from ..robustness.durability import quarantine

        try:
            quarantine(entry)
        except OSError:
            # a concurrent process quarantined (or replaced) it first —
            # the bad bytes are out of our path either way
            pass

    def _store_entry(self, key: str, compiled, label: str) -> None:
        from jax.experimental import serialize_executable as se

        from ..robustness.durability import commit_dir
        from .registry import kernel_stats

        final = self._entry_dir(key)
        if os.path.isdir(final):
            return
        try:
            payload, in_tree, out_tree = se.serialize(compiled)
        except Exception as exc:  # noqa: BLE001 — backend w/o serialization
            kernel_stats.record_aot(label, event="unserializable")
            log.info("executable for %s is not serializable on this "
                     "backend (%r); serving from the in-process copy only",
                     label, exc)
            return
        tmp = f"{final}.tmp.{os.getpid()}"
        try:
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
            with open(os.path.join(tmp, _PAYLOAD), "wb") as f:
                f.write(payload)
            with open(os.path.join(tmp, _TREES), "wb") as f:
                pickle.dump((in_tree, out_tree), f)
            with open(os.path.join(tmp, _META), "w") as f:
                json.dump({"format": AOT_FORMAT, "label": label,
                           "key": key, "fingerprint": self._fingerprint,
                           "payload_bytes": len(payload)}, f, indent=1,
                          sort_keys=True)
            commit_dir(tmp)
            os.replace(tmp, final)
        except OSError as exc:
            # two legitimate shapes land here: another process committed
            # this key first (rename onto a non-empty dir — its entry is
            # as good as ours), or the cache volume itself failed the
            # write (ENOSPC, permissions).  Either way the executable in
            # hand is valid and the process must keep serving from it —
            # a broken cache DISK degrades persistence, never dispatch.
            shutil.rmtree(tmp, ignore_errors=True)
            if not os.path.isdir(final):
                kernel_stats.record_aot(label, event="store_failed")
                log.warning("AOT cache store of %s failed (%r); serving "
                            "from the in-process copy only", label, exc)
            return
        kernel_stats.record_aot(label, event="store")

    # -- autotune decisions (the same durable root) --------------------------
    def _decision_dir(self, key: str) -> str:
        return os.path.join(self.root, _TUNE_DIR, key)

    def _decision_key(self, op: str, sig_repr: str) -> str:
        env = {"backend": self._fingerprint["backend"],
               "device_kind": self._fingerprint["device_kind"]}
        return _digest("autotune", f"{op}|{sig_repr}", "", env)

    def _load_decisions(self) -> Dict[Tuple[str, str], Dict]:
        """Scan (once per process) every committed decision entry;
        corrupt or skewed entries quarantine exactly like executables."""
        from ..robustness.durability import (CorruptStateError, quarantine,
                                             verify_dir)
        from .registry import kernel_stats

        decisions: Dict[Tuple[str, str], Dict] = {}
        root = os.path.join(self.root, _TUNE_DIR)
        device = {"backend": self._fingerprint["backend"],
                  "device_kind": self._fingerprint["device_kind"]}
        for name in sorted(os.listdir(root)):
            entry = os.path.join(root, name)
            if not os.path.isdir(entry) or ".corrupt" in name \
                    or ".tmp." in name:
                continue
            try:
                verify_dir(entry, allow_legacy=False)
                with open(os.path.join(entry, _DECISION)) as f:
                    dec = json.load(f)
                if dec.get("device") != device:
                    # a VALID decision from another backend/chip sharing
                    # the fleet cache root: not ours to use — and not
                    # ours to destroy (its owner still loads it)
                    continue
                decisions[(dec["op"], dec["sig"])] = dec
            except (CorruptStateError, KeyError, json.JSONDecodeError,
                    OSError) as exc:
                log.warning("autotune decision %s failed validation (%r); "
                            "quarantining (re-search on next encounter)",
                            entry, exc)
                kernel_stats.record_aot(name, event="quarantine")
                try:
                    quarantine(entry)
                except OSError:
                    # the entry vanished mid-scan (a concurrent re-tune's
                    # retire window) or another process quarantined it
                    # first — either way it is gone from the scan's view
                    pass
        return decisions

    def decisions(self) -> Dict[Tuple[str, str], Dict]:
        with self._lock:
            if self._decisions is None:
                self._decisions = self._load_decisions()
            return self._decisions

    def get_decision(self, op: str, sig_repr: str) -> Optional[Dict]:
        return self.decisions().get((op, sig_repr))

    def record_decision(self, decision: Dict) -> None:
        """Commit one measured decision (op + sig + winner + timings)
        durably and into the in-memory view.  Same tmp -> commit ->
        ``os.replace`` protocol as executables."""
        from ..robustness.durability import commit_dir

        final = self._decision_dir(
            self._decision_key(decision["op"], decision["sig"]))
        tmp = f"{final}.tmp.{os.getpid()}"
        try:
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
            with open(os.path.join(tmp, _DECISION), "w") as f:
                json.dump(decision, f, indent=1, sort_keys=True)
            commit_dir(tmp)
            if os.path.isdir(final):       # re-tune overwrites: retire the
                shutil.rmtree(final)       # old committed entry first
            os.replace(tmp, final)
        except OSError as exc:
            # lost the race to a concurrent tuner, or the cache volume
            # failed the write: the measured decision still applies
            # in-process (below) — persistence degrades, search does not
            shutil.rmtree(tmp, ignore_errors=True)
            if not os.path.isdir(final):
                log.warning("autotune decision store for %s failed (%r); "
                            "kept in-process only",
                            decision.get("op"), exc)
        with self._lock:
            if self._decisions is None:
                self._decisions = self._load_decisions()
            self._decisions[(decision["op"], decision["sig"])] = decision


# ---------------------------------------------------------------------------
# the process-wide active cache (config-resolved, test-overridable)
# ---------------------------------------------------------------------------

_ACTIVE: list = []          # [] = unresolved; [None] = resolved, disabled
_ACTIVE_LOCK = threading.Lock()


def active_cache() -> Optional[ExecutableCache]:
    """The process's cache, resolved once from
    ``FrameworkConfig.aot_cache_path`` (env
    ``FLINK_ML_TPU_AOT_CACHE_PATH``); None when no root is configured —
    every AOT hook then degrades to exactly the pre-cache behavior."""
    if not _ACTIVE:
        with _ACTIVE_LOCK:
            if not _ACTIVE:
                from ..utils.config import get_config

                path = get_config().aot_cache_path
                _ACTIVE.append(ExecutableCache(path) if path else None)
    return _ACTIVE[0]


def set_cache(cache: Optional[ExecutableCache]) -> None:
    """Pin (or disable, with None) the process cache — tests and embedding
    applications that manage their own config lifecycle."""
    with _ACTIVE_LOCK:
        _ACTIVE.clear()
        _ACTIVE.append(cache)


def reset_cache() -> None:
    """Forget the resolution so the next :func:`active_cache` re-reads
    config (tests restoring global state)."""
    with _ACTIVE_LOCK:
        _ACTIVE.clear()


# ---------------------------------------------------------------------------
# aot_jit — persistent-executable wrapper for module-level jits
# (the training step builders' pre-warm path)
# ---------------------------------------------------------------------------

def _contains_tracer(leaves) -> bool:
    import jax

    return any(isinstance(leaf, jax.core.Tracer) for leaf in leaves)


class _AotJit:
    """``jax.jit`` plus the persistent executable cache.

    With no cache configured (or when called with tracers — i.e. from
    inside an enclosing jit/scan, where an executable cannot be invoked)
    this IS the wrapped jit: identical dispatch, identical cache
    behavior.  With a cache, top-level calls route through
    ``lower().compile()`` + the durable entry for their
    (code, static-args, operand-shapes) key, so a later process replays
    the compile as a deserialize.  Outputs are bit-identical either way:
    both paths run the same lowered program.
    """

    def __init__(self, fun: Callable, *, static_argnames=(),
                 donate_argnums=()):
        import jax

        self._fun = fun
        self._jit = jax.jit(fun, static_argnames=static_argnames,
                            donate_argnums=donate_argnums)
        self._static = frozenset(
            (static_argnames,) if isinstance(static_argnames, str)
            else static_argnames)
        self._params = list(inspect.signature(fun).parameters)
        self._label = f"{fun.__module__}.{fun.__qualname__}"
        self._token = (self._label, _code_fingerprint(fun))
        self._keys: Dict[Any, str] = {}
        self.__name__ = getattr(fun, "__name__", "aot_jit")
        self.__doc__ = fun.__doc__
        self.__wrapped__ = fun

    def _split(self, args, kwargs):
        statics = []
        dyn_args = []
        for i, a in enumerate(args):
            name = (self._params[i] if i < len(self._params)
                    else f"*{i}")
            if name in self._static:
                statics.append((name, a))
            else:
                dyn_args.append(a)
        dyn_kwargs = {}
        for name, v in kwargs.items():
            if name in self._static:
                statics.append((name, v))
            else:
                dyn_kwargs[name] = v
        return tuple(statics), tuple(dyn_args), dyn_kwargs

    def __call__(self, *args, **kwargs):
        import jax

        cache = active_cache()
        if cache is None:
            return self._jit(*args, **kwargs)
        statics, dyn_args, dyn_kwargs = self._split(args, kwargs)
        leaves, treedef = jax.tree_util.tree_flatten((dyn_args, dyn_kwargs))
        if _contains_tracer(leaves):
            # inside an enclosing trace (chunk scans call these):
            # executables cannot run there — inline as a nested jit
            return self._jit(*args, **kwargs)
        memo_key = (stable_repr(sorted(statics)), str(treedef),
                    tuple((np.shape(leaf), np.result_type(leaf).str)
                          for leaf in leaves))
        key = self._keys.get(memo_key)
        if key is None:
            key = cache.key_for(
                "jit", repr((self._token, memo_key[0])),
                repr((memo_key[1], memo_key[2])))
            self._keys[memo_key] = key
        compiled, _source = cache.load_or_build(
            key, lambda: self._jit.lower(*args, **kwargs).compile(),
            label=self._label)
        try:
            return compiled(*dyn_args, **dyn_kwargs)
        except TypeError:
            # an arg aspect the shape/dtype key cannot see (e.g. weak
            # types) diverged from the lowering: serve correctness from
            # the plain jit and leave the entry for callers it fits
            return self._jit(*args, **kwargs)

    # uniform AOT-ness probe for tests/tooling
    @property
    def aot_label(self) -> str:
        return self._label


def aot_jit(fun: Optional[Callable] = None, *, static_argnames=(),
            donate_argnums=()):
    """Decorator form of :class:`_AotJit` (usable bare or with args)."""
    if fun is None:
        return lambda f: _AotJit(f, static_argnames=static_argnames,
                                 donate_argnums=donate_argnums)
    return _AotJit(fun, static_argnames=static_argnames,
                   donate_argnums=donate_argnums)
