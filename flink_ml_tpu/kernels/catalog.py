"""The registration catalog: importing this module imports every module
that registers kernels, so ``registry.lookup``/``ops()`` see the full
table no matter which consumer asked first.

Registrations live NEXT TO their implementations (an op's shape contract
is the kernel's own business, an op's planning policy the model's):

- ``ops/ell_scatter.py``      — ``ell_margin``, ``ell_scatter_apply``
- ``ops/emb_grad.py`` / ``ops/emb_grad_pallas.py`` — ``routed_table_grad``
- ``models/common/gbt.py``    — ``gbt_level_histograms``
- ``models/common/linear.py`` — ``linear_margins`` (stage convention)
- ``models/clustering/kmeans.py`` — ``kmeans_assign`` (stage),
  ``kmeans_update_stats``, ``kmeans_workset_update``
- ``models/recommendation/widedeep.py`` — ``widedeep_scores`` (stage)
- ``ops/int8_serving.py``      — "int8" backends of ``linear_margins``,
  ``kmeans_assign``, ``widedeep_scores`` (forced-lookup only; the
  servable bind path quantizes the params they consume)
- ``retrieval/ivf.py`` / ``ops/retrieve_pallas.py`` — ``retrieve``
  (stage convention; the IVF / IVF-PQ fused scan+top-k, first
  non-model op family)

This module is imported lazily by ``registry._ensure_catalog`` (first
lookup), never at ``flink_ml_tpu.kernels`` import — that keeps the
registry itself dependency-free and cycle-safe.
"""

from .. import ops  # noqa: F401  (ell + kmeans + emb_grad + retrieve)
from ..models.clustering import kmeans  # noqa: F401
from ..models.common import gbt, linear  # noqa: F401
from ..models.recommendation import widedeep  # noqa: F401
from ..retrieval import ivf  # noqa: F401  (the "xla" retrieve backend)
