"""Vector retrieval: IVF / IVF-PQ index build, search planning, and the
``retrieve`` kernel family — the repo's first non-model servable."""

from .ivf import IVFIndex, PQConfig, SearchPlan, retrieve_sig
from .metrics import RecallProbe, exact_neighbors, recall_at_k

__all__ = [
    "IVFIndex",
    "PQConfig",
    "RecallProbe",
    "SearchPlan",
    "exact_neighbors",
    "recall_at_k",
    "retrieve_sig",
]
