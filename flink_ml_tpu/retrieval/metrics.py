"""Retrieval quality instrumentation: recall harness + sampled probes.

``recall_at_k`` is the offline harness (tests, bench, parity envelopes);
``RecallProbe`` is the online form — a deterministic sample of live
queries re-scored against an EXACT float64 scan of the index's stored
vectors, published as the per-tenant ``recall_probe`` gauge through the
existing ``ServingMetrics`` subtree (so it rides the same ``MetricsTree``
snapshots, publish throttling, and NaN-is-absent convention every other
serving gauge does)."""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = ["RecallProbe", "exact_neighbors", "recall_at_k"]


def recall_at_k(found: np.ndarray, expected: np.ndarray) -> float:
    """Mean per-query overlap |found ∩ expected| / |expected|.

    ``found`` (n, k) may carry ``-1`` for unfilled result slots (never
    counted); ``expected`` (n, k') is the exact reference set."""
    found = np.asarray(found, np.int64)
    expected = np.asarray(expected, np.int64)
    if found.ndim != 2 or expected.ndim != 2 or found.shape[0] != expected.shape[0]:
        raise ValueError("found/expected must be (n, k)-shaped with "
                         "matching n")
    if expected.shape[0] == 0 or expected.shape[1] == 0:
        return 1.0
    hits = 0
    for row_found, row_exp in zip(found, expected):
        real = set(int(i) for i in row_found if i >= 0)
        hits += len(real.intersection(int(i) for i in row_exp))
    return hits / float(expected.size)


def exact_neighbors(queries: np.ndarray, vectors: np.ndarray,
                    ids: np.ndarray, k: int) -> np.ndarray:
    """Exact top-k ids by brute-force float64 squared L2 (first-index
    ties) — the oracle every approximate path is scored against."""
    q = np.asarray(queries, np.float64)
    v = np.asarray(vectors, np.float64)
    ids = np.asarray(ids, np.int64)
    if v.shape[0] == 0:
        return np.full((q.shape[0], k), -1, np.int64)
    d2 = (np.sum(q * q, axis=1)[:, None] + np.sum(v * v, axis=1)[None, :]
          - 2.0 * q @ v.T)
    k_eff = min(k, v.shape[0])
    top = np.argsort(d2, axis=1, kind="stable")[:, :k_eff]
    out = np.full((q.shape[0], k), -1, np.int64)
    out[:, :k_eff] = ids[top]
    return out


class RecallProbe:
    """Sampled online recall: every ``observe`` keeps a deterministic
    Bernoulli sample of the batch, scores the index's answer against the
    exact scan of its stored vectors, and folds the result into a
    running mean; ``publish`` pushes that mean through the tenant's
    ``ServingMetrics.on_recall_probe`` gauge."""

    def __init__(self, index, *, k: Optional[int] = None,
                 nprobe: Optional[int] = None, sample: float = 0.25,
                 seed: int = 0):
        if not 0.0 < sample <= 1.0:
            raise ValueError(f"sample={sample} must be in (0, 1]")
        self._index = index
        self._k = index.k if k is None else int(k)
        self._nprobe = nprobe
        self._sample = float(sample)
        self._rng = np.random.default_rng(seed)
        self._hits = 0.0
        self._total = 0

    def observe(self, queries: np.ndarray,
                neighbors: Optional[np.ndarray] = None) -> Optional[float]:
        """Score a (sampled) query batch; returns this batch's recall or
        ``None`` when the sample kept no rows.  Pass the ``neighbors``
        the serve path already computed to probe exactly what was
        served; omitted, the probe searches the index itself."""
        queries = np.asarray(queries, np.float32)
        keep = self._rng.random(queries.shape[0]) < self._sample
        if not keep.any():
            return None
        sampled = queries[keep]
        if neighbors is None:
            found, _ = self._index.search(sampled, nprobe=self._nprobe,
                                          k=self._k)
        else:
            found = np.asarray(neighbors, np.int64)[keep, :self._k]
        ids, vectors = self._index.stored_vectors()
        exact = exact_neighbors(sampled, vectors, ids, self._k)
        batch = recall_at_k(found, exact)
        self._hits += batch * exact.size
        self._total += exact.size
        return batch

    @property
    def value(self) -> float:
        """Running mean recall (NaN until the first kept sample — the
        gauges' is-absent convention)."""
        return self._hits / self._total if self._total else float("nan")

    def publish(self, serving_metrics) -> float:
        """Push the running mean through the tenant's ``recall_probe``
        gauge; returns the published value."""
        value = self.value
        serving_metrics.on_recall_probe(value)
        return value

    def reset(self) -> Tuple[float, int]:
        """Roll the window: returns (mean, sampled count) and zeroes the
        accumulators."""
        out = (self.value, self._total)
        self._hits, self._total = 0.0, 0
        return out
