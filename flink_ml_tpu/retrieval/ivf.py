"""IVF / IVF-PQ vector index: build, search planning, incremental updates.

The index is the repo's first NON-model servable (ISSUE 19): approximate
nearest-neighbor retrieval packaged behind the exact seams models serve
through — a registry-dispatched ``retrieve`` kernel plan, the bucketed
``run_kernel`` dispatch surface, rebind-safe generation swaps, and the
PR 7 delta codec for incremental posting-list updates.

**Index layout.**  ``IVFIndex.build(vectors, nlist, pq=None)`` trains the
coarse quantizer with the EXISTING workset KMeans fit (the delta-iteration
Lloyd's from ``models/clustering/kmeans.py`` — no second clustering
implementation), then assigns every vector to its nearest centroid's
posting list.  Lists are device-resident padded row blocks: each list
occupies ``block`` contiguous rows of one packed ``(nlist*block, d)``
array (the CSR row-block layout; ``offsets`` below are the CSR offsets of
the REAL rows), padded with exact zeros through the maskless
``pad_rows_to_block`` contract of ``utils/padding.py`` — pad rows carry
id ``-1`` and are masked inert inside the kernel, never corrected after.

**PQ variant.**  ``pq=PQConfig(m, ksub)`` stores residuals (vector minus
its coarse centroid) as ``m`` int8 codes per vector against per-subspace
codebooks.  Sub-codebooks are trained with the same workset KMeans on
each residual subspace and STORED through the ``kernels/quantize.py``
recipe (per-row symmetric max-abs int8 codes + f32 scales,
``quantize_rows``); encoding argmins against the DECODED book, so the
codes are exact argmins of the values the kernel actually scans with.

**Search.**  ONE registry-dispatched kernel per ``(nprobe, k, dim, pq)``
schema: coarse-probe selection, masked posting-list scan (flat f32 or PQ
lookup-table distances) and top-k merge are a single fused program —
candidate distances never round-trip HBM.  The XLA backend below runs
everywhere; ``ops/retrieve_pallas.py`` registers a VMEM-blocked Pallas
backend gated TPU-only, bitwise-equal per row in interpret mode (the
parity matrix in ``tests/test_kernels.py`` enforces both an exact
brute-force oracle and a recall envelope per backend).

**Updates.**  ``updated(inserts, delete_ids)`` edits posting-list blocks
in place (swap-remove deletes, free-slot inserts) and reports ``"delta"``
— the changed rows ride the PR 7 sparse delta codec under digest
verification.  When a list overflows its block or the centroid drift
(max per-list ||member mean - centroid|| over the centroid RMS norm)
crosses ``drift_threshold``, it reports ``"reanchor"`` with a freshly
built index instead: same-shape re-anchors publish as one FullUpdate,
shape-changing ones go through ``registry.deploy``.  Generation swaps
are atomic either way — in-flight queries finish on the old lists.
"""

from __future__ import annotations

import dataclasses

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..data.table import Table
from ..kernels.quantize import quantize_rows
from ..kernels.registry import lookup, register_kernel
from ..utils.padding import pad_rows_to_block, require_block_rows

__all__ = [
    "IVFIndex",
    "PQConfig",
    "SearchPlan",
    "adc_distances",
    "coarse_distances",
    "decode_codebooks",
    "flat_distances",
    "pq_lut",
    "retrieve_sig",
]


@dataclasses.dataclass(frozen=True)
class PQConfig:
    """Product-quantization config: ``m`` subspaces of ``dim // m``
    components each, ``ksub`` codebook entries per subspace (int8 codes,
    so at most 127), trained for ``max_iter`` workset-KMeans rounds."""

    m: int
    ksub: int = 16
    max_iter: int = 8


@dataclasses.dataclass(frozen=True)
class SearchPlan:
    """The planned search schema: the registry signature, the plan-static
    tuple, and the backend the registry resolved for this host."""

    sig: tuple
    static: tuple
    backend: str


def retrieve_sig(nprobe: int, k: int, dim: int, m: int, ksub: int,
                 nlist: int, block: int) -> tuple:
    """The ``retrieve`` op's registry signature — one kernel schema per
    (nprobe, k, dim, pq) point; ``m == 0`` is the flat-f32 scan."""
    return (nprobe, k, dim, m, ksub, nlist, block)


# ---------------------------------------------------------------------------
# shared distance expressions.  Both backends (the XLA stage fn below and
# the Pallas kernel body in ops/retrieve_pallas.py) call THESE helpers, so
# per-row outputs are expression-identical by construction — the parity
# matrix asserts bitwise equality in interpret mode.  Broadcasting over
# leading batch dims keeps one definition serving the vectorized XLA form
# (b, nprobe, ...) and the per-query Pallas form (1, ...).
# ---------------------------------------------------------------------------

def coarse_distances(q, centroids):
    """Selection-only coarse scores ``||c||^2 - 2 q.c`` for ``q`` of shape
    (..., d) against (nlist, d) — the ``q^2`` term is rank-invariant and
    omitted, exactly like the KMeans assign kernel's pairwise."""
    c2 = jnp.sum(centroids * centroids, axis=-1)
    qc = jnp.dot(q, centroids.T, preferred_element_type=jnp.float32)
    return c2 - 2.0 * qc


def flat_distances(q, vecs):
    """Full squared L2 ``||q - x||^2`` (as ``q^2 + x^2 - 2 q.x``) for
    ``q`` (..., d) against row blocks ``vecs`` (..., L, d) -> (..., L)."""
    q2 = jnp.sum(q * q, axis=-1)[..., None]
    x2 = jnp.sum(vecs * vecs, axis=-1)
    qx = jnp.einsum("...d,...ld->...l", q, vecs)
    return q2 + x2 - 2.0 * qx


def decode_codebooks(cb_q, cb_s):
    """Dequantize the stored per-subspace codebooks: int8 codes
    (m, ksub, dsub) times per-row scales (m, ksub) — the exact inverse of
    the ``quantize_rows`` recipe they were stored with."""
    return cb_q.astype(jnp.float32) * cb_s[..., None]


def pq_lut(resid, codebooks, one):
    """Per-(query, probe) ADC lookup table: squared L2 from the query's
    residual subvectors (..., m, dsub) to every codebook entry
    (m, ksub, dsub) -> (..., m, ksub).

    ``one`` must be a RUNTIME f32 1.0 (see :func:`runtime_one`): it pins
    the rounding of each squared term before the reduction adds.  LLVM
    may contract a mul feeding an add into one fma, skipping the mul's
    intermediate rounding — and it decides differently for the two
    backends' fusion shapes, a 1-ulp parity break.  With the runtime
    mul in between, the square is always rounded (mul-mul never
    contracts) and any fma THROUGH the barrier is value-identical
    (``fma(t, 1, c)`` rounds to exactly ``t + c``) — the registry's
    ``_run_plan`` rounding-barrier argument, applied inside the
    expression."""
    return jnp.sum(((resid[..., None, :] - codebooks) ** 2) * one,
                   axis=-1)


def runtime_one(x):
    """An exactly-1.0 f32 the compiler must treat as runtime data: float
    ``x * 0`` is never algebraically simplified (NaN/Inf semantics), so
    the chain can't constant-fold.  ``x`` must be a finite runtime
    value — both backends derive it from the codebook scales."""
    return x * 0.0 + 1.0


def adc_distances(lut, codes):
    """Asymmetric-distance scan: gather each candidate's per-subspace LUT
    entries and sum.  ``lut`` (..., m, ksub), ``codes`` (..., L, m) ->
    (..., L)."""
    idx = jnp.swapaxes(codes.astype(jnp.int32), -1, -2)
    return jnp.sum(jnp.take_along_axis(lut, idx, axis=-1), axis=-2)


# ---------------------------------------------------------------------------
# the XLA backend: ONE fused stage — coarse-probe selection, masked
# posting-list scan, top-k merge.  Candidate distances live only as
# fusion-internal values of this one dispatched program.
# ---------------------------------------------------------------------------

def _retrieve_stage_xla(static, params, cols):
    """Stage-convention ``retrieve`` kernel (XLA lowering, every host).

    Pad rows of the query batch are inert (row-independent outputs,
    sliced off by the dispatch fetch); pad slots of the posting lists
    carry id ``-1`` and are masked to ``+inf`` distance, so they can win
    a top-k slot only when fewer than k real candidates were scanned —
    reported as neighbor ``-1`` at distance ``+inf``, never a fake id.

    The flat scan runs as a ``lax.map`` over the query batch with
    ``dynamic_slice`` slab reads rather than one batched gather:
    XLA:CPU scalarizes a (b, nprobe) gather of (block, d) row slabs to
    per-element loads and then re-streams the materialized candidate
    tensor through each fused consumer, which on the bench corpus is
    an order of magnitude slower than the flat matmul it is supposed
    to beat.  A dynamic-slice of a contiguous row block is a plain
    copy, and the whole per-query scan (norms, dot, mask, top-k) stays
    resident in cache.  The distance math is identical expression for
    expression, so the per-row bits — and Pallas parity — are
    unchanged.  PQ codes are ~d/m times smaller per row, the batched
    gather is not the bottleneck there, and the LUT build wants the
    query batch whole, so the PQ path keeps the batched form."""
    (qcol, ncol, dcol, nprobe, k, nlist, block, m, _ksub) = static
    q = cols[qcol]                                       # (b, d)
    cents = params["centroids"]
    coarse = coarse_distances(q, cents)                  # (b, nlist)
    _, probes = jax.lax.top_k(-coarse, nprobe)           # (b, nprobe)
    if m:
        pids = params["ids"][probes]                     # (b, nprobe, L)
        codes = params["codes"].reshape(nlist, block, m)[probes]
        resid = q[:, None, :] - cents[probes]            # (b, nprobe, d)
        one = runtime_one(params["cb_s"][0, 0])
        # the same runtime-1.0 pins the decoded books' rounding: the
        # decode mul feeding the LUT subtraction is itself a contraction
        # candidate (fused multiply-subtract)
        books = decode_codebooks(params["cb_q"], params["cb_s"]) * one
        lut = pq_lut(resid.reshape(resid.shape[:-1] + (m, -1)),
                     books, one)
        dist = adc_distances(lut, codes)                 # (b, nprobe, L)
        dist = jnp.where(pids >= 0, dist, jnp.inf)
        flat_d = dist.reshape(dist.shape[0], -1)
        flat_i = pids.reshape(pids.shape[0], -1)
        neg, pos = jax.lax.top_k(-flat_d, k)
        nbrs = jnp.take_along_axis(flat_i, pos, axis=1)
        return {ncol: nbrs.astype(jnp.int32), dcol: -neg}

    vecs = params["vecs"]                                # (nlist*block, d)
    ids = params["ids"]                                  # (nlist, block)

    def scan_one(args):
        qi, pr = args                                    # (d,), (nprobe,)
        dists, pids = [], []
        for j in range(nprobe):
            slab = jax.lax.dynamic_slice(
                vecs, (pr[j] * block, 0), (block, vecs.shape[1]))
            dists.append(
                flat_distances(qi[None, None, :], slab[None, None])[0, 0])
            pids.append(
                jax.lax.dynamic_slice(ids, (pr[j], 0), (1, block))[0])
        dist = jnp.stack(dists)                          # (nprobe, L)
        pid = jnp.stack(pids)                            # (nprobe, L)
        dist = jnp.where(pid >= 0, dist, jnp.inf)
        neg, pos = jax.lax.top_k(-dist.reshape(-1), k)
        return jnp.take(pid.reshape(-1), pos), -neg

    nbrs, dist = jax.lax.map(scan_one, (q, probes))
    return {ncol: nbrs.astype(jnp.int32), dcol: dist}


# ---------------------------------------------------------------------------
# the index
# ---------------------------------------------------------------------------

class IVFIndex:
    """A built IVF / IVF-PQ index: device params + host bookkeeping.

    ``params`` is the canonical publish pytree (a flat dict — the delta
    publisher's ``params_of_model`` adapter returns it verbatim):
    ``centroids`` (nlist, d) f32, ``ids`` (nlist, block) int32 (-1 =
    empty slot), ``counts`` (nlist,) int32, and either ``vecs``
    (nlist*block, d) f32 (flat) or ``codes`` (nlist*block, m) int8 +
    ``cb_q``/``cb_s`` codebooks (PQ).  Everything else (the id->vector
    store for drift/re-anchor/exact-scan probes) is host-side only and
    never ships to serving."""

    query_col = "query"
    neighbors_col = "neighbors"
    distances_col = "distances"

    def __init__(self, *, params: Dict[str, np.ndarray], nlist: int,
                 block: int, dim: int, k: int, nprobe: int,
                 pq: Optional[PQConfig], seed: int, list_slack: int,
                 drift_threshold: Optional[float], max_iter: int,
                 store: Dict[int, np.ndarray]):
        self.params = params
        self.nlist = int(nlist)
        self.block = int(block)
        self.dim = int(dim)
        self.k = int(k)
        self.nprobe = int(nprobe)
        self.pq = pq
        self.seed = int(seed)
        self.list_slack = int(list_slack)
        self.drift_threshold = drift_threshold
        self.max_iter = int(max_iter)
        self._store = store

    # -- build --------------------------------------------------------------
    @classmethod
    def build(cls, vectors, nlist: int, pq: Optional[PQConfig] = None, *,
              k: int = 10, nprobe: Optional[int] = None,
              ids=None, seed: int = 0, list_slack: int = 8,
              drift_threshold: Optional[float] = 0.25, max_iter: int = 10,
              block: Optional[int] = None) -> "IVFIndex":
        """Train the coarse quantizer (workset KMeans fit), assign vectors
        to padded posting-list row blocks, and (PQ) encode residuals.

        ``block`` (rows per list, a multiple of 8) is normally sized to
        the fullest list plus ``list_slack`` insert headroom; passing it
        explicitly pins the device shapes — the same-shape re-anchor
        path uses this so a rebuilt index can publish as one FullUpdate
        instead of a full redeploy."""
        from ..models.clustering.kmeans import KMeans

        vectors = np.ascontiguousarray(np.asarray(vectors, np.float32))
        if vectors.ndim != 2 or vectors.shape[0] == 0:
            raise ValueError("vectors must be a non-empty (n, d) array")
        n, dim = vectors.shape
        if not 1 <= nlist <= n:
            raise ValueError(f"nlist={nlist} must be in [1, n={n}]")
        ids = (np.arange(n, dtype=np.int32) if ids is None
               else np.asarray(ids, np.int32))
        if ids.shape != (n,) or len(set(ids.tolist())) != n:
            raise ValueError("ids must be n unique int32 values")
        if np.any(ids < 0):
            raise ValueError("ids must be non-negative (-1 marks pad "
                             "slots in the posting lists)")
        if pq is not None:
            if dim % pq.m:
                raise ValueError(f"PQ m={pq.m} must divide dim={dim}")
            if not 2 <= pq.ksub <= 127:
                raise ValueError("PQ ksub must be in [2, 127] (int8 "
                                 "codes)")
            if n < pq.ksub:
                raise ValueError(f"PQ needs n >= ksub={pq.ksub}")

        coarse_fit = (KMeans().set_k(nlist).set_workset(True)
                      .set_seed(seed).set_max_iter(max_iter)
                      .fit(Table({"features": vectors})))
        centroids = np.asarray(
            coarse_fit.get_model_data()[0]["centroids"][0], np.float32)
        centroids = _refine_balance(centroids, vectors)
        assign = _nearest_list(centroids, vectors)
        counts = np.bincount(assign, minlength=nlist).astype(np.int32)
        need = int(counts.max()) if n else 1
        if block is None:
            block = _round_up8(max(need + list_slack, 8))
        elif need > block:
            raise ValueError(f"block={block} cannot hold the fullest "
                             f"list ({need} rows)")
        require_block_rows(block, 8, op="retrieve")

        ids2 = np.full((nlist, block), -1, np.int32)
        rows_of: List[np.ndarray] = []
        for lst in range(nlist):
            rows = np.flatnonzero(assign == lst)
            rows_of.append(rows)
            ids2[lst, :rows.size] = ids[rows]
        params: Dict[str, np.ndarray] = {
            "centroids": centroids,
            "ids": ids2,
            "counts": counts,
        }
        if pq is None:
            params["vecs"] = _pack_blocks(vectors, rows_of, block, dim,
                                          np.float32)
        else:
            cb_q, cb_s = _fit_codebooks(
                vectors - centroids[assign], pq, seed, max_iter)
            codes = _encode_pq(vectors - centroids[assign], cb_q, cb_s)
            params["codes"] = _pack_blocks(codes, rows_of, block, pq.m,
                                           np.int8)
            params["cb_q"], params["cb_s"] = cb_q, cb_s
        store = {int(i): vectors[j].copy()
                 for j, i in enumerate(ids.tolist())}
        return cls(params=params, nlist=nlist, block=block, dim=dim, k=k,
                   nprobe=(max(1, nlist // 8) if nprobe is None
                           else int(nprobe)),
                   pq=pq, seed=seed, list_slack=list_slack,
                   drift_threshold=drift_threshold, max_iter=max_iter,
                   store=store)

    # -- search planning ----------------------------------------------------
    def sig(self) -> tuple:
        pq = self.pq
        return retrieve_sig(self.nprobe, self.k, self.dim,
                            pq.m if pq else 0, pq.ksub if pq else 0,
                            self.nlist, self.block)

    def _static(self) -> tuple:
        pq = self.pq
        return (self.query_col, _NN_STAGE, _DIST_STAGE, self.nprobe,
                self.k, self.nlist, self.block, pq.m if pq else 0,
                pq.ksub if pq else 0)

    def search_plan(self) -> SearchPlan:
        """Resolve this index's (nprobe, k, dim, pq) schema against the
        kernel registry: Pallas on TPU hosts, the XLA lowering
        everywhere else — the availability/supports predicates decide,
        never a call-site branch."""
        entry = lookup("retrieve", self.sig())
        return SearchPlan(sig=self.sig(), static=self._static(),
                          backend=entry.backend)

    def with_options(self, *, nprobe: Optional[int] = None,
                     k: Optional[int] = None) -> "IVFIndex":
        """A view of the same index at a different operating point (new
        plan schema, same posting lists) — the bench's nprobe sweep."""
        clone = dataclasses.replace if False else None  # noqa: F841
        out = IVFIndex.__new__(IVFIndex)
        out.__dict__.update(self.__dict__)
        if nprobe is not None:
            if not 1 <= nprobe <= self.nlist:
                raise ValueError(f"nprobe={nprobe} not in [1, "
                                 f"nlist={self.nlist}]")
            out.nprobe = int(nprobe)
        if k is not None:
            out.k = int(k)
        return out

    def transform_kernel(self, schema):
        """Chain TERMINAL: the registry-resolved fused scan as a
        StageKernel — the same (fn, static) plan the serving executor,
        the fused pipelines, and offline ``transform`` dispatch."""
        from ..api.chain import StageKernel, numeric_entry

        if numeric_entry(schema, self.query_col) is None:
            return None
        entry = lookup("retrieve", self.sig())
        ncol, dcol = self.neighbors_col, self.distances_col

        def post(host):
            return {ncol: host[_NN_STAGE].astype(np.int64),
                    dcol: host[_DIST_STAGE]}

        return StageKernel(
            fn=entry.fn, static=self._static(),
            params={k: np.asarray(v) for k, v in self.params.items()},
            consumes=(self.query_col,),
            produces=(_NN_STAGE, _DIST_STAGE), post=post)

    # -- search -------------------------------------------------------------
    def transform(self, *inputs) -> List[Table]:
        """Batch search: appends ``neighbors`` (n, k) int64 ids (-1 for
        unfilled slots) and ``distances`` (n, k) f32 — squared L2 for
        flat, the ADC lookup-table approximation for PQ."""
        (table,) = inputs
        from ..api.chain import run_kernel

        kernel = self.transform_kernel(table.schema())
        if kernel is None:
            raise TypeError(
                f"IVFIndex.transform needs a numeric {self.query_col!r} "
                "column of query vectors")
        cols = run_kernel(kernel, table, op="retrieve")
        out = table.with_column(self.neighbors_col,
                                cols[self.neighbors_col])
        return [out.with_column(self.distances_col,
                                cols[self.distances_col])]

    def search(self, queries, *, nprobe: Optional[int] = None,
               k: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        """Convenience entry: (neighbor ids (n, k) int64, distances
        (n, k) f32) for a raw (n, d) query array."""
        index = self.with_options(nprobe=nprobe, k=k)
        out = index.transform(Table({self.query_col: np.asarray(
            queries, np.float32)}))[0]
        return (np.asarray(out[self.neighbors_col]),
                np.asarray(out[self.distances_col]))

    def scan_fraction(self, queries, nprobe: Optional[int] = None) -> float:
        """Analytic scan accounting: the mean over queries of (real rows
        in the probed lists) / (live rows) — derived from the coarse
        selection and the CSR counts, not from timing."""
        nprobe = self.nprobe if nprobe is None else int(nprobe)
        queries = np.asarray(queries, np.float32)
        cents = self.params["centroids"]
        coarse = (np.sum(cents * cents, axis=1)[None, :]
                  - 2.0 * queries @ cents.T)
        probes = np.argsort(coarse, axis=1, kind="stable")[:, :nprobe]
        live = max(1, self.num_vectors)
        scanned = self.params["counts"][probes].sum(axis=1)
        return float(np.mean(scanned) / live)

    # -- bookkeeping ---------------------------------------------------------
    @property
    def num_vectors(self) -> int:
        return int(self.params["counts"].sum())

    @property
    def offsets(self) -> np.ndarray:
        """CSR list offsets of the REAL rows (exclusive cumsum of
        ``counts``; ``offsets[-1]`` is the live row total) — the logical
        addressing the padded row blocks materialize at stride
        ``block``."""
        return np.concatenate(
            ([0], np.cumsum(self.params["counts"], dtype=np.int64)))

    def stored_vectors(self) -> Tuple[np.ndarray, np.ndarray]:
        """(ids (n,) int32, vectors (n, d) f32) of every live vector in
        ascending id order — the exact-scan reference for recall
        probes and the re-anchor rebuild corpus."""
        order = sorted(self._store)
        ids = np.asarray(order, np.int32)
        if not order:
            return ids, np.zeros((0, self.dim), np.float32)
        return ids, np.stack([self._store[i] for i in order])

    def centroid_drift(self) -> float:
        """Max over non-empty lists of ||member mean - centroid||, over
        the RMS centroid norm — the configurable re-anchor signal."""
        cents = self.params["centroids"].astype(np.float64)
        scale = float(np.sqrt(np.mean(np.sum(cents * cents, axis=1))))
        ids2, counts = self.params["ids"], self.params["counts"]
        worst = 0.0
        for lst in range(self.nlist):
            cnt = int(counts[lst])
            if not cnt:
                continue
            members = np.stack([self._store[int(i)]
                                for i in ids2[lst, :cnt]])
            gap = float(np.linalg.norm(
                members.astype(np.float64).mean(axis=0) - cents[lst]))
            worst = max(worst, gap)
        return worst / (scale + 1e-12)

    # -- incremental updates -------------------------------------------------
    def updated(self, inserts=None, insert_ids=None,
                delete_ids=()) -> Tuple[str, "IVFIndex"]:
        """Apply inserts/deletes; returns ``(mode, new_index)`` with this
        index untouched (in-flight queries finish on the old lists).

        ``mode == "delta"``: only the touched posting-list rows changed —
        publish ``new_index.params`` through the delta codec.  ``mode ==
        "reanchor"``: a list overflowed its block or centroid drift
        crossed the threshold, and ``new_index`` is a fresh build over
        the surviving + inserted vectors (same ``block`` kept when the
        new occupancy still fits, so the re-anchor can publish as one
        same-shape FullUpdate)."""
        inserts = (np.zeros((0, self.dim), np.float32) if inserts is None
                   else np.asarray(inserts, np.float32).reshape(-1, self.dim))
        if insert_ids is None:
            nxt = (max(self._store) + 1) if self._store else 0
            insert_ids = np.arange(nxt, nxt + inserts.shape[0],
                                   dtype=np.int32)
        insert_ids = np.asarray(insert_ids, np.int32).reshape(-1)
        if insert_ids.shape[0] != inserts.shape[0]:
            raise ValueError("insert_ids must match inserts rows")
        for vid in insert_ids.tolist():
            if vid in self._store or vid < 0:
                raise ValueError(f"insert id {vid} already live (or "
                                 "negative)")

        params = {name: arr.copy() for name, arr in self.params.items()}
        store = dict(self._store)
        ids2, counts = params["ids"], params["counts"]
        slot = {int(ids2[lst, j]): (lst, j)
                for lst in range(self.nlist)
                for j in range(int(counts[lst]))}
        for did in delete_ids:
            did = int(did)
            if did not in slot:
                raise KeyError(f"delete id {did} is not in the index")
            lst, j = slot.pop(did)
            last = int(counts[lst]) - 1
            if j != last:
                moved = int(ids2[lst, last])
                ids2[lst, j] = moved
                slot[moved] = (lst, j)
                self._move_row(params, lst, last, j)
            ids2[lst, last] = -1
            self._clear_row(params, lst, last)
            counts[lst] = last
            del store[did]

        cents = params["centroids"]
        overflow = False
        for vec, vid in zip(inserts, insert_ids.tolist()):
            lst = int(_nearest_list(cents, vec[None])[0])
            j = int(counts[lst])
            if j >= self.block:
                overflow = True
                break
            ids2[lst, j] = vid
            self._write_row(params, lst, j, vec)
            counts[lst] = j + 1
            slot[vid] = (lst, j)
            store[vid] = vec.copy()

        if overflow:
            merged = dict(self._store)
            for did in delete_ids:
                merged.pop(int(did), None)
            merged.update({int(i): v.copy()
                           for i, v in zip(insert_ids.tolist(), inserts)})
            return "reanchor", self._rebuilt(merged)

        out = IVFIndex.__new__(IVFIndex)
        out.__dict__.update(self.__dict__)
        out.params = params
        out._store = store
        if (self.drift_threshold is not None
                and out.centroid_drift() > self.drift_threshold):
            return "reanchor", self._rebuilt(store)
        return "delta", out

    def rebound(self, params: Dict[str, Any]) -> "IVFIndex":
        """The publish-side clone: same plan schema, new param buffers —
        what ``model_with_params`` hands the rebind fast path.  Host
        bookkeeping stays with the producer's authoritative copy."""
        out = IVFIndex.__new__(IVFIndex)
        out.__dict__.update(self.__dict__)
        out.params = {name: np.asarray(arr) for name, arr in params.items()}
        return out

    def _rebuilt(self, store: Dict[int, np.ndarray]) -> "IVFIndex":
        order = sorted(store)
        vectors = np.stack([store[i] for i in order])
        counts = np.bincount(
            _nearest_list(self.params["centroids"], vectors),
            minlength=self.nlist)
        keep = (int(counts.max()) + self.list_slack <= self.block)

        def build(block):
            return IVFIndex.build(
                vectors, self.nlist, self.pq, k=self.k,
                nprobe=self.nprobe, ids=np.asarray(order, np.int32),
                seed=self.seed, list_slack=self.list_slack,
                drift_threshold=self.drift_threshold,
                max_iter=self.max_iter, block=block)

        if keep:
            # the occupancy estimate above used the OLD centroids; the
            # re-anchor refits them, so the same-shape attempt (one
            # FullUpdate publish instead of a redeploy) can still
            # overflow — fall through to a fresh block size then
            try:
                return build(self.block)
            except ValueError:
                pass
        return build(None)

    # row edits shared by insert/delete (vecs for flat, codes for PQ)
    def _move_row(self, params, lst, src, dst):
        base = lst * self.block
        for name in ("vecs", "codes"):
            if name in params:
                params[name][base + dst] = params[name][base + src]

    def _clear_row(self, params, lst, j):
        base = lst * self.block
        for name in ("vecs", "codes"):
            if name in params:
                params[name][base + j] = 0

    def _write_row(self, params, lst, j, vec):
        base = lst * self.block
        if "vecs" in params:
            params["vecs"][base + j] = vec
        else:
            resid = vec - params["centroids"][lst]
            params["codes"][base + j] = _encode_pq(
                resid[None], params["cb_q"], params["cb_s"])[0]


#: staging column names — the device outputs are chain-terminal staging
#: values; the host ``post`` maps them to the public columns (the
#: ``__chain_assign__`` idiom of the KMeans terminal)
_NN_STAGE = "__retrieve_nn__"
_DIST_STAGE = "__retrieve_dist__"


# ---------------------------------------------------------------------------
# host-side build helpers (deterministic numpy — never on the serve path)
# ---------------------------------------------------------------------------

def _round_up8(n: int) -> int:
    return -(-int(n) // 8) * 8


def _nearest_list(centroids: np.ndarray, vectors: np.ndarray) -> np.ndarray:
    """Nearest-centroid assignment (f32 expression, first-index ties) —
    the same pairwise form the kernels rank with."""
    c = np.asarray(centroids, np.float32)
    v = np.asarray(vectors, np.float32)
    scores = np.sum(c * c, axis=1)[None, :] - 2.0 * (v @ c.T)
    return np.argmin(scores, axis=1).astype(np.int32)


def _refine_balance(centroids: np.ndarray, vectors: np.ndarray,
                    rounds: Optional[int] = None) -> np.ndarray:
    """Split-heaviest / merge-lightest refinement of the coarse fit.

    The workset KMeans fit can leave a heavy tail — a few centroids
    covering many natural clusters — and the padded row-block layout
    charges every probe for the FULLEST list, so one fat list inflates
    the whole index's scan cost (``block`` is sized to ``max(counts)``,
    not the mean).  Each round takes the heaviest list, splits its
    members at the median of their projection onto the farthest
    member's direction (both halves always non-empty), and re-uses the
    lightest list's centroid slot for the second half; only the two
    touched lists' members are locally re-assigned between rounds — the
    caller's final global ``_nearest_list`` pass restores the
    nearest-centroid invariant.  Deterministic, pure numpy, stops when
    the heaviest list is within 2x of the mean occupancy."""
    c = np.array(centroids, np.float32, copy=True)
    n, nlist = vectors.shape[0], c.shape[0]
    if nlist < 2 or n == 0:
        return c
    assign = _nearest_list(c, vectors)
    counts = np.bincount(assign, minlength=nlist)
    cap = max(2.0 * n / nlist, 8.0)
    for _ in range(nlist if rounds is None else rounds):
        h = int(counts.argmax())
        lo = int(counts.argmin())
        if h == lo or counts[h] <= cap or counts[h] < 2:
            break
        rows = np.flatnonzero(assign == h)
        pts = vectors[rows]
        dvec = pts - c[h]
        far = dvec[int(np.argmax(np.einsum("nd,nd->n", dvec, dvec)))]
        proj = dvec @ far
        side = proj > np.median(proj)
        if not side.any() or side.all():
            break
        c[h] = pts[side].mean(axis=0)
        c[lo] = pts[~side].mean(axis=0)
        moved = np.concatenate([rows, np.flatnonzero(assign == lo)])
        assign[moved] = _nearest_list(c, vectors[moved])
        counts = np.bincount(assign, minlength=nlist)
    return c


def _pack_blocks(rows: np.ndarray, rows_of: List[np.ndarray], block: int,
                 width: int, dtype) -> np.ndarray:
    """Pack per-list member rows into the (nlist*block, width) row-block
    array; non-empty lists pad through the maskless exact-zero
    ``pad_rows_to_block`` contract (pad rows are masked inert by their
    ``-1`` ids, so zero filler is never corrected downstream)."""
    out = np.zeros((len(rows_of) * block, width), dtype)
    for lst, members in enumerate(rows_of):
        if not members.size:
            continue
        (padded,), _ = pad_rows_to_block((rows[members],), block)
        out[lst * block:(lst + 1) * block] = padded.astype(dtype)
    return out


def _fit_codebooks(resid: np.ndarray, pq: PQConfig, seed: int,
                   max_iter: int) -> Tuple[np.ndarray, np.ndarray]:
    """Per-subspace codebooks: workset-KMeans on each residual subspace,
    stored through the ``quantize_rows`` recipe (int8 codes + per-row
    f32 scales)."""
    from ..models.clustering.kmeans import KMeans

    dsub = resid.shape[1] // pq.m
    cb_q = np.empty((pq.m, pq.ksub, dsub), np.int8)
    cb_s = np.empty((pq.m, pq.ksub), np.float32)
    for s in range(pq.m):
        sub = np.ascontiguousarray(resid[:, s * dsub:(s + 1) * dsub])
        fit = (KMeans().set_k(pq.ksub).set_workset(True)
               .set_seed(seed + 1 + s).set_max_iter(pq.max_iter)
               .fit(Table({"features": sub})))
        book = np.asarray(fit.get_model_data()[0]["centroids"][0],
                          np.float32)
        cb_q[s], cb_s[s] = quantize_rows(book)
    return cb_q, cb_s


def _encode_pq(resid: np.ndarray, cb_q: np.ndarray,
               cb_s: np.ndarray) -> np.ndarray:
    """int8 PQ codes: per-subspace argmin against the DECODED codebook —
    the exact values the kernel's LUT scans with."""
    m, _ksub, dsub = cb_q.shape
    decoded = cb_q.astype(np.float32) * cb_s[..., None]
    codes = np.empty((resid.shape[0], m), np.int8)
    for s in range(m):
        sub = resid[:, s * dsub:(s + 1) * dsub]
        d2 = np.sum(
            (sub[:, None, :] - decoded[s][None, :, :]) ** 2, axis=-1)
        codes[:, s] = np.argmin(d2, axis=1).astype(np.int8)
    return codes


# ---------------------------------------------------------------------------
# registry entry.  The Pallas backend registers from
# ops/retrieve_pallas.py (kernels live in ops/, models and indexes look
# them up); the catalog imports both so any consumer's first lookup sees
# the full backend set.
# ---------------------------------------------------------------------------

def _register_retrieve_kernels() -> None:
    register_kernel("retrieve", "xla", _retrieve_stage_xla,
                    convention="stage")


_register_retrieve_kernels()
