"""Controller inputs — one typed frame per tick over the metrics tree.

The policy must not grope around a nested snapshot dict: this module
turns ``MetricsTree.snapshot()`` into a :class:`SignalFrame` — the
closed set of numbers the ISSUE 17 decision loop consumes:

- per-tenant interactive/standard/bulk **p99 + queue depth + shed
  counters** (from the scheduler's ``tenants.<name>.*`` subtree, the
  PR 14 export), with shed counters turned into **windowed rates**
  (counter deltas over the sample interval — a counter's absolute value
  says nothing about *now*);
- **model staleness** (max over tenants, plus the optionally-designated
  learner tenant's own) — the continuous learner's freshness bound;
- **fleet gauges** (size, membership epoch, suppressions) from the
  elastic coordinator's subtree;
- **chip-idle fraction** from the scheduler's busy-accounting gauge
  (ISSUE 17 obs satellite) — computed by the scheduler in ITS OWN clock
  domain, so this module never divides one clock's busy seconds by
  another clock's wall delta.

Clock discipline (the PR 5 ``CheckpointManager`` pattern): the sampler's
``clock=`` stamps frames and windows rate computations; the controller
injects ONE clock through sampler, policy, and its own latency gauges,
so a test advancing a fake clock moves every timer coherently and MTTR
accounting never mixes domains.

A missing surface degrades to neutral, never to a fake number: no
scheduler subtree means empty tenants and NaN idle fraction; a
NaN/absent staleness (never published) stays NaN — the policy treats
NaN as "unknown, do not actuate on it" (the ``obs/tree.py``
absent-not-faked export stance).
"""

from __future__ import annotations

import math
import time

from dataclasses import dataclass
from typing import Any, Callable, Dict, Mapping, Optional

__all__ = ["SignalFrame", "SignalSource", "TenantSignal"]


def _num(value: Any, default: float = float("nan")) -> float:
    """A finite float, or ``default`` — snapshot leaves may be absent,
    None, or NaN-by-contract (never-published staleness)."""
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return default
    return float(value)


@dataclass(frozen=True)
class TenantSignal:
    """One tenant's slice of the frame."""

    name: str
    slo: str
    p99_ms: float
    queue_depth: float
    shed_total: float
    shed_rate_per_s: float
    staleness_s: float


@dataclass(frozen=True)
class SignalFrame:
    """Everything the policy reads, one tick.  Frozen: a decision is a
    pure function of one frame plus policy state."""

    at: float
    tenants: Mapping[str, TenantSignal]
    #: worst (max) p99 over the named SLO class, ms; NaN when the class
    #: has no tenants yet
    interactive_p99_ms: float
    #: per-class queue depth (the ISSUE 17 obs satellite gauges)
    queue_depth: Mapping[str, float]
    #: per-class windowed shed rate, events/s over the sample interval
    shed_rate: Mapping[str, float]
    #: scheduler busy-accounting idle fraction over ITS window [0, 1]
    chip_idle_fraction: float
    #: max model staleness over every tenant (NaN = never published)
    staleness_s: float
    #: the designated learner tenant's staleness (falls back to the max)
    learner_staleness_s: float
    fleet_size: int
    membership_epoch: int
    #: max live model generation over tenants — carried for trace
    #: correlation ONLY; the policy never keys a decision on it (the
    #: publish-storm immunity contract, tested)
    max_generation: float
    #: the scheduler's brownout ladder rung (ISSUE 20): nonzero while a
    #: failover has the fleet capacity-short and classes are being shed
    #: at admission — the policy holds capacity-yielding moves while it
    #: is up (shrinking serving mid-failover would fight the driver)
    brownout_level: int = 0


class SignalSource:
    """Samples a :class:`~flink_ml_tpu.obs.tree.MetricsTree` into
    :class:`SignalFrame`\\s, windowing counters against the previous
    sample.  ``scheduler_key``/``elastic_key`` name the tree providers
    (the ``default_tree`` names)."""

    def __init__(self, tree: Any, *,
                 clock: Callable[[], float] = time.monotonic,
                 scheduler_key: str = "scheduler",
                 elastic_key: str = "elastic",
                 learner_tenant: Optional[str] = None):
        self._tree = tree
        self.clock = clock
        self.scheduler_key = scheduler_key
        self.elastic_key = elastic_key
        self.learner_tenant = learner_tenant
        self._prev_at: Optional[float] = None
        self._prev_shed: Dict[str, float] = {}
        self.samples = 0

    # -- parsing -----------------------------------------------------------
    @staticmethod
    def _tenant_rows(sched: Mapping[str, Any]) -> Dict[str, Dict[str, Any]]:
        """Group the scheduler's flat dotted keys
        (``tenants.<name>.<metric...>``) back into per-tenant dicts."""
        rows: Dict[str, Dict[str, Any]] = {}
        for key, value in sched.items():
            parts = str(key).split(".")
            if len(parts) < 3 or parts[0] != "tenants":
                continue
            rows.setdefault(parts[1], {})[".".join(parts[2:])] = value
        return rows

    def sample(self) -> SignalFrame:
        now = self.clock()
        snap = self._tree.snapshot()
        sched = snap.get(self.scheduler_key, {}) or {}
        elastic = snap.get(self.elastic_key, {}) or {}

        from ..serving.scheduler import SLO_CLASSES

        tenants: Dict[str, TenantSignal] = {}
        shed_total = {slo: 0.0 for slo in SLO_CLASSES}
        max_staleness = float("nan")
        max_generation = float("nan")
        dt = (now - self._prev_at) if self._prev_at is not None else None
        for name, row in self._tenant_rows(sched).items():
            slo = str(row.get("slo", "standard"))
            staleness = _num(row.get("model_staleness_seconds"))
            shed = _num(row.get("shed"), 0.0)
            prev = self._prev_shed.get(f"tenant:{name}", shed)
            rate = ((shed - prev) / dt) if dt else 0.0
            self._prev_shed[f"tenant:{name}"] = shed
            tenants[name] = TenantSignal(
                name=name, slo=slo,
                p99_ms=_num(row.get("latency_p99_ms")),
                queue_depth=_num(row.get("queue_depth"), 0.0),
                shed_total=shed, shed_rate_per_s=rate,
                staleness_s=staleness)
            if math.isfinite(staleness) and not (
                    math.isfinite(max_staleness)
                    and max_staleness >= staleness):
                max_staleness = staleness
            gen = _num(row.get("model_generation"))
            if math.isfinite(gen) and not (
                    math.isfinite(max_generation)
                    and max_generation >= gen):
                max_generation = gen

        queue_depth, shed_rate = {}, {}
        for slo in SLO_CLASSES:
            queue_depth[slo] = _num(sched.get(f"queue_depth_{slo}"), 0.0)
            total = _num(sched.get(f"shed_{slo}"), 0.0)
            prev = self._prev_shed.get(f"class:{slo}", total)
            shed_rate[slo] = ((total - prev) / dt) if dt else 0.0
            self._prev_shed[f"class:{slo}"] = total
            shed_total[slo] = total

        inter = [t.p99_ms for t in tenants.values()
                 if t.slo == SLO_CLASSES[0] and math.isfinite(t.p99_ms)]
        learner_staleness = max_staleness
        if self.learner_tenant is not None \
                and self.learner_tenant in tenants:
            learner_staleness = tenants[self.learner_tenant].staleness_s

        frame = SignalFrame(
            at=now, tenants=tenants,
            interactive_p99_ms=max(inter) if inter else float("nan"),
            queue_depth=queue_depth, shed_rate=shed_rate,
            chip_idle_fraction=_num(sched.get("chip_idle_fraction")),
            staleness_s=max_staleness,
            learner_staleness_s=learner_staleness,
            fleet_size=int(_num(elastic.get("fleet_size"), 0.0)),
            membership_epoch=int(_num(elastic.get("membership_epoch"),
                                      0.0)),
            max_generation=max_generation,
            brownout_level=int(_num(sched.get("brownout_level"), 0.0)))
        self._prev_at = now
        self.samples += 1
        return frame
