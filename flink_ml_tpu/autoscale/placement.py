"""Versioned placement map — who owns which chips, this generation.

The control plane's single source of truth: a :class:`PlacementMap`
assigns every servable tenant a **chip set** and the continuous learner
a **fleet extent** (workers, in the
:class:`~flink_ml_tpu.parallel.elastic.ElasticCoordinator`'s worker
units).  The PR 14 scheduler and the elastic coordinator both READ the
live map; only the controller writes it, and every write is an atomic
generation-by-generation publish through a :class:`PlacementStore`:

- **Immutable maps, lock-free reads.**  A published map is frozen; the
  store's ``current()`` is one reference read (the
  ``serving/registry.py`` atomicity stance — a consumer captures the
  reference once per decision and never sees a half-built placement).
- **Durable publish via the PR 5 commit protocol.**  With a ``path``
  configured, each publish serializes the map to ``<path>.tmp`` and
  ``os.replace``\\s it over ``path`` BEFORE the in-memory swap — a crash
  between the two leaves a newer map on disk than in memory, which
  :meth:`PlacementStore.load` reconciles at restart (re-publishing a
  placement is idempotent: actuators converge on whatever the live map
  says).  A half-written file can never sit at the trusted path
  (``flink_ml_tpu/autoscale`` is in the graftlint atomic-writes durable
  set).
- **Single-writer generations.**  ``publish`` is compare-and-swap
  against the generation the caller based its edit on
  (``expected_generation``) — a racing writer gets
  :class:`PlacementConflict`, the ``serving/registry.py``
  ``GenerationConflict`` stance, never a silent clobber.

Capacity invariant, validated at every publish: the serving chip union
and the learner's chips (``learner_workers * chips_per_worker``) must
fit ``total_chips`` together.  Tenant chip sets MAY overlap each other
(two servables sharing a chip is exactly the PR 14 multi-tenant
posture); serving and the learner never share a chip — that boundary
is the thing the controller exists to move deliberately.
"""

from __future__ import annotations

import json
import os
import threading
import time

from dataclasses import dataclass
from typing import Any, Callable, Dict, Mapping, Optional, Sequence, Tuple

__all__ = ["PlacementConflict", "PlacementMap", "PlacementStore"]


class PlacementConflict(RuntimeError):
    """A conditional publish lost the race: the live placement
    generation is not the one the caller edited against."""


@dataclass(frozen=True)
class PlacementMap:
    """One published placement: frozen, so a reference captured by a
    scheduler tick or a chunk-boundary poll stays internally consistent
    for as long as the consumer holds it."""

    generation: int
    #: tenant name -> sorted chip ids its servable is placed on
    servables: Mapping[str, Tuple[int, ...]]
    #: the continuous learner's fleet extent, in coordinator worker units
    learner_workers: int
    #: store-clock stamp of the publish (the controller's clock domain)
    published_at: float = 0.0

    def chips_for(self, tenant: str) -> Tuple[int, ...]:
        return tuple(self.servables.get(tenant, ()))

    def serving_chips(self) -> Tuple[int, ...]:
        """The union of every tenant's chip set, sorted."""
        out = set()
        for chips in self.servables.values():
            out.update(chips)
        return tuple(sorted(out))

    def as_dict(self) -> Dict[str, Any]:
        return {
            "generation": self.generation,
            "servables": {name: list(chips)
                          for name, chips in sorted(self.servables.items())},
            "learner_workers": self.learner_workers,
            "published_at": self.published_at,
        }

    @classmethod
    def from_dict(cls, data: Mapping[str, Any]) -> "PlacementMap":
        return cls(
            generation=int(data["generation"]),
            servables={str(name): tuple(int(c) for c in chips)
                       for name, chips in dict(data["servables"]).items()},
            learner_workers=int(data["learner_workers"]),
            published_at=float(data.get("published_at", 0.0)),
        )


class PlacementStore:
    """The one writer-side object: validates, persists, and swaps
    placement generations.  Reads (``current()``) are a single
    reference fetch of an immutable map — no lock, the registry's
    ``live_generation`` stance — so the scheduler's dispatch loop and
    the coordinator's chunk-boundary poll can consult the placement at
    full rate."""

    def __init__(self, total_chips: int, *, chips_per_worker: int = 1,
                 path: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic):
        if total_chips < 1:
            raise ValueError("total_chips must be >= 1")
        if chips_per_worker < 1:
            raise ValueError("chips_per_worker must be >= 1")
        self.total_chips = int(total_chips)
        self.chips_per_worker = int(chips_per_worker)
        self.path = path
        self.clock = clock
        self._lock = threading.Lock()
        self._current = PlacementMap(generation=0, servables={},
                                     learner_workers=0)
        self.publishes = 0

    # -- reads -------------------------------------------------------------
    def current(self) -> PlacementMap:
        """The live map — one reference read, immutable thereafter."""
        return self._current

    @property
    def generation(self) -> int:
        return self._current.generation

    # -- validation --------------------------------------------------------
    def _validate(self, servables: Mapping[str, Sequence[int]],
                  learner_workers: int) -> Dict[str, Tuple[int, ...]]:
        if learner_workers < 0:
            raise ValueError("learner_workers must be >= 0")
        norm: Dict[str, Tuple[int, ...]] = {}
        union = set()
        for name, chips in servables.items():
            chips = tuple(sorted(int(c) for c in chips))
            if len(set(chips)) != len(chips):
                raise ValueError(
                    f"tenant {name!r} placement repeats a chip: {chips}")
            for c in chips:
                if not 0 <= c < self.total_chips:
                    raise ValueError(
                        f"tenant {name!r} placed on chip {c} outside the "
                        f"pool [0, {self.total_chips})")
            norm[name] = chips
            union.update(chips)
        learner_chips = learner_workers * self.chips_per_worker
        if len(union) + learner_chips > self.total_chips:
            raise ValueError(
                f"placement overcommits the fleet: {len(union)} serving "
                f"chip(s) + {learner_workers} learner worker(s) x "
                f"{self.chips_per_worker} chip(s) > {self.total_chips} "
                "total — serving and the learner never share a chip")
        return norm

    # -- the publish protocol ----------------------------------------------
    def publish(self, servables: Mapping[str, Sequence[int]],
                learner_workers: int, *,
                expected_generation: Optional[int] = None) -> PlacementMap:
        """Validate, persist (tmp -> ``os.replace``), then swap the live
        reference as the next generation.  ``expected_generation`` makes
        the swap conditional (compare-and-swap against the generation the
        caller edited) — a concurrent publish raises
        :class:`PlacementConflict` instead of silently clobbering."""
        norm = self._validate(servables, learner_workers)
        with self._lock:
            base = self._current.generation
            if expected_generation is not None \
                    and base != expected_generation:
                raise PlacementConflict(
                    f"placement publish expected generation "
                    f"{expected_generation} but {base} is live; re-read "
                    "current() and re-derive the edit")
            pmap = PlacementMap(
                generation=base + 1, servables=norm,
                learner_workers=int(learner_workers),
                published_at=self.clock())
        # durable BEFORE visible (the PR 5 commit order): a crash here
        # leaves generation N+1 on disk and N live in memory — load()
        # reconciles forward, and republishing a placement is idempotent
        if self.path is not None:
            self._write(pmap)
        with self._lock:
            if self._current.generation != base:
                raise PlacementConflict(
                    f"placement publish raced: generation moved "
                    f"{base} -> {self._current.generation} during the "
                    "durable write")
            self._current = pmap        # THE swap: one reference assign
            self.publishes += 1
        from ..obs.trace import tracer

        tracer.instant("placement_publish", cat="autoscale",
                       generation=pmap.generation,
                       x_learner_workers=str(pmap.learner_workers),
                       x_serving_chips=str(len(pmap.serving_chips())))
        return pmap

    def _write(self, pmap: PlacementMap) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(pmap.as_dict(), f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def load(self) -> Optional[PlacementMap]:
        """Restart reconciliation: adopt the on-disk map when it is ahead
        of memory (the crash-between-write-and-swap window).  Returns
        the adopted map, or ``None`` when there was nothing newer."""
        if self.path is None or not os.path.exists(self.path):
            return None
        with open(self.path) as f:
            pmap = PlacementMap.from_dict(json.load(f))
        with self._lock:
            if pmap.generation <= self._current.generation:
                return None
            self._current = pmap
        return pmap

    # -- observability -----------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        pmap = self._current
        return {
            "generation": pmap.generation,
            "learner_workers": pmap.learner_workers,
            "serving_chips": len(pmap.serving_chips()),
            "total_chips": self.total_chips,
            "publishes": self.publishes,
        }
