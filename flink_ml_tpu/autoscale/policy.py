"""The decision loop — capacity follows traffic, with hysteresis.

One policy object owns the serving/training split of a fixed chip
budget and decides, frame by frame, whether to move it:

- **Scale serving out** when interactive pressure is high — p99 at the
  SLO high-watermark, interactive queue depth past its threshold, or
  interactive sheds happening AT ALL (a shed is the envelope already
  torn, not a leading indicator).  Chips come from the learner: a
  serving scale-up is a PR 12 AOT cache-hit warm (seconds), and the
  matching training preemption is a PR 15 chunk-boundary resize
  (lossless by construction) — so acting is cheap and the policy leans
  toward protecting interactive traffic.
- **Yield trough capacity to training** when pressure is low AND chips
  are measurably idle — the learner grows one worker at a time toward
  its max, driving staleness down during the diurnal trough.
- **Hold** otherwise.

Thrash control, the part production controllers live or die on:

- **Deadband**: scale-out triggers at ``p99 >= high_frac * target``,
  release requires ``p99 <= low_frac * target`` — between the
  watermarks NOTHING moves, so a p99 oscillating inside the band
  (noisy quantiles, GC hiccups) produces zero churn.
- **Min-dwell**: after any actuation the policy holds for
  ``min_dwell_s`` on the injected clock regardless of signals, which
  bounds decisions/minute by construction (the hysteresis matrix in
  ``tests/test_autoscale.py`` asserts the ceiling).
- **Publish-storm immunity by construction**: a decision is a pure
  function of (pressure, idle, staleness, dwell state) — model
  generations and publish counters are carried in the frame for trace
  correlation only and never read here, so 30 back-to-back generations
  cause zero placement churn (tested).

NaN inputs (never-published staleness, a class with no tenants yet) are
treated as "unknown": they can never satisfy a trigger, so a cold
control plane holds instead of actuating on absent data.
"""

from __future__ import annotations

import math
import time

from dataclasses import dataclass
from typing import Callable, Optional

from .signals import SignalFrame

__all__ = ["AutoscalePolicy", "Decision", "PolicyConfig",
           "DECISION_HOLD", "DECISION_SCALE_SERVING",
           "DECISION_YIELD_TO_TRAINING"]

#: decision kinds: serving takes a worker's chips from the learner /
#: the learner gets a worker's chips back / nothing moves
DECISION_SCALE_SERVING = "scale_serving"
DECISION_YIELD_TO_TRAINING = "yield_to_training"
DECISION_HOLD = "hold"


@dataclass(frozen=True)
class Decision:
    """One tick's verdict: the target split plus WHY — ``reason`` is
    what the controller stamps on its tracer instant, so a Perfetto
    trace reads as a causal story ("p99 1.9x target" -> preempt)."""

    kind: str
    reason: str
    serving_chips: int
    learner_workers: int
    at: float

    @property
    def actuates(self) -> bool:
        return self.kind != DECISION_HOLD


@dataclass(frozen=True)
class PolicyConfig:
    """Watermarks and dwell for one fleet.  ``total_chips`` is the whole
    budget; serving owns whatever the learner doesn't
    (``serving = total - learner_workers * chips_per_worker``)."""

    #: interactive p99 SLO target, ms — the PR 14 envelope
    p99_target_ms: float
    total_chips: int
    chips_per_worker: int = 1
    #: deadband watermarks as fractions of the target
    high_frac: float = 0.9
    low_frac: float = 0.5
    #: interactive queue depth that forces scale-out regardless of p99
    queue_high: int = 64
    #: idle fraction at-or-above which trough capacity yields to training
    idle_high: float = 0.5
    #: staleness at-or-above which the trough handoff is also URGENT
    #: (reported in the reason; NaN staleness never triggers anything)
    staleness_high_s: float = 60.0
    #: minimum seconds between actuations (the injected-clock dwell)
    min_dwell_s: float = 10.0
    min_serving_chips: int = 1
    min_learner_workers: int = 0
    max_learner_workers: Optional[int] = None

    def __post_init__(self):
        if self.p99_target_ms <= 0:
            raise ValueError("p99_target_ms must be positive")
        if not 0.0 < self.low_frac < self.high_frac:
            raise ValueError(
                "need 0 < low_frac < high_frac — an inverted deadband "
                "actuates on both edges at once")
        if self.total_chips < 1 or self.chips_per_worker < 1:
            raise ValueError("total_chips/chips_per_worker must be >= 1")
        if self.min_serving_chips < 0 or self.min_learner_workers < 0:
            raise ValueError("placement floors must be >= 0")
        if self.min_serving_chips \
                + self.min_learner_workers * self.chips_per_worker \
                > self.total_chips:
            raise ValueError("placement floors overcommit total_chips")


class AutoscalePolicy:
    """Stateful hysteresis around the pure per-frame trigger logic.
    ``decide`` never touches an actuator — it returns a
    :class:`Decision` the controller turns into placement + elastic
    transitions, so the unit matrix can drive the policy with synthetic
    frames and a fake clock."""

    def __init__(self, config: PolicyConfig, *,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config
        self.clock = clock
        self._last_actuation_at: Optional[float] = None
        self.decisions = 0
        self.actuations = 0
        self.holds = 0
        self.last_reason = ""

    # -- trigger predicates (pure, NaN-safe) --------------------------------
    def _pressure(self, frame: SignalFrame) -> Optional[str]:
        """The scale-out trigger, or None.  NaN compares false on every
        branch — absent data never actuates."""
        cfg = self.config
        from ..serving.scheduler import SLO_INTERACTIVE

        p99 = frame.interactive_p99_ms
        if p99 >= cfg.high_frac * cfg.p99_target_ms:
            return (f"interactive p99 {p99:.1f}ms >= "
                    f"{cfg.high_frac:.2f}x target {cfg.p99_target_ms}ms")
        depth = frame.queue_depth.get(SLO_INTERACTIVE, 0.0)
        if depth >= cfg.queue_high:
            return (f"interactive queue depth {depth:.0f} >= "
                    f"{cfg.queue_high}")
        if frame.shed_rate.get(SLO_INTERACTIVE, 0.0) > 0.0:
            return "interactive sheds observed — envelope already torn"
        return None

    def _trough(self, frame: SignalFrame) -> Optional[str]:
        """The yield-to-training trigger, or None.  An active brownout
        (ISSUE 20) vetoes the yield outright: the fleet is
        capacity-short after a chip loss and whole classes are being
        shed at admission — handing chips to the learner now would
        fight the failover driver's recovery (idle fraction can look
        deceptively high mid-failover because browned-out classes stop
        arriving)."""
        cfg = self.config
        if getattr(frame, "brownout_level", 0) > 0:
            return None
        p99 = frame.interactive_p99_ms
        p99_low = (not math.isfinite(p99)
                   or p99 <= cfg.low_frac * cfg.p99_target_ms)
        if not p99_low:
            return None
        idle = frame.chip_idle_fraction
        if not (math.isfinite(idle) and idle >= cfg.idle_high):
            return None
        reason = (f"trough: idle fraction {idle:.2f} >= {cfg.idle_high}, "
                  f"p99 below {cfg.low_frac:.2f}x target")
        staleness = frame.learner_staleness_s
        if math.isfinite(staleness) and staleness >= cfg.staleness_high_s:
            reason += f"; learner staleness {staleness:.0f}s"
        return reason

    # -- the loop body -------------------------------------------------------
    def decide(self, frame: SignalFrame, *,
               learner_workers: int) -> Decision:
        """One tick: current split in, target split out.  The split is
        expressed as the learner's worker count; serving owns the rest
        of the budget."""
        cfg = self.config
        self.decisions += 1
        now = frame.at

        def _hold(reason: str) -> Decision:
            self.holds += 1
            self.last_reason = reason
            return Decision(
                kind=DECISION_HOLD, reason=reason, at=now,
                serving_chips=cfg.total_chips
                - learner_workers * cfg.chips_per_worker,
                learner_workers=learner_workers)

        def _move(kind: str, reason: str, workers: int) -> Decision:
            self._last_actuation_at = now
            self.actuations += 1
            self.last_reason = reason
            return Decision(
                kind=kind, reason=reason, at=now,
                serving_chips=cfg.total_chips
                - workers * cfg.chips_per_worker,
                learner_workers=workers)

        pressure = self._pressure(frame)
        trough = None if pressure else self._trough(frame)
        if pressure is None and trough is None:
            return _hold("deadband")
        if self._last_actuation_at is not None \
                and now - self._last_actuation_at < cfg.min_dwell_s:
            return _hold(
                f"min-dwell: {now - self._last_actuation_at:.1f}s since "
                f"last actuation < {cfg.min_dwell_s}s "
                f"(suppressed: {pressure or trough})")
        if pressure is not None:
            target = learner_workers - 1
            if target < cfg.min_learner_workers:
                return _hold(f"{pressure}; learner already at its "
                             f"floor {cfg.min_learner_workers}")
            return _move(DECISION_SCALE_SERVING,
                         f"{pressure}; preempting one learner worker",
                         target)
        target = learner_workers + 1
        max_workers = cfg.max_learner_workers
        if max_workers is None:
            max_workers = (cfg.total_chips - cfg.min_serving_chips) \
                // cfg.chips_per_worker
        if target > max_workers \
                or cfg.total_chips - target * cfg.chips_per_worker \
                < cfg.min_serving_chips:
            return _hold(f"{trough}; learner already at its ceiling")
        return _move(DECISION_YIELD_TO_TRAINING,
                     f"{trough}; granting one learner worker", target)

    def snapshot(self) -> dict:
        return {
            "decisions": self.decisions,
            "actuations": self.actuations,
            "holds": self.holds,
            "last_reason": self.last_reason,
        }
