"""Unified autoscaling control plane (ISSUE 17).

One controller over four prior PRs' actuators: it consumes the
graftscope metrics tree (PR 13), publishes a versioned
:class:`PlacementMap` splitting the chip budget between the PR 14
multi-tenant scheduler and the PR 15 elastic learner, and continuously
rebalances — serving scales out as diurnal traffic ramps (a PR 12
AOT cache-hit warm), the trough yields to training, and interactive
load preempts it back (a lossless PR 15 boundary resize) — with
hysteresis so noise never thrashes the fleet.

Modules: :mod:`~.placement` (the versioned map + durable store),
:mod:`~.signals` (typed frames over ``MetricsTree.snapshot()``),
:mod:`~.policy` (deadband + min-dwell decision loop),
:mod:`~.controller` (the actuation loop; every decision a tracer
instant).
"""

from .controller import AutoscaleController
from .placement import PlacementConflict, PlacementMap, PlacementStore
from .policy import (DECISION_HOLD, DECISION_SCALE_SERVING,
                     DECISION_YIELD_TO_TRAINING, AutoscalePolicy,
                     Decision, PolicyConfig)
from .signals import SignalFrame, SignalSource, TenantSignal

__all__ = [
    "AutoscaleController",
    "AutoscalePolicy",
    "Decision",
    "DECISION_HOLD",
    "DECISION_SCALE_SERVING",
    "DECISION_YIELD_TO_TRAINING",
    "PlacementConflict",
    "PlacementMap",
    "PlacementStore",
    "PolicyConfig",
    "SignalFrame",
    "SignalSource",
    "TenantSignal",
]
