"""The actuation loop — one tick: sample, decide, publish, move.

:class:`AutoscaleController` is the brain over four prior PRs'
actuators.  Each :meth:`tick`:

1. **samples** a :class:`~.signals.SignalFrame` from the metrics tree
   (the :class:`~.signals.SignalSource`);
2. **decides** through the :class:`~.policy.AutoscalePolicy` hysteresis
   loop;
3. on an actuating decision, **publishes** the next
   :class:`~.placement.PlacementMap` generation (atomic, durable, CAS —
   :class:`~.placement.PlacementStore`) and then moves the actuators to
   match it:

   - **serving**: :meth:`SharedScheduler.apply_placement` rescales WFQ
     weights to the tenants' chip counts, and every placed tenant's
     servable is confirmed warm against the :class:`ModelRegistry` —
     cheap by construction, because a scale-up of an already-served
     schema is a PR 12 AOT cache-hit walk, not a compile;
   - **training**: :meth:`ElasticCoordinator.request_resize` — applied
     at the learner's NEXT chunk boundary through the same
     register/preempt seam as injected churn, so a controller
     preemption is exactly a PR 15 lossless boundary resize.

Every decision — actuating or held — is a graftscope tracer instant
(``autoscale_decision``, with the policy's reason string), so a
Perfetto trace reads as a causal story of why the fleet moved.

Clock discipline (ISSUE 17 satellite): the controller takes ONE
``clock=`` and the convenience constructor threads it through sampler
and policy, so dwell timers, staleness windows, and the
``decision_latency_s`` gauge live in a single injected domain — a fake
clock in tests advances all of them coherently, and MTTR-style
accounting never divides one clock's delta by another's.

Like :class:`~flink_ml_tpu.obs.tree.ObsSampler`, the controller can
run tick-on-demand (tests, bench replay loops) or as a background
daemon thread (``start()``/``stop()``); the thread's cadence uses the
wall sleep of ``threading.Event.wait`` but every *measurement* stays on
the injected clock.
"""

from __future__ import annotations

import threading
import time

from typing import Any, Callable, Dict, List, Optional

from .placement import PlacementMap, PlacementStore
from .policy import AutoscalePolicy, Decision
from .signals import SignalFrame, SignalSource

__all__ = ["AutoscaleController"]


class AutoscaleController:
    """Wire a sampler, a policy, and a placement store onto the live
    actuators.  ``scheduler`` / ``elastic`` are each optional — a
    serving-only or training-only deployment still gets decisions and
    placements; the missing actuator is simply not moved."""

    def __init__(self, *, store: PlacementStore, policy: AutoscalePolicy,
                 signals: SignalSource,
                 scheduler: Any = None, elastic: Any = None,
                 health: Any = None,
                 clock: Callable[[], float] = time.monotonic,
                 interval_s: float = 1.0):
        if interval_s <= 0:
            raise ValueError("interval_s must be positive")
        self.store = store
        self.policy = policy
        self.signals = signals
        self.scheduler = scheduler
        self.elastic = elastic
        #: the serving fleet's lease table (ISSUE 20): when wired, the
        #: layout skips declared-dead chips, so a controller tick racing
        #: a failover converges onto the SAME survivor set — the two
        #: writers already share one placement generation stream (CAS);
        #: sharing the health view means the retry loser re-derives an
        #: edit the winner would also have made, never a re-placement
        #: back onto a dead chip
        self.health = health
        self.clock = clock
        self.interval_s = interval_s
        self.ticks = 0
        self.actuations = 0
        self.conflicts = 0
        #: decision→publish→actuate latency of the last tick, seconds in
        #: the INJECTED clock domain (the end-to-end clock satellite)
        self.last_decision_latency_s = float("nan")
        self.last_decision: Optional[Decision] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @classmethod
    def build(cls, tree: Any, *, store: PlacementStore,
              policy_config: Any, scheduler: Any = None,
              elastic: Any = None, health: Any = None,
              clock: Callable[[], float] = time.monotonic,
              learner_tenant: Optional[str] = None,
              interval_s: float = 1.0) -> "AutoscaleController":
        """The one-clock convenience constructor: build sampler + policy
        sharing ``clock`` (the PR 5 ``CheckpointManager`` injection
        pattern) over an existing metrics tree."""
        signals = SignalSource(tree, clock=clock,
                               learner_tenant=learner_tenant)
        policy = AutoscalePolicy(policy_config, clock=clock)
        return cls(store=store, policy=policy, signals=signals,
                   scheduler=scheduler, elastic=elastic, health=health,
                   clock=clock, interval_s=interval_s)

    # -- placement synthesis -------------------------------------------------
    def _tenant_names(self) -> List[str]:
        if self.scheduler is None:
            return sorted(self.store.current().servables)
        return self.scheduler.tenants()

    def _layout(self, serving_chips: int) -> Dict[str, List[int]]:
        """Tenant -> chip set for a serving extent of ``serving_chips``:
        every servable spans the whole serving slice (chips
        ``[0, serving_chips)`` — the learner owns the top of the pool),
        which is exactly the PR 14 shared-device posture; the WFQ layer,
        not the chip boundary, arbitrates between servables.  With a
        fleet-health view wired (ISSUE 20), declared-dead chips drop
        out of the slice — a tick landing mid-failover lays out onto
        the survivors, never back onto the corpse."""
        chips = list(range(serving_chips))
        if self.health is not None:
            down = set(self.health.down())
            live = [c for c in chips if c not in down]
            chips = live or chips
        return {name: chips for name in self._tenant_names()}

    # -- actuation -----------------------------------------------------------
    def _actuate(self, decision: Decision, pmap: PlacementMap) -> None:
        if self.scheduler is not None:
            self.scheduler.apply_placement(pmap)
            self._confirm_warm(pmap)
        if self.elastic is not None:
            self.elastic.request_resize(decision.learner_workers,
                                        reason=decision.reason)

    def _confirm_warm(self, pmap: PlacementMap) -> None:
        """Every placed tenant must be servable the moment traffic
        shifts onto its (re)grown chip set: confirm readiness against
        the registry.  For an already-served schema this is a no-op
        read — the admission-is-compilation-free receipt — and a
        not-yet-warm servable gets its warm-up here, OFF the dispatch
        path (the scheduler keeps serving the old placement
        meanwhile)."""
        registry = getattr(self.scheduler, "registry", None)
        if registry is None:
            return
        for name in pmap.servables:
            try:
                tenant = self.scheduler.tenant(name)
                deployed = registry.current(tenant.serve_name)
            except KeyError:
                continue        # placed but not admitted (yet): no-op
            servable = deployed.servable
            if not getattr(servable, "ready", True):
                servable.warm_up()

    # -- the loop body -------------------------------------------------------
    def tick(self) -> Decision:
        """One control iteration: sample -> decide -> publish ->
        actuate.  Always returns the decision (holds included); the
        tracer instant carries kind + reason either way."""
        from ..obs.trace import tracer
        from .placement import PlacementConflict

        t0 = self.clock()
        self.ticks += 1
        frame: SignalFrame = self.signals.sample()
        base = self.store.current()
        decision = self.policy.decide(
            frame, learner_workers=base.learner_workers)
        actuated = False
        if decision.actuates:
            try:
                pmap = self.store.publish(
                    self._layout(decision.serving_chips),
                    decision.learner_workers,
                    expected_generation=base.generation)
            except PlacementConflict:
                # a racing writer moved the map under us: skip this
                # tick's actuation and re-derive from the fresh map
                # next tick — never actuate a stale edit
                self.conflicts += 1
            else:
                self._actuate(decision, pmap)
                self.actuations += 1
                actuated = True
        self.last_decision = decision
        self.last_decision_latency_s = self.clock() - t0
        tracer.instant(
            "autoscale_decision", cat="autoscale",
            generation=self.store.current().generation,
            x_kind=decision.kind, x_reason=decision.reason,
            x_actuated=str(actuated),
            x_learner_workers=str(decision.learner_workers),
            x_serving_chips=str(decision.serving_chips))
        return decision

    # -- background thread ---------------------------------------------------
    def start(self) -> "AutoscaleController":
        if self._thread is not None:
            raise RuntimeError("controller already started")
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — the control plane
                    pass           # must never kill the data plane
        self._thread = threading.Thread(
            target=loop, daemon=True, name="flink-ml-tpu-autoscale")
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    # -- observability -------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """MetricsTree provider (``default_tree(autoscale=...)``):
        controller counters + the policy's decision ledger + the live
        placement — the control plane observes itself through the same
        tree it reads."""
        out: Dict[str, Any] = {
            "ticks": self.ticks,
            "actuations": self.actuations,
            "conflicts": self.conflicts,
            "decision_latency_s": self.last_decision_latency_s,
        }
        if self.last_decision is not None:
            out["last_kind"] = self.last_decision.kind
            out["last_reason"] = self.last_decision.reason
        for key, value in self.policy.snapshot().items():
            out[f"policy_{key}"] = value
        for key, value in self.store.snapshot().items():
            out[f"placement_{key}"] = value
        return out
