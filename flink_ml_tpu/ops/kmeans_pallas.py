"""Pallas KMeans kernels — the fit/transform hot path, fused in VMEM.

The XLA expansion of one Lloyd's iteration (pairwise matmul -> argmin ->
one_hot -> einsum, ``models/clustering/kmeans.py``) materialises two
``(n, k)`` intermediates in HBM (scores and the one-hot matrix): for the
headline shape (n=1M, d=64, k=256, f32) that is ~3 GB of HBM traffic per
iteration, which makes the step memory-bound (~3.3 ms/iter, ~300 iter/s on
one v5e chip).  These kernels tile the points over a sequential TPU grid and
keep the score/one-hot tiles in VMEM, so HBM traffic drops to reading the
points once (~256 MB) plus the (k, d) outputs:

    XLA fused path                          : ~300 iter/s   (3.3 ms/it)
    kmeans_update_stats  tie_policy="split" : ~730 iter/s   (1.4 ms/it)
    kmeans_update_stats  tie_policy="fast"  : ~1070 iter/s  (0.93 ms/it)
    kmeans_update_stats  tie_policy="first" : r3 numbers above; "first"
        (the r4 fit default) replaces "split"'s division with
        where/min/compare passes — expected between the two, measured
        on TPU by tests_tpu + bench each round.

(one v5e chip, 480-iteration fused scans so the ~70 ms tunnel round-trip is
amortised; bf16 dots measure within noise of f32 — the MXU is not the
bottleneck at d=64, the VPU passes over the (block_n, k) tile are.)

Design notes:

- **No mask input.**  Padding rows must be exact zeros — the MASKLESS
  kernel padding contract of ``utils/padding.py`` (``pad_rows_to_block``
  zero-fill + :func:`require_block_rows` divisibility; the shared rule
  every registered kernel pads by, not a module-local convention).  A
  zero row scores ``||c||^2`` against every centroid, so all padding
  lands on the centroid nearest the origin and contributes nothing to
  ``sums``; the caller subtracts the padding count from that one cluster
  (:func:`pad_correction`) — an exact fix that saves one HBM read + one
  (block_n, k) VPU pass over keeping a mask.  (The workset kernel below
  instead uses the MASKED contract: it needs the pad mask anyway to
  merge cached assignments, see :func:`kmeans_workset_update`.)
- **tie_policy="fast"** assigns a point to *every* centroid at exactly the
  minimum distance (``scores <= min``).  For continuous f32 data exact ties
  are measure-zero; the known benign case is duplicated centroids, which
  receive identical (double-counted) updates and therefore stay identical —
  the same fixed point Lloyd's has.  **"split"** divides tied points
  fractionally among the minimisers (exact expected-assignment semantics)
  at ~30% throughput cost.  **"first"** (the fit default since r4) keeps
  the reference's exact first-index-argmin semantics: the smallest tied
  column index via where/row-min/compare over an iota tile — no argmin
  loop, no division.
- a true ``argmin`` inside a Mosaic kernel lowers to a slow
  index-tracking loop (~6 ms/it measured), so the fit kernels compute
  assignment one-hots directly (see the policies above) rather than
  indices; the transform kernel (:func:`kmeans_assign_reduce`) does use
  argmin, because prediction needs indices and runs once, not
  ``max_iter`` times.
- ``||p||^2`` is omitted everywhere: it shifts each score row uniformly and
  cannot change which centroids attain the row minimum.

The reference computes the same statistics as a keyed network shuffle +
window reduce (``flink-ml-lib/.../clustering/kmeans/KMeans.java:172-196``);
here the whole reduction happens on-chip.
"""

from __future__ import annotations

import functools

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.padding import require_block_rows

__all__ = [
    "kmeans_assign_reduce",
    "kmeans_update_stats",
    "kmeans_workset_update",
    "update_stats_sharded",
    "pad_correction",
    "pick_block_n",
    "pick_block_n_measured",
    "pick_block_n_workset",
    "pick_block_n_workset_measured",
    "supported",
    "workset_supported",
]

_VMEM_BUDGET = 12 * 1024 * 1024  # leave headroom below the ~16 MB/core VMEM


def _stats_tile_bytes(d: int, k: int, block_n: int) -> int:
    """THE per-tile VMEM model of the stats kernels: one (block_n, k) f32
    score tile + a (block_n, d) points tile + the (k, d)/(k,)
    accumulators.  One score-sized tile is the right model: Mosaic
    reuses the buffer across the compare/one-hot chain (empirically
    block_n=8192, k=256, d=64 compiles and runs on v5e).  Every
    supported()/pick_block_n variant in this module derives from this
    ONE formula."""
    return block_n * k * 4 + block_n * d * 4 + k * d * 4 + k * 4


def supported(d: int, k: int, block_n: int = 8192) -> bool:
    """True if the stats-kernel tile (:func:`_stats_tile_bytes`) fits the
    VMEM budget."""
    return _stats_tile_bytes(d, k, block_n) <= _VMEM_BUDGET


def _pick_block(n: Optional[int], fits) -> Optional[int]:
    """Largest power-of-two block (<= 8192, >= 128) satisfying ``fits``
    and — when ``n`` is given — dividing ``n``; None if nothing works
    (caller falls back to XLA)."""
    bn = 8192
    while bn >= 128:
        if (n is None or n % bn == 0) and fits(bn):
            return bn
        bn //= 2
    return None


def pick_block_n(n: Optional[int], d: int, k: int) -> Optional[int]:
    """Largest viable stats-kernel block.  Pass ``n=None`` when the
    caller zero-pads to the block anyway (the estimator does)."""
    return _pick_block(n, lambda bn: supported(d, k, bn))


def _viable_blocks(fits) -> list:
    """Every power-of-two block (8192 down to 128) passing ``fits`` —
    the candidate set the measured search ranks (the analytic descent
    only ever took the largest)."""
    return [bn for bn in (8192, 4096, 2048, 1024, 512, 256, 128)
            if fits(bn)]


def _measured_block(op: str, d: int, k: int, candidates: list,
                    runner_factory, *, analytic: int) -> int:
    """Resolve a block size by measurement through the registry
    autotuner (``kernels/autotune.py``): ``choose`` honors a recorded
    decision for ``(op, ("block_n", d, k))`` without running anything;
    a first encounter times every candidate on a synthetic probe of the
    kernel's real entry point and persists the winner.  With autotuning
    disabled (no cache root) the analytic pick stands — exactly the
    pre-autotune behavior."""
    from ..kernels import autotune

    if len(candidates) == 1 or not autotune.enabled():
        return analytic
    choice, _ = autotune.choose(
        op, ("block_n", d, k),
        {str(bn): runner_factory(bn) for bn in candidates},
        kind="block", probe=f"synthetic n={max(candidates)} d={d} k={k}")
    return int(choice)


def _probe_operands(n: int, d: int, k: int):
    # centroids drawn SEPARATELY from the points: the probe's k must be
    # the real fit's k even when k exceeds the largest candidate block,
    # or the persisted winner would be measured on the wrong problem
    rng = np.random.default_rng(1234)
    points = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    cents = jnp.asarray(rng.normal(size=(k, d)).astype(np.float32))
    return points, cents


def pick_block_n_measured(d: int, k: int, *, interpret: bool = False,
                          candidates: Optional[list] = None
                          ) -> Optional[int]:
    """The measured form of :func:`pick_block_n` (ISSUE 12): instead of
    trusting the VMEM model to rank blocks, time the stats kernel at
    every viable block size once per (d, k, device kind) and persist the
    winner in the autotune cache — every later process reuses the
    decision without re-searching.  Falls back to the analytic pick when
    autotuning is disabled; returns None exactly when the analytic
    descent would (no viable block -> XLA fallback)."""
    cands = (candidates if candidates is not None
             else _viable_blocks(lambda bn: supported(d, k, bn)))
    if not cands:
        return None
    # probe operands are lazy AND shared across candidates: a recorded
    # decision allocates nothing, a fresh search allocates one set
    probe: list = []

    def runner(bn):
        def thunk():
            if not probe:
                probe.append(_probe_operands(max(cands), d, k))
            points, cents = probe[0]
            return kmeans_update_stats(points, cents, block_n=bn,
                                       interpret=interpret)
        return thunk

    return _measured_block("kmeans_update_stats", d, k, cands, runner,
                           analytic=max(cands))


def pick_block_n_workset_measured(d: int, k: int, *,
                                  interpret: bool = False,
                                  candidates: Optional[list] = None
                                  ) -> Optional[int]:
    """Measured twin of :func:`pick_block_n_workset` for the fused
    workset kernel (same decision protocol, its own op key — the two
    kernels have different VPU/VMEM profiles, so one winner must never
    be assumed to transfer to the other)."""
    cands = (candidates if candidates is not None
             else _viable_blocks(lambda bn: workset_supported(d, k, bn)))
    if not cands:
        return None
    probe: list = []

    def runner(bn):
        def thunk():
            if not probe:
                n = max(cands)
                points, cents = _probe_operands(n, d, k)
                probe.append((points, cents, jnp.zeros((n,), jnp.int32),
                              jnp.ones((n,), jnp.float32)))
            points, cents, prev, ones = probe[0]
            return kmeans_workset_update(points, cents, prev, ones,
                                         ones, block_n=bn,
                                         interpret=interpret)
        return thunk

    return _measured_block("kmeans_workset_update", d, k, cands, runner,
                           analytic=max(cands))


def _stats_kernel(tie_policy: str, compute_dtype):
    def kern(points_ref, cent_ref, c2_ref, sums_ref, counts_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            sums_ref[:] = jnp.zeros_like(sums_ref)
            counts_ref[:] = jnp.zeros_like(counts_ref)

        pts = points_ref[:]
        scores = (-2.0 * jnp.dot(pts.astype(compute_dtype),
                                 cent_ref[:].astype(compute_dtype).T,
                                 preferred_element_type=jnp.float32)
                  + c2_ref[:])                                    # (bn, k)
        mins = jnp.min(scores, axis=1, keepdims=True)
        is_min = scores <= mins
        if tie_policy == "first":
            # exact first-index-argmin semantics WITHOUT an argmin loop
            # (which lowers to a ~6 ms index-tracking scan in Mosaic):
            # the first minimiser is the smallest column index among the
            # tied minima — one where + row-min + compare, all cheap VPU
            # passes (no division like "split").
            k = scores.shape[1]
            iota = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
            first = jnp.min(jnp.where(is_min, iota, k), axis=1,
                            keepdims=True)
            onehot = (iota == first).astype(jnp.float32)
        else:
            onehot = is_min.astype(jnp.float32)
            if tie_policy == "split":
                onehot = onehot / jnp.sum(onehot, axis=1, keepdims=True)
        sums_ref[:] += jnp.dot(onehot.T.astype(compute_dtype),
                               pts.astype(compute_dtype),
                               preferred_element_type=jnp.float32)
        counts_ref[:] += jnp.sum(onehot, axis=0)

    return kern


def _assign_kernel(points_ref, cent_ref, c2_ref,
                   assign_ref, sums_ref, counts_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)

    pts = points_ref[:]
    scores = (-2.0 * jnp.dot(pts, cent_ref[:].T,
                             preferred_element_type=jnp.float32)
              + c2_ref[:])
    assign = jnp.argmin(scores, axis=1)
    assign_ref[:] = assign.astype(jnp.int32)

    k = sums_ref.shape[0]
    onehot = (assign[:, None]
              == jax.lax.broadcasted_iota(jnp.int32, (pts.shape[0], k), 1))
    onehot = onehot.astype(jnp.float32)
    sums_ref[:] += jnp.dot(onehot.T, pts,
                           preferred_element_type=jnp.float32)
    counts_ref[:] += jnp.sum(onehot, axis=0)


def _check_block(n: int, block_n: int, op: str = "kmeans_pallas") -> None:
    # the shared registered-kernel invariant (utils/padding.py), not a
    # module-local rule: every blocked kernel raises the same message
    require_block_rows(n, block_n, op=op)


@functools.partial(jax.jit,
                   static_argnames=("block_n", "tie_policy", "compute_dtype",
                                    "interpret"))
def kmeans_update_stats(points: jnp.ndarray, centroids: jnp.ndarray, *,
                        block_n: int = 8192, tie_policy: str = "fast",
                        compute_dtype=jnp.float32, interpret: bool = False
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fit hot path: ``(points (n, d), centroids (k, d)) ->
    (sums (k, d) f32, counts (k,) f32)``.

    ``n`` must be a multiple of ``block_n``; pad with all-zero rows and
    correct the counts with :func:`pad_correction`.
    """
    if tie_policy not in ("first", "fast", "split"):
        raise ValueError(f"tie_policy must be 'first', 'fast' or 'split', "
                         f"got {tie_policy!r}")
    n, d = points.shape
    k = centroids.shape[0]
    _check_block(n, block_n)
    c2 = jnp.sum(centroids * centroids, axis=1)[None, :]

    return pl.pallas_call(
        _stats_kernel(tie_policy, compute_dtype),
        grid=(n // block_n,),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((k,), jnp.float32),
        ],
        interpret=interpret,
    )(points, centroids, c2)


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def kmeans_assign_reduce(points: jnp.ndarray, centroids: jnp.ndarray, *,
                         block_n: int = 2048, interpret: bool = False
                         ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Transform path: also emits per-point assignments (first-index argmin).
    ``(points (n, d), centroids (k, d)) ->
    (assignments (n,) int32, sums (k, d), counts (k,))``.
    Padding rows get a garbage (but in-range) assignment — slice them off."""
    n, d = points.shape
    k = centroids.shape[0]
    _check_block(n, block_n)
    c2 = jnp.sum(centroids * centroids, axis=1)[None, :]

    return pl.pallas_call(
        _assign_kernel,
        grid=(n // block_n,),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((block_n,), lambda i: (i,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n,), jnp.int32),
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((k,), jnp.float32),
        ],
        interpret=interpret,
    )(points, centroids, c2)


def pad_correction(counts: jnp.ndarray, centroids: jnp.ndarray,
                   n_pad, tie_policy: str = "fast") -> jnp.ndarray:
    """Remove the contribution of ``n_pad`` all-zero padding rows: they all
    landed on the centroid(s) with the smallest norm, added nothing to
    ``sums``, and ``n_pad`` to those clusters' counts.

    ``tie_policy`` must name the policy of the kernel that produced
    ``counts``, so the fix stays exact even when several centroids tie for
    minimal norm (e.g. duplicated init centroids):

    - ``"fast"``   — :func:`kmeans_update_stats` counted padding fully on
      *every* tied centroid
    - ``"split"``  — fractionally across the tied centroids
    - ``"argmin"`` / ``"first"`` — :func:`kmeans_assign_reduce` /
      :func:`kmeans_update_stats` with ``tie_policy="first"`` counted it
      on the first tied index only (first-index argmin semantics)
    """
    c2 = jnp.sum(centroids * centroids, axis=1)
    if tie_policy in ("argmin", "first"):
        tied = jax.nn.one_hot(jnp.argmin(c2), counts.shape[0],
                              dtype=counts.dtype)
    elif tie_policy in ("fast", "split"):
        tied = (c2 <= jnp.min(c2)).astype(counts.dtype)
        if tie_policy == "split":
            tied = tied / jnp.sum(tied)
    else:
        raise ValueError(
            f"tie_policy must be 'first', 'fast', 'split' or 'argmin', "
            f"got {tie_policy!r}")
    return counts - n_pad * tied


def update_stats_sharded(points: jnp.ndarray, centroids: jnp.ndarray,
                         mesh, *, block_n: int = 8192,
                         tie_policy: str = "fast",
                         compute_dtype=jnp.float32,
                         interpret: bool = False
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Mesh-parallel stats: each device runs the kernel on its row shard,
    partial (k, d)/(k,) results are summed with one ``psum`` over the
    ``data`` axis (the ICI allreduce replacing the reference's keyed network
    shuffle).  Per-shard row count must be a multiple of ``block_n``."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.collectives import shard_map_fn

    def shard_fn(pts, cents):
        sums, counts = kmeans_update_stats(
            pts, cents, block_n=block_n, tie_policy=tie_policy,
            compute_dtype=compute_dtype, interpret=interpret)
        return (jax.lax.psum(sums, "data"), jax.lax.psum(counts, "data"))

    # the shared shim turns the replication check off on every JAX version
    # (pallas_call out_shapes carry no varying-mesh-axes annotation)
    return shard_map_fn(shard_fn, mesh=mesh,
                        in_specs=(P("data", None), P(None, None)),
                        out_specs=(P(None, None), P(None)))(points, centroids)


# ---------------------------------------------------------------------------
# Fused workset assign+update (PR 10 hot path): one VMEM pass per tile
# computes the Hamerly scoring (distances, first-index argmin, best and
# second-best distances), merges with the cached assignment under the
# active mask, AND accumulates the Lloyd's statistics — the (n, k)
# distance matrix, the is_min compare tiles, and the (n, k) one-hot all
# live and die in VMEM instead of round-tripping HBM between the scoring
# expression and the stats einsum of the XLA workset body
# (``models/clustering/kmeans.py::kmeans_workset_epoch_step``).
# ---------------------------------------------------------------------------

def workset_supported(d: int, k: int, block_n: int = 8192) -> bool:
    """VMEM model of :func:`kmeans_workset_update`: the shared stats-tile
    footprint (:func:`_stats_tile_bytes`) plus the per-tile
    assign/bound/mask vectors (~6 lane vectors of block_n f32/i32)."""
    extra = 6 * block_n * 4
    return _stats_tile_bytes(d, k, block_n) + extra <= _VMEM_BUDGET


def pick_block_n_workset(n: Optional[int], d: int, k: int) -> Optional[int]:
    """Largest viable workset-kernel block (``n=None`` when the caller
    pads to the block — the estimator does)."""
    return _pick_block(n, lambda bn: workset_supported(d, k, bn))


def _workset_kernel(k: int):
    def kern(points_ref, cent_ref, c2_ref, prev_ref, active_ref, padm_ref,
             assign_ref, dbest_ref, dsec_ref, sums_ref, counts_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            sums_ref[:] = jnp.zeros_like(sums_ref)
            counts_ref[:] = jnp.zeros_like(counts_ref)

        pts = points_ref[:]
        # EXPRESSION-identical to EuclideanDistanceMeasure.pairwise (the
        # XLA workset body's scoring): the bound cache decays in TRUE
        # distance space, so the kernel must emit root distances, and
        # matching the expression keeps the per-row results bit-identical
        # to the XLA body in interpret mode (the parity oracle).
        p2 = jnp.sum(pts * pts, axis=-1, keepdims=True)          # (bn, 1)
        cross = jnp.dot(pts, cent_ref[:].T,
                        preferred_element_type=jnp.float32)      # (bn, k)
        dists = jnp.sqrt(jnp.maximum(p2 - 2.0 * cross + c2_ref[:], 0.0))
        mins = jnp.min(dists, axis=1, keepdims=True)
        is_min = dists <= mins
        # first-index argmin WITHOUT an argmin loop (the stats-kernel
        # trick): smallest tied column index via iota + row-min
        iota = jax.lax.broadcasted_iota(jnp.int32, dists.shape, 1)
        fresh = jnp.min(jnp.where(is_min, iota, k), axis=1)      # (bn,)
        d_sec = jnp.min(jnp.where(iota == fresh[:, None],
                                  jnp.inf, dists), axis=1)

        # merge: active points take the fresh score, settled points keep
        # their cached assignment (provably identical, see the body doc)
        on = active_ref[:] > 0
        assign = jnp.where(on, fresh, prev_ref[:]).astype(jnp.int32)
        assign_ref[:] = assign
        dbest_ref[:] = mins[:, 0]
        dsec_ref[:] = d_sec

        onehot = (iota == assign[:, None]).astype(jnp.float32)
        onehot = onehot * padm_ref[:][:, None]        # masked contract
        sums_ref[:] += jnp.dot(onehot.T, pts,
                               preferred_element_type=jnp.float32)
        counts_ref[:] += jnp.sum(onehot, axis=0)

    return kern


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def kmeans_workset_update(points: jnp.ndarray, centroids: jnp.ndarray,
                          prev_assign: jnp.ndarray, active: jnp.ndarray,
                          pad_mask: jnp.ndarray, *, block_n: int = 2048,
                          interpret: bool = False):
    """Fused bound-filtered scoring + stats for one workset Lloyd's round:
    ``(points (n, d), centroids (k, d), prev_assign (n,) i32,
    active (n,) f32 0/1, pad_mask (n,) f32 0/1) ->
    (assign (n,) i32, d_best (n,), d_second (n,), sums (k, d),
    counts (k,))``.

    ``assign`` is already MERGED (fresh first-index argmin where active,
    the cached assignment elsewhere); ``d_best``/``d_second`` are the
    FRESH per-point best/second-best root distances — the caller keeps
    its old bounds where the point was settled, then applies the drift
    decay exactly as the XLA body does.  Stats are masked by
    ``pad_mask`` (the MASKED padding contract,
    ``utils/padding.py::pad_rows_with_mask(multiple=block_n)``) — no
    pad-correction step, unlike the maskless BSP stats kernel.

    Parity: per-row outputs are expression-identical to the XLA workset
    body; ``sums`` accumulate tile-sequentially, so they match the XLA
    einsum to f32 summation order (allclose, not bitwise — asserted in
    the cross-backend matrix of ``tests/test_kernels.py``).  Euclidean
    only (the bounds need root distances)."""
    n, d = points.shape
    k = centroids.shape[0]
    _check_block(n, block_n, op="kmeans_workset_update")
    c2 = jnp.sum(centroids * centroids, axis=1)[None, :]

    return pl.pallas_call(
        _workset_kernel(k),
        grid=(n // block_n,),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n,), lambda i: (i,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n,), lambda i: (i,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n,), lambda i: (i,),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((block_n,), lambda i: (i,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n,), lambda i: (i,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n,), lambda i: (i,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n,), jnp.int32),
            jax.ShapeDtypeStruct((n,), jnp.float32),
            jax.ShapeDtypeStruct((n,), jnp.float32),
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((k,), jnp.float32),
        ],
        interpret=interpret,
    )(points, centroids, c2, prev_assign.astype(jnp.int32),
      active.astype(jnp.float32), pad_mask.astype(jnp.float32))
