"""Pallas KMeans kernels — fused assign/reduce alternatives to the XLA path.

Two kernels over point tiles (VMEM-resident, sequential TPU grid):

- :func:`kmeans_assign_reduce`: argmin assignment + one-hot partial sums
  and counts, also emitting per-point assignments (what a fused
  ``transform`` wants).
- :func:`kmeans_update_stats`: the fit hot path — min+equality instead of
  argmin (Mosaic lowers reductions much faster than index-tracking argmin;
  ties are split fractionally), sums/counts only.

Measured on one v5e chip (n=1M, d=64, k=256, 30 iters, f32):
    XLA fused path (models/clustering/kmeans.py) : ~236-251 iter/s
    kmeans_update_stats (block_n=2048)           : ~212 iter/s
    kmeans_assign_reduce (argmin in-kernel)      : ~104-124 iter/s

XLA's own fusion of matmul+argmin+one-hot already keeps the (n, k)
intermediates out of HBM, so the estimator keeps the XLA path as default;
these kernels are the maintained starting point for future tuning (bf16
scores, k-tiling) and the CPU-interpret reference for kernel tests.
``||p||^2`` is omitted everywhere — it shifts each score row uniformly, so
assignments are unchanged.
"""

from __future__ import annotations

import functools

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["kmeans_assign_reduce", "kmeans_update_stats", "supported"]


def supported(d: int, k: int) -> bool:
    """VMEM budget check: centroids (k, d) + a (block_n, k) score tile must
    fit comfortably."""
    return k * d * 4 <= 4 * 1024 * 1024 and k <= 4096


def _assign_kernel(points_ref, mask_ref, cent_ref, c2_ref,
                   assign_ref, sums_ref, counts_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)

    pts = points_ref[:]                                     # (bn, d)
    scores = (-2.0 * jnp.dot(pts, cent_ref[:].T,
                             preferred_element_type=jnp.float32)
              + c2_ref[:])                                  # (bn, k)
    assign = jnp.argmin(scores, axis=1)                     # (bn,)
    assign_ref[:] = assign.astype(jnp.int32)

    k = sums_ref.shape[0]
    onehot = (assign[:, None]
              == jax.lax.broadcasted_iota(jnp.int32, (pts.shape[0], k), 1))
    onehot = onehot.astype(jnp.float32) * mask_ref[:][:, None]
    sums_ref[:] += jnp.dot(onehot.T, pts,
                           preferred_element_type=jnp.float32)
    counts_ref[:] += jnp.sum(onehot, axis=0)


def _stats_kernel(points_ref, mask_ref, cent_ref, c2_ref,
                  sums_ref, counts_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        sums_ref[:] = jnp.zeros_like(sums_ref)
        counts_ref[:] = jnp.zeros_like(counts_ref)

    pts = points_ref[:]
    scores = (-2.0 * jnp.dot(pts, cent_ref[:].T,
                             preferred_element_type=jnp.float32)
              + c2_ref[:])
    mins = jnp.min(scores, axis=1, keepdims=True)
    onehot = (scores <= mins).astype(jnp.float32)
    onehot = onehot / jnp.sum(onehot, axis=1, keepdims=True)  # split ties
    onehot = onehot * mask_ref[:][:, None]
    sums_ref[:] += jnp.dot(onehot.T, pts,
                           preferred_element_type=jnp.float32)
    counts_ref[:] += jnp.sum(onehot, axis=0)


def _common_specs(block_n: int, d: int, k: int):
    return [
        pl.BlockSpec((block_n, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((block_n,), lambda i: (i,), memory_space=pltpu.VMEM),
        pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, k), lambda i: (0, 0), memory_space=pltpu.VMEM),
    ]


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def kmeans_assign_reduce(points: jnp.ndarray, mask: jnp.ndarray,
                         centroids: jnp.ndarray, *, block_n: int = 2048,
                         interpret: bool = False
                         ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(points (n,d), mask (n,), centroids (k,d)) ->
    (assignments (n,) int32, sums (k,d), counts (k,)).
    n must be a multiple of block_n (pad with mask=0 rows)."""
    n, d = points.shape
    k = centroids.shape[0]
    if n % block_n:
        raise ValueError(f"n={n} must be a multiple of block_n={block_n}")
    c2 = jnp.sum(centroids * centroids, axis=1)[None, :]

    return pl.pallas_call(
        _assign_kernel,
        grid=(n // block_n,),
        in_specs=_common_specs(block_n, d, k),
        out_specs=[
            pl.BlockSpec((block_n,), lambda i: (i,), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n,), jnp.int32),
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((k,), jnp.float32),
        ],
        interpret=interpret,
    )(points, mask, centroids, c2)


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def kmeans_update_stats(points: jnp.ndarray, mask: jnp.ndarray,
                        centroids: jnp.ndarray, *, block_n: int = 2048,
                        interpret: bool = False
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fit hot path: (sums (k,d), counts (k,)) without assignments."""
    n, d = points.shape
    k = centroids.shape[0]
    if n % block_n:
        raise ValueError(f"n={n} must be a multiple of block_n={block_n}")
    c2 = jnp.sum(centroids * centroids, axis=1)[None, :]

    return pl.pallas_call(
        _stats_kernel,
        grid=(n // block_n,),
        in_specs=_common_specs(block_n, d, k),
        out_specs=[
            pl.BlockSpec((k, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k, d), jnp.float32),
            jax.ShapeDtypeStruct((k,), jnp.float32),
        ],
        interpret=interpret,
    )(points, mask, centroids, c2)
