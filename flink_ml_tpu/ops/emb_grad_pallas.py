"""Fused Mosaic fold for the routed embedding gradient — the Wide&Deep
backward hot path, stage 2 of ``ops/emb_grad.py`` in ONE VMEM pass.

BENCH_r05 put the routed embedding-gradient step at the top of the
Wide&Deep profile: the dense towers ride the MXU while the table
gradient is bounded by HBM streaming.  The XLA routed path is already
scatter-free, but its segmented suffix-fold materialises the full
``(S, E)`` sorted-gradient array in HBM once per fold pass —
``fold_passes`` is ``ceil(log2(max_run))``, and one heavy-hitter id
appearing in most of an 8192-row batch drives it to ~13, i.e. ~13
read+write round trips of the 213k x 16 f32 slot array (~220 MB of HBM
traffic per step at bench shape) for what is arithmetically a handful
of masked adds per element.

This kernel runs ALL fold passes on a VMEM tile: HBM traffic drops to
one read + one write of ``(S, E)`` regardless of ``fold_passes``
(~2/13ths of the unfused fold's traffic at the bench shape — the
analytic accounting ``bench.py::bench_kernels`` reports).  Correctness
across tile boundaries uses a halo: the fold only propagates values
from HIGHER to LOWER sorted positions over distances < ``2^fold_passes``,
so with ``block_n >= 2^fold_passes`` a tile's fully-folded rows depend
on at most the next tile — each grid step loads its own block plus the
following one (the input is padded by one zero block with sentinel id
-1, which can never extend a run: real ids are >= 0).

The fold expression is element-identical to ``emb_grad._folded_ext``
(same masked shift-add tree), so the fused path is BIT-exact with the
XLA routed gradient — asserted in interpret mode by the
``tests/test_kernels.py`` parity matrix.  The surrounding stages stay
XLA: the permutation gather and the ``pos_map`` placement gather are
single streaming passes XLA already lowers well.

Registered as the ``pallas`` backend of registry op
``routed_table_grad`` (gather placement, ``fold_passes >= 1``);
``EmbGradRoute.resolve_apply`` picks it up on TPU automatically.
"""

from __future__ import annotations

import functools

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils.padding import require_block_rows

__all__ = ["fold_block_n", "fold_runs_fused",
           "routed_table_grad_gather_fused", "routed_apply_fused"]

#: fold tiles: smallest block worth a grid step; the VMEM footprint is
#: 2 blocks of (block_n, E) f32 + 2 id blocks — tiny for any E <= 128.
_MIN_BLOCK = 256
_MAX_BLOCK = 8192


def fold_block_n(S: int, fold_passes: int) -> Optional[int]:
    """Smallest viable power-of-two block for a sorted axis of ``S``
    slots: ``>= 2^fold_passes`` (the halo argument above), ``>= 256``,
    dividing ``S``.  None when no block ``<= 8192`` works — the caller
    falls back to the XLA fold."""
    bn = max(_MIN_BLOCK, 1 << max(fold_passes, 0))
    while bn <= _MAX_BLOCK:
        if S % bn == 0:
            return bn
        bn <<= 1
    return None


def _fold_kernel(fold_passes: int, block_n: int):
    def kern(g_ref, g_next_ref, id_ref, id_next_ref, out_ref):
        g = jnp.concatenate([g_ref[:], g_next_ref[:]], axis=0)  # (2bn, E)
        ids = jnp.concatenate([id_ref[:], id_next_ref[:]])      # (2bn,)
        offs = 1
        for _ in range(fold_passes):
            # element-identical to emb_grad._folded_ext's pass: add the
            # row offs below iff it continues this row's run
            same = jnp.concatenate(
                [ids[offs:] == ids[:-offs],
                 jnp.zeros((offs,), bool)])
            shifted = jnp.concatenate(
                [g[offs:], jnp.zeros((offs, g.shape[1]), g.dtype)], axis=0)
            g = g + jnp.where(same[:, None], shifted, 0.0)
            offs *= 2
        # rows [0, bn) saw every in-run contribution within 2^fold_passes
        # - 1 <= 2bn - bn rows of lookahead — exact; the halo rows are
        # the next grid step's problem
        out_ref[:] = g[:block_n]

    return kern


@functools.partial(jax.jit,
                   static_argnames=("fold_passes", "block_n", "interpret"))
def fold_runs_fused(g_sorted: jnp.ndarray, sorted_ids: jnp.ndarray, *,
                    fold_passes: int, block_n: int,
                    interpret: bool = False) -> jnp.ndarray:
    """All ``fold_passes`` segmented-fold passes of ``(S, E)`` sorted
    gradient rows in one Mosaic pass (run starts end up holding full run
    sums, exactly as ``emb_grad._folded_ext`` computes them — minus its
    appended zero row, which the caller re-appends)."""
    squeeze = g_sorted.ndim == 1
    if squeeze:
        g_sorted = g_sorted[:, None]
    S, E = g_sorted.shape
    require_block_rows(S, block_n, op="fold_runs_fused")
    if (1 << fold_passes) > block_n:
        raise ValueError(
            f"fold_runs_fused: 2^fold_passes={1 << fold_passes} exceeds "
            f"block_n={block_n} — a run could span more than the one-block "
            "halo; use fold_block_n to size the block")
    # one zero pad block with sentinel id -1: real ids are >= 0, so no
    # run extends into the pad and the last tile's halo reads are inert
    g_pad = jnp.concatenate(
        [g_sorted, jnp.zeros((block_n, E), g_sorted.dtype)], axis=0)
    id_pad = jnp.concatenate(
        [sorted_ids.astype(jnp.int32),
         jnp.full((block_n,), -1, jnp.int32)])

    out = pl.pallas_call(
        _fold_kernel(fold_passes, block_n),
        grid=(S // block_n,),
        in_specs=[
            pl.BlockSpec((block_n, E), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n, E), lambda i: (i + 1, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n,), lambda i: (i,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n,), lambda i: (i + 1,),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_n, E), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((S, E), g_sorted.dtype),
        interpret=interpret,
    )(g_pad, g_pad, id_pad, id_pad)
    return out[:, 0] if squeeze else out


def routed_table_grad_gather_fused(g_flat: jnp.ndarray, order: jnp.ndarray,
                                   sorted_ids: jnp.ndarray,
                                   pos_map: jnp.ndarray, *,
                                   fold_passes: int, block_n: int,
                                   interpret: bool = False) -> jnp.ndarray:
    """Gather-placement routed table gradient with the fused fold:
    XLA permutation gather -> one Mosaic fold pass -> XLA placement
    gather.  Bit-exact with ``emb_grad.routed_table_grad_gather``."""
    squeeze = g_flat.ndim == 1
    g2 = g_flat[:, None] if squeeze else g_flat
    g = jnp.take(g2, order, axis=0, unique_indices=True)
    if fold_passes:
        g = fold_runs_fused(g, sorted_ids, fold_passes=fold_passes,
                            block_n=block_n, interpret=interpret)
    g_ext = jnp.concatenate(
        [g, jnp.zeros((1, g.shape[1]), g.dtype)], axis=0)
    out = jnp.take(g_ext, pos_map, axis=0)
    return out[:, 0] if squeeze else out


def routed_apply_fused(route, g_flat, *step_arrays, interpret: bool = False):
    """``pallas`` backend of registry op ``routed_table_grad`` (gather
    placement only — the supports predicate gates)."""
    order, sid, pos_map = step_arrays
    bn = fold_block_n(int(order.shape[0]), route.fold_passes)
    return routed_table_grad_gather_fused(
        g_flat, order, sid, pos_map, fold_passes=route.fold_passes,
        block_n=bn, interpret=interpret)


def _fused_route_supported(sig: tuple) -> bool:
    """sig = (placement, fold_passes, slots_per_step) from
    ``EmbGradRoute.kernel_sig``.  fold_passes == 0 has nothing to fuse
    (the XLA path is already gather -> gather)."""
    if len(sig) != 3:
        return False
    placement, fold_passes, slots = sig
    return (placement == "gather" and fold_passes >= 1
            and fold_block_n(int(slots), int(fold_passes)) is not None)


def _register() -> None:
    from ..kernels.registry import register_kernel, tpu_only

    register_kernel("routed_table_grad", "pallas", routed_apply_fused,
                    priority=20, supports=_fused_route_supported,
                    available=tpu_only)


_register()
