"""Int8 serving backends for the hot scoring ops (ISSUE 18).

Each op here is the "int8" registry backend of a stage-convention
serving kernel whose "xla" backend lives next to its model
(``linear_margins`` / ``kmeans_assign`` / ``widedeep_scores``).  The
contract is weight-only quantization with the f32 expression kept
bit-for-bit: params arrive as the ``{"q": int8, "s": f32}`` pytrees
produced by :func:`flink_ml_tpu.kernels.quantize.quantize_stage_params`,
dequantize in-program (one exact cast + one f32 multiply), then run the
SAME margin/assign/score expression as the f32 kernel — so the only
divergence from f32 is the quantization error the parity matrix's
accuracy-envelope harnesses gate (rank/decision agreement, not bitwise).

Tables gather-then-dequantize (codes gathered as int8, each row scaled
by its own per-row scale), never dequantize-then-gather: the f32 table
must not materialize, on-chip residency being the entire point — the
same order the ``EmbeddingRowCache`` int8 pools use, so cached and
uncached serving produce identical bits from identical codes.

These entries register with an ``available`` gate that always says no:
auto-pick must NEVER select them, because they require the quantized
param pytree only ``make_servable(..., precision="int8")`` builds.  A
forced ``lookup(op, backend="int8")`` — which bypasses availability by
contract — is the one route in, and the servable bind path is the one
caller.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["int8_linear_margins", "int8_kmeans_assign",
           "int8_widedeep_scores"]


def int8_linear_margins(static, params, cols):
    """``linear_margins`` on dequantized weights — expression-identical
    to ``_linear_chain_kernel`` after the one multiply that rebuilds
    ``w`` (per-tensor scale for vector ``w``, per-class for multiclass);
    ``b`` is f32 passthrough (intercepts never quantize)."""
    from ..api.chain import as_matrix
    from ..kernels.quantize import dequantize
    from ..models.common.linear import _stable_margins

    (fcol, mcol) = static
    X = as_matrix(cols[fcol])
    qw = params["w"]
    w = dequantize(qw["q"], qw["s"],
                   None if qw["q"].ndim == 1 else 1)
    return {mcol: _stable_margins(X.astype(jnp.float32), w, params["b"])}


def int8_kmeans_assign(static, params, cols):
    """``kmeans_assign`` on dequantized centroids (per-centroid-row
    scales) — same pairwise/argmin expression as
    ``_kmeans_chain_kernel``; the measure singleton rides the
    plan-static tuple exactly as in the f32 plan."""
    from ..api.chain import as_matrix
    from ..kernels.quantize import dequantize

    (fcol, acol, measure) = static
    pts = as_matrix(cols[fcol])
    centroids = dequantize(params["centroids"]["q"],
                           params["centroids"]["s"], 0)
    dists = measure.pairwise(pts.astype(jnp.float32), centroids)
    return {acol: jnp.argmin(dists, axis=1)}


def int8_widedeep_scores(static, params, cols):
    """``widedeep_scores`` with int8 tables and mlp matrices.  The
    ``wide_cat``/``emb`` gathers run on the int8 codes and dequantize
    the GATHERED rows only; the dense tower dequantizes its (small)
    matrices in-program.  Biases, ``wide_b`` and the id ``offsets``
    are exact passthrough."""
    from ..kernels.quantize import (
        dequantize,
        dequantize_rows,
        dequantize_widedeep_rest,
    )
    from ..models.recommendation.widedeep import forward_from_rows

    (dcol, ccol, scol) = static
    qnet = params["net"]
    dense = cols[dcol].astype(jnp.float32)
    cat = cols[ccol] + params["offsets"][None, :]
    wide_rows = dequantize(qnet["wide_cat"]["q"][cat],
                           qnet["wide_cat"]["s"])
    emb_rows = dequantize_rows(qnet["emb"]["q"][cat],
                               qnet["emb"]["s"][cat])
    scores = jax.nn.sigmoid(forward_from_rows(
        dequantize_widedeep_rest(qnet), dense, wide_rows, emb_rows))
    return {scol: scores}


def _quantized_params_only() -> bool:
    """Availability gate that always refuses: int8 entries consume the
    quantized param pytree only the servable bind path builds, so
    auto-pick (which would hand them the f32 params) must never see
    them.  Forced ``lookup(op, backend="int8")`` bypasses this by the
    registry's own contract — that asymmetry IS the admission path."""
    return False


def _register_int8_kernels() -> None:
    from ..kernels.registry import register_kernel

    register_kernel("linear_margins", "int8", int8_linear_margins,
                    convention="stage", available=_quantized_params_only)
    register_kernel("kmeans_assign", "int8", int8_kmeans_assign,
                    convention="stage", available=_quantized_params_only)
    register_kernel("widedeep_scores", "int8", int8_widedeep_scores,
                    convention="stage", available=_quantized_params_only)


_register_int8_kernels()
