from .emb_grad import (  # noqa: F401
    EmbGradRoute,
    emb_grad_route,
    routed_table_grad,
    routed_table_grad_gather,
)
from .ell_scatter import (  # noqa: F401
    EllLayout,
    ell_layout,
    ell_layout_device,
    ell_scatter_apply,
)
from .kmeans_pallas import (  # noqa: F401
    kmeans_assign_reduce,
    kmeans_update_stats,
    pad_correction,
    pick_block_n,
    supported,
    update_stats_sharded,
)
