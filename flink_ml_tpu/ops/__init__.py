from .emb_grad import (  # noqa: F401
    EmbGradRoute,
    emb_grad_route,
    routed_table_grad,
    routed_table_grad_gather,
)
from .emb_grad_pallas import (  # noqa: F401
    fold_runs_fused,
    routed_table_grad_gather_fused,
)
from . import int8_serving  # noqa: F401  (registers the "int8" backends)
from .ell_scatter import (  # noqa: F401
    EllLayout,
    ell_layout,
    ell_layout_device,
    ell_scatter_apply,
)
from . import retrieve_pallas  # noqa: F401  (the "pallas" retrieve backend)
from .kmeans_pallas import (  # noqa: F401
    kmeans_assign_reduce,
    kmeans_update_stats,
    kmeans_workset_update,
    pad_correction,
    pick_block_n,
    pick_block_n_workset,
    supported,
    update_stats_sharded,
)
