from .kmeans_pallas import (  # noqa: F401
    kmeans_assign_reduce,
    kmeans_update_stats,
)
