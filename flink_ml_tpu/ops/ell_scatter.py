"""ELL-format scatter-add — the Pallas hot path behind the mixed-layout
linear trainers.

Problem: one SGD step on the Criteo-shaped mixed layout must apply
``w[cat[b,j]] += -lr * r[b]`` for ~1M random (slot -> weight) pairs per
batch.  XLA's scatter on TPU issues one random HBM read-modify-write per
slot (~6 ms per 850k slots measured on v5e — the whole step budget), and
a sort at runtime costs more than the scatter.  But the trainers replay
the SAME epoch tensor every epoch (``models/common/sgd.py`` builds it
once), so the slot->row routing is **static**: we pay one host/device
sort per fit and turn every training step's scatter into dense,
vectorized VMEM work.

Layout (built once per step by :func:`ell_layout`): flatten the
``(batch, nnz)`` categorical indices, sort by index, and bucket by
weight-table row ``idx >> 7`` (the table viewed as ``(d/128, 128)``
lanes).  Each table row gets up to 128 slots (``src`` = which batch row
each slot charges, ``lo`` = the lane it hits, sorted ascending within
the row); rows with more slots spill to a small overflow list (heavy
hitters — e.g. a label-marker feature — land there).

The step then computes, per row, the per-lane update total
``delta[row, l] = sum_s upd[row, s] * [lo[row, s] == l]`` with NO random
writes: because ``lo`` is sorted within the row, the lane totals are
differences of the running cumulative sum of ``upd`` picked at static
positions::

    C    = cumsum(upd, lanes)          # 7 shifted adds, exact f32
    G    = C[P] * M                    # one lane-local take_along_axis
    delta = G - shift(G, 1 lane)       # static boundary differences

where ``P[row, l]`` = position of the last slot with ``lo <= l`` (static,
precomputed; clamped to 0 and masked by ``M`` when no such slot).  All
three stages are lane-local vector ops Mosaic executes at VPU rate
(~0.3 ms per 1M slots on v5e vs ~6 ms for the XLA scatter).  The kernel
result is bit-identical to a sorted-order scatter; it differs from
XLA's scatter only in f32 summation order.

The reference has no analog (its updates ride keyed network shuffles,
``flink-ml-lib/.../clustering/kmeans/KMeans.java:172-196``); this is the
TPU-native replacement for that reduction machinery at the per-element
scale the Criteo config (BASELINE.md) demands.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["EllLayout", "ell_layout", "ell_layout_device",
           "ell_scatter_apply", "supported", "ELL_WIDTH"]

ELL_WIDTH = 128          # slots per table row = one lane tile
#: Table rows per Mosaic grid step in the fused kernels.  8 measured
#: best in the r4 block sweep; the per-row one-hot transients are
#: block-size-independent, so this only trades grid overhead against
#: scheduling granularity.
_FUSED_BLOCK_ROWS = 8
_LANES = 128             # table view (d // 128, 128)


def supported(num_features: int) -> bool:
    """Kernel precondition: the weight table reshapes into at least 128
    whole 128-lane rows (``_pick_block_rows`` then always finds a valid
    power-of-two grid block, down to a single block of all rows)."""
    return num_features % _LANES == 0 and num_features // _LANES >= 128


def _pick_block_rows(rows: int) -> int:
    for br in (2048, 1024, 512, 256, 128):
        if rows % br == 0 and rows >= br:
            return br
    return rows


@dataclass
class EllLayout:
    """Static per-step routing for :func:`ell_scatter_apply`.

    All arrays are per-step stacks: leading dim = steps.

    Heavy hitters: an index occurring more than ``heavy_threshold`` times
    in a step (power-law categories — label markers, dominant tokens;
    real Criteo categorical frequencies are Zipfian) would flood a
    per-slot path, so ALL its slots leave the ELL grid for a dense count
    matrix: its update is ``-lr * (counts @ r)`` — one tiny matmul plus
    an H-element scatter instead of thousands of per-slot ops.
    """
    src: jnp.ndarray       # (steps, rows, 128) i32: batch row charged, or
                           #   ``batch`` (points at the zero pad of r_ext)
    pos: jnp.ndarray       # (steps, rows, 128) i32: clamped csum pick P
    mask: jnp.ndarray      # (steps, rows, 128) f32: 0 where P was empty
    ovf_idx: jnp.ndarray   # (steps, cap) i32: overflow weight indices (0 pad)
    ovf_src: jnp.ndarray   # (steps, cap) i32: overflow batch rows (batch pad)
    heavy_idx: jnp.ndarray  # (steps, H) i32: heavy indices (0 pad)
    heavy_cnt: jnp.ndarray  # (steps, H, batch): per-row counts (i16), or
                            #   per-row VALUE SUMS (f32) with `values`
                            #   (all-zero rows for padding entries)
    batch: int             # rows per batch (r vector length)
    num_features: int
    # generic (indices, values) sparse layout only (None for the mixed
    # implicit-1.0 layout):
    val: Optional[jnp.ndarray] = None      # (steps, rows, 128) f32
    ovf_val: Optional[jnp.ndarray] = None  # (steps, cap) f32
    # capacity bookkeeping: slots NEEDED per step, regardless of what
    # the static caps could hold.  Populated by every builder since r4
    # (the host builders additionally raise when a FORCED cap is
    # exceeded; the device builder only records, see assert_capacities)
    need_ovf: Optional[jnp.ndarray] = None    # (steps,) i32
    need_heavy: Optional[jnp.ndarray] = None  # (steps,) i32

    @property
    def steps(self) -> int:
        return self.src.shape[0]

    def assert_capacities(self) -> "EllLayout":
        """Fail loudly if the device builder dropped slots: any step whose
        required overflow/heavy slots exceed the static caps produced a
        silently-wrong layout (ADVICE r3).  One tiny device->host read."""
        if self.need_ovf is not None:
            cap = self.ovf_idx.shape[1]
            worst = int(jnp.max(self.need_ovf))
            if worst > cap:
                raise ValueError(
                    f"ELL overflow needs {worst} slots in some step > "
                    f"ovf_cap {cap}; gradients would silently drop slots "
                    "— raise ovf_cap")
        if self.need_heavy is not None:
            hcap = self.heavy_idx.shape[1]
            worst_h = int(jnp.max(self.need_heavy))
            if worst_h > hcap:
                raise ValueError(
                    f"ELL heavy path needs {worst_h} indices in some step "
                    f"> heavy_cap {hcap}; raise heavy_cap")
        return self

    def trim_overflow(self, margin: int = 2) -> "EllLayout":
        """Slice the overflow arrays down to the measured need (x
        ``margin``, rounded to 8).  The XLA overflow scatter's cost
        scales with the STATIC cap, not the real spill count — a
        generous 2^13 cap measured ~1.8 ms/step against a need of 180
        (r4 TPU_STEP_BREAKDOWN) — and every builder front-compacts the
        real entries, so slicing is exact.  No-op when the cap is
        already tight or the need is unknown."""
        if self.need_ovf is None:
            return self
        cap = max(8, int(np.asarray(self.need_ovf).max()) * margin)
        cap += (-cap) % 8
        if cap >= self.ovf_idx.shape[1]:
            return self
        return replace(
            self, ovf_idx=self.ovf_idx[:, :cap],
            ovf_src=self.ovf_src[:, :cap],
            ovf_val=None if self.ovf_val is None
            else self.ovf_val[:, :cap])


HEAVY_THRESHOLD = 512   # slots per index per step before the dense path


def _check_heavy_threshold(heavy_threshold: int) -> None:
    """A threshold below ELL_WIDTH would let a heavy run inflate the raw
    ``pos`` of kept same-row slots past their rank among kept slots, so
    their cumsum picks would read the zero pad — silently dropped
    updates.  With threshold >= ELL_WIDTH every slot after a heavy run
    has pos > 127 and routes to overflow, which is exact."""
    if heavy_threshold < ELL_WIDTH:
        raise ValueError(
            f"heavy_threshold must be >= ELL_WIDTH ({ELL_WIDTH}); "
            f"got {heavy_threshold}")


def _ell_one_step(flat: np.ndarray, batch: int, nnz: int, rows: int,
                  heavy_threshold: int,
                  values: "Optional[np.ndarray]" = None
                  ) -> Tuple[np.ndarray, ...]:
    """Host layout for one step's flattened indices (batch*nnz,).  With
    ``values`` (same flat shape), each slot carries a coefficient: the
    layout also emits the value arrays and the heavy matrix holds VALUE
    SUMS instead of counts (the (indices, values) sparse layout)."""
    b_of = np.repeat(np.arange(batch, dtype=np.int32), nnz)
    # sentinel indices (>= num_features, e.g. padding rows marked by the
    # streaming trainer) drop out of the layout entirely — a zero-pad
    # would fabricate an artificially heavy index 0
    in_range = flat < rows * _LANES
    if not in_range.all():
        flat = flat[in_range]
        b_of = b_of[in_range]
        if values is not None:
            values = values[in_range]
    order = np.argsort(flat, kind="stable")
    sidx = flat[order]
    ssrc = b_of[order]
    svals = values[order] if values is not None else None
    row = sidx >> 7
    lo = (sidx & 127).astype(np.int32)
    starts = np.searchsorted(row, np.arange(rows, dtype=np.int64))
    pos = np.arange(flat.size, dtype=np.int64) - starts[row]
    # heavy indices: the whole run leaves the per-slot paths (positions of
    # later same-row slots keep counting past them — a heavy row's other
    # slots simply overflow, a negligible cost next to the run itself)
    run_start = np.searchsorted(sidx, sidx, side="left")
    run_end = np.searchsorted(sidx, sidx, side="right")
    heavy_slot = (run_end - run_start) > heavy_threshold
    keep = (pos < ELL_WIDTH) & ~heavy_slot

    src = np.full((rows, ELL_WIDTH), batch, np.int32)
    src[row[keep], pos[keep]] = ssrc[keep]
    val = None
    if svals is not None:
        val = np.zeros((rows, ELL_WIDTH), np.float32)
        val[row[keep], pos[keep]] = svals[keep]
    hist = np.zeros((rows, 128), np.int64)
    np.add.at(hist, (row[keep], lo[keep]), 1)
    P = np.cumsum(hist, axis=1) - 1
    mask = (P >= 0).astype(np.float32)
    Pc = np.maximum(P, 0).astype(np.int32)

    spill = ~keep & ~heavy_slot
    ovf_idx = sidx[spill].astype(np.int32)
    ovf_src = ssrc[spill]
    ovf_val = svals[spill].astype(np.float32) if svals is not None else None

    h_idx = np.unique(sidx[heavy_slot]).astype(np.int32)
    h_cnt = np.zeros((h_idx.size, batch),
                     np.int16 if svals is None else np.float32)
    if h_idx.size:
        h_rank = np.searchsorted(h_idx, sidx[heavy_slot])
        np.add.at(h_cnt, (h_rank, ssrc[heavy_slot]),
                  1 if svals is None else svals[heavy_slot])
    return src, Pc, mask, ovf_idx, ovf_src, h_idx, h_cnt, val, ovf_val


_ELL_NATIVE = None
_ELL_NATIVE_TRIED = False


def _native_ell():
    """The C++ builder (native/ell_layout.cpp) or None (numpy fallback).
    ~1.2 us/slot numpy vs ~0.06 us/slot native — the layout build is the
    host hot path of fit() (32 s -> ~1.5 s at the default product shape)."""
    global _ELL_NATIVE, _ELL_NATIVE_TRIED
    if not _ELL_NATIVE_TRIED:
        _ELL_NATIVE_TRIED = True
        from ..utils.native_lib import load_native_lib

        _ELL_NATIVE = load_native_lib("ell_layout")
    return _ELL_NATIVE


def _ell_layout_native(lib, cat_indices: np.ndarray, num_features: int,
                       heavy_threshold: int,
                       values: "Optional[np.ndarray]",
                       pad_ovf_cap: Optional[int],
                       pad_heavy_cap: Optional[int]):
    """Native counting-sort build; semantics identical to the numpy path
    (heavy f32 value-sums may differ in summation order only)."""
    import ctypes

    steps, batch, nnz = cat_indices.shape
    rows = num_features // _LANES
    flat = np.ascontiguousarray(cat_indices, np.int32)
    with_values = values is not None
    vals = (np.ascontiguousarray(values, np.float32) if with_values
            else None)

    src = np.empty((steps, rows, ELL_WIDTH), np.int32)
    pos = np.empty((steps, rows, ELL_WIDTH), np.int32)
    mask = np.empty((steps, rows, ELL_WIDTH), np.float32)
    val = (np.empty((steps, rows, ELL_WIDTH), np.float32) if with_values
           else None)
    need_o = np.zeros((steps,), np.int32)
    need_h = np.zeros((steps,), np.int32)

    def run(ovf_cap: int, heavy_cap: int):
        ovf_idx = np.empty((steps, ovf_cap), np.int32)
        ovf_src = np.empty((steps, ovf_cap), np.int32)
        ovf_val = (np.empty((steps, ovf_cap), np.float32) if with_values
                   else None)
        heavy_idx = np.empty((steps, heavy_cap), np.int32)
        heavy_cnt = np.empty((steps, heavy_cap, batch),
                             np.float32 if with_values else np.int16)

        def ptr(a, typ):
            return (a.ctypes.data_as(ctypes.POINTER(typ))
                    if a is not None else None)

        rc = lib.ell_build(
            ptr(flat, ctypes.c_int32), ptr(vals, ctypes.c_float),
            ctypes.c_int64(steps), ctypes.c_int64(batch),
            ctypes.c_int64(nnz), ctypes.c_int64(rows),
            ctypes.c_int64(heavy_threshold),
            ctypes.c_int64(ovf_cap), ctypes.c_int64(heavy_cap),
            ptr(src, ctypes.c_int32), ptr(pos, ctypes.c_int32),
            ptr(mask, ctypes.c_float), ptr(val, ctypes.c_float),
            ptr(ovf_idx, ctypes.c_int32), ptr(ovf_src, ctypes.c_int32),
            ptr(ovf_val, ctypes.c_float), ptr(heavy_idx, ctypes.c_int32),
            heavy_cnt.ctypes.data_as(ctypes.c_void_p),
            ptr(need_o, ctypes.c_int32), ptr(need_h, ctypes.c_int32))
        return rc, ovf_idx, ovf_src, ovf_val, heavy_idx, heavy_cnt

    # first call: forced caps verbatim, else a generous guess; a capacity
    # miss reports exact needs and one retry lands it
    cap0 = pad_ovf_cap if pad_ovf_cap is not None else max(1024, batch)
    cap0 += (-cap0) % 8
    h0 = pad_heavy_cap if pad_heavy_cap is not None else 16
    rc, ovf_idx, ovf_src, ovf_val, heavy_idx, heavy_cnt = run(cap0, h0)
    need_ovf, need_heavy = int(need_o.max()), int(need_h.max())
    # forced-cap contract: compare against the UNROUNDED caps regardless
    # of rc — rounding cap0 up to a multiple of 8 must never absorb a
    # need the caller's exact cap would have rejected
    if pad_ovf_cap is not None and need_ovf > pad_ovf_cap:
        raise ValueError(
            f"overflow needs {need_ovf} slots > forced cap "
            f"{pad_ovf_cap}; raise the cap (streaming: ell_ovf_cap)")
    if pad_heavy_cap is not None and need_heavy > pad_heavy_cap:
        raise ValueError(
            f"{need_heavy} heavy indices > forced cap "
            f"{pad_heavy_cap}; raise the cap (streaming: "
            "ell_heavy_cap)")
    if rc:
        cap0 = max(cap0, need_ovf + (-need_ovf) % 8)
        h0 = max(h0, need_heavy)
        rc, ovf_idx, ovf_src, ovf_val, heavy_idx, heavy_cnt = run(cap0, h0)
        assert rc == 0, "native ell_build retry with exact caps failed"

    # shrink to the numpy builder's exact cap arithmetic
    cap = pad_ovf_cap if pad_ovf_cap is not None else max(8, need_ovf)
    cap += (-cap) % 8
    H = pad_heavy_cap if pad_heavy_cap is not None else max(1, need_heavy)
    return (src, pos, mask,
            np.ascontiguousarray(ovf_idx[:, :cap]),
            np.ascontiguousarray(ovf_src[:, :cap]),
            None if not with_values
            else np.ascontiguousarray(ovf_val[:, :cap]),
            np.ascontiguousarray(heavy_idx[:, :H]),
            np.ascontiguousarray(heavy_cnt[:, :H]),
            val, need_o.copy(), need_h.copy())


def ell_layout(cat_indices: np.ndarray, num_features: int,
               heavy_threshold: int = HEAVY_THRESHOLD,
               values: "Optional[np.ndarray]" = None,
               pad_ovf_cap: Optional[int] = None,
               pad_heavy_cap: Optional[int] = None,
               device: bool = True) -> EllLayout:
    """Build the static routing from a ``(steps, batch, nnz)`` int epoch
    tensor of categorical indices (host numpy; one-time per fit).  Pass
    ``values`` (same shape, float) for the generic sparse layout —
    slots then scatter ``value * r`` instead of ``r``.

    ``pad_ovf_cap`` / ``pad_heavy_cap`` force EXACT capacities (for
    streaming callers whose every batch must share one compiled shape);
    a batch exceeding a forced cap raises rather than dropping slots.
    ``device=False`` keeps every array host numpy (streaming callers
    hand the layout to a prefetch pipeline that does the one
    device_put; a device round-trip per batch would defeat the
    overlap).  Indices >= num_features are sentinels and drop out of
    the layout (padding rows)."""
    _check_heavy_threshold(heavy_threshold)
    steps, batch, nnz = cat_indices.shape
    rows = num_features // _LANES
    wrap = jnp.asarray if device else np.asarray
    lib = _native_ell()
    if lib is not None:
        (n_src, n_pos, n_mask, n_oi, n_os, n_ov, n_hi, n_hc, n_val,
         need_o, need_h) = _ell_layout_native(
            lib, np.asarray(cat_indices), num_features, heavy_threshold,
            values, pad_ovf_cap, pad_heavy_cap)
        return EllLayout(
            src=wrap(n_src), pos=wrap(n_pos), mask=wrap(n_mask),
            ovf_idx=wrap(n_oi), ovf_src=wrap(n_os),
            heavy_idx=wrap(n_hi), heavy_cnt=wrap(n_hc),
            val=None if n_val is None else wrap(n_val),
            ovf_val=None if n_ov is None else wrap(n_ov),
            batch=batch, num_features=num_features,
            need_ovf=need_o, need_heavy=need_h)
    outs = [_ell_one_step(
        np.asarray(cat_indices[s], np.int64).reshape(-1), batch, nnz, rows,
        heavy_threshold,
        None if values is None
        else np.asarray(values[s], np.float32).reshape(-1))
        for s in range(steps)]
    need_ovf = max(o[3].size for o in outs)
    need_heavy = max(o[5].size for o in outs)
    if pad_ovf_cap is not None and need_ovf > pad_ovf_cap:
        raise ValueError(
            f"overflow needs {need_ovf} slots > forced cap {pad_ovf_cap}; "
            "raise the cap (streaming: ell_ovf_cap)")
    if pad_heavy_cap is not None and need_heavy > pad_heavy_cap:
        raise ValueError(
            f"{need_heavy} heavy indices > forced cap {pad_heavy_cap}; "
            "raise the cap (streaming: ell_heavy_cap)")
    cap = pad_ovf_cap if pad_ovf_cap is not None else max(8, need_ovf)
    cap += (-cap) % 8
    ovf_idx = np.zeros((steps, cap), np.int32)
    ovf_src = np.full((steps, cap), batch, np.int32)
    H = (pad_heavy_cap if pad_heavy_cap is not None
         else max(1, need_heavy))
    heavy_idx = np.zeros((steps, H), np.int32)
    heavy_cnt = np.zeros((steps, H, batch),
                         np.int16 if values is None else np.float32)
    val = ovf_val = None
    if values is not None:
        val = np.zeros((steps, rows, ELL_WIDTH), np.float32)
        ovf_val = np.zeros((steps, cap), np.float32)
    for s, o in enumerate(outs):
        ovf_idx[s, :o[3].size] = o[3]
        ovf_src[s, :o[4].size] = o[4]
        heavy_idx[s, :o[5].size] = o[5]
        heavy_cnt[s, :o[6].shape[0]] = o[6]
        if values is not None:
            val[s] = o[7]
            ovf_val[s, :o[8].size] = o[8]
    return EllLayout(
        src=wrap(np.stack([o[0] for o in outs])),
        pos=wrap(np.stack([o[1] for o in outs])),
        mask=wrap(np.stack([o[2] for o in outs])),
        ovf_idx=wrap(ovf_idx), ovf_src=wrap(ovf_src),
        heavy_idx=wrap(heavy_idx), heavy_cnt=wrap(heavy_cnt),
        val=None if val is None else wrap(val),
        ovf_val=None if ovf_val is None else wrap(ovf_val),
        batch=batch, num_features=num_features,
        need_ovf=np.asarray([o[3].size for o in outs], np.int32),
        need_heavy=np.asarray([o[5].size for o in outs], np.int32))


def ell_layout_device(cat_indices: jnp.ndarray, num_features: int,
                      ovf_cap: int = 1 << 16, heavy_cap: int = 8,
                      heavy_threshold: int = HEAVY_THRESHOLD,
                      values: Optional[jnp.ndarray] = None) -> EllLayout:
    """Device-side layout builder (jit, vmapped over steps) for callers
    whose epoch tensor already lives in HBM (e.g. the benchmark, where
    host round-trips are prohibitively slow through a tunnel).  Overflow
    and heavy capacities are static; slots beyond them are DROPPED from
    the layout, so callers must either size ``ovf_cap``/``heavy_cap``
    generously or call :meth:`EllLayout.assert_capacities` on the result
    (the returned ``need_ovf``/``need_heavy`` record what each step
    actually required)."""
    _check_heavy_threshold(heavy_threshold)
    steps, batch, nnz = cat_indices.shape
    rows = num_features // _LANES
    b_of = jnp.repeat(jnp.arange(batch, dtype=jnp.int32), nnz)

    with_values = values is not None

    @functools.partial(jax.jit, static_argnums=())
    @jax.vmap
    def build(flat, fvals):
        order = jnp.argsort(flat)
        sidx = flat[order]
        ssrc = b_of[order]
        # implicit-1.0 callers skip all value plumbing at trace time
        svals = fvals[order] if with_values else None
        row = sidx >> 7
        lo = (sidx & 127).astype(jnp.int32)
        starts = jnp.searchsorted(row, jnp.arange(rows, dtype=sidx.dtype))
        pos = jnp.arange(flat.size, dtype=jnp.int32) - starts[row]
        run_start = jnp.searchsorted(sidx, sidx, side="left")
        run_end = jnp.searchsorted(sidx, sidx, side="right")
        heavy_slot = (run_end - run_start) > heavy_threshold
        keep = (pos < ELL_WIDTH) & ~heavy_slot
        src = jnp.full((rows, ELL_WIDTH), batch, jnp.int32)
        # overflow slots target column ELL_WIDTH, which mode="drop"
        # discards (an in-bounds dummy would race the real slot there)
        src = src.at[row, jnp.where(keep, pos, ELL_WIDTH)].set(
            ssrc, mode="drop")
        val = (jnp.zeros((rows, ELL_WIDTH), jnp.float32).at[
            row, jnp.where(keep, pos, ELL_WIDTH)].set(svals, mode="drop")
            if with_values else jnp.zeros((1, 1), jnp.float32))
        hist = jnp.zeros((rows, 128), jnp.int32).at[row, lo].add(
            keep.astype(jnp.int32), mode="drop")
        P = jnp.cumsum(hist, axis=1) - 1
        mask = (P >= 0).astype(jnp.float32)
        Pc = jnp.maximum(P, 0).astype(jnp.int32)
        spill = ~keep & ~heavy_slot
        ovf_slot = jnp.cumsum(spill.astype(jnp.int32)) - 1
        ovf_i = jnp.zeros((ovf_cap,), jnp.int32).at[
            jnp.where(spill, ovf_slot, ovf_cap)].set(
            jnp.where(spill, sidx.astype(jnp.int32), 0), mode="drop")
        ovf_s = jnp.full((ovf_cap,), batch, jnp.int32).at[
            jnp.where(spill, ovf_slot, ovf_cap)].set(
            jnp.where(spill, ssrc, batch), mode="drop")
        ovf_v = (jnp.zeros((ovf_cap,), jnp.float32).at[
            jnp.where(spill, ovf_slot, ovf_cap)].set(
            jnp.where(spill, svals, 0.0), mode="drop")
            if with_values else jnp.zeros((1,), jnp.float32))
        # heavy runs: rank = number of heavy runs starting at or before
        # this slot - 1 (first-occurrence compaction)
        is_first = jnp.arange(flat.size, dtype=jnp.int32) == run_start
        h_rank = jnp.cumsum((is_first & heavy_slot).astype(jnp.int32)) - 1
        h_i = jnp.zeros((heavy_cap,), jnp.int32).at[
            jnp.where(is_first & heavy_slot, h_rank, heavy_cap)].set(
            jnp.where(heavy_slot, sidx.astype(jnp.int32), 0), mode="drop")
        if with_values:
            h_c = jnp.zeros((heavy_cap, batch), jnp.float32).at[
                jnp.where(heavy_slot, h_rank, heavy_cap), ssrc].add(
                svals, mode="drop")
        else:
            h_c = jnp.zeros((heavy_cap, batch), jnp.int16).at[
                jnp.where(heavy_slot, h_rank, heavy_cap), ssrc].add(
                1, mode="drop")
        n_ovf = jnp.sum(spill.astype(jnp.int32))
        n_heavy = jnp.sum((is_first & heavy_slot).astype(jnp.int32))
        return src, Pc, mask, ovf_i, ovf_s, h_i, h_c, val, ovf_v, \
            n_ovf, n_heavy

    flat_steps = cat_indices.reshape(steps, -1).astype(jnp.int32)
    fvals = (values.reshape(steps, -1).astype(jnp.float32) if with_values
             else jnp.zeros((steps, 1), jnp.float32))  # unused placeholder
    src, Pc, mask, ovf_i, ovf_s, h_i, h_c, val, ovf_v, n_ovf, n_heavy = \
        build(flat_steps, fvals)
    return EllLayout(src=src, pos=Pc, mask=mask, ovf_idx=ovf_i,
                     ovf_src=ovf_s, heavy_idx=h_i, heavy_cnt=h_c,
                     val=val if with_values else None,
                     ovf_val=ovf_v if with_values else None,
                     batch=batch, num_features=num_features,
                     need_ovf=n_ovf, need_heavy=n_heavy)


def _csum_pick_tail(x, p, m, w, block_rows: int):
    """THE scatter tail shared by both Mosaic kernels: exact inclusive
    cumsum along lanes (7 shifted adds — fixed f32 order, deterministic,
    no MXU rounding), static-position pick, boundary difference."""
    for k in (1, 2, 4, 8, 16, 32, 64):
        x = x + jnp.concatenate(
            [jnp.zeros((block_rows, k), jnp.float32), x[:, :-k]],
            axis=1)
    G = jnp.take_along_axis(x, p, axis=1) * m
    Gs = jnp.concatenate(
        [jnp.zeros((block_rows, 1), jnp.float32), G[:, :-1]], axis=1)
    return w + G - Gs


def _kernel(block_rows: int):
    def kern(u_ref, p_ref, m_ref, w_ref, out_ref):
        out_ref[:] = _csum_pick_tail(u_ref[:], p_ref[:], m_ref[:],
                                     w_ref[:], block_rows)
    return kern


@functools.partial(jax.jit, static_argnames=("interpret",))
def ell_scatter_apply(w: jnp.ndarray, upd: jnp.ndarray, pos: jnp.ndarray,
                      mask: jnp.ndarray, *, interpret: bool = False
                      ) -> jnp.ndarray:
    """``w + scatter(upd)`` where ``upd (rows, 128)`` holds per-slot update
    values in ELL order and ``pos``/``mask`` are the static csum picks from
    :func:`ell_layout`.  ``w`` is flat ``(rows*128,)``."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = upd.shape[0]
    br = _pick_block_rows(rows)
    w2 = w.reshape(rows, _LANES)
    out = pl.pallas_call(
        _kernel(br), grid=(rows // br,),
        in_specs=[pl.BlockSpec((br, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)] * 4,
        out_specs=pl.BlockSpec((br, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
        interpret=interpret,
    )(upd, pos, mask, w2)
    return out.reshape(-1)


def _fused_kernel(block_rows: int, r_rows: int, precision,
                  with_val: bool):
    """Compute the u-gather ``u = -lr * r_ext[src]`` INSIDE the kernel
    via a one-hot MXU matmul + lane-local pick, then run the csum/pick/
    diff scatter.  Rationale: the XLA blocked gather is DMA-transaction-
    bound (~1.7-2.5 ns/slot = ~2-2.5 ms/step at 1M slots — confirmed the
    dominant step cost by the r4 ablation: dropping it moved the full
    step 7.79 -> 2.17 ms), while r_ext is tiny (fits VMEM): per 128-slot
    row the one-hot contraction against the (r_rows, 128) view of r_ext
    costs ~33 kMAC/slot — MXU work instead of the transaction stall
    (measured: full step 6.53 ms fused vs 8.92 XLA-oracle, r4 ablation).
    ``with_val`` multiplies each slot by a per-slot value (the generic
    sparse layout's explicit feature values)."""
    def kern(src_ref, p_ref, m_ref, r2dt_ref, w_ref, *rest):
        (val_ref, out_ref) = rest if with_val else (None, rest[0])
        src = src_ref[:]                       # (block_rows, 128) i32
        r2dt = r2dt_ref[:]                     # (128, r_rows) f32: the
        hi = src // 128                        #   PRE-SCALED -lr*r_ext,
        lo = src % 128                         #   lane-major
        # everything below is built in its CONSUMED orientation — no
        # transposes or (128, 1) concats anywhere (per-iteration Mosaic
        # relayouts measured ~10x the contraction's MXU floor, r4
        # TPU_STEP_BREAKDOWN)
        lane0 = jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0)
        rows_out = []
        for r in range(block_rows):
            # OHT[j, s] = [hi[r, s] == j] over the r_ext rows
            oht = (jax.lax.broadcasted_iota(jnp.int32, (r_rows, 128), 0)
                   == hi[r][None, :]).astype(jnp.float32)
            # G1T[l, s] = r_ext2d[l, hi[r, s]]
            g1t = jnp.dot(r2dt, oht, preferred_element_type=jnp.float32,
                          precision=precision)
            # pick each slot's lane via masked column-sum (Mosaic's
            # gather lowering rejects (128, 1)-index take_along_axis)
            pick = jnp.where(lane0 == lo[r][None, :], g1t, 0.0)
            rows_out.append(jnp.sum(pick, axis=0, keepdims=True))
        u = jnp.concatenate(rows_out, axis=0)  # (block_rows, 128)
        if with_val:
            u = u * val_ref[:]
        out_ref[:] = _csum_pick_tail(u, p_ref[:], m_ref[:], w_ref[:],
                                     block_rows)
    return kern


@functools.partial(jax.jit, static_argnames=("interpret", "precision"))
def ell_scatter_apply_fused(w: jnp.ndarray, r_ext: jnp.ndarray,
                            src: jnp.ndarray, pos: jnp.ndarray,
                            mask: jnp.ndarray, *, lr,
                            val: Optional[jnp.ndarray] = None,
                            precision: str = "default",
                            interpret: bool = False) -> jnp.ndarray:
    """``w + scatter(-lr * val * r_ext[src])`` with the gather fused into
    the Mosaic kernel (see :func:`_fused_kernel`).  ``r_ext`` length must
    be a multiple of 128 (:func:`sgd._extended_r` pads to 256) and the
    table must have a multiple of 8 rows (every ``supported()`` power-of
    -two size does).  ``lr`` is traced — it scales ``r_ext`` OUTSIDE the
    kernel, so learning-rate sweeps share one compiled executable.
    Small block (8 rows) keeps the per-block one-hot tile in VMEM.
    ``val`` is an optional per-slot ``(rows, 128)`` multiplier (the
    explicit feature values of the generic sparse layout); None means
    the mixed layout's implicit 1.0.

    ``precision`` sets the one-hot contraction's MXU mode: ``"default"``
    (single bf16 pass — gathered values carry ~2^-8 relative truncation,
    harmless gradient noise for SGD) or ``"highest"`` (multi-pass f32 —
    exact parity with the XLA gather, ~3x the contraction's MXU cost)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = src.shape[0]
    if r_ext.shape[0] % 128:
        raise ValueError(
            f"fused kernel needs len(r_ext) % 128 == 0, got "
            f"{r_ext.shape[0]}; pad with sgd._extended_r")
    r_rows = r_ext.shape[0] // 128
    br = _FUSED_BLOCK_ROWS
    if rows % br:
        raise ValueError(
            f"fused kernel needs rows % {br} == 0, got {rows}; use "
            "ell_scatter_apply")
    # lane-major view of the scaled residuals, transposed ONCE here so
    # the kernel's per-row contraction consumes it without relayout
    r2dt = ((-lr) * r_ext).reshape(r_rows, 128).T
    w2 = w.reshape(rows, _LANES)
    block = pl.BlockSpec((br, 128), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    operands = [src, pos, mask, r2dt, w2]
    in_specs = [block, block, block,
                pl.BlockSpec((128, r_rows), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
                block]
    if val is not None:
        operands.append(val)
        in_specs.append(block)
    out = pl.pallas_call(
        _fused_kernel(br, r_rows, precision, val is not None),
        grid=(rows // br,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((br, 128), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, _LANES), jnp.float32),
        interpret=interpret,
    )(*operands)
    return out.reshape(-1)


def ell_scatter_apply_xla(w: jnp.ndarray, upd: jnp.ndarray,
                          pos: jnp.ndarray, mask: jnp.ndarray
                          ) -> jnp.ndarray:
    """Pure-XLA twin of :func:`ell_scatter_apply` (same csum/pick math) for
    backends without Mosaic.  Used by CPU tests and as the correctness
    oracle."""
    rows = upd.shape[0]
    x = jnp.cumsum(upd, axis=1)
    G = jnp.take_along_axis(x, pos, axis=1) * mask
    Gs = jnp.concatenate(
        [jnp.zeros((rows, 1), jnp.float32), G[:, :-1]], axis=1)
    return (w.reshape(rows, _LANES) + G - Gs).reshape(-1)


# ---------------------------------------------------------------------------
# Forward (margin) path over the SAME layout: the r4 TPU ablation showed
# the ``w[cat]`` forward gather costs ~3.4 ms/step at bench shape — the
# other transaction-bound half of the mixed step.  Every slot's table
# position is already encoded in pos/mask (slots sorted by lane within a
# row; ``pos[l]`` = last slot with lane <= l, mask = lane non-empty), so
# the margin contribution of the in-grid slots is computable with zero
# extra layout state: recover each slot's own lane as
# ``lane(s) = #{l : pos_eff[l] < s}`` (pos_eff = pos restored to -1 on
# masked lanes), pick ``w`` at that lane (a full-shape lane-local
# take_along_axis — the Mosaic-supported gather form), and accumulate
# per-sample sums with two one-hot MXU contractions into an extended
# margin table (pad slots carry ``src == batch`` and land in the
# discarded pad region, exactly like the backward path's r_ext pad).
# ---------------------------------------------------------------------------

def _slot_lanes_xla(pos: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Per-slot lane recovery, XLA form: vmapped searchsorted over rows.
    ``pos_eff`` is nondecreasing per row, so ``#{l : pos_eff[l] < s}`` is
    a left-insertion point.  Clamped to 127: pad slots (beyond every
    boundary) pick an arbitrary real lane and are discarded via their
    ``src == batch`` routing."""
    pos_eff = pos + mask.astype(jnp.int32) - 1
    s_iota = jnp.arange(ELL_WIDTH, dtype=jnp.int32)
    lanes = jax.vmap(
        lambda p: jnp.searchsorted(p, s_iota, side="left"))(pos_eff)
    return jnp.minimum(lanes, ELL_WIDTH - 1).astype(jnp.int32)


def ell_margin_xla(w: jnp.ndarray, src: jnp.ndarray, pos: jnp.ndarray,
                   mask: jnp.ndarray, m_len: int,
                   val: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """In-grid margin contributions, scattered to an ``(m_len,)`` extended
    per-sample table (``m_len`` = the :func:`sgd._extended_r` length;
    callers slice ``[:batch]``).  Pure-XLA twin of
    :func:`ell_margin_fused` for CPU backends and as the oracle."""
    lanes = _slot_lanes_xla(pos, mask)
    g = jnp.take_along_axis(w.reshape(-1, _LANES), lanes, axis=1)
    if val is not None:
        g = g * val
    return jnp.zeros((m_len,), jnp.float32).at[src.reshape(-1)].add(
        g.reshape(-1), mode="drop")


def _margin_kernel(block_rows: int, m_rows: int, precision,
                   with_val: bool):
    """Mosaic margin kernel: per block of ``block_rows`` table rows,
    recover slot lanes from pos/mask (VPU compare + row-sum), pick the
    block's weights at those lanes (full-shape lane-local gather), and
    accumulate ``margin_ext[m, l] += sum_s [src==m*128+l] * g[s]`` via a
    per-row one-hot MXU contraction into the grid-shared accumulator."""
    from jax.experimental import pallas as pl

    def kern(src_ref, p_ref, m_ref, w_ref, *rest):
        (val_ref, out_ref) = rest if with_val else (None, rest[0])
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        src = src_ref[:]                        # (block_rows, 128) i32
        p_eff = p_ref[:] + m_ref[:].astype(jnp.int32) - 1
        s_iota = jax.lax.broadcasted_iota(
            jnp.int32, (ELL_WIDTH, ELL_WIDTH), 1)   # [l, s] = s
        lane_rows = []
        for r in range(block_rows):
            # lane(s) = #{l : p_eff[l] < s}; (1, 128) row, no transpose
            cmp = (p_eff[r][:, None] < s_iota).astype(jnp.int32)
            lane_rows.append(jnp.sum(cmp, axis=0, keepdims=True))
        lanes = jnp.minimum(jnp.concatenate(lane_rows, axis=0),
                            ELL_WIDTH - 1)
        g = jnp.take_along_axis(w_ref[:], lanes, axis=1)
        if with_val:
            g = g * val_ref[:]
        hi = src // 128
        lo = src % 128
        acc = jnp.zeros((m_rows, ELL_WIDTH), jnp.float32)
        for r in range(block_rows):
            # AT[m, s] = [hi[s] == m] * g[s];  B[s, l] = [lo[s] == l] —
            # both built in the dot's consumed orientation (a dim-0
            # dot_general contraction forces a per-iteration Mosaic
            # relayout, measured ~10x the MXU floor, r4 breakdown)
            at = jnp.where(
                jax.lax.broadcasted_iota(
                    jnp.int32, (m_rows, ELL_WIDTH), 0) == hi[r][None, :],
                g[r][None, :], 0.0)
            b = (lo[r][:, None] == jax.lax.broadcasted_iota(
                jnp.int32, (ELL_WIDTH, ELL_WIDTH), 1)).astype(jnp.float32)
            acc = acc + jnp.dot(at, b,
                                preferred_element_type=jnp.float32,
                                precision=precision)
        out_ref[:] += acc
    return kern


@functools.partial(jax.jit, static_argnames=("m_len", "interpret",
                                             "precision"))
def ell_margin_fused(w: jnp.ndarray, src: jnp.ndarray, pos: jnp.ndarray,
                     mask: jnp.ndarray, *, m_len: int,
                     val: Optional[jnp.ndarray] = None,
                     precision: str = "default",
                     interpret: bool = False) -> jnp.ndarray:
    """Forward twin of :func:`ell_scatter_apply_fused`: per-sample margin
    contributions of the in-grid slots, on the MXU instead of the
    transaction-bound ``w[cat]`` gather.  Returns a flat f32 table of
    length >= ``m_len`` (rounded up to whole 8x128 tiles — callers slice
    ``[:batch]``).  ``val`` is the per-slot explicit-value multiplier of
    the generic sparse layout; ``precision`` as in
    :func:`ell_scatter_apply_fused`."""
    rows = src.shape[0]
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    br = _FUSED_BLOCK_ROWS
    if rows % br:
        raise ValueError(
            f"fused margin kernel needs rows % {br} == 0, got {rows}; "
            "use ell_margin_xla")
    if m_len % 128:
        raise ValueError(
            f"m_len must be a multiple of 128, got {m_len}; use the "
            "sgd._extended_r length")
    m_rows = m_len // 128
    m_rows += (-m_rows) % 8          # whole sublane tiles for the MXU
    w2 = w.reshape(rows, _LANES)
    block = pl.BlockSpec((br, 128), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    operands = [src, pos, mask, w2]
    in_specs = [block] * 4
    if val is not None:
        operands.append(val)
        in_specs.append(block)
    out = pl.pallas_call(
        _margin_kernel(br, m_rows, precision, val is not None),
        grid=(rows // br,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((m_rows, 128), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m_rows, ELL_WIDTH), jnp.float32),
        interpret=interpret,
    )(*operands)
    return out.reshape(-1)


# ---------------------------------------------------------------------------
# kernel-registry entries (kernels/registry.py): the ELL hot paths under
# ONE uniform signature per op, so the training step builders resolve
# their implementation with a lookup instead of branching on a
# ``use_pallas`` flag by hand.  Backend selection mirrors the legacy
# branches exactly: fully-fused Mosaic when the table grid divides into
# 8-row blocks, the gather + Mosaic-scatter pair otherwise, pure XLA off
# TPU (and as the forced oracle).
# ---------------------------------------------------------------------------

def ell_margin_xla_entry(w, src, pos, mask, *, m_len: int, val=None,
                         precision: str = "default", interpret: bool = False):
    """XLA backend of op ``ell_margin`` (registry signature; ``precision``
    and ``interpret`` are Mosaic knobs the XLA lowering has no use for —
    it always accumulates in f32)."""
    return ell_margin_xla(w, src, pos, mask, m_len, val=val)


# -- lane-blocked weight gather ---------------------------------------------
# Shared with the model layer (sgd.py re-imports these): ops/ owns the
# device-kernel helpers, models look them up — never the other way
# around (an ops -> models import would cycle through the kernels
# catalog the moment a lazy import is hoisted).  Blocked and elementwise
# paths produce bitwise-equal values; blocking only changes the lowering
# (lane-aligned row-gather + one-hot lane select instead of XLA's
# per-element gather).

_GATHER_LANES = 256


def use_blocked(d: int) -> bool:
    return d % _LANES == 0 and d >= _LANES


def blocked_gather(w, idx):
    """``w[idx]`` via lane-aligned row-gather + one-hot lane select."""
    d = w.shape[0]
    lanes = (_GATHER_LANES if d % _GATHER_LANES == 0 and d >= _GATHER_LANES
             else _LANES)
    flat = idx.reshape(-1)
    hi, lo = flat // lanes, flat % lanes
    onehot = lo[:, None] == jnp.arange(lanes, dtype=lo.dtype)[None, :]
    rows = w.reshape(-1, lanes)[hi]
    return jnp.sum(jnp.where(onehot, rows, 0), axis=-1).reshape(idx.shape)


def gather_weights(w, idx):
    return blocked_gather(w, idx) if use_blocked(w.shape[0]) else w[idx]


def _ell_pair_update(r_ext, src, lr, val):
    g = gather_weights(r_ext, src)
    return (-lr) * (g if val is None else val * g)


def ell_scatter_apply_pair(w, r_ext, src, pos, mask, *, lr, val=None,
                           precision: str = "default",
                           interpret: bool = False):
    """``pallas-pair`` backend of op ``ell_scatter_apply``: the XLA slot
    gather feeding the Mosaic csum/pick scatter kernel — the fallback for
    table grids the 8-row fused kernel cannot block."""
    return ell_scatter_apply(w, _ell_pair_update(r_ext, src, lr, val),
                             pos, mask, interpret=interpret)


def ell_scatter_apply_xla_entry(w, r_ext, src, pos, mask, *, lr, val=None,
                                precision: str = "default",
                                interpret: bool = False):
    """XLA backend of op ``ell_scatter_apply`` (gather + csum/pick in pure
    XLA — the CPU path and the parity oracle)."""
    return ell_scatter_apply_xla(w, _ell_pair_update(r_ext, src, lr, val),
                                 pos, mask)


def _fused_blockable(sig: tuple) -> bool:
    """Shape contract of the fused ELL kernels: ``sig = (table_rows,)``
    must divide into the 8-row Mosaic grid blocks."""
    return bool(sig) and sig[0] % _FUSED_BLOCK_ROWS == 0


def _register_ell_kernels() -> None:
    from ..kernels.registry import register_kernel, tpu_only

    register_kernel("ell_margin", "pallas", ell_margin_fused,
                    priority=20, supports=_fused_blockable,
                    available=tpu_only)
    register_kernel("ell_margin", "xla", ell_margin_xla_entry)
    register_kernel("ell_scatter_apply", "pallas", ell_scatter_apply_fused,
                    priority=30, supports=_fused_blockable,
                    available=tpu_only)
    register_kernel("ell_scatter_apply", "pallas-pair",
                    ell_scatter_apply_pair, priority=20,
                    available=tpu_only)
    register_kernel("ell_scatter_apply", "xla", ell_scatter_apply_xla_entry)


_register_ell_kernels()
