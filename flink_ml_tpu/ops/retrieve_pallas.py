"""Fused Pallas ``retrieve`` backend: coarse probe -> DMA posting lists
-> masked scan -> top-k merge, one VMEM-resident program per query.

The XLA lowering (``retrieval/ivf.py``) gathers the probed posting-list
blocks into a ``(b, nprobe, block)`` candidate tensor; XLA:TPU keeps the
distance tiles fused but the gathered vector blocks themselves still
round-trip HBM once per operand of the scan.  This kernel streams each
probed block HBM->VMEM with an explicit async copy instead: the posting
arrays stay in ``pltpu.ANY`` (HBM) and only the ``nprobe`` blocks a
query actually probes ever move, directly into a reused VMEM scratch
buffer — candidate distances and the running top-k never exist outside
VMEM.

Parity contract: per-row outputs are BITWISE-equal to the XLA backend in
interpret mode (asserted by the ``tests/test_kernels.py`` matrix).  The
kernel guarantees this by construction —

- distance expressions are THE shared helpers of ``retrieval/ivf.py``
  (``coarse_distances`` / ``flat_distances`` / ``pq_lut`` /
  ``adc_distances``), never re-derived forms;
- probes are consumed in ascending (distance, list-index) order — the
  exact order ``lax.top_k`` emits them, reproduced with the
  where/min/iota first-index selection of the KMeans Pallas kernels (a
  true argmin would lower to a slow Mosaic index loop);
- the running top-k merge breaks distance ties by candidate POSITION
  (k kept slots first, then the block in row order), which provably
  equals ``lax.top_k``'s lowest-flat-index tie rule because kept slots
  always originate from earlier flat positions than the block being
  merged.  Consumed slots are neutralised in both coordinates (distance
  -> +inf AND position -> out-of-range) so an all-+inf tail can never
  re-select them.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..kernels.registry import register_kernel, tpu_only
from ..retrieval.ivf import (adc_distances, coarse_distances,
                             decode_codebooks, flat_distances, pq_lut,
                             runtime_one)

__all__ = ["retrieve_stage_pallas", "fused_supported"]

_VMEM_BUDGET = 12 * 1024 * 1024  # headroom below the ~16 MB/core VMEM


def _tile_bytes(dim: int, m: int, ksub: int, nlist: int, block: int,
                k: int) -> int:
    """Per-step VMEM model: resident centroids + the DMA'd posting block
    (+ decoded PQ books and LUT) + the merge tiles.  The merge chain is
    modelled as ~4 live (1, k + block) tiles (candidates, positions, the
    compare masks) — unrolled steps reuse the same buffers."""
    resident = nlist * dim * 4 + nlist * 4          # centroids + coarse row
    if m:
        resident += block * m + block * 4           # codes buf + ids buf
        resident += m * ksub * (dim // m) * 8       # cb int8 + decoded f32
        resident += m * ksub * 4 + m * block * 4    # LUT + gathered entries
    else:
        resident += block * dim * 4 + block * 4
    merge = 4 * (k + block) * 4
    return resident + merge


def fused_supported(sig: tuple) -> bool:
    """supports() predicate for the fused kernel: a well-formed
    ``retrieve`` signature whose working set fits the VMEM budget.
    Shape-permissive beyond that — a forced ``lookup(backend="pallas")``
    still honours this predicate, so it must accept every schema the
    kernel can actually run (the parity matrix exercises it in interpret
    mode on every host)."""
    if len(sig) != 7:
        return False
    nprobe, k, dim, m, ksub, nlist, block = sig
    if block % 8 or not 1 <= nprobe <= nlist or k < 1 or dim < 1:
        return False
    if m and (dim % m or not 2 <= ksub <= 127):
        return False
    return _tile_bytes(dim, m, ksub, nlist, block, k) <= _VMEM_BUDGET


def _select_first_min(scores, iota, out_of_range):
    """Smallest index attaining the row minimum — the KMeans Pallas
    where/min/iota idiom (first-index argmin without an argmin loop)."""
    mins = jnp.min(scores, axis=1, keepdims=True)
    return jnp.min(jnp.where(scores <= mins, iota, out_of_range))


def _merge_topk(best_d, best_i, dist, ids_row, k: int):
    """Merge one probed block into the running top-k.  Tie rule: smallest
    candidate position (kept slots 0..k-1, block slots k..), which equals
    ``lax.top_k``'s lowest-flat-index rule — see the module docstring."""
    total = k + dist.shape[1]
    cand_d = jnp.concatenate([best_d, dist], axis=1)
    cand_i = jnp.concatenate([best_i, ids_row], axis=1)
    pos = jax.lax.broadcasted_iota(jnp.int32, (1, total), 1)
    out_d, out_i = [], []
    for _ in range(k):
        dmin = jnp.min(cand_d, axis=1, keepdims=True)
        tied = cand_d <= dmin
        pmin = jnp.min(jnp.where(tied, pos, total), axis=1, keepdims=True)
        sel = pos == pmin                      # exactly one slot
        out_d.append(jnp.sum(jnp.where(sel, cand_d, 0.0), axis=1,
                             keepdims=True))
        out_i.append(jnp.sum(jnp.where(sel, cand_i, 0), axis=1,
                             keepdims=True))
        cand_d = jnp.where(sel, jnp.inf, cand_d)
        pos = jnp.where(sel, total, pos)       # never re-selectable
    return (jnp.concatenate(out_d, axis=1),
            jnp.concatenate(out_i, axis=1).astype(jnp.int32))


def _flat_kernel(nprobe: int, k: int, block: int, nlist: int):
    def kern(q_ref, cent_ref, ids_hbm, vecs_hbm, nn_ref, nd_ref,
             vec_buf, ids_buf, sem_v, sem_i):
        q = q_ref[:]                                     # (1, d)
        coarse = coarse_distances(q, cent_ref[:])        # (1, nlist)
        iota_l = jax.lax.broadcasted_iota(jnp.int32, (1, nlist), 1)
        best_d = jnp.full((1, k), jnp.inf, jnp.float32)
        best_i = jnp.full((1, k), -1, jnp.int32)
        for _ in range(nprobe):
            probe = _select_first_min(coarse, iota_l, nlist)
            coarse = jnp.where(iota_l == probe, jnp.inf, coarse)
            cp_v = pltpu.make_async_copy(
                vecs_hbm.at[pl.ds(probe * block, block), :], vec_buf,
                sem_v)
            cp_i = pltpu.make_async_copy(
                ids_hbm.at[pl.ds(probe, 1), :], ids_buf, sem_i)
            cp_v.start()
            cp_i.start()
            cp_v.wait()
            cp_i.wait()
            dist = flat_distances(q, vec_buf[:][None])   # (1, block)
            ids_row = ids_buf[:]                         # (1, block)
            dist = jnp.where(ids_row >= 0, dist, jnp.inf)
            best_d, best_i = _merge_topk(best_d, best_i, dist, ids_row, k)
        nn_ref[:] = best_i
        nd_ref[:] = best_d

    return kern


def _pq_kernel(nprobe: int, k: int, block: int, nlist: int, m: int):
    def kern(q_ref, cent_ref, cbq_ref, cbs_ref, ids_hbm, codes_hbm,
             nn_ref, nd_ref, code_buf, ids_buf, sem_c, sem_i):
        q = q_ref[:]                                     # (1, d)
        one = runtime_one(cbs_ref[0, 0])
        # mirror of the XLA stage: runtime-1.0 pins the decode rounding
        books = decode_codebooks(cbq_ref[:], cbs_ref[:]) * one
        coarse = coarse_distances(q, cent_ref[:])
        iota_l = jax.lax.broadcasted_iota(jnp.int32, (1, nlist), 1)
        best_d = jnp.full((1, k), jnp.inf, jnp.float32)
        best_i = jnp.full((1, k), -1, jnp.int32)
        for _ in range(nprobe):
            probe = _select_first_min(coarse, iota_l, nlist)
            coarse = jnp.where(iota_l == probe, jnp.inf, coarse)
            cp_c = pltpu.make_async_copy(
                codes_hbm.at[pl.ds(probe * block, block), :], code_buf,
                sem_c)
            cp_i = pltpu.make_async_copy(
                ids_hbm.at[pl.ds(probe, 1), :], ids_buf, sem_i)
            cp_c.start()
            cp_i.start()
            cp_c.wait()
            cp_i.wait()
            cent = jax.lax.dynamic_slice(
                cent_ref[:], (probe, 0), (1, q.shape[1]))
            resid = q - cent                             # (1, d)
            lut = pq_lut(resid.reshape(1, m, -1), books, one)
            dist = adc_distances(lut, code_buf[:][None])  # (1, block)
            ids_row = ids_buf[:]
            dist = jnp.where(ids_row >= 0, dist, jnp.inf)
            best_d, best_i = _merge_topk(best_d, best_i, dist, ids_row, k)
        nn_ref[:] = best_i
        nd_ref[:] = best_d

    return kern


@functools.partial(
    jax.jit, static_argnames=("nprobe", "k", "nlist", "block", "interpret"))
def retrieve_flat_fused(q, centroids, ids, vecs, *, nprobe: int, k: int,
                        nlist: int, block: int, interpret: bool = False):
    """Fused flat-f32 search: ``(q (b, d), centroids (nlist, d), ids
    (nlist, block) i32, vecs (nlist*block, d)) -> (neighbors (b, k) i32,
    distances (b, k) f32)``."""
    b, d = q.shape
    return pl.pallas_call(
        _flat_kernel(nprobe, k, block, nlist),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nlist, d), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec((1, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, k), jnp.int32),
            jax.ShapeDtypeStruct((b, k), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, d), jnp.float32),
            pltpu.VMEM((1, block), jnp.int32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(q, centroids, ids, vecs)


@functools.partial(
    jax.jit,
    static_argnames=("nprobe", "k", "nlist", "block", "m", "interpret"))
def retrieve_pq_fused(q, centroids, ids, codes, cb_q, cb_s, *, nprobe: int,
                      k: int, nlist: int, block: int, m: int,
                      interpret: bool = False):
    """Fused IVF-PQ search: int8 code blocks DMA'd per probe, LUT built
    in VMEM from the decoded per-subspace codebooks."""
    b, d = q.shape
    ksub = cb_q.shape[1]
    return pl.pallas_call(
        _pq_kernel(nprobe, k, block, nlist, m),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, d), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((nlist, d), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((m, ksub, d // m), lambda i: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((m, ksub), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec((1, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, k), jnp.int32),
            jax.ShapeDtypeStruct((b, k), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, m), jnp.int8),
            pltpu.VMEM((1, block), jnp.int32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(q, centroids, cb_q, cb_s, ids, codes)


def retrieve_stage_pallas(static, params, cols, *, interpret: bool = False):
    """Stage-convention entry: same (static, params, cols) contract and
    staging outputs as the XLA stage in ``retrieval/ivf.py``."""
    (qcol, ncol, dcol, nprobe, k, nlist, block, m, _ksub) = static
    q = cols[qcol]
    if m:
        nbrs, dists = retrieve_pq_fused(
            q, params["centroids"], params["ids"], params["codes"],
            params["cb_q"], params["cb_s"], nprobe=nprobe, k=k,
            nlist=nlist, block=block, m=m, interpret=interpret)
    else:
        nbrs, dists = retrieve_flat_fused(
            q, params["centroids"], params["ids"], params["vecs"],
            nprobe=nprobe, k=k, nlist=nlist, block=block,
            interpret=interpret)
    return {ncol: nbrs, dcol: dists}


def _register() -> None:
    register_kernel("retrieve", "pallas", retrieve_stage_pallas,
                    priority=10, supports=fused_supported,
                    available=tpu_only, convention="stage")


_register()
