"""Statically-routed embedding-gradient scatter — the Wide&Deep backward
hot path.

Problem: the Wide&Deep backward must form the dense gradient of the
stacked ``(total_vocab, emb_dim)`` embedding table from per-slot gradient
rows: ``g_table[cat[b, f]] += g_rows[b, f]`` for ~213k slots per batch at
the bench shape.  Autodiff lowers this to XLA's general scatter-add —
one random HBM read-modify-write per slot with conflict handling, which
the r4 TPU measurement put at ~9.4 of the 18.8 ms step (the backward's
dominant cost, `R4_TPU_STATUS.md`).

But bounded fits replay the SAME epoch tensor every epoch
(``models/common/sgd.py`` builds it once), so — exactly as with the LR
family's ELL kernels (``ops/ell_scatter.py``) — the slot routing is
**static**: we pay one host sort per fit and turn the per-step scatter
into four conflict-free streaming stages:

1. ``g_sorted = g_flat[order]`` — a static PERMUTATION gather
   (``unique_indices=True``: every source row read exactly once),
2. a segmented suffix-fold (Hillis–Steele) over runs of equal ids:
   after ``ceil(log2(max_run))`` masked shift-adds, the slot at each
   run's START holds the full run sum — ``fold_passes`` is static per
   fit (0 passes when every id in a step is unique),
3. placement of the run sums into the dense table, in one of two forms
   chosen at route-build time:

   - ``placement="gather"`` (default): ``dense = g_folded_ext[pos_map]``
     — a per-step static INVERSE map (``pos_map[v]`` = sorted position
     of vocab row ``v``'s run start, or ``S`` for untouched rows, which
     reads the appended zero row).  NO scatter exists anywhere in the
     step: the dense gradient is one streaming row-gather, which XLA
     lowers far better than any scatter and fuses into the Adam
     consumer.  Costs ``steps x num_rows`` i32 of route storage.
   - ``placement="scatter"``: compaction pick of run-start rows at
     static positions, then ``zeros.at[out_ids].set(run_sums,
     indices_are_sorted=True, unique_indices=True, mode="drop")`` —
     with unique ascending indices XLA needs no conflict handling and
     no read-modify-write; padded entries carry ascending OUT-OF-RANGE
     sentinels (``num_rows + rank``) so they stay unique and are
     dropped, never silently aliased.  Route storage stays
     ``O(slots)``, for vocabularies so large the inverse map would not
     fit.

The result equals the XLA scatter-add up to f32 summation order (runs
fold pairwise instead of sequentially).  The same route applies to any
per-slot payload width: the wide tower's ``(total_vocab,)`` scalar
table reuses it with ``E == 1``.

The reference has no analog — its one DNN-shaped config never existed
(`/root/reference/flink-ml-lib` ships KMeans only); this is the
TPU-native replacement for what its keyed-shuffle reduction
(``flink-ml-lib/.../clustering/kmeans/KMeans.java:172-196``) would have
had to become at embedding-gradient scale.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["EmbGradRoute", "emb_grad_route", "routed_table_grad",
           "routed_table_grad_gather"]

#: placement="auto" picks gather until the inverse map would cost more
#: than this (steps x num_rows x 4 bytes of route storage), then falls
#: back to the O(slots) scatter placement.
_POS_MAP_BUDGET_BYTES = 512 << 20


@dataclass
class EmbGradRoute:
    """Static per-step routing for :func:`routed_table_grad` /
    :func:`routed_table_grad_gather`.

    All arrays are per-step stacks (leading dim = steps) so a
    ``lax.scan`` over steps slices them with one dynamic index.
    Exactly one of the placement array groups is populated — ``pos_map``
    for ``placement="gather"``, ``out_pos``/``out_ids`` for
    ``placement="scatter"``.
    """
    order: jnp.ndarray       # (steps, S) i32: sort permutation of the
                             #   flattened (batch*fields) slot ids
    sorted_ids: jnp.ndarray  # (steps, S) i32: ids in sorted order
    fold_passes: int         # static: ceil(log2(max run length)) over
                             #   every step (0 when all ids unique)
    num_rows: int            # destination table rows (total vocab)
    placement: str = "gather"
    # gather placement:
    pos_map: Optional[jnp.ndarray] = None  # (steps, num_rows) i32:
                             #   run-start position of each vocab row's
                             #   run, S for untouched rows (zero row)
    # scatter placement:
    out_pos: Optional[jnp.ndarray] = None  # (steps, U) i32: run-start
                             #   positions into the sorted axis; pad = S
                             #   (reads the appended zero row)
    out_ids: Optional[jnp.ndarray] = None  # (steps, U) i32: unique ids
                             #   per run, ascending; pad = num_rows +
                             #   rank (unique, out of range -> dropped)

    @property
    def steps(self) -> int:
        return self.order.shape[0]

    def stacked_arrays(self):
        """The per-step array stack a scan body threads through (order
        matches :meth:`step_slice`)."""
        if self.placement == "gather":
            return (self.order, self.sorted_ids, self.pos_map)
        return (self.order, self.sorted_ids, self.out_pos, self.out_ids)

    def step_slice(self, i):
        """The per-step arrays for scan bodies at step ``i`` (dynamic
        index OK)."""
        return tuple(a[i] for a in self.stacked_arrays())

    def apply(self, g_flat, *step_arrays):
        """Dense table gradient from one step's slice (either
        placement) — the XLA lowering of registry op
        ``routed_table_grad``."""
        if self.placement == "gather":
            order, sid, pos_map = step_arrays
            return routed_table_grad_gather(
                g_flat, order, sid, pos_map,
                fold_passes=self.fold_passes)
        order, sid, out_pos, out_ids = step_arrays
        return routed_table_grad(
            g_flat, order, sid, out_pos, out_ids,
            num_rows=self.num_rows, fold_passes=self.fold_passes)

    def kernel_sig(self) -> tuple:
        """The ``(placement, fold_passes, slots_per_step)`` schema
        signature registry op ``routed_table_grad`` selects backends
        on."""
        return (self.placement, self.fold_passes, int(self.order.shape[1]))

    def resolve_apply(self, backend: Optional[str] = None):
        """Registry-resolved per-step apply: ``fn(g_flat, *step_arrays)``.

        The training step builders (``widedeep._make_train_ops``) call
        this ONCE at step-build time instead of hardcoding the XLA
        lowering — on TPU the fused Mosaic fold
        (``ops/emb_grad_pallas.py``) is picked up automatically, off TPU
        (or with ``backend="xla"`` forced) this is exactly
        :meth:`apply`."""
        from ..kernels.registry import lookup

        entry = lookup("routed_table_grad", sig=self.kernel_sig(),
                       backend=backend)

        def apply_fn(g_flat, *step_arrays):
            return entry.fn(self, g_flat, *step_arrays)

        return apply_fn


def emb_grad_route(cat_steps: np.ndarray, num_rows: int,
                   u_cap: Optional[int] = None,
                   device: bool = True,
                   placement: str = "gather") -> EmbGradRoute:
    """Build the static routing from a ``(steps, batch, fields)`` int
    epoch tensor of (already offset) categorical ids — host numpy, one
    time per fit.

    ``placement`` picks how run sums land in the dense table (see module
    doc): ``"gather"`` (default — scatter-free, ``steps x num_rows``
    route storage) or ``"scatter"`` (``O(slots)`` storage).  ``u_cap``
    (scatter placement) forces the unique-run capacity for streaming
    callers whose batches must share one compiled shape; a step with
    more unique ids raises rather than dropping gradient rows.
    ``device=False`` keeps the arrays host numpy for callers that manage
    their own placement.
    """
    if placement not in ("auto", "gather", "scatter"):
        raise ValueError(f"unknown placement {placement!r}")
    cat_steps = np.asarray(cat_steps)
    steps = cat_steps.shape[0]
    S = int(np.prod(cat_steps.shape[1:]))
    if placement == "auto":
        # gather's inverse map costs steps x num_rows i32 — the right
        # trade until it rivals the epoch data itself; past the budget
        # (large vocab x many steps) fall back to O(slots) scatter
        placement = ("gather"
                     if steps * num_rows * 4 <= _POS_MAP_BUDGET_BYTES
                     else "scatter")
    orders = np.empty((steps, S), np.int32)
    sids = np.empty((steps, S), np.int32)
    starts_list = []
    max_run = 1
    for s in range(steps):
        flat = cat_steps[s].reshape(-1)
        order = np.argsort(flat, kind="stable").astype(np.int32)
        sid = flat[order].astype(np.int32)
        orders[s] = order
        sids[s] = sid
        start = np.empty(S, bool)
        start[0] = True
        np.not_equal(sid[1:], sid[:-1], out=start[1:])
        pos = np.flatnonzero(start).astype(np.int32)
        starts_list.append((pos, sid[pos]))
        runs = np.diff(np.append(pos, S))
        max_run = max(max_run, int(runs.max(initial=1)))
    fold_passes = (max(0, int(np.ceil(np.log2(max_run))))
                   if max_run > 1 else 0)
    wrap = jnp.asarray if device else np.asarray
    # the u_cap contract holds for BOTH placements (a caller-forced cap
    # must never be silently ignored); gather just has no U-shaped
    # arrays to size with it
    need_u = max(p.size for p, _ in starts_list)
    if u_cap is not None and need_u > u_cap:
        raise ValueError(
            f"route needs {need_u} unique ids in some step > forced "
            f"u_cap {u_cap}; gradient rows would silently drop — raise "
            "the cap")
    if placement == "gather":
        pos_map = np.full((steps, num_rows), S, np.int32)
        for s, (pos, uids) in enumerate(starts_list):
            pos_map[s][uids] = pos
        return EmbGradRoute(
            order=wrap(orders), sorted_ids=wrap(sids),
            pos_map=wrap(pos_map), fold_passes=fold_passes,
            num_rows=num_rows, placement="gather")
    U = u_cap if u_cap is not None else need_u
    out_pos = np.full((steps, U), S, np.int32)
    # pad ids: ascending out-of-range sentinels — unique (the scatter's
    # unique_indices claim stays true) and dropped by mode="drop"
    out_ids = (num_rows
               + np.arange(U, dtype=np.int32)[None, :].repeat(steps, 0))
    for s, (pos, uids) in enumerate(starts_list):
        out_pos[s, :pos.size] = pos
        out_ids[s, :uids.size] = uids
    return EmbGradRoute(
        order=wrap(orders), sorted_ids=wrap(sids),
        out_pos=wrap(out_pos), out_ids=wrap(out_ids),
        fold_passes=fold_passes, num_rows=num_rows, placement="scatter")


def _folded_ext(g_flat, order, sorted_ids, fold_passes):
    """Stages 1-2 shared by both placements: static permutation gather,
    then the segmented suffix-fold — after pass k (offset 2^k), g[i]
    holds the sum of the sorted rows i .. min(run_end, i + 2^(k+1) - 1).
    Returns ``(g_ext, squeeze)`` where ``g_ext (S+1, E)`` carries an
    appended zero row (position ``S`` — what padded picks read)."""
    squeeze = g_flat.ndim == 1
    if squeeze:
        g_flat = g_flat[:, None]
    S, E = g_flat.shape
    g = jnp.take(g_flat, order, axis=0, unique_indices=True)
    offs = 1
    for _ in range(fold_passes):
        same = jnp.concatenate(
            [sorted_ids[offs:] == sorted_ids[:-offs],
             jnp.zeros((offs,), bool)])
        shifted = jnp.concatenate(
            [g[offs:], jnp.zeros((offs, E), g.dtype)], axis=0)
        g = g + jnp.where(same[:, None], shifted, 0.0)
        offs *= 2
    return jnp.concatenate([g, jnp.zeros((1, E), g.dtype)], axis=0), \
        squeeze


def routed_table_grad(g_flat: jnp.ndarray, order: jnp.ndarray,
                      sorted_ids: jnp.ndarray, out_pos: jnp.ndarray,
                      out_ids: jnp.ndarray, *, num_rows: int,
                      fold_passes: int) -> jnp.ndarray:
    """The dense ``(num_rows, E)`` table gradient from per-slot rows
    ``g_flat (S, E)`` via one step's route slice, SCATTER placement (see
    module doc).  Equals ``zeros.at[ids].add(g_flat)`` up to f32
    summation order.  ``num_rows``/``fold_passes`` are static."""
    g_ext, squeeze = _folded_ext(g_flat, order, sorted_ids, fold_passes)
    run_sums = jnp.take(g_ext, out_pos, axis=0, unique_indices=True)
    out = jnp.zeros((num_rows, g_ext.shape[1]), g_ext.dtype).at[
        out_ids].set(run_sums, indices_are_sorted=True,
                     unique_indices=True, mode="drop")
    return out[:, 0] if squeeze else out


def routed_table_grad_gather(g_flat: jnp.ndarray, order: jnp.ndarray,
                             sorted_ids: jnp.ndarray,
                             pos_map: jnp.ndarray, *,
                             fold_passes: int) -> jnp.ndarray:
    """GATHER placement: the dense gradient is one streaming row-gather
    of the folded array at the static inverse map — no scatter exists
    anywhere (see module doc).  ``pos_map (num_rows,)`` holds each vocab
    row's run-start position in sorted order (``S`` = untouched -> the
    appended zero row).  Same result as :func:`routed_table_grad`."""
    g_ext, squeeze = _folded_ext(g_flat, order, sorted_ids, fold_passes)
    out = jnp.take(g_ext, pos_map, axis=0)
    return out[:, 0] if squeeze else out


# ---------------------------------------------------------------------------
# kernel-registry entry (XLA backend of op ``routed_table_grad``; the
# fused Mosaic fold registers the "pallas" backend from
# ``ops/emb_grad_pallas.py``).  The registry signature is
# ``fn(route, g_flat, *step_arrays)`` so one entry serves every payload
# width — the (S, E) embedding rows and the (S,) wide-scalar table alike.
# ---------------------------------------------------------------------------

def routed_apply_xla(route: EmbGradRoute, g_flat, *step_arrays):
    """XLA backend of op ``routed_table_grad``."""
    return EmbGradRoute.apply(route, g_flat, *step_arrays)


def _register_emb_grad_kernels() -> None:
    from ..kernels.registry import register_kernel

    register_kernel("routed_table_grad", "xla", routed_apply_xla)


_register_emb_grad_kernels()
