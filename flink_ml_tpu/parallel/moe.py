"""Expert parallelism — a routed mixture-of-experts FFN over an ``"expert"``
mesh axis.

Absent from the reference (its only axis is Flink subtask data parallelism,
SURVEY §2.10); included so the mesh vocabulary covers ep alongside dp/tp/pp/sp.

TPU-first shape (the GShard/Mesh-TF recipe, not a scatter/gather port):
routing is expressed as two einsums against a dense 0/1 dispatch tensor
``(tokens, experts, capacity)``.  Everything is static-shaped — the MXU sees
three large matmuls — and when tokens are sharded over ``"data"`` while
expert buffers are sharded over ``"expert"``, the sharding constraint on the
dispatched activations makes GSPMD insert the canonical all-to-all on ICI.
Tokens over a full expert's capacity are dropped (their combine weight is 0,
standard capacity-factor semantics), so shapes never depend on the routing.
"""

from __future__ import annotations

import math

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["EXPERT_AXIS", "MoEParams", "init_moe", "moe_apply", "moe_sharding"]

EXPERT_AXIS = "expert"


class MoEParams(NamedTuple):
    wg: jax.Array    # (d_model, n_experts) router
    w_in: jax.Array  # (n_experts, d_model, d_hidden)
    w_out: jax.Array  # (n_experts, d_hidden, d_model)


def init_moe(rng: np.random.Generator, d_model: int, d_hidden: int,
             n_experts: int) -> MoEParams:
    scale_in = 1.0 / math.sqrt(d_model)
    scale_out = 1.0 / math.sqrt(d_hidden)
    return MoEParams(
        wg=jnp.asarray(rng.normal(size=(d_model, n_experts)) * scale_in,
                       jnp.float32),
        w_in=jnp.asarray(
            rng.normal(size=(n_experts, d_model, d_hidden)) * scale_in,
            jnp.float32),
        w_out=jnp.asarray(
            rng.normal(size=(n_experts, d_hidden, d_model)) * scale_out,
            jnp.float32),
    )


def moe_sharding(mesh: Mesh, *, expert_axis: str = EXPERT_AXIS) -> MoEParams:
    """Shardings placing one expert group per device along ``expert_axis``
    (router replicated)."""
    return MoEParams(
        wg=NamedSharding(mesh, P()),
        w_in=NamedSharding(mesh, P(expert_axis)),
        w_out=NamedSharding(mesh, P(expert_axis)),
    )


def moe_apply(params: MoEParams, x: jax.Array, *,
              capacity_factor: float = 1.25,
              group_size: Optional[int] = None,
              mesh: Optional[Mesh] = None,
              expert_axis: str = EXPERT_AXIS,
              data_axis: Optional[str] = None) -> jax.Array:
    """Top-1 routed MoE FFN: ``(tokens, d_model) -> (tokens, d_model)``.

    Call under jit with ``params`` placed per :func:`moe_sharding` and the
    owning ``mesh`` passed in; with ``mesh=None`` no sharding constraints are
    applied (single-device / oracle use).

    ``group_size`` bounds the dispatch/combine tensors: routing happens
    independently within fixed-size token groups (the GShard group dim), so
    dispatch memory is O(T * group_size * capacity_factor) instead of
    O(capacity_factor * T^2).  With ``data_axis`` set and more than one
    group, groups are sharded over the data axis and the dispatched expert
    buffers over ``expert_axis`` — the layout change between the two is the
    canonical MoE all-to-all, inserted by GSPMD.
    """

    def constrain(arr, spec):
        if mesh is None:
            return arr
        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(mesh, spec))

    n_tokens, d_model = x.shape
    n_experts = params.wg.shape[1]
    size = group_size or n_tokens
    if n_tokens % size:
        raise ValueError(
            f"tokens {n_tokens} not divisible by group_size={size}")
    n_groups = n_tokens // size
    capacity = max(1, int(math.ceil(size / n_experts * capacity_factor)))
    group_spec = data_axis if (data_axis and n_groups > 1) else None

    xg = x.reshape(n_groups, size, d_model)                     # (G, S, d)
    # Routing bookkeeping runs in f32 regardless of x.dtype: a bf16 cumsum
    # is inexact past 256 and would collide queue positions (tokens silently
    # summed into one capacity slot).
    gates = jax.nn.softmax(
        xg.astype(jnp.float32) @ params.wg.astype(jnp.float32), axis=-1)
    top1 = jnp.argmax(gates, axis=-1)                           # (G, S)
    gate_val = jnp.take_along_axis(gates, top1[..., None], axis=-1)[..., 0]

    onehot = jax.nn.one_hot(top1, n_experts, dtype=jnp.float32)  # (G, S, E)
    # Position of each token in its expert's queue; tokens past capacity drop.
    pos = jnp.cumsum(onehot, axis=1) * onehot - onehot
    within = (pos < capacity).astype(jnp.float32)
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                            dtype=jnp.float32)                  # (G, S, E, C)
    dispatch = onehot[..., None] * within[..., None] * pos_oh
    dispatch_x = dispatch.astype(x.dtype)   # exact: 0/1 values

    expert_in = jnp.einsum("gsec,gsd->gecd", dispatch_x, xg)    # (G, E, C, d)
    expert_in = constrain(
        expert_in, P(group_spec, expert_axis, None, None))      # all_to_all
    hidden = jax.nn.gelu(
        jnp.einsum("gecd,edh->gech", expert_in, params.w_in))
    expert_out = jnp.einsum("gech,ehd->gecd", hidden, params.w_out)
    expert_out = constrain(
        expert_out, P(group_spec, expert_axis, None, None))
    combine = (dispatch * gate_val[..., None, None]).astype(expert_out.dtype)
    y = jnp.einsum("gsec,gecd->gsd", combine, expert_out)
    y = y.reshape(n_tokens, d_model).astype(x.dtype)
    if data_axis is not None:
        y = constrain(y, P(data_axis, None))
    return y
