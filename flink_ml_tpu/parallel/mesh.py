"""Device mesh + sharding helpers — the framework's parallelism substrate.

The reference's only parallelism is data parallelism over Flink subtasks with
hash/rebalance network shuffles (SURVEY §2.10).  Here the equivalent is a
``jax.sharding.Mesh`` with named axes and ``NamedSharding`` annotations; XLA
inserts the collectives (psum/all-gather/reduce-scatter) that replace the
reference's shuffles, and they ride ICI instead of the datacenter network.

Axis convention used across the framework:
- ``"data"``  — batch-dim sharding (the reference's subtask parallelism)
- ``"model"`` — tensor/feature-dim sharding (absent in the reference;
  reserved so TP can be layered on without API change, SURVEY §7)
"""

from __future__ import annotations

import math

from contextlib import contextmanager
from typing import Any, Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.padding import pad_rows_with_mask

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "device_mesh",
    "axis_process_count",
    "data_sharding",
    "fetch_replicated",
    "local_axis_multiple",
    "mesh_process_count",
    "put_sharded",
    "replicated",
    "shard_batch",
    "replicate",
    "default_mesh",
    "use_mesh",
    "local_device_count",
    "pad_rows_with_mask",
]

DATA_AXIS = "data"
MODEL_AXIS = "model"

_DEFAULT_MESH: Optional[Mesh] = None


def local_device_count() -> int:
    """Devices attached to THIS host (on a multi-host pod this differs from
    the global count — size per-host batches with this)."""
    return len(jax.local_devices())


def device_mesh(axis_sizes: Optional[Mapping[str, int]] = None,
                devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a named mesh.

    Default: all devices on one ``"data"`` axis (pure DP, the reference's
    model).  Pass e.g. ``{"data": 4, "model": 2}`` for a DP x TP mesh; a
    ``-1`` size is inferred from the device count.
    """
    devices = list(devices if devices is not None else jax.devices())
    if not axis_sizes:
        axis_sizes = {DATA_AXIS: len(devices)}
    names = list(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    if sizes.count(-1) > 1:
        raise ValueError("At most one mesh axis may be -1")
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        if len(devices) % known:
            raise ValueError(
                f"Cannot infer -1 axis: {len(devices)} devices not divisible "
                f"by {known}")
        sizes[sizes.index(-1)] = len(devices) // known
    if math.prod(sizes) != len(devices):
        raise ValueError(
            f"Mesh {dict(zip(names, sizes))} needs {math.prod(sizes)} devices, "
            f"have {len(devices)}")
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, axis_names=tuple(names))


def default_mesh() -> Mesh:
    """The process-wide default mesh (all devices, one data axis), created
    lazily; override scoped-ly with :func:`use_mesh`."""
    global _DEFAULT_MESH
    if _DEFAULT_MESH is None:
        _DEFAULT_MESH = device_mesh()
    return _DEFAULT_MESH


@contextmanager
def use_mesh(mesh: Mesh):
    global _DEFAULT_MESH
    prev = _DEFAULT_MESH
    _DEFAULT_MESH = mesh
    try:
        yield mesh
    finally:
        _DEFAULT_MESH = prev


def data_sharding(mesh: Optional[Mesh] = None, *,
                  axis: str = DATA_AXIS) -> NamedSharding:
    """Batch-dim sharding: leading dim split over the data axis (the analog
    of the reference's keyBy/rebalance partitioning, ``KMeans.java:181``)."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Fully-replicated sharding (the analog of ``.broadcast()`` model/
    centroid streams, ``KMeans.java:152``)."""
    mesh = mesh or default_mesh()
    return NamedSharding(mesh, P())


def _pad_rows(arr: np.ndarray, multiple: int) -> np.ndarray:
    return pad_rows_with_mask(arr, multiple)[0]


def shard_batch(tree: Any, mesh: Optional[Mesh] = None, *,
                axis: str = DATA_AXIS, pad: bool = True) -> Any:
    """Place a pytree of host arrays with the leading dim sharded over
    ``axis``.  With ``pad=True`` rows are padded (repeating row 0) to a
    multiple of the PER-PROCESS axis size — callers carrying a mask should
    use ``Table.pad_to_multiple`` instead to keep the mask.  On a
    process-spanning mesh each process passes its own rows (the
    :func:`put_sharded` contract)."""
    mesh = mesh or default_mesh()
    if axis not in mesh.shape:
        raise ValueError(f"Mesh has no axis {axis!r}; axes: {list(mesh.shape)}")
    n = local_axis_multiple(mesh, axis)
    spec = P(axis)

    def put(x):
        arr = np.asarray(x)
        if pad and arr.shape and arr.shape[0] % n:
            arr = _pad_rows(arr, n)
        return put_sharded(arr, mesh, spec)

    if axis_process_count(mesh, axis) > 1:
        # unequal per-process shards would infer different global shapes
        # on each host and deadlock the first collective; check up front
        from jax.experimental import multihost_utils

        first = next(iter(jax.tree_util.tree_leaves(tree)), None)
        if first is not None:
            rows = np.asarray(first).shape[0]
            rows += (-rows) % n if pad else 0
            gathered = np.asarray(multihost_utils.process_allgather(
                np.asarray([rows], np.int64))).reshape(-1)
            if not np.all(gathered == gathered[0]):
                raise ValueError(
                    "shard_batch on a process-spanning axis requires equal "
                    f"padded row counts per process; got {gathered.tolist()}")

    return jax.tree_util.tree_map(put, tree)


def mesh_process_count(mesh: Mesh) -> int:
    """Distinct processes owning the mesh's devices (1 = single-host)."""
    return len({d.process_index for d in mesh.devices.flat})


def axis_process_count(mesh: Mesh, axis: str) -> int:
    """Distinct processes along ONE mesh axis (an axis laid out entirely
    within each host counts 1 even on a multi-host mesh).

    Every line along the axis must cross the same number of processes —
    sampling one line on an irregular layout would mis-size per-process
    padding and surface later as an opaque collective/shape error, so
    irregularity raises here instead."""
    ax = list(mesh.axis_names).index(axis)
    devs = np.moveaxis(np.asarray(mesh.devices), ax, 0)
    lines = devs.reshape(devs.shape[0], -1)
    counts = {len({d.process_index for d in lines[:, i]})
              for i in range(lines.shape[1])}
    if len(counts) > 1:
        raise ValueError(
            f"irregular process layout along mesh axis {axis!r}: lines "
            f"cross {sorted(counts)} distinct processes; lay the mesh out "
            "so every line along the axis spans the same process count")
    return counts.pop()


def local_axis_multiple(mesh: Mesh, axis: str = DATA_AXIS,
                        row_multiple: int = 1) -> int:
    """Per-process row-padding multiple for arrays sharded over ``axis``,
    with a clear error for axes that do not divide over their processes."""
    n_axis = int(mesh.shape[axis])
    procs = axis_process_count(mesh, axis)
    if procs > 1 and (n_axis % procs or n_axis < procs):
        raise ValueError(
            f"axis {axis!r} of size {n_axis} does not divide over the "
            f"{procs} processes it spans; shape the mesh with the axis as "
            "a multiple of the process count")
    return (n_axis // procs) * row_multiple


def put_sharded(arr: np.ndarray, mesh: Mesh, spec: P):
    """Place a host array on the mesh under ``spec``: plain device_put on a
    single-host mesh; on a process-spanning mesh each process contributes
    its LOCAL slice along the sharded dims
    (``jax.make_array_from_process_local_data``) and the global array is
    the assembly over processes."""
    sharding = NamedSharding(mesh, spec)
    if mesh_process_count(mesh) > 1:
        return jax.make_array_from_process_local_data(sharding, arr)
    return jax.device_put(arr, sharding)


def assemble_process_local(batch: Any, shardings: Any) -> tuple:
    """Multi-host prefetch transfer: assemble each process's LOCAL batch
    arrays into the global (non-fully-addressable) arrays in process
    order — the ``put_fn`` the streaming trainers hand to
    ``prefetch_to_device`` on process-spanning meshes."""
    return tuple(
        jax.make_array_from_process_local_data(sh, np.asarray(a))
        for a, sh in zip(batch, shardings))


def fetch_replicated(tree: Any) -> Any:
    """device_get that also handles non-fully-addressable arrays
    (multi-host).  A replicated array's local replica IS the global
    value; a sharded one (e.g. the model-axis LR weight of
    ``sgd._mixed_update_sharded``) is assembled with one cross-process
    allgather of its shards — every process gets the full array, the
    same collective-fetch stance as ``iteration/checkpoint.py``."""
    def get(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            if x.sharding.is_fully_replicated:
                return np.asarray(x.addressable_data(0))
            from jax.experimental import multihost_utils

            return np.asarray(
                multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))

    return jax.tree_util.tree_map(get, tree)


def replicate(tree: Any, mesh: Optional[Mesh] = None) -> Any:
    """device_put a pytree fully replicated over the mesh (multi-host-safe:
    on a process-spanning mesh every process must pass identical values)."""
    mesh = mesh or default_mesh()
    sharding = replicated(mesh)
    if mesh_process_count(mesh) > 1:
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(
                sharding, np.asarray(x)), tree)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)
