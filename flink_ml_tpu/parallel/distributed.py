"""Multi-host distributed runtime.

The reference scales out via Flink's cluster runtime: TaskManagers connect
over netty, the JobManager coordinates (SURVEY §2.10 control plane).  The
TPU-native equivalent is the JAX distributed runtime: one process per host,
ICI collectives inside a pod slice, DCN across slices, and a tiny control
plane (this module) for initialization, meshes spanning hosts, host-local ->
global array assembly, and barriers.

Usage on a pod (one process per host):

    from flink_ml_tpu.parallel import distributed as dist
    dist.initialize()                      # env-driven on TPU pods
    mesh = dist.global_mesh({"data": -1})  # all devices on all hosts
    batch = dist.host_local_to_global(local_batch, mesh, axis="data")
    ... iterate(...) exactly as single-host — the jitted step is SPMD ...

Everything degrades gracefully to single-process (the default environment
here and in tests).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Mapping, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import device_mesh

__all__ = [
    "initialize",
    "is_initialized",
    "ProcessInfo",
    "process_info",
    "global_mesh",
    "hybrid_mesh",
    "host_local_to_global",
    "global_to_host_local",
    "barrier",
    "broadcast_from_host0",
]

_INITIALIZED = False


_POD_ENV_VARS = ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                 "MEGASCALE_COORDINATOR_ADDRESS", "CLOUD_TPU_TASK_ID")


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join the JAX distributed runtime (the analog of TaskManagers
    registering with the JobManager).

    MUST run before any other JAX call on multi-host — jax.distributed
    requires an uninitialized backend.  With explicit args the call is
    mandatory and errors propagate; with no args it auto-initializes when a
    pod launcher environment is detected (coordinator env vars) and is a
    no-op single-process.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    explicit = (coordinator_address is not None
                or num_processes not in (None, 1)
                or process_id is not None)
    import os

    pod_env = any(v in os.environ for v in _POD_ENV_VARS)
    if explicit or pod_env:
        # Explicit multi-process request (or launcher env): never silently
        # degrade — failures here mean the job would run single-host.
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    _INITIALIZED = True


def is_initialized() -> bool:
    # Deliberately does NOT probe jax.process_count(): that would initialize
    # the backend, breaking a later initialize() on multi-host.
    return _INITIALIZED


@dataclass
class ProcessInfo:
    process_index: int
    process_count: int
    local_device_count: int
    global_device_count: int

    @property
    def is_coordinator(self) -> bool:
        return self.process_index == 0


def process_info() -> ProcessInfo:
    return ProcessInfo(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=len(jax.local_devices()),
        global_device_count=len(jax.devices()),
    )


def global_mesh(axis_sizes: Optional[Mapping[str, int]] = None) -> Mesh:
    """A mesh over ALL devices across ALL hosts (jax.devices() is global)."""
    return device_mesh(axis_sizes, devices=jax.devices())


def hybrid_mesh(ici_axes: Mapping[str, int], dcn_axis: str = "dcn") -> Mesh:
    """Two-tier mesh: the leading axis spans hosts over DCN, the remaining
    axes span each host's chips over ICI.  Shard batch over ``dcn_axis`` x
    'data' and keep model axes inside a host so heavy collectives ride ICI
    (the scaling-book layout rule)."""
    ici_sizes = list(ici_axes.values())
    n_proc = jax.process_count()
    expected = n_proc * int(np.prod(ici_sizes))
    if expected != len(jax.devices()):
        raise ValueError(
            f"hybrid mesh {n_proc} hosts x {dict(ici_axes)} needs {expected} "
            f"devices, have {len(jax.devices())}")
    if n_proc == 1:
        devices = np.asarray(jax.devices()).reshape((1, *ici_sizes))
        return Mesh(devices, axis_names=(dcn_axis, *ici_axes.keys()))
    from jax.experimental import mesh_utils

    # create_hybrid_device_mesh takes same-rank per-granule and DCN shapes;
    # the total mesh is their elementwise product.
    dev_array = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=[1] + ici_sizes,
        dcn_mesh_shape=[n_proc] + [1] * len(ici_sizes),
    )
    return Mesh(dev_array.reshape((n_proc, *ici_sizes)),
                axis_names=(dcn_axis, *ici_axes.keys()))


def host_local_to_global(tree: Any, mesh: Mesh, axis: str = "data") -> Any:
    """Assemble per-host local batches into one global sharded array (each
    host contributes its shard — the multi-host input pipeline step; wraps
    ``multihost_utils.host_local_array_to_global_array``)."""
    if jax.process_count() == 1:
        sharding = NamedSharding(mesh, P(axis))
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sharding), tree)
    from jax.experimental import multihost_utils

    return multihost_utils.host_local_array_to_global_array(
        tree, mesh, P(axis))


def global_to_host_local(tree: Any, mesh: Mesh, axis: str = "data") -> Any:
    """Inverse of :func:`host_local_to_global`."""
    if jax.process_count() == 1:
        return jax.tree_util.tree_map(np.asarray, jax.device_get(tree))
    from jax.experimental import multihost_utils

    return multihost_utils.global_array_to_host_local_array(
        tree, mesh, P(axis))


def barrier(tag: str = "flink_ml_tpu") -> None:
    """Cross-host barrier (the control-plane alignment point; no-op
    single-process)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(tag)


def broadcast_from_host0(tree: Any) -> Any:
    """Make host 0's value visible on every process (the analog of the
    coordinator fanning out a GloballyAlignedEvent payload)."""
    if jax.process_count() == 1:
        return tree
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(tree)
