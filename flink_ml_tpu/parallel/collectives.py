"""Collective helpers for explicit-SPMD iteration bodies.

The reference's data plane is Flink's netty shuffle chosen by partitioners
(SURVEY §2.10); the TPU-native data plane is XLA collectives over ICI.  Most
bodies never call these directly — jit + NamedSharding lets XLA insert them —
but explicit ``shard_map`` bodies (ring attention, custom reductions, the
termination vote) use this thin, named vocabulary.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "psum",
    "pmean",
    "pmax",
    "all_gather",
    "reduce_scatter",
    "ppermute_ring",
    "axis_index",
    "axis_size",
    "shard_map_fn",
    "sparse_all_reduce",
    "quantized_all_reduce",
]


def psum(x: Any, axis: str) -> Any:
    """All-reduce sum over a mesh axis (the gradient/centroid aggregation
    that replaces the reference's keyed reduce + network shuffle)."""
    return lax.psum(x, axis)


def pmean(x: Any, axis: str) -> Any:
    return lax.pmean(x, axis)


def pmax(x: Any, axis: str) -> Any:
    return lax.pmax(x, axis)


def all_gather(x: Any, axis: str, *, tiled: bool = True) -> Any:
    """Gather shards along the leading dim (the broadcast-variable fan-in)."""
    return lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x: Any, axis: str, *, scatter_dimension: int = 0) -> Any:
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dimension,
                            tiled=True)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    """Static size of a bound mesh axis.  Newer JAX has ``lax.axis_size``;
    on older releases ``lax.psum(1, axis)`` of a Python literal constant-
    folds to the same static int."""
    size = getattr(lax, "axis_size", None)
    if size is not None:
        return size(axis)
    return lax.psum(1, axis)


def sparse_all_reduce(idx: jnp.ndarray, vals: jnp.ndarray, n: int,
                      axes) -> jnp.ndarray:
    """All-gather form of a sparse all-reduce over one flat length-``n``
    segment: each participant contributes ``k`` (index, value) pairs, and
    every participant scatter-adds the gathered pairs locally.  THE
    bucket-reduce primitive of ``grad_reduce``'s top-k modes — each call
    is one independent pair of ``all_gather``s with no data dependence on
    any other bucket or on the step's compute, which is exactly what lets
    XLA's latency-hiding scheduler overlap bucket ``k`` of step ``n``
    with step ``n+1``'s forward/backward."""
    all_idx = lax.all_gather(idx, axes)        # (P, k)
    all_vals = lax.all_gather(vals, axes)
    return jnp.zeros((n,), vals.dtype).at[all_idx.reshape(-1)].add(
        all_vals.reshape(-1))


def quantized_all_reduce(q: jnp.ndarray, scale: jnp.ndarray,
                         axes) -> jnp.ndarray:
    """Dequantize-and-sum all-reduce of one block-quantized segment:
    ``q`` (nb, block) int8 payload + ``scale`` (nb, 1) f32 per-block
    scales are all-gathered and summed locally.  Like
    :func:`sparse_all_reduce`, one independent collective pair per call —
    the schedulable unit of the bucketed int8 reduce."""
    all_q = lax.all_gather(q, axes)            # (P, nb, block)
    all_scale = lax.all_gather(scale, axes)    # (P, nb, 1)
    return jnp.sum(all_q.astype(jnp.float32) * all_scale, axis=0)


def ppermute_ring(x: Any, axis: str, *, shift: int = 1) -> Any:
    """Rotate shards around the ring formed by a mesh axis (the KV rotation
    of ring attention; rides neighbor ICI links only)."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def shard_map_fn(fn: Callable, mesh: Mesh, in_specs, out_specs,
                 check_vma: bool = False, **kwargs) -> Callable:
    """``jax.shard_map`` with this framework's default flags — THE compat
    shim for every explicit-SPMD body in the repo: newer JAX exposes
    ``jax.shard_map(check_vma=...)``, older releases only
    ``jax.experimental.shard_map.shard_map(check_rep=...)``; both mean
    "skip the replication/varying-axes check" (off here because
    ``pallas_call`` out_shapes carry no varying-mesh-axes annotation).
    Extra ``kwargs`` (e.g. ``auto=``) pass through untouched."""
    import inspect

    sm = getattr(jax, "shard_map", None)
    if sm is None:  # older JAX
        from jax.experimental.shard_map import shard_map as sm  # type: ignore

    params = inspect.signature(sm).parameters
    if "check_vma" in params:
        kwargs["check_vma"] = check_vma
    elif "check_rep" in params:
        kwargs["check_rep"] = check_vma
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              **kwargs)
