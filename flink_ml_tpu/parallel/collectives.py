"""Collective helpers for explicit-SPMD iteration bodies.

The reference's data plane is Flink's netty shuffle chosen by partitioners
(SURVEY §2.10); the TPU-native data plane is XLA collectives over ICI.  Most
bodies never call these directly — jit + NamedSharding lets XLA insert them —
but explicit ``shard_map`` bodies (ring attention, custom reductions, the
termination vote) use this thin, named vocabulary.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "FILL_VEC_LEN",
    "psum",
    "pmean",
    "pmax",
    "all_gather",
    "reduce_scatter",
    "ppermute_ring",
    "axis_index",
    "axis_size",
    "shard_map_fn",
    "sparse_all_reduce",
    "sparse_all_reduce_rd",
    "fixed_point_all_reduce",
    "quantized_all_reduce",
    "rd_topology",
]

# Fixed layout of the per-call fill-in vector returned by
# :func:`sparse_all_reduce_rd`.  The slot count is independent of the
# participant count (rounds <= FILL_ROUND_SLOTS, i.e. hops up to 2**16
# participants) so reducer state carrying these vectors keeps ONE static
# shape across elastic resizes — the same invariant every other
# grad_reduce state leaf obeys.
FILL_ROUND_SLOTS = 16                           # halving slots [0, 16)
FILL_DOUBLING_BASE = FILL_ROUND_SLOTS           # doubling slots [16, 32)
FILL_UNION_SLOT = 2 * FILL_ROUND_SLOTS          # 32: union |support| count
FILL_SWITCH_SLOT = FILL_UNION_SLOT + 1          # 33: 1.0 if densified
FILL_PREFOLD_SLOT = FILL_SWITCH_SLOT + 1        # 34: entries sent pre-fold
FILL_POSTFOLD_SLOT = FILL_PREFOLD_SLOT + 1      # 35: elements sent post-fold
FILL_VEC_LEN = FILL_POSTFOLD_SLOT + 1           # 36


def psum(x: Any, axis: str) -> Any:
    """All-reduce sum over a mesh axis (the gradient/centroid aggregation
    that replaces the reference's keyed reduce + network shuffle)."""
    return lax.psum(x, axis)


def pmean(x: Any, axis: str) -> Any:
    return lax.pmean(x, axis)


def pmax(x: Any, axis: str) -> Any:
    return lax.pmax(x, axis)


def all_gather(x: Any, axis: str, *, tiled: bool = True) -> Any:
    """Gather shards along the leading dim (the broadcast-variable fan-in)."""
    return lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x: Any, axis: str, *, scatter_dimension: int = 0) -> Any:
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dimension,
                            tiled=True)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str) -> int:
    """Static size of a bound mesh axis.  Newer JAX has ``lax.axis_size``;
    on older releases ``lax.psum(1, axis)`` of a Python literal constant-
    folds to the same static int."""
    size = getattr(lax, "axis_size", None)
    if size is not None:
        return size(axis)
    return lax.psum(1, axis)


def sparse_all_reduce(idx: jnp.ndarray, vals: jnp.ndarray, n: int,
                      axes) -> jnp.ndarray:
    """All-gather form of a sparse all-reduce over one flat length-``n``
    segment: each participant contributes ``k`` (index, value) pairs, and
    every participant scatter-adds the gathered pairs locally.  The
    LEGACY wire protocol of ``grad_reduce``'s top-k modes — every
    participant receives all P contributions (``(P-1) * 8k`` bytes), the
    P-fold redundancy SparCML's split-allreduce removes; kept as the
    oracle/fallback for multi-axis reductions, with
    :func:`sparse_all_reduce_rd` as the topology-aware replacement.
    Each call is one independent pair of ``all_gather``s with no data
    dependence on any other bucket or on the step's compute, which is
    exactly what lets XLA's latency-hiding scheduler overlap bucket
    ``k`` of step ``n`` with step ``n+1``'s forward/backward."""
    all_idx = lax.all_gather(idx, axes)        # (P, k)
    all_vals = lax.all_gather(vals, axes)
    return jnp.zeros((n,), vals.dtype).at[all_idx.reshape(-1)].add(
        all_vals.reshape(-1))


def rd_topology(p: int) -> Tuple[int, int, int]:
    """``(core, rounds, extras)`` of the recursive-halving/doubling
    schedule over ``p`` participants: a ``core = 2**floor(log2 p)`` rank
    group runs the log2 rounds; the ``extras = p - core`` leftover ranks
    fold their contribution in before round one and receive the result
    after the last round (the standard non-power-of-two embedding)."""
    if p < 1:
        raise ValueError(f"participant count must be >= 1, got {p}")
    core = 1 << (p.bit_length() - 1)
    rounds = core.bit_length() - 1
    if rounds > FILL_ROUND_SLOTS:
        raise ValueError(
            f"hop of {p} participants needs {rounds} rounds; the fill "
            f"accounting layout caps at {FILL_ROUND_SLOTS}")
    return core, rounds, p - core


def _merge_dedup(idx_a: jnp.ndarray, val_a: jnp.ndarray,
                 idx_b: jnp.ndarray, val_b: jnp.ndarray,
                 sentinel: int, cap: int
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Union two (idx, val) sets, summing values at duplicate indices.
    Invalid entries carry ``idx == sentinel`` (> every real index) and
    ``val == 0``; the output is sorted by index, compacted to the front,
    sentinel-padded, and sliced to ``cap`` (the caller guarantees the
    distinct count fits)."""
    idx = jnp.concatenate([idx_a, idx_b])
    val = jnp.concatenate([val_a, val_b])
    if idx.shape[0] == 0:
        return idx[:cap], val[:cap]
    order = jnp.argsort(idx)
    idx, val = idx[order], val[order]
    first = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), idx[1:] != idx[:-1]])
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1
    m = idx.shape[0]
    out_val = jnp.zeros((m,), val.dtype).at[seg].add(val)
    out_idx = jnp.full((m,), sentinel, idx.dtype).at[seg].min(idx)
    return out_idx[:cap], out_val[:cap]


def sparse_all_reduce_rd(idx: jnp.ndarray, vals: jnp.ndarray, n: int,
                         axis: str,
                         uniform_axes: Optional[Tuple[str, ...]] = None
                         ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Recursive-halving/doubling sparse all-reduce over ONE named axis
    (SparCML's split-allreduce, arXiv:1802.08021): log2(P) halving
    rounds of pairwise ``ppermute`` exchanges route each (index, value)
    set toward the rank that owns its index range — merging partner sets
    with duplicate-index summation at every hop — then log2(P) doubling
    rounds gather the reduced pieces back.  Fill-in (the union support
    growing round over round) is measured, not assumed: when the psum'd
    union count densifies past break-even (sparse doubling at 8 B/entry
    would ship more than dense doubling at 4 B/element, i.e.
    ``2*|union| > n_pad``), the doubling phase switches to dense block
    exchanges — a ``lax.cond`` whose predicate is psum-derived, so every
    participant switches together.  Non-power-of-two P runs a
    ``2**floor(log2 P)`` core with pre/post folding
    (:func:`rd_topology`).

    ``uniform_axes``: every mesh axis of the enclosing ``shard_map``
    whose shards run this reduce concurrently.  The switchover ``cond``
    holds collectives, so its predicate must be identical on EVERY
    device in the program, not just within this hop's subgroup —
    sibling groups along the other axes (e.g. the ICI columns of a
    hierarchical reduce, each compressing a different gradient shard)
    reaching different branches is an XLA collective-order deadlock.
    The union count is therefore ``pmax``'d over the non-hop axes
    before the comparison: one group past break-even switches them
    all.  The ``fill`` union slot still reports THIS group's union —
    accounting stays per-group truth; only the decision is global.

    Contract matches :func:`sparse_all_reduce`: ``0 <= idx < n``
    (duplicate indices within one contribution sum; out-of-range entries
    are dropped), result is the elementwise sum of every participant's
    scattered contribution.  f32 summation ORDER differs from the
    all-gather form (tree order vs gather order), so exact-mode A/B is
    asserted elementwise-close, not bitwise, by callers.

    Returns ``(dense_result (n,), fill (FILL_VEC_LEN,) f32)`` — the fill
    vector carries per-round sent-entry counts (halving slots [0, 16),
    doubling slots [16, 32)), the union count, the switchover flag, and
    the pre/post fold traffic, in the fixed layout the module constants
    name.  grad_reduce carries an EMA of it in reducer state and
    ``payload_bytes`` turns it into measured bytes-on-wire."""
    p = axis_size(axis)
    k = int(idx.shape[0])
    dtype = vals.dtype
    fill = jnp.zeros((FILL_VEC_LEN,), jnp.float32)
    if p == 1 or k == 0:
        dense = jnp.zeros((n,), dtype).at[idx].add(vals, mode="drop")
        return dense, fill
    core, rounds, extras = rd_topology(p)
    n_pad = -(-n // core) * core
    sentinel = n_pad
    rank = lax.axis_index(axis)
    is_core = rank < core

    ok = (idx >= 0) & (idx < n)
    cur_i = jnp.where(ok, idx.astype(jnp.int32), sentinel)
    cur_v = jnp.where(ok, vals, 0).astype(dtype)
    empty_i = jnp.zeros((0,), jnp.int32)
    empty_v = jnp.zeros((0,), dtype)
    # dedup within this participant's own contribution (also compacts)
    cur_i, cur_v = _merge_dedup(cur_i, cur_v, empty_i, empty_v,
                                sentinel, k)
    cnt = jnp.sum(cur_i < sentinel)
    cap = k

    # -- pre-fold: extras hand their set to rank (self - core) ------------
    if extras:
        perm = [(core + i, i) for i in range(extras)]
        r_i = lax.ppermute(cur_i, axis, perm)
        r_v = lax.ppermute(cur_v, axis, perm)
        r_c = lax.ppermute(cnt, axis, perm)
        valid = jnp.arange(k) < r_c          # non-receivers get zeros
        r_i = jnp.where(valid, r_i, sentinel)
        r_v = jnp.where(valid, r_v, 0)
        m_i, m_v = _merge_dedup(cur_i, cur_v, r_i, r_v, sentinel, 2 * k)
        pad_i = jnp.full((2 * k,), sentinel, jnp.int32)
        cur_i = jnp.where(is_core, m_i, pad_i)
        cur_v = jnp.where(is_core, m_v, 0)
        fill = fill.at[FILL_PREFOLD_SLOT].set(
            jnp.where(is_core, 0, cnt).astype(jnp.float32))
        cap = 2 * k

    # -- recursive halving: route entries to their range owner ------------
    lo = jnp.zeros((), jnp.int32)
    width = n_pad
    for r in range(rounds):
        dist = core >> (r + 1)
        half = width // 2
        mid = lo + half
        bit = (rank >> (rounds - 1 - r)) & 1
        send_mask = jnp.where(bit == 0, cur_i >= mid, cur_i < mid)
        send_i = jnp.where(send_mask, cur_i, sentinel)
        send_v = jnp.where(send_mask, cur_v, 0)
        sent = jnp.sum(send_mask & (cur_i < sentinel))
        perm = [(i, i ^ dist) for i in range(core)]
        r_i = lax.ppermute(send_i, axis, perm)
        r_v = lax.ppermute(send_v, axis, perm)
        keep_i = jnp.where(send_mask, sentinel, cur_i)
        keep_v = jnp.where(send_mask, 0, cur_v)
        cap_next = min(2 * cap, half)
        cur_i, cur_v = _merge_dedup(keep_i, keep_v, r_i, r_v,
                                    sentinel, cap_next)
        cap = cap_next
        lo = jnp.where(bit == 0, lo, mid)
        width = half
        fill = fill.at[r].set(sent.astype(jnp.float32))

    # -- measured fill-in decides the doubling wire format ----------------
    cnt = jnp.sum(cur_i < sentinel)
    union = lax.psum(jnp.where(is_core, cnt, 0), axis)
    switch_stat = union
    sibling_axes = tuple(a for a in (uniform_axes or ()) if a != axis)
    if sibling_axes:
        switch_stat = lax.pmax(switch_stat, sibling_axes)
    switched = (2 * switch_stat) > n_pad
    w = n_pad // core

    def _sparse_doubling(args):
        ci, cv, _ = args
        d = []
        for j in range(rounds):
            dist = 1 << j
            perm = [(i, i ^ dist) for i in range(core)]
            d.append(jnp.sum(ci < sentinel).astype(jnp.float32))
            r_i = lax.ppermute(ci, axis, perm)
            r_v = lax.ppermute(cv, axis, perm)
            # partner ranges are disjoint from mine: concat, no dedup
            ci = jnp.concatenate([ci, r_i])
            cv = jnp.concatenate([cv, r_v])
        dense = jnp.zeros((n_pad,), dtype).at[ci].add(cv, mode="drop")
        return dense, jnp.stack(d)

    def _dense_doubling(args):
        ci, cv, lo_ = args
        dense = jnp.zeros((n_pad,), dtype).at[ci].add(cv, mode="drop")
        d = []
        for j in range(rounds):
            dist = 1 << j
            size = w << j
            start = ((rank >> j) << j) * w
            piece = lax.dynamic_slice(dense, (start,), (size,))
            perm = [(i, i ^ dist) for i in range(core)]
            recv = lax.ppermute(piece, axis, perm)
            partner_start = (((rank ^ dist) >> j) << j) * w
            dense = lax.dynamic_update_slice(dense, recv,
                                             (partner_start,))
            d.append(jnp.float32(size))
        return dense, jnp.stack(d)

    dense, d_sent = lax.cond(switched, _dense_doubling, _sparse_doubling,
                             (cur_i, cur_v, lo))
    fill = lax.dynamic_update_slice(fill, d_sent, (FILL_DOUBLING_BASE,))
    fill = fill.at[FILL_UNION_SLOT].set(union.astype(jnp.float32))
    fill = fill.at[FILL_SWITCH_SLOT].set(switched.astype(jnp.float32))

    # -- post-fold: result back out to the extras -------------------------
    if extras:
        perm = [(i, core + i) for i in range(extras)]
        recv = lax.ppermute(dense, axis, perm)
        dense = jnp.where(is_core, dense, recv)
        fill = fill.at[FILL_POSTFOLD_SLOT].set(jnp.where(
            rank < extras, jnp.float32(n_pad), jnp.float32(0)))
        # extras' round slots carry garbage from the rounds they sat out
        round_mask = jnp.arange(FILL_VEC_LEN) < FILL_UNION_SLOT
        fill = jnp.where(jnp.logical_and(round_mask,
                                         jnp.logical_not(is_core)),
                         0.0, fill)
    return dense[:n], fill


def fixed_point_all_reduce(q: jnp.ndarray, axis: str) -> jnp.ndarray:
    """Exact int32 all-reduce by recursive doubling over ONE named axis:
    log2(P) pairwise ``ppermute`` exchanges, each hop ADDING integer
    payloads — SwitchML's in-fabric pool semantics (arXiv:1903.06701)
    emulated per hop, so quantization error stays one rounding per
    participant no matter how many hops the sum crosses, and the result
    is bit-identical on every participant (integer addition is exactly
    associative).  Non-power-of-two P folds the extras in before round
    one and broadcasts the total back after the last round."""
    p = axis_size(axis)
    if p == 1:
        return q
    core, rounds, extras = rd_topology(p)
    rank = lax.axis_index(axis)
    if extras:
        perm = [(core + i, i) for i in range(extras)]
        recv = lax.ppermute(q, axis, perm)   # non-receivers: int zeros
        q = q + recv
    for j in range(rounds):
        dist = 1 << j
        perm = [(i, i ^ dist) for i in range(core)]
        recv = lax.ppermute(q, axis, perm)
        q = q + recv
    if extras:
        perm = [(i, core + i) for i in range(extras)]
        recv = lax.ppermute(q, axis, perm)
        q = jnp.where(rank >= core, recv, q)
    return q


def quantized_all_reduce(q: jnp.ndarray, scale: jnp.ndarray,
                         axes) -> jnp.ndarray:
    """Dequantize-and-sum all-reduce of one block-quantized segment:
    ``q`` (nb, block) int8 payload + ``scale`` (nb, 1) f32 per-block
    scales are all-gathered and summed locally.  Like
    :func:`sparse_all_reduce`, one independent collective pair per call —
    the schedulable unit of the bucketed int8 reduce.

    This f32 dequantize-THEN-sum is the **legacy accumulation**
    (``GradReduceConfig.int8_accum="dequant"``, the default): each
    participant's payload is dequantized against its OWN scale before
    the f32 sum, so P stochastic roundings accumulate.  The int32-hop
    alternative (``int8_accum="fixed"``) shares one ``pmax`` scale per
    hop and sums integer codes through :func:`fixed_point_all_reduce`,
    dequantizing once — the two agree within the shared-scale quantum
    envelope (cross-checked in ``tests/test_grad_reduce.py``; an
    agreement envelope, not bit-equality — the orders round
    differently by design)."""
    all_q = lax.all_gather(q, axes)            # (P, nb, block)
    all_scale = lax.all_gather(scale, axes)    # (P, nb, 1)
    return jnp.sum(all_q.astype(jnp.float32) * all_scale, axis=0)


def ppermute_ring(x: Any, axis: str, *, shift: int = 1) -> Any:
    """Rotate shards around the ring formed by a mesh axis (the KV rotation
    of ring attention; rides neighbor ICI links only)."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def shard_map_fn(fn: Callable, mesh: Mesh, in_specs, out_specs,
                 check_vma: bool = False, **kwargs) -> Callable:
    """``jax.shard_map`` with this framework's default flags — THE compat
    shim for every explicit-SPMD body in the repo: newer JAX exposes
    ``jax.shard_map(check_vma=...)``, older releases only
    ``jax.experimental.shard_map.shard_map(check_rep=...)``; both mean
    "skip the replication/varying-axes check" (off here because
    ``pallas_call`` out_shapes carry no varying-mesh-axes annotation).
    Extra ``kwargs`` (e.g. ``auto=``) pass through untouched."""
    import inspect

    sm = getattr(jax, "shard_map", None)
    if sm is None:  # older JAX
        from jax.experimental.shard_map import shard_map as sm  # type: ignore

    params = inspect.signature(sm).parameters
    if "check_vma" in params:
        kwargs["check_vma"] = check_vma
    elif "check_rep" in params:
        kwargs["check_rep"] = check_vma
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              **kwargs)
