"""Pipeline parallelism — GPipe-style microbatch scheduling over a mesh axis.

The reference has no model parallelism of any kind (its only axis is Flink
subtask data parallelism, SURVEY §2.10); the mesh API here reserved room for
model axes (SURVEY §7, `parallel/mesh.py`).  This module fills the "pp" slot:
layer stages are placed one-per-device along a ``"pipe"`` mesh axis and
microbatches flow through the ring with ``lax.ppermute`` — every hop is one
neighbor ICI link, never DCN.

Design (TPU-first, not a port):
- The schedule is a single ``lax.scan`` of ``n_micro + P - 1`` steps compiled
  into one XLA program: no host round-trips between microbatches, and XLA
  overlaps the ppermute with the next step's stage compute.
- The whole thing is differentiable: ``jax.grad`` through the scan+ppermute
  yields the reverse-order backward pipeline automatically — no hand-written
  1F1B schedule is needed for correctness (it costs one extra activation
  stash per in-flight microbatch, the usual GPipe memory shape).
- Stages must be shape-homogeneous (each maps ``(mb, d) -> (mb, d)``), the
  standard condition for ring pipelining.

Composes with the other axes: batch dims can stay sharded over ``"data"``
while stages split over ``"pipe"`` (tested on the 8-device CPU mesh).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .collectives import axis_size, ppermute_ring, shard_map_fn

__all__ = ["PIPE_AXIS", "pipeline_apply", "build_pipeline"]

PIPE_AXIS = "pipe"


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any, xs: jax.Array, *,
                   axis: str = PIPE_AXIS) -> jax.Array:
    """Run a P-stage pipeline over microbatches.  **Call inside shard_map**
    (or use :func:`build_pipeline` which wraps this).

    Per-device view: ``stage_params`` is THIS device's stage parameters,
    ``xs`` is the full ``(n_micro, mb, ...)`` microbatch stack (stage 0 reads
    it; other stages receive activations from their ring predecessor).
    Returns the ``(n_micro, mb, ...)`` outputs of the LAST stage on every
    device (combined with a masked psum).
    """
    n_stages = axis_size(axis)
    idx = lax.axis_index(axis)
    n_micro = xs.shape[0]
    n_steps = n_micro + n_stages - 1

    def step(carry, t):
        act, outs = carry
        # Stage 0 injects microbatch t (clamped in the drain phase where no
        # new work enters); later stages consume the ring-permuted activation.
        mb_in = lax.dynamic_index_in_dim(
            xs, jnp.clip(t, 0, n_micro - 1), 0, keepdims=False)
        inp = jnp.where(idx == 0, mb_in, act)
        y = stage_fn(stage_params, inp)
        # The last stage finishes microbatch t-(P-1) at step t.
        o = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        cur = lax.dynamic_index_in_dim(outs, o, 0, keepdims=False)
        write = jnp.logical_and(idx == n_stages - 1, t >= n_stages - 1)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(write, y, cur), o, 0)
        act = ppermute_ring(y, axis)
        return (act, outs), None

    act0 = jnp.zeros(xs.shape[1:], xs.dtype)
    outs0 = jnp.zeros_like(xs)
    (_, outs), _ = lax.scan(step, (act0, outs0),
                            jnp.arange(n_steps, dtype=jnp.int32))
    # Only the last stage holds real outputs (everyone else still has the
    # zeros init); the psum both selects them and replicates across the axis.
    return lax.psum(jnp.where(idx == n_stages - 1, outs, 0.0), axis)


def build_pipeline(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   mesh: Mesh, *, n_micro: int, axis: str = PIPE_AXIS,
                   data_axis: Optional[str] = None) -> Callable:
    """Wrap :func:`pipeline_apply` into a jitted batch-level function.

    ``fn(stacked_params, batch) -> out`` where ``stacked_params`` has a
    leading stage dimension of size ``mesh.shape[axis]`` on every leaf and
    ``batch`` is ``(B, ...)`` with ``B`` divisible by ``n_micro``.  With
    ``data_axis`` set, the microbatch dim stays sharded over it (dp x pp).
    """
    if axis not in mesh.shape:
        raise ValueError(f"Mesh has no axis {axis!r}; axes: {list(mesh.shape)}")
    n_stages = int(mesh.shape[axis])

    param_spec = P(axis)
    xs_spec = P(None, data_axis) if data_axis else P(None)

    @partial(shard_map_fn, mesh=mesh,
             in_specs=(param_spec, xs_spec), out_specs=xs_spec)
    def sharded(stacked_params, xs):
        # shard_map leaves a leading stage dim of 1 on every param leaf.
        local = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
        return pipeline_apply(stage_fn, local, xs, axis=axis)

    @jax.jit
    def fn(stacked_params, batch):
        b = batch.shape[0]
        if b % n_micro:
            raise ValueError(f"batch {b} not divisible by n_micro={n_micro}")
        leaf = jax.tree_util.tree_leaves(stacked_params)[0]
        if leaf.shape[0] != n_stages:
            raise ValueError(
                f"params leading dim {leaf.shape[0]} != pipe axis {n_stages}")
        xs = batch.reshape(n_micro, b // n_micro, *batch.shape[1:])
        out = sharded(stacked_params, xs)
        return out.reshape(b, *batch.shape[1:])

    return fn
