from .mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    default_mesh,
    device_mesh,
    local_device_count,
    replicate,
    replicated,
    shard_batch,
    use_mesh,
)
