from .mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    default_mesh,
    device_mesh,
    local_device_count,
    replicate,
    replicated,
    shard_batch,
    use_mesh,
)
from . import collectives  # noqa: F401
from .ring_attention import attention_reference, ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from . import distributed  # noqa: F401
