from .mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    default_mesh,
    device_mesh,
    local_device_count,
    replicate,
    replicated,
    shard_batch,
    use_mesh,
)
from . import collectives  # noqa: F401
from . import grad_reduce  # noqa: F401
from .grad_reduce import GradReduceConfig  # noqa: F401
from . import elastic  # noqa: F401
from .elastic import ElasticCoordinator, ResizeRequested  # noqa: F401
from .moe import (  # noqa: F401
    EXPERT_AXIS,
    MoEParams,
    init_moe,
    moe_apply,
    moe_sharding,
)
from .pipeline_parallel import (  # noqa: F401
    PIPE_AXIS,
    build_pipeline,
    pipeline_apply,
)
from .ring_attention import attention_reference, ring_attention  # noqa: F401
from .ulysses import ulysses_attention  # noqa: F401
from . import distributed  # noqa: F401
