"""Elastic data-parallel membership — the fleet as a runtime input.

The meshes every fit in this repo trains on were frozen at ``fit()``
time: one preempted worker killed the run, and ``resilient_fit`` could
only restart onto the *same* fleet.  MLFabric (PAPERS.md) treats
membership as an input the scheduler reacts to; at production scale
preemption is routine, so this module makes the **dcn axis of the
hybrid mesh grow and shrink between chunk boundaries**:

- :class:`ElasticCoordinator` — a heartbeat **lease table** over live
  workers with an injected clock (`clock=`), so lease expiry is a
  deterministic, testable event rather than a wall-clock race.  Each
  worker owns ``chips_per_worker`` devices from a fixed pool; the
  current fleet materializes as a ``(dcn, data)`` mesh over the live
  workers' devices in join order.
- Membership churn is **injectable through the fault seams**: the
  streaming fits call :meth:`ElasticCoordinator.poll` once per chunk
  boundary, which fires the ``elastic.membership`` fault scope — a
  scheduled ``"join"`` / ``"preempt"`` fault (:mod:`..robustness.faults`)
  becomes a deterministic join/leave transition, so chaos tests replay
  bit-identically, schedule for schedule, exactly like crash injection.
- A **resize is a restore onto a different mesh**: when ``poll``
  reports a changed fleet, the fit cuts a chunk-boundary checkpoint
  (PR 5 layout, now carrying mesh-shape metadata) and raises
  :class:`ResizeRequested`; ``resilient_fit(elastic=...)`` rebuilds the
  mesh at the new dcn extent and re-runs with ``resume=True``.  The
  restore re-shards the full training carry — params/optimizer state
  replicate onto the new mesh, and the participant-stacked reducer
  state (EF residuals, ``pending`` overlap buffers, adaptive
  rung/EMA/tick, rounding keys) routes through
  :func:`~.grad_reduce.reshard_state`, which re-embeds residuals at
  their new shard slices the way the PR 3 hierarchical composition
  already does.

Exactness contract: a resize at a chunk boundary is **bit-exact vs a
fixed fleet of the new size** restoring the same cut (same reduce
order — both sides route through the same reshard mapping and the same
compiled program).  A worker *death mid-chunk* degrades to the existing
crash path: the supervisor revokes the victim's lease
(:meth:`ElasticCoordinator.on_failure`) and recovery resumes from the
newest valid cut onto the surviving fleet.  Both transitions share one
code path and one ``RecoveryReport``.
"""

from __future__ import annotations

import threading
import time

from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["ElasticCoordinator", "FleetView", "ResizeRequested",
           "WorkerLease", "MEMBERSHIP_SCOPE"]

#: The fault scope :meth:`ElasticCoordinator.poll` fires once per chunk
#: boundary — schedule ``"preempt"`` / ``"join"`` faults against it to
#: drive deterministic membership churn (indices count chunk boundaries
#: across the whole supervised run, attempts included).
MEMBERSHIP_SCOPE = "elastic.membership"


class ResizeRequested(RuntimeError):
    """Raised by an elastic fit at a chunk boundary AFTER the boundary
    checkpoint is durable: membership changed, so training must restore
    onto the new fleet's mesh.  Handled by
    ``resilient_fit(elastic=...)`` — reaching user code means a fit ran
    with ``membership=`` but without an elastic supervisor."""

    def __init__(self, *, step: int, fleet_size: int,
                 membership_epoch: int):
        super().__init__(
            f"fleet changed to {fleet_size} worker(s) (membership epoch "
            f"{membership_epoch}) at step {step}; restore onto the new "
            "mesh")
        self.step = step
        self.fleet_size = fleet_size
        self.membership_epoch = membership_epoch


@dataclass
class WorkerLease:
    """One worker's seat in the fleet: the devices it contributes and
    the heartbeat lease that keeps it alive.  ``expires_at`` is in the
    coordinator's injected clock domain; ``order`` is the join order
    (the deterministic LIFO victim rule keys on it)."""

    worker_id: str
    devices: Tuple[Any, ...]
    joined_at: float
    expires_at: float
    order: int


@dataclass(frozen=True)
class FleetView:
    """An immutable snapshot of membership: what :meth:`mesh` was built
    from, and what the obs gauges export."""

    epoch: int
    workers: Tuple[str, ...]

    @property
    def size(self) -> int:
        return len(self.workers)


class ElasticCoordinator:
    """Heartbeat lease table + mesh factory for an elastic dcn fleet.

    Workers own ``chips_per_worker`` devices from ``devices`` (default:
    every local device), assigned lowest-free-first so the fleet's
    device layout — and therefore the mesh, the programs, and the
    numerics — is a pure function of the transition history.  The mesh
    is ``{dcn_axis: fleet_size, data_axis: chips_per_worker}`` over the
    live workers' devices in join order: heavy collectives ride the
    intra-worker axis, the elastic (resized) extent is the leading dcn
    axis — the ``hybrid_mesh`` layout with the host dimension made
    dynamic.

    Transitions:

    - :meth:`register` / :meth:`leave` — planned join/leave;
    - :meth:`fail` — unplanned death (lease revoked; the supervisor's
      :meth:`on_failure` calls this with the deterministic LIFO victim
      when a crash carries no worker identity);
    - :meth:`expire` — clock-driven: a worker whose lease lapsed
      (missed heartbeats past ``lease_timeout_s``) is declared dead.
      ``lease_timeout_s=None`` (the single-process harness default)
      disables expiry — transitions then come only from explicit calls
      and injected faults.

    Every transition bumps ``membership_epoch`` and appends to
    ``transitions`` (the audit log chaos tests read, the
    ``plan.fires`` analog).  ``min_workers``/``max_workers`` bound the
    fleet; a transition that would cross a bound is *suppressed* and
    counted (``suppressed``) rather than raised — a chaos schedule must
    not be able to kill the run by shrinking past the floor.
    """

    SCOPE = MEMBERSHIP_SCOPE

    def __init__(self, *, chips_per_worker: int = 1,
                 initial_workers: Optional[int] = None,
                 min_workers: int = 1,
                 max_workers: Optional[int] = None,
                 lease_timeout_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 devices: Optional[List[Any]] = None,
                 dcn_axis: str = "dcn", data_axis: str = "data"):
        import jax

        if chips_per_worker < 1:
            raise ValueError("chips_per_worker must be >= 1")
        self._pool: List[Any] = list(
            devices if devices is not None else jax.devices())
        pool_max = len(self._pool) // chips_per_worker
        if pool_max < 1:
            raise ValueError(
                f"device pool of {len(self._pool)} cannot seat one worker "
                f"of {chips_per_worker} chip(s)")
        self.chips_per_worker = int(chips_per_worker)
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers if max_workers is not None
                               else pool_max)
        self.max_workers = min(self.max_workers, pool_max)
        if not 1 <= self.min_workers <= self.max_workers:
            raise ValueError(
                f"need 1 <= min_workers ({self.min_workers}) <= "
                f"max_workers ({self.max_workers})")
        self.lease_timeout_s = lease_timeout_s
        self.clock = clock
        self.dcn_axis = dcn_axis
        self.data_axis = data_axis
        self._lock = threading.RLock()
        self._leases: Dict[str, WorkerLease] = {}
        self._epoch = 0            # membership epoch: bumps per transition
        self._built_epoch = -1     # epoch the last mesh() materialized
        self._next_id = 0
        self._next_order = 0
        #: audit log: (kind, worker_id, membership_epoch) per transition,
        #: kinds join/leave/preempt/death/expire/suppressed
        self.transitions: List[Tuple[str, str, int]] = []
        self.counters: Dict[str, int] = {
            "joins": 0, "leaves": 0, "preemptions": 0, "deaths": 0,
            "expirations": 0, "suppressed": 0, "resizes": 0,
            "controller_requests": 0,
        }
        #: controller-initiated resize pending application at a chunk
        #: boundary: (target_workers, at_boundary, reason)
        self._pending_resize: Optional[Tuple[int, Optional[int], str]] = None
        #: chunk boundaries seen so far — one per :meth:`poll` call, the
        #: same index space FaultPlan schedules against
        self._boundary_polls = 0
        n0 = initial_workers if initial_workers is not None else pool_max
        if not self.min_workers <= n0 <= self.max_workers:
            raise ValueError(
                f"initial_workers={n0} outside "
                f"[{self.min_workers}, {self.max_workers}]")
        for _ in range(n0):
            self.register()
        # the initial fleet is the baseline, not a pending resize
        self.transitions.clear()
        self.counters["joins"] = 0
        self._epoch = 0
        self._built_epoch = 0

    # -- lease table -------------------------------------------------------

    def _expiry(self, now: float) -> float:
        if self.lease_timeout_s is None:
            return float("inf")
        return now + self.lease_timeout_s

    def _free_devices(self) -> List[Any]:
        held = {id(d) for lease in self._leases.values()
                for d in lease.devices}
        return [d for d in self._pool if id(d) not in held]

    def _record(self, kind: str, worker_id: str) -> None:
        self._epoch += 1
        self.transitions.append((kind, worker_id, self._epoch))
        from ..obs.trace import tracer

        tracer.instant("membership", cat="train", x_kind=kind,
                       x_worker=worker_id, x_fleet=len(self._leases))

    def register(self, worker_id: Optional[str] = None) -> Optional[str]:
        """A worker joins: seat it on the next free devices (lowest pool
        index first — deterministic layout).  Returns the worker id, or
        ``None`` when the join was suppressed (fleet already at
        ``max_workers`` / pool exhausted)."""
        with self._lock:
            if len(self._leases) >= self.max_workers:
                self.counters["suppressed"] += 1
                self.transitions.append(
                    ("suppressed", worker_id or "<join>", self._epoch))
                return None
            free = self._free_devices()
            devs = tuple(free[:self.chips_per_worker])
            if worker_id is None:
                worker_id = f"w{self._next_id}"
            self._next_id += 1
            if worker_id in self._leases:
                raise ValueError(f"worker {worker_id!r} already registered")
            now = self.clock()
            self._leases[worker_id] = WorkerLease(
                worker_id=worker_id, devices=devs, joined_at=now,
                expires_at=self._expiry(now), order=self._next_order)
            self._next_order += 1
            self.counters["joins"] += 1
            self._record("join", worker_id)
            return worker_id

    def heartbeat(self, worker_id: str) -> None:
        """Renew a worker's lease (no membership change)."""
        with self._lock:
            lease = self._leases.get(worker_id)
            if lease is None:
                raise KeyError(f"no live lease for worker {worker_id!r}")
            lease.expires_at = self._expiry(self.clock())

    def _remove(self, worker_id: str, kind: str) -> bool:
        if worker_id not in self._leases:
            raise KeyError(f"no live lease for worker {worker_id!r}")
        if len(self._leases) <= self.min_workers:
            self.counters["suppressed"] += 1
            self.transitions.append(("suppressed", worker_id, self._epoch))
            return False
        del self._leases[worker_id]
        self.counters[{"leave": "leaves", "preempt": "preemptions",
                       "death": "deaths", "expire": "expirations"}[kind]] += 1
        self._record(kind, worker_id)
        return True

    def leave(self, worker_id: str) -> bool:
        """Planned departure (drained at the next chunk boundary)."""
        with self._lock:
            return self._remove(worker_id, "leave")

    def fail(self, worker_id: str) -> bool:
        """Unplanned death: the lease is revoked immediately."""
        with self._lock:
            return self._remove(worker_id, "death")

    def expire(self) -> List[str]:
        """Clock-driven reaping: every worker whose lease lapsed is
        declared dead.  Returns the expired worker ids."""
        with self._lock:
            now = self.clock()
            lapsed = [w for w, lease in self._leases.items()
                      if lease.expires_at < now]
            return [w for w in lapsed if self._remove(w, "expire")]

    def _newest(self) -> Optional[str]:
        if not self._leases:
            return None
        return max(self._leases.values(), key=lambda l: l.order).worker_id

    def preempt(self) -> Optional[str]:
        """The injected-``"preempt"`` transition: remove the newest
        live worker (LIFO — deterministic by construction, so a seeded
        schedule always removes the same seat)."""
        with self._lock:
            victim = self._newest()
            if victim is not None and self._remove(victim, "preempt"):
                return victim
            return None

    # -- fleet views -------------------------------------------------------

    @property
    def fleet_size(self) -> int:
        with self._lock:
            return len(self._leases)

    @property
    def membership_epoch(self) -> int:
        with self._lock:
            return self._epoch

    def live_workers(self) -> Tuple[str, ...]:
        """Live worker ids in join order (the mesh's dcn order)."""
        with self._lock:
            return tuple(sorted(self._leases,
                                key=lambda w: self._leases[w].order))

    def fleet(self) -> FleetView:
        with self._lock:
            return FleetView(epoch=self._epoch, workers=self.live_workers())

    def mesh(self):
        """Materialize the CURRENT fleet as a ``(dcn, data)`` mesh over
        the live workers' devices in join order — and mark that fleet
        consumed, so :meth:`poll` reports ``True`` only for membership
        the training mesh has not absorbed yet."""
        from jax.sharding import Mesh

        with self._lock:
            workers = self.live_workers()
            devs = [d for w in workers
                    for d in self._leases[w].devices]
            self._built_epoch = self._epoch
            dev_array = np.asarray(devs, dtype=object).reshape(
                len(workers), self.chips_per_worker)
            return Mesh(dev_array, axis_names=(self.dcn_axis,
                                               self.data_axis))

    # -- controller-initiated transitions ----------------------------------

    def request_resize(self, target_workers: int, *,
                       at_boundary: Optional[int] = None,
                       reason: str = "controller") -> int:
        """Ask the fleet to become ``target_workers`` at a chunk
        boundary (ISSUE 17: the autoscale controller's training
        actuator).  The request is NOT applied here — it is applied by
        :meth:`poll`, walking the fleet toward the target through the
        SAME :meth:`register`/:meth:`preempt` transitions the injected
        fault seam uses, so the PR 15 chaos matrix (torn cut during
        resize, death mid-chunk, bit-exact restore on the shrunken
        fleet) covers controller preemptions for free.

        ``at_boundary`` pins application to a specific boundary index
        (the FaultPlan index space: poll invocations across the whole
        run) — ``None`` means the next boundary.  The target is clamped
        to ``[min_workers, max_workers]``; a later request replaces a
        pending one (last-writer-wins: the controller's newest intent is
        the only one that matters).  Returns the clamped target."""
        target = max(self.min_workers,
                     min(int(target_workers), self.max_workers))
        with self._lock:
            self._pending_resize = (target, at_boundary, str(reason))
            self.counters["controller_requests"] += 1
        from ..obs.trace import tracer

        tracer.instant("resize_requested", cat="train",
                       x_target=target, x_reason=str(reason))
        return target

    def _apply_pending_resize(self) -> None:
        """Walk the fleet to a due pending target — called from
        :meth:`poll` only, AFTER the fault seam (an injected transition
        this boundary is part of the state the controller's request
        converges from, not something it races)."""
        with self._lock:
            if self._pending_resize is None:
                return
            target, at_boundary, _reason = self._pending_resize
            if at_boundary is not None \
                    and self._boundary_polls <= at_boundary:
                return
            self._pending_resize = None
        while True:
            with self._lock:
                n = len(self._leases)
            if n < target:
                if self.register() is None:
                    return      # suppressed at the bound: stop walking
            elif n > target:
                if self.preempt() is None:
                    return
            else:
                return

    # -- the chunk-boundary seam ------------------------------------------

    def poll(self, step: Optional[int] = None) -> bool:
        """The fits' once-per-chunk-boundary membership check.

        Fires the ``elastic.membership`` fault seam (one invocation per
        boundary — schedule indices count boundaries across the whole
        supervised run), translating an injected ``"join"`` into
        :meth:`register` and an injected ``"preempt"`` into
        :meth:`preempt`; any other injected kind (e.g. ``"crash"``)
        propagates to the caller like a crash at any other seam.  Then
        reaps lapsed leases and reports whether membership moved past
        the fleet the current mesh was built from — ``True`` means the
        caller must cut a boundary checkpoint and raise
        :class:`ResizeRequested`."""
        from ..robustness.faults import (
            InjectedJoin,
            InjectedPreemption,
            fault_point,
        )

        with self._lock:
            self._boundary_polls += 1
        try:
            fault_point(self.SCOPE)
        except InjectedPreemption:
            self.preempt()
        except InjectedJoin:
            self.register()
        self._apply_pending_resize()
        self.expire()
        with self._lock:
            return self._epoch != self._built_epoch

    def on_failure(self, exc: Optional[BaseException] = None
                   ) -> Optional[str]:
        """The supervisor's crash hook: first reap lapsed leases (a real
        worker death surfaces as silence — missed heartbeats); if no
        lease had lapsed AND the failure is worker-loss-shaped (an
        injected crash or a lost-peer connection/timeout — a disk-full
        or corrupt-state error is NOT a dead worker, and shrinking on
        it would monotonically evict healthy seats on I/O blips),
        revoke the newest worker's lease (the deterministic stand-in
        for 'the crashed worker' in the single-process harness, bounded
        by ``min_workers``).  Returns the removed worker id, or
        ``None`` when the fleet stayed put (recovery then resumes on
        the same mesh — plain crash recovery)."""
        from ..robustness.faults import InjectedCrash

        expired = self.expire()
        if expired:
            return expired[0]
        if exc is not None and not isinstance(
                exc, (InjectedCrash, ConnectionError, TimeoutError)):
            return None
        with self._lock:
            victim = self._newest()
            if (victim is not None
                    and len(self._leases) > self.min_workers
                    and self._remove(victim, "death")):
                return victim
            return None

    def note_resize(self) -> None:
        """Supervisor hook: count a completed resize transition (the
        restore-onto-new-mesh the ``resizes`` gauge reports)."""
        with self._lock:
            self.counters["resizes"] += 1

    # -- observability -----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Fleet-state snapshot for a :class:`~..obs.tree.MetricsTree`
        (``default_tree(elastic=...)``)."""
        with self._lock:
            pending = self._pending_resize
            return {
                "fleet_size": len(self._leases),
                "membership_epoch": self._epoch,
                "workers": list(self.live_workers()),
                "chips_per_worker": self.chips_per_worker,
                "min_workers": self.min_workers,
                "max_workers": self.max_workers,
                "boundary_polls": self._boundary_polls,
                "pending_resize_target": (pending[0] if pending is not None
                                          else -1),
                **{k: int(v) for k, v in self.counters.items()},
            }

    def publish(self, group) -> None:
        """Export the fleet gauges into a ``MetricGroup`` subtree
        (``elastic.fleet_size`` etc.) next to every other framework
        metric."""
        sub = group.add_group("elastic")
        snap = self.snapshot()
        for key in ("fleet_size", "membership_epoch", "chips_per_worker",
                    "min_workers", "max_workers"):
            sub.gauge(key).set(snap[key])
        for key in ("joins", "leaves", "preemptions", "deaths",
                    "expirations", "suppressed", "resizes",
                    "controller_requests"):
            sub.gauge(key).set(snap[key])
