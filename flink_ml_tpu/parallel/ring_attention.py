"""Ring attention — sequence/context parallelism over the mesh ring.

Long-context support is first-class in this framework (the reference has no
attention anywhere — SURVEY §5 "long-context: absent" — but the driver brief
requires the capability).  Two standard schemes:

- :func:`ring_attention`: Q stays put; K/V blocks rotate around the mesh
  ring via ``ppermute`` (neighbor ICI links only), with a numerically-stable
  online-softmax accumulation — memory per device is O(seq/devices), so
  context length scales linearly with the ring size.
- :func:`ulysses_attention` (see ulysses.py): all-to-all re-shard from
  sequence-sharded to head-sharded, run dense local attention, a2a back.

Layout convention: ``(batch, seq, heads, head_dim)``, sequence sharded over
the given mesh axis.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from .collectives import shard_map_fn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ring_attention", "attention_reference"]


def attention_reference(q, k, v, *, causal: bool = False, scale=None):
    """Vanilla full attention (the correctness oracle for the parallel
    schemes).  Shapes (b, s, h, d)."""
    b, s_q, h, d = q.shape
    scale = scale or (1.0 / np.sqrt(d))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((s_q, k.shape[1]), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _block_attend(q, k, v, q_offset, k_offset, scale, causal):
    """Scores of a local Q block against one K/V block with running-softmax
    stats.  Returns (numerator, running max, running denom)."""
    s_q, s_k = q.shape[1], k.shape[1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale        # (b,h,sq,sk)
    if causal:
        q_idx = q_offset + lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0)
        k_idx = k_offset + lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1)
        scores = jnp.where((k_idx <= q_idx)[None, None], scores, -jnp.inf)
    block_max = jnp.max(scores, axis=-1)                        # (b,h,sq)
    # guard fully-masked rows (all -inf) -> exp(0)=..0 contribution
    safe_max = jnp.where(jnp.isfinite(block_max), block_max, 0.0)
    probs = jnp.exp(scores - safe_max[..., None])
    probs = jnp.where(jnp.isfinite(scores), probs, 0.0)
    numer = jnp.einsum("bhqk,bkhd->bqhd", probs, v)             # (b,sq,h,d)
    denom = jnp.sum(probs, axis=-1)                             # (b,h,sq)
    return numer, safe_max, denom


def _online_merge(acc, update):
    """Merge two (numer, max, denom) softmax partials."""
    n1, m1, d1 = acc
    n2, m2, d2 = update
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    numer = n1 * a1.transpose(0, 2, 1)[..., None] \
        + n2 * a2.transpose(0, 2, 1)[..., None]
    denom = d1 * a1 + d2 * a2
    return numer, m, denom


def ring_attention(q, k, v, *, mesh: Mesh, axis: str = "seq",
                   causal: bool = False, scale: Optional[float] = None):
    """Exact attention with sequence sharded over ``axis``.

    Each device holds one Q/K/V block; K/V rotate ``axis_size`` times around
    the ring (``ppermute`` to the right neighbor) while Q stays resident,
    merging block results with online softmax — the classic ring schedule
    (Liu et al., Ring Attention; also the blockwise-parallel formulation).
    """
    d = q.shape[-1]
    scale = scale or (1.0 / np.sqrt(d))
    n = int(mesh.shape[axis])
    block = q.shape[1] // n
    if q.shape[1] % n:
        raise ValueError(f"seq len {q.shape[1]} not divisible by ring size {n}")

    def local(qb, kb, vb):
        idx = lax.axis_index(axis)
        q_off = idx * block
        # Start with the local block, then rotate k/v (n-1) times.
        numer, m, denom = _block_attend(qb, kb, vb, q_off, idx * block,
                                        scale, causal)

        def step(i, carry):
            numer, m, denom, k_cur, v_cur = carry
            k_cur = _rot(k_cur)
            v_cur = _rot(v_cur)
            # after i+1 rotations this device holds the block originally at
            # ring position (idx - i - 1) mod n
            src = (idx - i - 1) % n
            upd = _block_attend(qb, k_cur, v_cur, q_off, src * block,
                                scale, causal)
            numer, m, denom = _online_merge((numer, m, denom), upd)
            return numer, m, denom, k_cur, v_cur

        def _rot(x):
            perm = [(j, (j + 1) % n) for j in range(n)]
            return lax.ppermute(x, axis, perm)

        numer, m, denom, _, _ = lax.fori_loop(
            0, n - 1, step, (numer, m, denom, kb, vb))
        denom = jnp.maximum(denom, 1e-20)
        return numer / denom.transpose(0, 2, 1)[..., None]

    spec = P(None, axis, None, None)
    fn = shard_map_fn(local, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)
    return fn(q, k, v)
