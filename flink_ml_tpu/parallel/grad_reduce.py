"""Configurable gradient reduction: exact / sparse / quantized / hierarchical.

The reference's entire scale-out story is its network shuffle layer; the
TPU-native analog has so far been the implicit all-reduce GSPMD inserts
for data-parallel gradients.  This module makes that reduction an explicit,
configurable operator so gradient bytes-on-wire become a first-class,
measured quantity (the SparCML/SwitchML posture — arXiv:1802.08021,
arXiv:1903.06701):

- ``mode="exact"``   — ``lax.psum``; adopters keep their legacy implicit
  path when the config is absent or exact, so the default is bit-identical
  to the pre-reducer code.
- ``mode="topk"``    — per-leaf top-|g| sparsification at ``density`` with
  **error feedback**: the unsent residual is carried in reducer state
  (EF-SGD semantics — what was not sent this step is added to the next
  step's gradient, so the compression error stays bounded instead of
  accumulating).  The reduce itself is the all-gather form of a sparse
  all-reduce: each participant contributes ``k`` (index, value) pairs and
  every participant scatter-adds the gathered pairs locally.
- ``mode="int8"``    — block-quantized reduce: per-``block_size`` max-abs
  scales, **stochastic rounding** (unbiased — no residual needed; the
  rounding key is carried in reducer state), int8 payloads + f32 scales
  all-gathered and dequantized-summed locally.
- hierarchical (``dcn_axis`` set) — the two-tier composition for
  :func:`~flink_ml_tpu.parallel.distributed.hybrid_mesh`:
  ``reduce_scatter`` over the fast ICI axis first (exact), the compressed
  all-reduce over the slow ``dcn`` axis on the 1/I-sized shard, then
  ``all_gather`` back over ICI — only the inter-host hop pays for (or
  benefits from) compression.

All reduction functions must run inside an SPMD context (``shard_map``)
with the named axes bound; reducer state is per-participant — adopters
carry it with a leading participant dim sharded over the reduction axes
(see :func:`init_state`) so it rides scan carries and checkpoints like any
other optimizer state.

r11 (communication-scheduled training) adds three orthogonal knobs:

- ``bucket_count=B`` — the flattened gradient is cut into B size-balanced
  **buckets**, each reduced by its own independent collective
  (:func:`plan_buckets`).  Exact mode is bit-identical bucketed or not
  (psum is elementwise); compressed modes select top-k per bucket instead
  of per leaf.  Independent bucket collectives are what XLA's
  latency-hiding scheduler can overlap with compute.
- ``overlap=True`` — adopters run the **one-step-stale pipelined apply**
  (:func:`pipelined_reduce`): the previous step's gradient buckets are
  reduced while the current step's forward/backward runs (the two are
  data-independent), and the optimizer applies each bucket's reduced
  value as it lands.  Legal under error feedback: the EF residual absorbs
  the one-step staleness exactly as it absorbs sparsification (MLFabric's
  scheduling posture).  ``exact`` mode keeps a fence — overlap is ignored
  and the path stays bit-identical to the blocking psum.
- ``adaptive=True`` — per-leaf **variable-rate compression** (SparCML's
  variable-sparsity case): the carried residual-norm/gradient-norm ratio
  (EMA, reducer state) selects a rung of ``density_ladder`` — a density,
  or an ``"int8"``/``"exact"`` fallback — per leaf every
  ``adaptive_window`` steps.  Selection is computed from psum'd norms, so
  every participant takes the same ``lax.switch`` branch.

r20 (topology-aware wire protocol) replaces the all-gather transport of
the compressed hop:

- ``wire_protocol`` — ``"auto"`` (default) runs the top-k family's
  sparse all-reduce as **recursive halving/doubling**
  (:func:`~.collectives.sparse_all_reduce_rd`) whenever the compressed
  hop spans a single named axis (the dcn hop of every hierarchical
  config, and flat single-axis reductions), falling back to the legacy
  all-gather form for multi-axis hops; ``"rd"`` / ``"allgather"`` force
  one or the other.  Per-round fill-in lands in the ``fill`` /
  ``union`` reducer-state leaves and :func:`payload_bytes` turns it
  into measured bytes-on-wire next to the analytic best/worst bounds.
  Exact mode never routes through the sparse protocol, so it stays
  bit-identical to the legacy path.
- ``dcn_schedule="earliest"`` — hierarchical bucketed reduces chain an
  ``optimization_barrier`` token through the buckets in consumption
  order (earliest-needed bucket first, MLFabric's schedule), so the dcn
  collectives issue in the order the overlap pipeline applies them
  instead of racing; ``"free"`` keeps the unordered launch.  The
  barrier is the identity, so the two schedules are bit-identical in
  value (asserted in tests) — only issue order changes.
- ``int8_accum="fixed"`` — the int8 hop quantizes against a SHARED
  (pmax'd) per-block scale and accumulates int32 per hop
  (:func:`~.collectives.fixed_point_all_reduce` — SwitchML pool
  semantics), one rounding per participant no matter the hop count;
  ``"dequant"`` keeps the legacy dequantize-to-f32-then-sum.
"""

from __future__ import annotations

import math

from dataclasses import dataclass
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .collectives import (
    FILL_DOUBLING_BASE,
    FILL_POSTFOLD_SLOT,
    FILL_PREFOLD_SLOT,
    FILL_ROUND_SLOTS,
    FILL_SWITCH_SLOT,
    FILL_UNION_SLOT,
    FILL_VEC_LEN,
    rd_topology,
)

__all__ = [
    "BucketPlan",
    "GradReduceConfig",
    "MODES",
    "bucket_report",
    "drain_pending",
    "effective_ladder",
    "hop_axis",
    "init_state",
    "mesh_layout",
    "needs_state",
    "payload_bytes",
    "pipelined_reduce",
    "plan_buckets",
    "reduce_gradients",
    "reduction_axes",
    "reshard_state",
    "resolved_wire_protocol",
    "squeeze_state",
    "state_participants",
    "unsqueeze_state",
    "wants_overlap",
]

MODES = ("exact", "topk", "int8")
WIRE_PROTOCOLS = ("auto", "rd", "allgather")
INT8_ACCUMS = ("dequant", "fixed")
DCN_SCHEDULES = ("earliest", "free")

AxisSpec = Union[str, Tuple[str, ...]]


@dataclass(frozen=True)
class GradReduceConfig:
    """How data-parallel gradients are summed across the mesh.

    ``axis`` is the (fast/ICI) reduction axis; ``dcn_axis`` — when set —
    selects the hierarchical composition: exact reduce-scatter over
    ``axis``, the configured compression over ``dcn_axis`` only, gather
    back.  With ``dcn_axis=None`` the compression applies to the whole
    flat reduce over ``axis``.

    ``density`` (topk) is the fraction of each leaf's elements sent per
    step (``k = max(1, floor(density * n))`` — floor, so the advertised
    compression ratio is a lower bound).  ``block_size`` (int8) is the
    elements-per-scale quantization granule; ``seed`` feeds the stochastic
    rounding stream.

    ``bucket_count=B`` cuts the flat gradient into B size-balanced
    buckets, each reduced by its own independent collective (0 keeps the
    legacy per-leaf reduce).  ``overlap=True`` asks adopters for the
    one-step-stale pipelined apply (fenced off — ignored — in ``exact``
    mode, which stays bit-identical to the blocking psum).
    ``adaptive=True`` (topk only) re-selects each leaf's rung of
    ``density_ladder`` — a density in (0, 1], or the strings ``"int8"`` /
    ``"exact"`` — every ``adaptive_window`` steps from the carried
    residual/gradient norm ratio: above ``adaptive_target`` the leaf
    climbs one rung toward fidelity, below half the target it descends
    one rung toward thrift.  An empty ladder defaults to
    ``(density / 4, density, "exact")``.

    ``wire_protocol`` selects the sparse transport of the top-k family:
    ``"auto"`` (recursive halving/doubling on single-named-axis hops,
    all-gather otherwise), ``"rd"``, or ``"allgather"``.
    ``int8_accum`` selects the int8 hop's accumulator: ``"dequant"``
    (legacy f32 dequantize-then-sum) or ``"fixed"`` (shared scales,
    int32 per-hop accumulation).  ``dcn_schedule`` orders hierarchical
    bucket transfers: ``"earliest"`` (consumption order, default) or
    ``"free"`` (unordered launch).
    """

    mode: str = "exact"
    density: float = 0.1
    block_size: int = 256
    axis: AxisSpec = "data"
    dcn_axis: Optional[str] = None
    seed: int = 0
    bucket_count: int = 0
    overlap: bool = False
    adaptive: bool = False
    adaptive_window: int = 8
    adaptive_target: float = 0.5
    density_ladder: Tuple = ()
    wire_protocol: str = "auto"
    int8_accum: str = "dequant"
    dcn_schedule: str = "earliest"

    def __post_init__(self):
        if self.wire_protocol not in WIRE_PROTOCOLS:
            raise ValueError(f"wire_protocol must be one of "
                             f"{WIRE_PROTOCOLS}, got {self.wire_protocol!r}")
        if self.int8_accum not in INT8_ACCUMS:
            raise ValueError(f"int8_accum must be one of {INT8_ACCUMS}, "
                             f"got {self.int8_accum!r}")
        if self.dcn_schedule not in DCN_SCHEDULES:
            raise ValueError(f"dcn_schedule must be one of "
                             f"{DCN_SCHEDULES}, got {self.dcn_schedule!r}")
        single_hop = self.dcn_axis is not None or \
            isinstance(self.axis, str) or len(tuple(self.axis)) == 1
        if self.wire_protocol == "rd" and not single_hop:
            raise ValueError(
                "wire_protocol='rd' runs pairwise ppermute rounds over ONE "
                "named axis; this config's compressed hop spans "
                f"axis={self.axis!r} — set a dcn_axis or use 'allgather'")
        if self.int8_accum == "fixed" and not single_hop:
            raise ValueError(
                "int8_accum='fixed' accumulates int32 over ONE named axis; "
                f"this config's hop spans axis={self.axis!r}")
        if self.mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {self.mode!r}")
        if self.mode == "topk" and not 0.0 < self.density <= 1.0:
            raise ValueError(f"density must be in (0, 1], got {self.density}")
        if self.mode == "int8" and self.block_size <= 0:
            raise ValueError(
                f"block_size must be positive, got {self.block_size}")
        if self.dcn_axis is not None and not isinstance(self.axis, str):
            raise ValueError(
                "hierarchical reduction needs a single ICI axis name; got "
                f"axis={self.axis!r}")
        if self.bucket_count < 0:
            raise ValueError(
                f"bucket_count must be >= 0, got {self.bucket_count}")
        if self.adaptive:
            if self.mode != "topk":
                raise ValueError(
                    "adaptive density is a topk-family policy (the ladder "
                    "may contain int8/exact fallback rungs); set "
                    f"mode='topk', got mode={self.mode!r}")
            if self.adaptive_window < 1:
                raise ValueError("adaptive_window must be >= 1, got "
                                 f"{self.adaptive_window}")
            if self.adaptive_target <= 0:
                raise ValueError("adaptive_target must be positive, got "
                                 f"{self.adaptive_target}")
            for spec in self.density_ladder:
                if isinstance(spec, str):
                    if spec not in ("exact", "int8"):
                        raise ValueError(
                            "ladder rungs are densities in (0, 1] or "
                            f"'exact'/'int8', got {spec!r}")
                elif not 0.0 < float(spec) <= 1.0:
                    raise ValueError(
                        f"ladder density {spec!r} not in (0, 1]")
        elif self.density_ladder:
            raise ValueError("density_ladder requires adaptive=True")


def effective_ladder(config: GradReduceConfig) -> Tuple:
    """The adaptive rung ladder, ordered cheapest -> highest fidelity.
    Rung selection moves +1 (toward the end / exact) when the residual
    ratio runs hot and -1 when it runs cold."""
    if config.density_ladder:
        return tuple(config.density_ladder)
    return (max(config.density / 4.0, 1e-4), config.density, "exact")


def _initial_rung(config: GradReduceConfig) -> int:
    """Start every leaf at the configured density's rung (the middle of
    the default ladder) so the first window behaves like plain topk."""
    lad = effective_ladder(config)
    for i, spec in enumerate(lad):
        if not isinstance(spec, str) and float(spec) == config.density:
            return i
    return len(lad) // 2


def wants_overlap(config: Optional[GradReduceConfig]) -> bool:
    """True when adopters should run the one-step-stale pipelined apply.
    ``exact`` mode keeps the fence: overlap is ignored so the default
    path stays bit-identical to the blocking psum."""
    return (config is not None and config.overlap
            and config.mode != "exact")


def _carries_ef(config: GradReduceConfig) -> bool:
    return config.mode == "topk" or config.adaptive


def _bucketed(config: GradReduceConfig) -> bool:
    """Whether the reduce routes through the bucket planner (explicit
    buckets, or adaptive — which needs per-leaf transport units)."""
    return config.bucket_count > 0 or config.adaptive


def reduction_axes(config: GradReduceConfig) -> Tuple[str, ...]:
    """Every mesh axis the reduction sums over (ICI axes + the dcn axis)."""
    axes = (config.axis,) if isinstance(config.axis, str) else tuple(
        config.axis)
    if config.dcn_axis is not None:
        axes = (config.dcn_axis,) + axes
    return axes


def hop_axis(config: GradReduceConfig) -> Optional[str]:
    """The single named axis the COMPRESSED hop runs over — the dcn axis
    of a hierarchical config, or the flat reduction axis when it is one
    name — or ``None`` when the flat hop spans multiple axes (pairwise
    rounds need one ring of partners)."""
    if config.dcn_axis is not None:
        return config.dcn_axis
    if isinstance(config.axis, str):
        return config.axis
    axes = tuple(config.axis)
    return axes[0] if len(axes) == 1 else None


def resolved_wire_protocol(config: GradReduceConfig) -> str:
    """The sparse transport the top-k family actually runs:
    ``wire_protocol="auto"`` resolves to recursive halving/doubling
    (``"rd"``) whenever :func:`hop_axis` names a single axis, and to the
    legacy ``"allgather"`` for multi-axis flat hops (config validation
    already rejects forcing ``"rd"`` there)."""
    if config.wire_protocol == "allgather":
        return "allgather"
    return "rd" if hop_axis(config) is not None else "allgather"


def _rd_engaged(config: GradReduceConfig) -> bool:
    """Whether this config's reduce carries per-round fill-in state —
    i.e. a top-k-family transport runs the recursive-doubling protocol."""
    return (config.mode == "topk" or config.adaptive) and \
        resolved_wire_protocol(config) == "rd"


def needs_state(config: GradReduceConfig) -> bool:
    return config.mode in ("topk", "int8")


def mesh_layout(config: GradReduceConfig, mesh) -> Tuple[Tuple[str, ...],
                                                         int, Any]:
    """(reduction axes, participant count, batch PartitionSpec entry) for
    running this config on ``mesh`` — THE one copy of the axis validation
    every adopter (sgd, widedeep) shares, with the loud error for axes
    the mesh does not have."""
    axes = reduction_axes(config)
    missing = [a for a in axes if a not in mesh.shape]
    if missing:
        raise ValueError(
            f"grad_reduce axes {missing} not in mesh {list(mesh.shape)}; "
            "build the mesh with the reduction axes (e.g. "
            "distributed.hybrid_mesh for a dcn axis)")
    n_participants = int(np.prod([mesh.shape[a] for a in axes]))
    return axes, n_participants, (axes if len(axes) > 1 else axes[0])


def _topk_k(n: int, density: float) -> int:
    return max(1, int(n * density))


def init_state(config: GradReduceConfig, grads_like: Any,
               n_participants: int) -> dict:
    """Per-participant reducer state, stacked over a leading participant
    dim of size ``n_participants`` (the product of the reduction axes'
    sizes) — adopters shard that dim over the reduction axes and squeeze
    it inside ``shard_map`` (:func:`squeeze_state`).

    ``topk`` carries the error-feedback residual (zeros-like every
    gradient leaf); ``int8`` carries one PRNG key per participant for the
    stochastic-rounding stream.  ``exact`` needs no state (``{}``).

    ``adaptive`` adds the policy state — per-leaf ratio EMA (``ema``),
    chosen rung (``rung``), and the step ``tick``; ``overlap`` adds
    ``pending``, the zeros-initialized one-step-stale gradient buffer
    (the first pipelined step reduces zeros, a deterministic no-op).
    All of it rides the same participant-stacked layout, so adopters'
    checkpoints round-trip the whole schedule for free.

    When the recursive-doubling wire protocol is engaged
    (:func:`resolved_wire_protocol`), two accounting leaves ride along:
    ``fill`` — the last step's per-transport-unit fill-in vector (the
    per-round sent-entry counts, union size, switchover flag and fold
    traffic of :func:`~.collectives.sparse_all_reduce_rd`, raw so
    ``payload_bytes(fill=...)`` reports calibrated measured bytes) —
    and ``union`` — a smoothed (EMA) union-density per unit, the
    switchover statistic.  Both have fleet-size-independent trailing
    shapes, so elastic resizes re-seat them without reshaping.
    """

    def stack(g):
        return jnp.zeros((n_participants,) + np.shape(g), jnp.float32)

    state: dict = {}
    lad = effective_ladder(config) if config.adaptive else ()
    if _carries_ef(config):
        state["ef"] = jax.tree_util.tree_map(stack, grads_like)
    if config.mode == "int8" or "int8" in lad:
        base = jax.random.PRNGKey(config.seed)
        state["key"] = jax.vmap(
            lambda i: jax.random.fold_in(base, i))(
                jnp.arange(n_participants, dtype=jnp.int32))
    if config.adaptive:
        n_leaves = len(jax.tree_util.tree_leaves(grads_like))
        state["ema"] = jnp.zeros((n_participants, n_leaves), jnp.float32)
        state["rung"] = jnp.full((n_participants, n_leaves),
                                 _initial_rung(config), jnp.int32)
        state["tick"] = jnp.zeros((n_participants,), jnp.int32)
    if _rd_engaged(config):
        n_units = _fill_units(grads_like, config)
        state["fill"] = jnp.zeros((n_participants, n_units, FILL_VEC_LEN),
                                  jnp.float32)
        state["union"] = jnp.zeros((n_participants, n_units), jnp.float32)
    if wants_overlap(config):
        state["pending"] = jax.tree_util.tree_map(stack, grads_like)
    return state


def _fill_units(grads_like: Any, config: GradReduceConfig) -> int:
    """Transport units the fill accounting is keyed on: buckets when the
    reduce is bucketed/adaptive, leaves otherwise — exactly the units
    :func:`_transport_units` accounts."""
    if _bucketed(config):
        return len(plan_buckets(grads_like, config).ranges)
    return len(jax.tree_util.tree_leaves(grads_like))


def squeeze_state(state: dict) -> dict:
    """Drop the leading participant dim of the local (1, ...) state slices
    inside ``shard_map``."""
    return jax.tree_util.tree_map(lambda a: a[0], state)


def unsqueeze_state(state: dict) -> dict:
    """Restore the leading participant dim on the way out of ``shard_map``."""
    return jax.tree_util.tree_map(lambda a: a[None], state)


def state_participants(state: Optional[dict]) -> Optional[int]:
    """The participant count a stacked reducer state was built for (the
    leading dim every leaf shares), or ``None`` for empty/absent state."""
    leaves = jax.tree_util.tree_leaves(state or {})
    if not leaves:
        return None
    return int(np.shape(leaves[0])[0])


def reshard_state(state: dict, n_new: int, *,
                  ici_size: int = 1) -> dict:
    """Re-shard participant-stacked reducer state onto a fleet of
    ``n_new`` participants — THE resize-as-restore mapping (elastic PR).
    The mapping depends only on the state's leaf keys and the (fixed)
    ICI extent, never on the reduce mode — which is why there is no
    config parameter.

    Mass-carrying leaves (``ef`` residual, ``pending`` overlap buffer)
    are **total-preserving**: the old participants' contributions are
    summed — per ICI position for the hierarchical layout, so each
    shard-domain residual stays embedded at its own slice exactly as
    :func:`_embed_shard` placed it — and the total is seated on the new
    fleet's first dcn group, the rest zero-initialized.  Policy state
    (``ema``/``rung``/``tick``) is replicated content by construction
    and broadcasts from participant 0; rounding ``key`` rows re-derive
    deterministically by folding the new participant index into
    participant 0's carried key.

    Deterministic and host-side: an elastic resize AND a fixed fleet of
    the new size restoring the same cut both route through this
    function, which is what makes the two bit-exact from the boundary
    onward (the fit-level contract asserted in tests/test_faults.py).

    The wire-protocol accounting leaves resize by their own rules:
    ``fill`` (last-step per-round sent counts) measures the OLD fleet's
    round structure — a different participant count has a different
    core/rounds/fold layout, so carrying the numbers over would
    misattribute bytes; it re-seats as zeros and the first post-resize
    step repopulates it.  ``union`` (union-density EMA) describes the
    gradient, not the fleet — psum-uniform within each dcn hop group,
    varying only across ICI columns — so it broadcasts from participant
    0 like the other policy leaves (a smoothed-statistic re-seed the
    next steps re-diverge, not an exact invariant).  Both have
    fleet-size-independent trailing shapes by construction, so the same
    rule applies at any resize.
    """
    n_old = state_participants(state)
    if n_old is None or n_old == n_new:
        return state
    if ici_size < 1 or n_old % ici_size or n_new % ici_size:
        raise ValueError(
            f"cannot reshard reducer state from {n_old} to {n_new} "
            f"participants at ici_size={ici_size}: both fleet sizes must "
            "be multiples of the (fixed) ICI extent")
    d_new = n_new // ici_size

    def collapse(a):
        a = np.asarray(a, np.float32)
        tail = a.shape[1:]
        total = a.reshape((n_old // ici_size, ici_size) + tail).sum(axis=0)
        out = np.zeros((d_new, ici_size) + tail, np.float32)
        out[0] = total
        return out.reshape((n_new,) + tail)

    def broadcast0(a):
        a = np.asarray(a)
        return np.broadcast_to(a[:1], (n_new,) + a.shape[1:]).copy()

    out: dict = {}
    for key, value in state.items():
        if key in ("ef", "pending"):
            out[key] = jax.tree_util.tree_map(collapse, value)
        elif key in ("ema", "rung", "tick", "union"):
            out[key] = broadcast0(value)
        elif key == "fill":
            a = np.asarray(value, np.float32)
            out[key] = np.zeros((n_new,) + a.shape[1:], np.float32)
        elif key == "key":
            base = jnp.asarray(np.asarray(value)[0])
            out[key] = np.asarray(jax.vmap(
                lambda i: jax.random.fold_in(base, i))(
                    jnp.arange(n_new, dtype=jnp.int32)))
        else:
            raise ValueError(
                f"unknown reducer-state leaf {key!r}: teach reshard_state "
                "its resize semantics before restoring it onto a "
                "different fleet")
    return out


# ---------------------------------------------------------------------------
# bucket planning (host side, static)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class BucketPlan:
    """Static transport plan: the flat concatenation of all gradient
    leaves cut into size-balanced contiguous ranges.  ``bucket_leaves``
    maps each bucket to the leaf indices it overlaps (a bucket is either
    a slice of one big leaf or a group of whole small leaves — or, at
    cut points, a tail+head pair; the adaptive rung of a bucket is the
    max — highest-fidelity — rung of its leaves)."""

    ranges: Tuple[Tuple[int, int], ...]
    leaf_offsets: Tuple[int, ...]
    leaf_sizes: Tuple[int, ...]
    leaf_shapes: Tuple[Tuple[int, ...], ...]
    bucket_leaves: Tuple[Tuple[int, ...], ...]

    @property
    def total(self) -> int:
        return self.leaf_offsets[-1]

    @property
    def bucket_sizes(self) -> Tuple[int, ...]:
        return tuple(hi - lo for lo, hi in self.ranges)


def plan_buckets(grads_like: Any, config: GradReduceConfig) -> BucketPlan:
    """Cut the flat gradient into ``config.bucket_count`` equal ranges
    (cut points ``round(i * total / B)`` — perfectly size-balanced, leaf
    boundaries not respected: transport is flat).  ``bucket_count=0``
    (the adaptive-only case) degrades to one bucket per leaf, the
    per-leaf transport the policy state is keyed on."""
    shapes = [tuple(np.shape(g))
              for g in jax.tree_util.tree_leaves(grads_like)]
    sizes = [int(np.prod(s, dtype=np.int64)) if s else 1 for s in shapes]
    offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
    total = int(offsets[-1])
    B = int(config.bucket_count)
    if B <= 0:
        ranges = [(int(offsets[i]), int(offsets[i + 1]))
                  for i in range(len(sizes))]
    else:
        B = max(1, min(B, total))
        cuts = [int(round(i * total / B)) for i in range(B + 1)]
        ranges = [(cuts[i], cuts[i + 1]) for i in range(B)
                  if cuts[i + 1] > cuts[i]]
    bucket_leaves = []
    for lo, hi in ranges:
        bucket_leaves.append(tuple(
            i for i in range(len(sizes))
            if offsets[i] < hi and offsets[i + 1] > lo))
    return BucketPlan(tuple(ranges), tuple(int(o) for o in offsets),
                      tuple(sizes), tuple(shapes), tuple(bucket_leaves))


# ---------------------------------------------------------------------------
# per-leaf compressed all-reduces (SPMD context)
# ---------------------------------------------------------------------------


def _topk_allreduce(flat: jnp.ndarray, axes: AxisSpec, density: float,
                    protocol: str = "allgather",
                    uniform_axes: Optional[Tuple[str, ...]] = None
                    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Sparse all-reduce of one flat leaf: every participant contributes
    its top-k (index, value) pairs.  ``protocol="rd"`` routes the pairs
    through recursive halving/doubling over the (single) named axis;
    ``"allgather"`` keeps the legacy every-participant-receives-all
    form.  Returns ``(reduced, unsent, fill)`` where ``unsent`` is this
    participant's residual (its accumulated gradient with the sent
    entries zeroed) and ``fill`` is the per-round fill-in vector (zeros
    under allgather, which has no rounds to account).  ``uniform_axes``
    (every axis of the enclosing shard_map, hierarchical callers pass
    :func:`reduction_axes`) keeps the rd switchover predicate
    mesh-uniform — see :func:`~.collectives.sparse_all_reduce_rd`."""
    from .collectives import sparse_all_reduce, sparse_all_reduce_rd

    k = _topk_k(flat.size, density)
    _, idx = lax.top_k(jnp.abs(flat), k)
    vals = flat[idx]
    unsent = flat.at[idx].set(0.0)
    if protocol == "rd":
        ax = axes if isinstance(axes, str) else tuple(axes)[0]
        reduced, fill = sparse_all_reduce_rd(idx, vals, flat.size, ax,
                                             uniform_axes=uniform_axes)
    else:
        reduced = sparse_all_reduce(idx, vals, flat.size, axes)
        fill = jnp.zeros((FILL_VEC_LEN,), jnp.float32)
    return reduced, unsent, fill


def _int8_allreduce(flat: jnp.ndarray, axes: AxisSpec, block: int,
                    key: jnp.ndarray,
                    accum: str = "dequant") -> jnp.ndarray:
    """Block-quantized all-reduce of one flat leaf: per-block max-abs
    scales, stochastic rounding (``floor(x/scale + u)``, u~U[0,1) — the
    unbiased round).  ``accum="dequant"`` (legacy) all-gathers int8
    payload + f32 scales and dequantize-sums locally — P dequantized
    roundings meet in f32, so worst-case error grows with P.
    ``accum="fixed"`` shares ONE pmax'd scale per block across the hop,
    accumulates the int32 codes in-fabric
    (:func:`~.collectives.fixed_point_all_reduce`) and dequantizes the
    exact integer total once — error stays one rounding per participant
    independent of P (the SwitchML posture)."""
    from .collectives import fixed_point_all_reduce, quantized_all_reduce

    n = flat.size
    n_pad = -(-n // block) * block
    padded = jnp.concatenate(
        [flat, jnp.zeros((n_pad - n,), flat.dtype)]) if n_pad > n else flat
    blocks = padded.reshape(-1, block)
    scale = jnp.maximum(jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
                        / 127.0, 1e-12)
    if accum == "fixed":
        ax = axes if isinstance(axes, str) else tuple(axes)[0]
        scale = lax.pmax(scale, ax)
        u = jax.random.uniform(key, blocks.shape)
        q = jnp.clip(jnp.floor(blocks / scale + u),
                     -127, 127).astype(jnp.int32)
        total_q = fixed_point_all_reduce(q, ax)
        return (total_q.astype(jnp.float32) * scale).reshape(-1)[:n]
    u = jax.random.uniform(key, blocks.shape)
    q = jnp.clip(jnp.floor(blocks / scale + u), -127, 127).astype(jnp.int8)
    total = quantized_all_reduce(q, scale, axes)
    return total.reshape(-1)[:n]


def _hier_scatter(flat: jnp.ndarray, ici_axis: str
                  ) -> Tuple[jnp.ndarray, int]:
    """Exact reduce-scatter of one flat leaf over the ICI axis: returns
    (per-participant shard summed over ICI, padded length)."""
    from .collectives import axis_size

    ici = axis_size(ici_axis)
    n = flat.size
    n_pad = -(-n // ici) * ici
    if n_pad > n:
        flat = jnp.concatenate([flat, jnp.zeros((n_pad - n,), flat.dtype)])
    shard = lax.psum_scatter(flat, ici_axis, scatter_dimension=0, tiled=True)
    return shard, n_pad


def _hier_gather(shard: jnp.ndarray, ici_axis: str, n: int,
                 shape) -> jnp.ndarray:
    return lax.all_gather(shard, ici_axis, tiled=True)[:n].reshape(shape)


def _embed_shard(shard: jnp.ndarray, ici_axis: str, n: int,
                 n_pad: int) -> jnp.ndarray:
    """Place this participant's shard-domain residual back in the full
    gradient domain (zeros outside its own slice) so reducer state keeps
    one uniform per-leaf shape in every mode.  At the next step the
    reduce-scatter routes each participant's slice back into exactly its
    shard — the shard-domain EF recursion, carried full-size."""
    i = lax.axis_index(ici_axis)
    full = jnp.zeros((n_pad,), shard.dtype)
    full = lax.dynamic_update_slice(full, shard, (i * shard.size,))
    return full[:n]


def _mode_spec(config: GradReduceConfig):
    """The single rung a non-adaptive config runs every bucket at."""
    return config.density if config.mode == "topk" else config.mode


def _segment_reducer(spec, config: GradReduceConfig):
    """Build ``branch(acc, key) -> (reduced, unsent, fill)`` for one flat
    segment at one rung — a density (EF top-k), ``"int8"`` (unbiased, the
    accumulated residual is fully consumed, so ``unsent = 0``) or
    ``"exact"`` (likewise).  Hierarchical configs wrap the rung's
    compressed hop in the ICI reduce-scatter / all-gather pair; the
    top-k rung's unsent comes back embedded in the full segment domain
    (:func:`_embed_shard`).  Every rung shares the signature so the
    adaptive ``lax.switch`` can select among them; exact/int8 rungs
    return a zero fill vector (no sparse rounds to account)."""
    axes = reduction_axes(config)
    hier = config.dcn_axis is not None
    proto = resolved_wire_protocol(config)

    def no_fill():
        return jnp.zeros((FILL_VEC_LEN,), jnp.float32)

    if spec == "exact":
        def branch(acc, key):
            if not hier:
                return lax.psum(acc, axes), jnp.zeros_like(acc), no_fill()
            shard, _ = _hier_scatter(acc, config.axis)
            shard = lax.psum(shard, config.dcn_axis)
            return (_hier_gather(shard, config.axis, acc.size, (acc.size,)),
                    jnp.zeros_like(acc), no_fill())
    elif spec == "int8":
        def branch(acc, key):
            if not hier:
                return (_int8_allreduce(acc, axes, config.block_size, key,
                                        config.int8_accum),
                        jnp.zeros_like(acc), no_fill())
            shard, _ = _hier_scatter(acc, config.axis)
            shard = _int8_allreduce(shard, config.dcn_axis,
                                    config.block_size, key,
                                    config.int8_accum)
            return (_hier_gather(shard, config.axis, acc.size, (acc.size,)),
                    jnp.zeros_like(acc), no_fill())
    else:
        density = float(spec)

        def branch(acc, key):
            if not hier:
                return _topk_allreduce(acc, axes, density, proto)
            shard, n_pad = _hier_scatter(acc, config.axis)
            red_s, unsent_s, fill = _topk_allreduce(
                shard, config.dcn_axis, density, proto,
                uniform_axes=reduction_axes(config))
            return (_hier_gather(red_s, config.axis, acc.size, (acc.size,)),
                    _embed_shard(unsent_s, config.axis, acc.size, n_pad),
                    fill)
    return branch


def _concat_flat(leaves) -> jnp.ndarray:
    if len(leaves) == 1:
        return leaves[0].reshape(-1)
    return jnp.concatenate([l.reshape(-1) for l in leaves])


def _split_flat(flat: jnp.ndarray, plan: BucketPlan):
    return [flat[plan.leaf_offsets[i]:plan.leaf_offsets[i + 1]].reshape(
        plan.leaf_shapes[i]) for i in range(len(plan.leaf_sizes))]


def _rd_padded(n: int, ici: int, core: int) -> int:
    """Elements of one ``n``-element transport unit as seen by the
    compressed hop: the ICI-scattered shard, padded to a multiple of
    the recursive-doubling core (the n_pad of sparse_all_reduce_rd)."""
    m = -(-n // max(ici, 1))
    return -(-m // core) * core


def _update_fill_state(new_state: dict, state: dict, fill_parts,
                       unit_sizes, config: GradReduceConfig) -> None:
    """Seat this step's per-unit fill-in vectors in reducer state:
    ``fill`` keeps the RAW last-step vectors (so payload_bytes reports
    calibrated measured bytes, not a warm-up-biased EMA), ``union``
    smooths the union density — the switchover statistic — with the
    adaptive machinery's EMA idiom.  Runs inside the SPMD context
    (axis sizes are static there)."""
    from .collectives import axis_size

    fills = jnp.stack(fill_parts)            # (n_units, FILL_VEC_LEN)
    new_state["fill"] = fills
    p = axis_size(hop_axis(config))
    core = rd_topology(p)[0]
    ici = axis_size(config.axis) if config.dcn_axis is not None else 1
    denom = jnp.asarray([_rd_padded(int(n), ici, core)
                         for n in unit_sizes], jnp.float32)
    new_state["union"] = 0.9 * state["union"] + 0.1 * (
        fills[:, FILL_UNION_SLOT] / denom)


def _reduce_bucketed(grads: Any, state: dict, config: GradReduceConfig
                     ) -> Tuple[Any, dict]:
    """Bucketed (and/or adaptive) reduce of the whole gradient tree: the
    flat concatenation is cut per :func:`plan_buckets` and each bucket
    runs its own independent collective — the schedulable unit the
    overlap pipeline rides.  With ``adaptive``, each bucket's rung is the
    max (highest-fidelity) rung of its leaves, selected by ``lax.switch``
    — the rung indices are derived from psum'd norms, so every
    participant takes the same branch and the collectives stay matched.

    Hierarchical compressed configs with ``dcn_schedule="earliest"``
    thread an ``optimization_barrier`` token through the buckets in
    index order — bucket ``i`` holds the flat range the optimizer apply
    consumes ``i``-th, so issue order matches consumption order
    (MLFabric's earliest-needed-first schedule) instead of leaving B
    same-priority dcn collectives to race.  The barrier is the
    identity: values are bit-identical to ``"free"`` (asserted in
    tests); only the dependency chain — and so XLA's issue order —
    changes.
    """
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    plan = plan_buckets(grads, config)
    axes = reduction_axes(config)
    has_ef = _carries_ef(config)
    lad = effective_ladder(config) if config.adaptive else ()
    new_state = dict(state)

    flat = _concat_flat(leaves)
    if has_ef:
        acc_flat = flat + _concat_flat(
            jax.tree_util.tree_leaves(state["ef"]))
    else:
        acc_flat = flat

    n_buckets = len(plan.ranges)
    if config.mode == "int8" or "int8" in lad:
        key, use = jax.random.split(state["key"])
        bucket_keys = jax.random.split(use, n_buckets)
        new_state["key"] = key
    else:
        bucket_keys = [jax.random.PRNGKey(0)] * n_buckets

    if config.adaptive:
        rungs = state["rung"]                            # (n_leaves,) i32
        branches = [_segment_reducer(spec, config) for spec in lad]
    chain = (config.dcn_axis is not None and config.mode != "exact"
             and config.dcn_schedule == "earliest" and n_buckets > 1)
    token = acc_flat[:1]
    out_parts, unsent_parts, fill_parts = [], [], []
    for bi, (lo, hi) in enumerate(plan.ranges):
        acc = acc_flat[lo:hi]
        if chain:
            acc, token = lax.optimization_barrier((acc, token))
        if config.adaptive:
            b_rung = jnp.max(rungs[np.asarray(plan.bucket_leaves[bi])])
            red, unsent, fill = lax.switch(b_rung, branches, acc,
                                           bucket_keys[bi])
        else:
            red, unsent, fill = _segment_reducer(
                _mode_spec(config), config)(acc, bucket_keys[bi])
        if chain:
            token = red[:1]
        out_parts.append(red)
        unsent_parts.append(unsent)
        fill_parts.append(fill)
    if "fill" in state:
        _update_fill_state(new_state, state, fill_parts,
                           plan.bucket_sizes, config)

    out_leaves = _split_flat(jnp.concatenate(out_parts) if n_buckets > 1
                             else out_parts[0], plan)
    if has_ef:
        ef_leaves = _split_flat(jnp.concatenate(unsent_parts)
                                if n_buckets > 1 else unsent_parts[0], plan)
        new_state["ef"] = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(state["ef"]), ef_leaves)

    if config.adaptive:
        # policy update: psum'd per-leaf norms -> ratio EMA -> windowed
        # rung step (identical on every participant by construction).
        # ONE batched psum for all 2*n_leaves scalars — per-collective
        # launch latency sits in the hot path this module optimizes.
        eps = 1e-12
        n_leaves = len(leaves)
        local_n2 = jnp.stack(
            [jnp.sum(jnp.square(l)) for l in leaves]
            + [jnp.sum(jnp.square(e)) for e in ef_leaves])
        summed_n2 = lax.psum(local_n2, axes)
        g_n2, r_n2 = summed_n2[:n_leaves], summed_n2[n_leaves:]
        ratio = jnp.sqrt(r_n2 / (g_n2 + eps))
        beta = 1.0 - 1.0 / config.adaptive_window
        ema = beta * state["ema"] + (1.0 - beta) * ratio
        tick = state["tick"] + 1
        up = (ema > config.adaptive_target).astype(jnp.int32)
        down = (ema < 0.5 * config.adaptive_target).astype(jnp.int32)
        proposed = jnp.clip(state["rung"] + up - down, 0, len(lad) - 1)
        new_state["rung"] = jnp.where(tick % config.adaptive_window == 0,
                                      proposed, state["rung"])
        new_state["ema"] = ema
        new_state["tick"] = tick

    return jax.tree_util.tree_unflatten(treedef, out_leaves), new_state


def reduce_gradients(grads: Any, state: dict, config: GradReduceConfig
                     ) -> Tuple[Any, dict]:
    """Sum ``grads`` across the mesh's reduction axes under ``config``.

    MUST run inside an SPMD context (``shard_map``) with
    ``reduction_axes(config)`` bound; ``grads`` are this participant's
    local contributions (their sum over participants is the quantity being
    approximated), ``state`` is this participant's squeezed reducer state
    (:func:`squeeze_state`).  Returns ``(reduced, new_state)``.
    ``mode="exact"`` is a plain per-leaf ``lax.psum`` over all reduction
    axes (hierarchical exact differs from the flat psum only in f32
    summation order).

    ``bucket_count > 0`` (or ``adaptive``) routes through the bucketed
    transport (:func:`_reduce_bucketed`): exact stays bit-identical
    (psum is elementwise — asserted in tests), compressed modes select
    top-k per bucket instead of per leaf.
    """
    if _bucketed(config):
        return _reduce_bucketed(grads, state, config)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    axes = reduction_axes(config)
    hier = config.dcn_axis is not None

    if config.mode == "exact":
        if not hier:
            return (jax.tree_util.tree_unflatten(
                treedef, [lax.psum(g, axes) for g in leaves]), state)
        out = []
        for g in leaves:
            shard, _ = _hier_scatter(g.reshape(-1), config.axis)
            shard = lax.psum(shard, config.dcn_axis)
            out.append(_hier_gather(shard, config.axis, g.size, g.shape))
        return jax.tree_util.tree_unflatten(treedef, out), state

    if config.mode == "topk":
        proto = resolved_wire_protocol(config)
        ef_leaves = jax.tree_util.tree_leaves(state["ef"])
        out, new_ef, fills = [], [], []
        for g, res in zip(leaves, ef_leaves):
            if not hier:
                acc = (g + res).reshape(-1)
                reduced, unsent, fill = _topk_allreduce(
                    acc, axes, config.density, proto)
                out.append(reduced.reshape(g.shape))
                new_ef.append(unsent.reshape(g.shape))
                fills.append(fill)
                continue
            # hierarchical: residual lives in the full gradient domain but
            # is nonzero only in this participant's own ICI slice, so the
            # reduce-scatter below re-injects it into exactly its shard.
            acc = (g + res).reshape(-1)
            shard, n_pad = _hier_scatter(acc, config.axis)
            reduced, unsent, fill = _topk_allreduce(
                shard, config.dcn_axis, config.density, proto,
                uniform_axes=reduction_axes(config))
            out.append(_hier_gather(reduced, config.axis, g.size, g.shape))
            new_ef.append(_embed_shard(unsent, config.axis, g.size,
                                       n_pad).reshape(g.shape))
            fills.append(fill)
        new_state = dict(state)
        new_state["ef"] = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(state["ef"]), new_ef)
        if "fill" in state:
            _update_fill_state(new_state, state, fills,
                               [g.size for g in leaves], config)
        return jax.tree_util.tree_unflatten(treedef, out), new_state

    # int8: one fresh rounding key per step, split per leaf
    key, use = jax.random.split(state["key"])
    leaf_keys = jax.random.split(use, max(len(leaves), 1))
    out = []
    for li, g in enumerate(leaves):
        if not hier:
            out.append(_int8_allreduce(g.reshape(-1), axes,
                                       config.block_size, leaf_keys[li],
                                       config.int8_accum).reshape(g.shape))
            continue
        shard, _ = _hier_scatter(g.reshape(-1), config.axis)
        shard = _int8_allreduce(shard, config.dcn_axis, config.block_size,
                                leaf_keys[li], config.int8_accum)
        out.append(_hier_gather(shard, config.axis, g.size, g.shape))
    new_state = dict(state)
    new_state["key"] = key
    return jax.tree_util.tree_unflatten(treedef, out), new_state


# ---------------------------------------------------------------------------
# overlap pipeline (SPMD context) + host-side drain
# ---------------------------------------------------------------------------


def pipelined_reduce(grads: Any, state: dict, config: GradReduceConfig
                     ) -> Tuple[Any, dict]:
    """The one-step-stale pipelined reduce: reduces the CARRIED pending
    gradient (the previous step's) and stores ``grads`` as the new
    pending.  The returned ``reduced`` has no data dependence on this
    step's ``grads``, so its bucket collectives can overlap the step's
    forward/backward compute — the schedule MLFabric argues for.  Legal
    under error feedback: the residual absorbs the staleness exactly as
    it absorbs sparsification.  The first step reduces the
    zeros-initialized pending — a deterministic no-op apply (top-k of
    zeros sends zeros, int8 quantizes zeros to zeros) — so no validity
    flag is needed.  Callers flush with :func:`drain_pending` at fit
    end; mid-fit checkpoints carry ``pending`` like any other state leaf
    and resume the schedule exactly."""
    pending = state["pending"]
    core = {k: v for k, v in state.items() if k != "pending"}
    reduced, new_core = reduce_gradients(pending, core, config)
    new_core["pending"] = grads
    return reduced, new_core


def drain_pending(state: dict) -> Any:
    """Host-side exact drain of everything a finished overlapped fit has
    not yet applied: the participant-sum of the carried ``pending``
    gradient plus the EF residual (both per-participant, stacked over
    the leading dim — for the hierarchical layout the residual slices
    are disjoint per participant, so the plain sum is exact there too).
    One apply at fit end costs one exact all-reduce worth of bytes and
    leaves zero unsent mass behind."""
    pend = jax.tree_util.tree_map(
        lambda a: np.asarray(a, np.float32).sum(0), state["pending"])
    if "ef" in state:
        ef = jax.tree_util.tree_map(
            lambda a: np.asarray(a, np.float32).sum(0), state["ef"])
        pend = jax.tree_util.tree_map(lambda p, e: p + e, pend, ef)
    return pend


# ---------------------------------------------------------------------------
# bytes-on-wire accounting (host side)
# ---------------------------------------------------------------------------


def _spec_payload(n: int, spec, config: GradReduceConfig) -> int:
    """Bytes ONE participant contributes for one ``n``-element transport
    unit at rung ``spec`` (a density, ``"int8"``, or ``"exact"``) on the
    compressed hop."""
    if spec == "exact":
        return 4 * n
    if spec == "int8":
        nb = -(-n // config.block_size)
        return n + 4 * nb                  # int8 payload + f32 scales
    # int32 index + f32 value per sent entry
    return 8 * _topk_k(n, float(spec))


def _transport_units(grads_like: Any, config: GradReduceConfig, rungs=None):
    """The (element count, rung spec) pairs the reduce actually ships:
    per leaf on the legacy path, per bucket when bucketed/adaptive —
    with each bucket's rung resolved exactly as :func:`_reduce_bucketed`
    resolves it (max over the bucket's leaves; ``rungs=None`` uses the
    initial rung everywhere)."""
    if not _bucketed(config):
        sizes = [int(np.prod(np.shape(g), dtype=np.int64) or 1)
                 for g in jax.tree_util.tree_leaves(grads_like)]
        return [(n, _mode_spec(config)) for n in sizes]
    plan = plan_buckets(grads_like, config)
    if not config.adaptive:
        return [(hi - lo, _mode_spec(config)) for lo, hi in plan.ranges]
    lad = effective_ladder(config)
    if rungs is None:
        rungs = [_initial_rung(config)] * len(plan.leaf_sizes)
    rungs = [int(r) for r in np.asarray(rungs).reshape(-1)]
    return [(hi - lo, lad[max(rungs[l] for l in plan.bucket_leaves[bi])])
            for bi, (lo, hi) in enumerate(plan.ranges)]


def _rd_wire_unit(n: int, k: int, p: int) -> Tuple[float, float]:
    """Analytic (best, worst) per-participant bytes-on-wire for ONE
    ``n``-element hop unit shipping ``k`` (index, value) entries under
    recursive halving/doubling over ``p`` participants — total bytes
    across the hop divided by ``p``.

    Best case: every participant picks the same support, so the union
    never grows — halving routes ``k(1 - 1/core)`` entries per rank,
    doubling gathers the same back (the SparCML ~P/2 saving over the
    all-gather's ``(p-1)k`` per rank).  Worst case: supports are
    disjoint, capacity doubles every round until the range bound bites,
    and the doubling phase ships ``min(sparse, dense-switchover)``.
    Folding (non-power-of-two p) adds the extras' entry hand-off up
    front and a dense result broadcast at the end — both counted."""
    core, rounds, extras = rd_topology(p)
    n_pad = -(-n // core) * core
    best = 8.0 * k * extras                       # pre-fold hand-off
    best += core * 8.0 * k * (1.0 - 1.0 / core)   # halving, union stays k
    best += 8.0 * k * (core - 1)                  # sparse doubling
    best += 4.0 * n_pad * extras                  # post-fold dense result
    worst = 8.0 * k * extras
    cap = min((2 if extras else 1) * k, n_pad)
    for r in range(rounds):
        half = n_pad >> (r + 1)
        worst += core * 8.0 * min(cap, half)
        cap = min(2 * cap, half) if half else 0
    union = min(p * k, n_pad)
    worst += (core - 1) * min(8.0 * union, 4.0 * n_pad)
    worst += 4.0 * n_pad * extras
    return best / p, worst / p


def _measured_wire_bytes(fill_rows: np.ndarray, rounds: int) -> float:
    """Per-participant measured bytes from fill vectors (participant-
    averaged rows, one per transport unit): 8 B per sparse entry in the
    halving rounds, doubling billed at 8 B/entry sparse blending to
    4 B/element dense by the switchover rate, plus the fold traffic."""
    total = 0.0
    for row in fill_rows:
        sw = float(row[FILL_SWITCH_SLOT])
        total += 8.0 * float(row[:rounds].sum())
        total += float(row[FILL_DOUBLING_BASE:FILL_DOUBLING_BASE
                           + rounds].sum()) * (8.0 - 4.0 * sw)
        total += 8.0 * float(row[FILL_PREFOLD_SLOT])
        total += 4.0 * float(row[FILL_POSTFOLD_SLOT])
    return total


def payload_bytes(grads_like: Any, config: GradReduceConfig, *,
                  ici_size: int = 1, rungs=None, hop_size: int = None,
                  fill=None) -> dict:
    """Honest per-participant, per-step payload accounting: the bytes each
    participant injects into the reduction it is compressing (indices +
    values for topk, int8 payload + per-block f32 scales for int8), vs the
    4-bytes/element dense payload of the same hop.  Schedule multipliers
    (ring ``2(P-1)/P`` for dense all-reduce, ``P-1`` for the all-gather
    sparse form) are deliberately excluded — they depend on the transport,
    the payload does not.

    Bucketed/adaptive configs account per BUCKET (top-k granularity
    follows the transport); ``rungs`` — the realized per-leaf rung
    indices fetched from reducer state — resolves the adaptive ladder,
    defaulting to the initial rung.

    Hierarchical configs report the two fabrics SEPARATELY: the
    compressed DCN hop ships the ICI-scattered shard (unit sizes
    ``ceil(n / ici_size)``) and reports as ``dcn_dense_bytes`` /
    ``dcn_compressed_bytes`` / ``dcn_compression_ratio``; the exact ICI
    reduce-scatter + all-gather bytes ride in ``ici_bytes``;
    ``total_wire_bytes`` sums both fabrics — the single number that used
    to be reported (``compressed_bytes``, kept as the DCN-hop alias) hid
    which fabric the compression actually saved.

    ``hop_size`` (the compressed hop's participant count) unlocks the
    schedule-INCLUSIVE ``wire`` section comparing the two sparse
    transports per participant: the all-gather's ``(P-1) * 8k`` received
    bytes vs recursive halving/doubling's analytic best (overlapping
    supports — the ~P/2 saving) and worst (disjoint supports) bounds,
    per round, fabric split intact (top-k units only; exact/int8 units
    ship the same bytes under either protocol).  ``fill`` — the ``fill``
    reducer-state leaf (participant-stacked or squeezed) — adds the
    MEASURED bytes and per-round fill-in curve of the realized run.
    The legacy fields above stay payload-only and unchanged."""
    units = _transport_units(grads_like, config, rungs)
    hier = config.dcn_axis is not None
    if hier and ici_size > 1:
        hop_units = [(-(-n // ici_size), spec) for n, spec in units]
    else:
        hop_units = units
    dense = sum(4 * n for n, _ in hop_units)
    compressed = sum(_spec_payload(n, spec, config)
                     for n, spec in hop_units)
    report = {
        "mode": config.mode,
        "dense_bytes": int(dense),
        "compressed_bytes": int(compressed),
        "compression_ratio": (round(dense / compressed, 3)
                              if compressed else None),
        "total_wire_bytes": int(compressed),
    }
    if _bucketed(config):
        report["bucket_count"] = len(units)
    if hier:
        # reduce-scatter + all-gather of the full unit over ICI, ring
        # schedule: each participant moves ~2 * 4n * (I-1)/I bytes
        ici = int(sum(
            math.ceil(2 * 4 * n * (ici_size - 1) / max(ici_size, 1))
            for n, _ in units))
        report["ici_bytes"] = ici
        report["dcn_dense_bytes"] = int(dense)
        report["dcn_compressed_bytes"] = int(compressed)
        report["dcn_compression_ratio"] = report["compression_ratio"]
        report["total_wire_bytes"] = int(compressed) + ici
    report["wire_protocol"] = (
        "rd" if _rd_engaged(config) else "allgather")
    if hop_size is not None and hop_size > 1:
        tk = [(n, float(spec)) for n, spec in hop_units
              if not isinstance(spec, str)]
        if tk:
            p = int(hop_size)
            core, rounds, extras = rd_topology(p)
            allgather = sum(8.0 * _topk_k(n, d) * (p - 1) for n, d in tk)
            best = worst = 0.0
            for n, d in tk:
                b, w = _rd_wire_unit(n, _topk_k(n, d), p)
                best += b
                worst += w
            wire = {
                "hop_participants": p,
                "core": core,
                "rounds": rounds,
                "extras": extras,
                "topk_units": len(tk),
                "allgather_bytes": int(round(allgather)),
                "rd_bytes_best": int(round(best)),
                "rd_bytes_worst": int(round(worst)),
                "rd_bytes_measured": None,
                "fill_rounds_measured": None,
                "switch_rate_measured": None,
                "reduction_vs_allgather_best": (
                    round(allgather / best, 3) if best else None),
                "reduction_vs_allgather_measured": None,
            }
            if fill is not None:
                f = np.asarray(fill, np.float32)
                if f.ndim == 3:          # participant-stacked state leaf
                    f = f.mean(axis=0)
                if f.ndim == 1:
                    f = f[None]
                measured = _measured_wire_bytes(f, rounds)
                wire["rd_bytes_measured"] = round(float(measured), 1)
                wire["fill_rounds_measured"] = [
                    round(float(v), 2) for v in f[:, :rounds].sum(axis=0)]
                wire["switch_rate_measured"] = round(
                    float(f[:, FILL_SWITCH_SLOT].mean()), 3)
                if measured:
                    wire["reduction_vs_allgather_measured"] = round(
                        allgather / measured, 3)
            report["wire"] = wire
    return report


def bucket_report(grads_like: Any, config: GradReduceConfig,
                  rungs=None) -> dict:
    """The analytic bucket plan the bench publishes even when timing legs
    are skipped (pure shape math, device-independent): bucket count,
    dense bytes per bucket, each bucket's resolved rung payload, and the
    per-leaf chosen density (``rungs`` = realized per-leaf rung indices
    from reducer state; ``None`` = the initial rung)."""
    plan = plan_buckets(grads_like, config)
    units = _transport_units(grads_like, config, rungs)
    lad = effective_ladder(config) if config.adaptive else ()
    if config.adaptive:
        if rungs is None:
            leaf_rungs = [_initial_rung(config)] * len(plan.leaf_sizes)
        else:
            leaf_rungs = [int(r) for r in np.asarray(rungs).reshape(-1)]
        leaf_specs = [lad[r] for r in leaf_rungs]
    else:
        leaf_specs = [_mode_spec(config)] * len(plan.leaf_sizes)

    def spec_entry(spec):
        if spec == "exact":
            return {"mode": "exact", "density": 1.0}
        if spec == "int8":
            return {"mode": "int8", "density": None}
        return {"mode": "topk", "density": float(spec)}

    # the transfer schedule _reduce_bucketed enforces: hierarchical
    # compressed reduces chain buckets in consumption order (earliest-
    # needed first); everything else launches unordered.
    chained = (config.dcn_axis is not None and config.mode != "exact"
               and config.dcn_schedule == "earliest" and len(units) > 1)
    return {
        "bucket_count": len(units),
        "bucket_bytes": [4 * n for n, _ in units],
        "bucket_payload_bytes": [_spec_payload(n, spec, config)
                                 for n, spec in units],
        "per_leaf": [{"leaf": i, "elems": plan.leaf_sizes[i],
                      **spec_entry(leaf_specs[i])}
                     for i in range(len(plan.leaf_sizes))],
        "schedule": {
            "policy": (config.dcn_schedule
                       if config.dcn_axis is not None else None),
            "order": list(range(len(units))) if chained else None,
        },
    }
