"""Configurable gradient reduction: exact / sparse / quantized / hierarchical.

The reference's entire scale-out story is its network shuffle layer; the
TPU-native analog has so far been the implicit all-reduce GSPMD inserts
for data-parallel gradients.  This module makes that reduction an explicit,
configurable operator so gradient bytes-on-wire become a first-class,
measured quantity (the SparCML/SwitchML posture — arXiv:1802.08021,
arXiv:1903.06701):

- ``mode="exact"``   — ``lax.psum``; adopters keep their legacy implicit
  path when the config is absent or exact, so the default is bit-identical
  to the pre-reducer code.
- ``mode="topk"``    — per-leaf top-|g| sparsification at ``density`` with
  **error feedback**: the unsent residual is carried in reducer state
  (EF-SGD semantics — what was not sent this step is added to the next
  step's gradient, so the compression error stays bounded instead of
  accumulating).  The reduce itself is the all-gather form of a sparse
  all-reduce: each participant contributes ``k`` (index, value) pairs and
  every participant scatter-adds the gathered pairs locally.
- ``mode="int8"``    — block-quantized reduce: per-``block_size`` max-abs
  scales, **stochastic rounding** (unbiased — no residual needed; the
  rounding key is carried in reducer state), int8 payloads + f32 scales
  all-gathered and dequantized-summed locally.
- hierarchical (``dcn_axis`` set) — the two-tier composition for
  :func:`~flink_ml_tpu.parallel.distributed.hybrid_mesh`:
  ``reduce_scatter`` over the fast ICI axis first (exact), the compressed
  all-reduce over the slow ``dcn`` axis on the 1/I-sized shard, then
  ``all_gather`` back over ICI — only the inter-host hop pays for (or
  benefits from) compression.

All reduction functions must run inside an SPMD context (``shard_map``)
with the named axes bound; reducer state is per-participant — adopters
carry it with a leading participant dim sharded over the reduction axes
(see :func:`init_state`) so it rides scan carries and checkpoints like any
other optimizer state.
"""

from __future__ import annotations

import math

from dataclasses import dataclass
from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = [
    "GradReduceConfig",
    "MODES",
    "init_state",
    "mesh_layout",
    "needs_state",
    "payload_bytes",
    "reduce_gradients",
    "reduction_axes",
    "squeeze_state",
    "unsqueeze_state",
]

MODES = ("exact", "topk", "int8")

AxisSpec = Union[str, Tuple[str, ...]]


@dataclass(frozen=True)
class GradReduceConfig:
    """How data-parallel gradients are summed across the mesh.

    ``axis`` is the (fast/ICI) reduction axis; ``dcn_axis`` — when set —
    selects the hierarchical composition: exact reduce-scatter over
    ``axis``, the configured compression over ``dcn_axis`` only, gather
    back.  With ``dcn_axis=None`` the compression applies to the whole
    flat reduce over ``axis``.

    ``density`` (topk) is the fraction of each leaf's elements sent per
    step (``k = max(1, floor(density * n))`` — floor, so the advertised
    compression ratio is a lower bound).  ``block_size`` (int8) is the
    elements-per-scale quantization granule; ``seed`` feeds the stochastic
    rounding stream.
    """

    mode: str = "exact"
    density: float = 0.1
    block_size: int = 256
    axis: AxisSpec = "data"
    dcn_axis: Optional[str] = None
    seed: int = 0

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(f"mode must be one of {MODES}, got {self.mode!r}")
        if self.mode == "topk" and not 0.0 < self.density <= 1.0:
            raise ValueError(f"density must be in (0, 1], got {self.density}")
        if self.mode == "int8" and self.block_size <= 0:
            raise ValueError(
                f"block_size must be positive, got {self.block_size}")
        if self.dcn_axis is not None and not isinstance(self.axis, str):
            raise ValueError(
                "hierarchical reduction needs a single ICI axis name; got "
                f"axis={self.axis!r}")


def reduction_axes(config: GradReduceConfig) -> Tuple[str, ...]:
    """Every mesh axis the reduction sums over (ICI axes + the dcn axis)."""
    axes = (config.axis,) if isinstance(config.axis, str) else tuple(
        config.axis)
    if config.dcn_axis is not None:
        axes = (config.dcn_axis,) + axes
    return axes


def needs_state(config: GradReduceConfig) -> bool:
    return config.mode in ("topk", "int8")


def mesh_layout(config: GradReduceConfig, mesh) -> Tuple[Tuple[str, ...],
                                                         int, Any]:
    """(reduction axes, participant count, batch PartitionSpec entry) for
    running this config on ``mesh`` — THE one copy of the axis validation
    every adopter (sgd, widedeep) shares, with the loud error for axes
    the mesh does not have."""
    axes = reduction_axes(config)
    missing = [a for a in axes if a not in mesh.shape]
    if missing:
        raise ValueError(
            f"grad_reduce axes {missing} not in mesh {list(mesh.shape)}; "
            "build the mesh with the reduction axes (e.g. "
            "distributed.hybrid_mesh for a dcn axis)")
    n_participants = int(np.prod([mesh.shape[a] for a in axes]))
    return axes, n_participants, (axes if len(axes) > 1 else axes[0])


def _topk_k(n: int, density: float) -> int:
    return max(1, int(n * density))


def init_state(config: GradReduceConfig, grads_like: Any,
               n_participants: int) -> dict:
    """Per-participant reducer state, stacked over a leading participant
    dim of size ``n_participants`` (the product of the reduction axes'
    sizes) — adopters shard that dim over the reduction axes and squeeze
    it inside ``shard_map`` (:func:`squeeze_state`).

    ``topk`` carries the error-feedback residual (zeros-like every
    gradient leaf); ``int8`` carries one PRNG key per participant for the
    stochastic-rounding stream.  ``exact`` needs no state (``{}``).
    """
    state: dict = {}
    if config.mode == "topk":
        state["ef"] = jax.tree_util.tree_map(
            lambda g: jnp.zeros((n_participants,) + np.shape(g), jnp.float32),
            grads_like)
    if config.mode == "int8":
        base = jax.random.PRNGKey(config.seed)
        state["key"] = jax.vmap(
            lambda i: jax.random.fold_in(base, i))(
                jnp.arange(n_participants, dtype=jnp.int32))
    return state


def squeeze_state(state: dict) -> dict:
    """Drop the leading participant dim of the local (1, ...) state slices
    inside ``shard_map``."""
    return jax.tree_util.tree_map(lambda a: a[0], state)


def unsqueeze_state(state: dict) -> dict:
    """Restore the leading participant dim on the way out of ``shard_map``."""
    return jax.tree_util.tree_map(lambda a: a[None], state)


# ---------------------------------------------------------------------------
# per-leaf compressed all-reduces (SPMD context)
# ---------------------------------------------------------------------------


def _topk_allreduce(flat: jnp.ndarray, axes: AxisSpec, density: float
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """All-gather sparse all-reduce of one flat leaf: every participant
    contributes its top-k (index, value) pairs; each scatter-adds the
    gathered pairs locally.  Returns ``(reduced, unsent)`` where
    ``unsent`` is this participant's residual (its accumulated gradient
    with the sent entries zeroed)."""
    k = _topk_k(flat.size, density)
    _, idx = lax.top_k(jnp.abs(flat), k)
    vals = flat[idx]
    unsent = flat.at[idx].set(0.0)
    all_idx = lax.all_gather(idx, axes)        # (P, k)
    all_vals = lax.all_gather(vals, axes)
    reduced = jnp.zeros_like(flat).at[all_idx.reshape(-1)].add(
        all_vals.reshape(-1))
    return reduced, unsent


def _int8_allreduce(flat: jnp.ndarray, axes: AxisSpec, block: int,
                    key: jnp.ndarray) -> jnp.ndarray:
    """Block-quantized all-reduce of one flat leaf: per-block max-abs
    scales, stochastic rounding (``floor(x/scale + u)``, u~U[0,1) — the
    unbiased round), int8 payload + f32 scales all-gathered, dequantized
    and summed locally."""
    n = flat.size
    n_pad = -(-n // block) * block
    padded = jnp.concatenate(
        [flat, jnp.zeros((n_pad - n,), flat.dtype)]) if n_pad > n else flat
    blocks = padded.reshape(-1, block)
    scale = jnp.maximum(jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
                        / 127.0, 1e-12)
    u = jax.random.uniform(key, blocks.shape)
    q = jnp.clip(jnp.floor(blocks / scale + u), -127, 127).astype(jnp.int8)
    all_q = lax.all_gather(q, axes)            # (P, nb, block)
    all_scale = lax.all_gather(scale, axes)    # (P, nb, 1)
    total = jnp.sum(all_q.astype(jnp.float32) * all_scale, axis=0)
    return total.reshape(-1)[:n]


def _hier_scatter(flat: jnp.ndarray, ici_axis: str
                  ) -> Tuple[jnp.ndarray, int]:
    """Exact reduce-scatter of one flat leaf over the ICI axis: returns
    (per-participant shard summed over ICI, padded length)."""
    from .collectives import axis_size

    ici = axis_size(ici_axis)
    n = flat.size
    n_pad = -(-n // ici) * ici
    if n_pad > n:
        flat = jnp.concatenate([flat, jnp.zeros((n_pad - n,), flat.dtype)])
    shard = lax.psum_scatter(flat, ici_axis, scatter_dimension=0, tiled=True)
    return shard, n_pad


def _hier_gather(shard: jnp.ndarray, ici_axis: str, n: int,
                 shape) -> jnp.ndarray:
    return lax.all_gather(shard, ici_axis, tiled=True)[:n].reshape(shape)


def _embed_shard(shard: jnp.ndarray, ici_axis: str, n: int,
                 n_pad: int) -> jnp.ndarray:
    """Place this participant's shard-domain residual back in the full
    gradient domain (zeros outside its own slice) so reducer state keeps
    one uniform per-leaf shape in every mode.  At the next step the
    reduce-scatter routes each participant's slice back into exactly its
    shard — the shard-domain EF recursion, carried full-size."""
    i = lax.axis_index(ici_axis)
    full = jnp.zeros((n_pad,), shard.dtype)
    full = lax.dynamic_update_slice(full, shard, (i * shard.size,))
    return full[:n]


def reduce_gradients(grads: Any, state: dict, config: GradReduceConfig
                     ) -> Tuple[Any, dict]:
    """Sum ``grads`` across the mesh's reduction axes under ``config``.

    MUST run inside an SPMD context (``shard_map``) with
    ``reduction_axes(config)`` bound; ``grads`` are this participant's
    local contributions (their sum over participants is the quantity being
    approximated), ``state`` is this participant's squeezed reducer state
    (:func:`squeeze_state`).  Returns ``(reduced, new_state)``.
    ``mode="exact"`` is a plain per-leaf ``lax.psum`` over all reduction
    axes (hierarchical exact differs from the flat psum only in f32
    summation order).
    """
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    axes = reduction_axes(config)
    hier = config.dcn_axis is not None

    if config.mode == "exact":
        if not hier:
            return (jax.tree_util.tree_unflatten(
                treedef, [lax.psum(g, axes) for g in leaves]), state)
        out = []
        for g in leaves:
            shard, _ = _hier_scatter(g.reshape(-1), config.axis)
            shard = lax.psum(shard, config.dcn_axis)
            out.append(_hier_gather(shard, config.axis, g.size, g.shape))
        return jax.tree_util.tree_unflatten(treedef, out), state

    if config.mode == "topk":
        ef_leaves = jax.tree_util.tree_leaves(state["ef"])
        out, new_ef = [], []
        for g, res in zip(leaves, ef_leaves):
            if not hier:
                acc = (g + res).reshape(-1)
                reduced, unsent = _topk_allreduce(acc, axes, config.density)
                out.append(reduced.reshape(g.shape))
                new_ef.append(unsent.reshape(g.shape))
                continue
            # hierarchical: residual lives in the full gradient domain but
            # is nonzero only in this participant's own ICI slice, so the
            # reduce-scatter below re-injects it into exactly its shard.
            acc = (g + res).reshape(-1)
            shard, n_pad = _hier_scatter(acc, config.axis)
            reduced, unsent = _topk_allreduce(shard, config.dcn_axis,
                                              config.density)
            out.append(_hier_gather(reduced, config.axis, g.size, g.shape))
            new_ef.append(_embed_shard(unsent, config.axis, g.size,
                                       n_pad).reshape(g.shape))
        new_state = dict(state)
        new_state["ef"] = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(state["ef"]), new_ef)
        return jax.tree_util.tree_unflatten(treedef, out), new_state

    # int8: one fresh rounding key per step, split per leaf
    key, use = jax.random.split(state["key"])
    leaf_keys = jax.random.split(use, max(len(leaves), 1))
    out = []
    for li, g in enumerate(leaves):
        if not hier:
            out.append(_int8_allreduce(g.reshape(-1), axes,
                                       config.block_size,
                                       leaf_keys[li]).reshape(g.shape))
            continue
        shard, _ = _hier_scatter(g.reshape(-1), config.axis)
        shard = _int8_allreduce(shard, config.dcn_axis, config.block_size,
                                leaf_keys[li])
        out.append(_hier_gather(shard, config.axis, g.size, g.shape))
    new_state = dict(state)
    new_state["key"] = key
    return jax.tree_util.tree_unflatten(treedef, out), new_state


# ---------------------------------------------------------------------------
# bytes-on-wire accounting (host side)
# ---------------------------------------------------------------------------


def _leaf_payload(n: int, config: GradReduceConfig) -> int:
    """Bytes ONE participant contributes for one leaf of ``n`` elements on
    the compressed hop."""
    if config.mode == "exact":
        return 4 * n
    if config.mode == "topk":
        # int32 index + f32 value per sent entry
        return 8 * _topk_k(n, config.density)
    nb = -(-n // config.block_size)
    return n + 4 * nb                      # int8 payload + f32 scales


def payload_bytes(grads_like: Any, config: GradReduceConfig, *,
                  ici_size: int = 1) -> dict:
    """Honest per-participant, per-step payload accounting: the bytes each
    participant injects into the reduction it is compressing (indices +
    values for topk, int8 payload + per-block f32 scales for int8), vs the
    4-bytes/element dense payload of the same hop.  Schedule multipliers
    (ring ``2(P-1)/P`` for dense all-reduce, ``P-1`` for the all-gather
    sparse form) are deliberately excluded — they depend on the transport,
    the payload does not.

    Hierarchical configs account the DCN hop (the one being compressed):
    leaf sizes shrink to the ICI-scattered shard ``ceil(n / ici_size)``;
    the exact ICI reduce-scatter/gather bytes ride separately in
    ``ici_bytes``.
    """
    shapes = [int(np.prod(np.shape(g), dtype=np.int64) or 1)
              for g in jax.tree_util.tree_leaves(grads_like)]
    hier = config.dcn_axis is not None
    if hier and ici_size > 1:
        hop_sizes = [-(-n // ici_size) for n in shapes]
    else:
        hop_sizes = shapes
    dense = sum(4 * n for n in hop_sizes)
    compressed = sum(_leaf_payload(n, config) for n in hop_sizes)
    report = {
        "mode": config.mode,
        "dense_bytes": int(dense),
        "compressed_bytes": int(compressed),
        "compression_ratio": (round(dense / compressed, 3)
                              if compressed else None),
    }
    if hier:
        # reduce-scatter + all-gather of the full leaf over ICI, ring
        # schedule: each participant moves ~2 * 4n * (I-1)/I bytes
        report["ici_bytes"] = int(sum(
            math.ceil(2 * 4 * n * (ici_size - 1) / max(ici_size, 1))
            for n in shapes))
    return report
