"""Ulysses-style sequence parallelism: all-to-all head/sequence re-shard.

Complementary to ring attention: instead of rotating K/V, one
``all_to_all`` turns sequence-sharded activations into head-sharded ones, a
dense local attention runs per device over the FULL sequence for its subset
of heads, and a second ``all_to_all`` restores sequence sharding.  Two a2a
hops instead of (n-1) ring steps — better when heads >= devices and the
interconnect is all-to-all capable (intra-pod ICI)."""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax import lax
from .collectives import shard_map_fn
from jax.sharding import Mesh, PartitionSpec as P

from .ring_attention import attention_reference

__all__ = ["ulysses_attention"]


def ulysses_attention(q, k, v, *, mesh: Mesh, axis: str = "seq",
                      causal: bool = False, scale: Optional[float] = None):
    """Exact attention with sequence sharded over ``axis``; heads must be
    divisible by the axis size.  Layout (b, s, h, d)."""
    n = int(mesh.shape[axis])
    b, s, h, d = q.shape
    if h % n:
        raise ValueError(f"heads {h} not divisible by axis size {n}")
    if s % n:
        raise ValueError(f"seq {s} not divisible by axis size {n}")

    def local(qb, kb, vb):
        # (b, s/n, h, d) -> (b, s, h/n, d): gather sequence, scatter heads
        def seq_to_heads(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        def heads_to_seq(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        q_h = seq_to_heads(qb)
        k_h = seq_to_heads(kb)
        v_h = seq_to_heads(vb)
        out = attention_reference(q_h, k_h, v_h, causal=causal, scale=scale)
        return heads_to_seq(out)

    spec = P(None, axis, None, None)
    fn = shard_map_fn(local, mesh=mesh, in_specs=(spec, spec, spec),
                      out_specs=spec)
    return fn(q, k, v)
