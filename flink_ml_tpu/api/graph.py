"""Graph / GraphBuilder / GraphModel — DAG composition of stages.

The reference snapshot ships only the linear ``Pipeline`` (SURVEY §2.1), but
the Flink ML 2.x API line pairs it with a Graph API for non-linear wiring:
stages consume and produce named tables, estimators are fitted on their
resolved inputs and replaced by their models, and the whole DAG is itself an
``Estimator`` whose fit yields a ``GraphModel``.

TPU-native reading: composition is pure host-side wiring — each node's
``fit``/``transform`` launches its own jitted programs; the graph adds no
device work of its own.  Acyclicity is by construction: a node's inputs must
be ``TableId``s that already exist when the node is added, so insertion
order IS a topological order.

Example::

    builder = GraphBuilder()
    raw = builder.source()
    scaled = builder.add_stage(StandardScaler(), [raw])[0]
    pred = builder.add_stage(KMeans(), [scaled])[0]
    graph = builder.build(inputs=[raw], outputs=[pred])   # an Estimator
    model = graph.fit(table)                              # a GraphModel
    (result,) = model.transform(table)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence

from ..utils import persist
from .stage import AlgoOperator, Estimator, Model, Stage

__all__ = ["TableId", "GraphBuilder", "Graph", "GraphModel"]


@dataclass(frozen=True)
class TableId:
    """Opaque handle for a table flowing through the graph."""

    id: int

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"TableId({self.id})"


@dataclass
class _GraphNode:
    stage: Stage
    inputs: List[int]
    outputs: List[int]


class GraphBuilder:
    """Accumulates nodes; ``build`` freezes them into a ``Graph``."""

    def __init__(self):
        self._next_id = 0
        self._known: set = set()
        self._nodes: List[_GraphNode] = []

    def _new_id(self) -> TableId:
        tid = TableId(self._next_id)
        self._next_id += 1
        self._known.add(tid.id)
        return tid

    def source(self) -> TableId:
        """Declare an external input table (the analog of
        ``GraphBuilder.createTableId`` used for graph inputs)."""
        return self._new_id()

    def add_stage(self, stage: Stage, inputs: Sequence[TableId],
                  n_outputs: int = 1) -> List[TableId]:
        """Wire ``stage`` to consume ``inputs``; returns its ``n_outputs``
        fresh output ids.  Inputs must already exist (sources or earlier
        outputs), which keeps the graph acyclic by construction."""
        if not isinstance(stage, (Estimator, AlgoOperator)):
            raise TypeError(f"{type(stage).__name__} is neither an Estimator "
                            "nor an AlgoOperator")
        if n_outputs < 1:
            raise ValueError("n_outputs must be >= 1")
        in_ids = []
        for t in inputs:
            if not isinstance(t, TableId) or t.id not in self._known:
                raise ValueError(f"Unknown input table {t!r}; inputs must "
                                 "come from source() or earlier add_stage()")
            in_ids.append(t.id)
        outs = [self._new_id() for _ in range(n_outputs)]
        self._nodes.append(_GraphNode(stage, in_ids, [o.id for o in outs]))
        return outs

    def build(self, inputs: Sequence[TableId],
              outputs: Sequence[TableId]) -> "Graph":
        input_ids = [t.id for t in inputs]
        # every node input must be reachable: a declared graph input or an
        # earlier node's output (a forgotten source() must fail here, not as
        # a bare KeyError mid-fit)
        available = set(input_ids)
        for node in self._nodes:
            for i in node.inputs:
                if i not in available:
                    raise ValueError(
                        f"Node input TableId({i}) is neither a build() input "
                        "nor produced by an earlier node — did you forget to "
                        "list a source() in build(inputs=...)?")
            available.update(node.outputs)
        for t in outputs:
            if t.id not in available:
                raise ValueError(f"Output {t!r} is produced by no node")
        return Graph(self._nodes, input_ids, [t.id for t in outputs])


def _run_node(stage: AlgoOperator, node: _GraphNode,
              env: Dict[int, object]) -> None:
    """Transform the node's resolved inputs into its output slots — THE one
    place the arity check and slot assignment live (fit and transform both
    route through it)."""
    results = stage.transform(*[env[i] for i in node.inputs])
    if len(results) < len(node.outputs):
        raise ValueError(
            f"{type(stage).__name__} produced {len(results)} tables, "
            f"but the graph wires {len(node.outputs)}")
    for out_id, table in zip(node.outputs, results):
        env[out_id] = table


class _GraphBase:
    """Shared wiring + persistence for Graph and GraphModel."""

    def __init__(self, nodes: Sequence[_GraphNode] = (),
                 input_ids: Sequence[int] = (),
                 output_ids: Sequence[int] = ()):
        super().__init__()  # continue the MRO into Estimator/Model params
        self._nodes = list(nodes)
        self._input_ids = list(input_ids)
        self._output_ids = list(output_ids)

    def _bind_inputs(self, inputs) -> Dict[int, object]:
        if len(inputs) != len(self._input_ids):
            raise ValueError(f"Expected {len(self._input_ids)} input tables, "
                             f"got {len(inputs)}")
        return dict(zip(self._input_ids, inputs))

    def _wiring(self) -> dict:
        return {
            "inputIds": self._input_ids,
            "outputIds": self._output_ids,
            "nodes": [{"inputs": n.inputs, "outputs": n.outputs}
                      for n in self._nodes],
        }

    def save(self, path: str) -> None:
        persist.save_metadata(self, path, {"graph": self._wiring()})
        for i, node in enumerate(self._nodes):
            node.stage.save(persist.stage_path(path, i))

    @classmethod
    def load(cls, path: str):
        meta = persist.load_metadata(path, cls)
        wiring = meta["graph"]
        nodes = [
            _GraphNode(persist.load_stage(persist.stage_path(path, i)),
                       spec["inputs"], spec["outputs"])
            for i, spec in enumerate(wiring["nodes"])
        ]
        return cls(nodes, wiring["inputIds"], wiring["outputIds"])


class Graph(_GraphBase, Estimator["GraphModel"]):
    """The frozen DAG as an Estimator: fitting walks nodes in insertion
    (= topological) order, fitting estimators on their resolved inputs and
    transforming through every node to feed downstream consumers."""

    def fit(self, *inputs) -> "GraphModel":
        env = self._bind_inputs(inputs)
        fitted: List[AlgoOperator] = []
        for node in self._nodes:
            if isinstance(node.stage, AlgoOperator):
                stage: AlgoOperator = node.stage
            else:
                stage = node.stage.fit(*[env[i] for i in node.inputs])
            fitted.append(stage)
            _run_node(stage, node, env)
        model_nodes = [_GraphNode(s, n.inputs, n.outputs)
                       for s, n in zip(fitted, self._nodes)]
        return GraphModel(model_nodes, self._input_ids, self._output_ids)


class GraphModel(_GraphBase, Model):
    """The fitted DAG: transform re-walks the wiring with models only."""

    def transform(self, *inputs) -> List:
        env = self._bind_inputs(inputs)
        for node in self._nodes:
            _run_node(node.stage, node, env)
        return [env[i] for i in self._output_ids]
