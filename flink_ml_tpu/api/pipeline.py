"""Pipeline and PipelineModel.

Mirror of ``api/core/Pipeline.java`` and ``api/core/PipelineModel.java``:
``Pipeline.fit`` walks the stage list, fits every Estimator into a Model,
and keeps transforming the inputs through each produced/passed stage up to
(and excluding) the last Estimator (``Pipeline.java:74-103``).  The result is
a ``PipelineModel`` chaining ``transform`` across all resulting stages
(``PipelineModel.java:58-64``).
"""

from __future__ import annotations

from typing import List, Sequence

from ..utils import persist
from .stage import AlgoOperator, Estimator, Model, Stage

__all__ = ["Pipeline", "PipelineModel"]


class Pipeline(Estimator["PipelineModel"]):
    def __init__(self, stages: Sequence[Stage] = ()):  # no-arg constructible
        super().__init__()
        self._stages: List[Stage] = list(stages)

    @property
    def stages(self) -> List[Stage]:
        return list(self._stages)

    def fit(self, *inputs) -> "PipelineModel":
        """``Pipeline.java:74-103`` semantics: only transform inputs while
        stages before the *last* Estimator still need them."""
        last_estimator_idx = -1
        for i, stage in enumerate(self._stages):
            if isinstance(stage, Estimator):
                last_estimator_idx = i

        transformed = list(inputs)
        model_stages: List[AlgoOperator] = []
        for i, stage in enumerate(self._stages):
            # AlgoOperator takes precedence over Estimator for dual-typed
            # stages, matching ``Pipeline.java:89-93``.
            if isinstance(stage, AlgoOperator):
                fitted: AlgoOperator = stage
            elif isinstance(stage, Estimator):
                fitted = stage.fit(*transformed)
            else:
                raise TypeError(
                    f"Pipeline stage {i} ({type(stage).__name__}) is neither "
                    "an Estimator nor an AlgoOperator")
            model_stages.append(fitted)
            if i < last_estimator_idx:
                transformed = list(fitted.transform(*transformed))
        return PipelineModel(model_stages)

    def save(self, path: str) -> None:
        persist.save_pipeline(self, self._stages, path)

    @classmethod
    def load(cls, path: str) -> "Pipeline":
        return cls(persist.load_pipeline(path, cls))


class PipelineModel(Model):
    def __init__(self, stages: Sequence[AlgoOperator] = ()):  # no-arg constructible
        super().__init__()
        self._stages: List[AlgoOperator] = list(stages)

    @property
    def stages(self) -> List[AlgoOperator]:
        return list(self._stages)

    def transform(self, *inputs) -> List:
        """Sequentially feed outputs of stage i into stage i+1
        (``PipelineModel.java:58-64``)."""
        tables = list(inputs)
        for stage in self._stages:
            tables = list(stage.transform(*tables))
        return tables

    def save(self, path: str) -> None:
        persist.save_pipeline(self, self._stages, path)

    @classmethod
    def load(cls, path: str) -> "PipelineModel":
        return cls(persist.load_pipeline(path, cls))
