"""Pipeline and PipelineModel.

Mirror of ``api/core/Pipeline.java`` and ``api/core/PipelineModel.java``:
``Pipeline.fit`` walks the stage list, fits every Estimator into a Model,
and keeps transforming the inputs through each produced/passed stage up to
(and excluding) the last Estimator (``Pipeline.java:74-103``).  The result is
a ``PipelineModel`` chaining ``transform`` across all resulting stages
(``PipelineModel.java:58-64``).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..utils import persist
from .stage import AlgoOperator, Estimator, Model, Stage

__all__ = ["Pipeline", "PipelineModel"]


def _stagewise(stages, tables: List) -> List:
    """The classic per-stage path.  A multi-output stage (RandomSplitter)
    fans the flow out; single-input stages then map over every table
    independently — the columnar-batch extension of
    ``PipelineModel.java:58-64`` (previously a >1-table flow had no
    defined semantics here)."""
    for stage in stages:
        if len(tables) == 1:
            tables = list(stage.transform(*tables))
        else:
            fanned: List = []
            for t in tables:
                fanned.extend(stage.transform(t))
            tables = fanned
    return tables


class Pipeline(Estimator["PipelineModel"]):
    def __init__(self, stages: Sequence[Stage] = ()):  # no-arg constructible
        super().__init__()
        self._stages: List[Stage] = list(stages)

    @property
    def stages(self) -> List[Stage]:
        return list(self._stages)

    def fit(self, *inputs) -> "PipelineModel":
        """``Pipeline.java:74-103`` semantics: only transform inputs while
        stages before the *last* Estimator still need them."""
        last_estimator_idx = -1
        for i, stage in enumerate(self._stages):
            if isinstance(stage, Estimator):
                last_estimator_idx = i

        transformed = list(inputs)
        model_stages: List[AlgoOperator] = []
        for i, stage in enumerate(self._stages):
            # AlgoOperator takes precedence over Estimator for dual-typed
            # stages, matching ``Pipeline.java:89-93``.
            if isinstance(stage, AlgoOperator):
                fitted: AlgoOperator = stage
            elif isinstance(stage, Estimator):
                fitted = stage.fit(*transformed)
            else:
                raise TypeError(
                    f"Pipeline stage {i} ({type(stage).__name__}) is neither "
                    "an Estimator nor an AlgoOperator")
            model_stages.append(fitted)
            if i < last_estimator_idx:
                transformed = list(fitted.transform(*transformed))
        return PipelineModel(model_stages)

    def save(self, path: str) -> None:
        persist.save_pipeline(self, self._stages, path)

    @classmethod
    def load(cls, path: str) -> "Pipeline":
        return cls(persist.load_pipeline(path, cls))


class PipelineModel(Model):
    def __init__(self, stages: Sequence[AlgoOperator] = ()):  # no-arg constructible
        super().__init__()
        self._stages: List[AlgoOperator] = list(stages)

    @property
    def stages(self) -> List[AlgoOperator]:
        return list(self._stages)

    def transform(self, *inputs) -> List:
        """Sequentially feed outputs of stage i into stage i+1
        (``PipelineModel.java:58-64``).

        When every stage in a run is chainable (``api/chain.py`` kernel
        protocol), the run executes as ONE fused jitted program instead
        of per-stage dispatch+transfer — bit-exact with the stagewise
        path, auto-selected, cached per input schema (and per row bucket
        through the shared segment jit)."""
        tables = list(inputs)
        plan = self._chain_plan(tables)
        if plan is not None:
            return plan.transform(*tables)
        return _stagewise(self._stages, tables)

    def _chain_plan(self, tables) -> Optional[object]:
        """The cached fused plan for this input schema, or None when the
        chain is disabled, no segment merges >= 2 stages, or plan build
        fails (every fallback is the stagewise path).

        The cache key includes every stage's live param values, so a
        post-build ``set_threshold(...)`` / ``set_prediction_col(...)``
        builds a fresh plan instead of serving the stale kernels the old
        values were baked into.  (Mutating fitted MODEL DATA in place via
        ``set_model_data`` after a transform is not fingerprinted —
        reload or rebuild the PipelineModel for that.)"""
        from ..data.table import Table
        from . import chain

        if not chain._enabled() or not self._stages or not tables:
            return None
        if not all(isinstance(t, Table) for t in tables):
            return None
        keys = {chain.raw_schema(t) for t in tables}
        if len(keys) != 1:
            return None          # mixed-schema flows stay stagewise
        params_key = tuple(
            tuple(sorted((p.name, repr(v))
                         for p, v in s._ensure_param_map().items()))
            if hasattr(s, "_ensure_param_map") else id(s)
            for s in self._stages)
        (schema_key,) = keys
        key = (schema_key, params_key)
        cache = self.__dict__.setdefault("_chain_plans", {})
        if key in cache:
            return cache[key]
        if len(cache) > 32:      # param-churn guard: plans are rebuildable
            cache.clear()
        example = tables[0].take(min(tables[0].num_rows, 8))
        try:
            plan = chain.compile_pipeline(self, example)
            plan = plan if plan.worthwhile else None
        except Exception:        # unported config/schema: stagewise
            plan = None
        cache[key] = plan
        return plan

    def save(self, path: str) -> None:
        persist.save_pipeline(self, self._stages, path)

    @classmethod
    def load(cls, path: str) -> "PipelineModel":
        return cls(persist.load_pipeline(path, cls))
