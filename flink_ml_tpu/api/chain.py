"""Operator chaining: fuse runs of row-wise pipeline stages into ONE
jitted program.

The reference's host runtime (Flink) chains consecutive operators into a
single task precisely to eliminate per-operator serialization hops.  Our
stagewise ``PipelineModel.transform`` pays the device-era equivalent —
one jit dispatch **and one host→device→host round trip per stage** —
because every feature transform does ``np.asarray(jit(...)(jnp.asarray(X)))``
on a host-resident Table.  This module removes that boundary:

- **Kernel protocol.**  A stage advertises chainability by implementing
  ``transform_kernel(schema) -> StageKernel | None`` (capability method:
  unported stages simply lack it, ported stages return ``None`` for
  configurations/schemas they cannot express as a pure device fn — e.g.
  string-domain columns, ``handleInvalid="error"`` policies whose raise
  is host control flow).  A :class:`StageKernel` is a pure
  ``columns -> columns`` device function plus a params pytree; all
  instance state lives in ``params`` (runtime device arguments), all
  shape/name configuration in a hashable ``static`` tuple.

- **Segments.**  :func:`compile_pipeline` walks the stage list and
  greedily groups maximal runs of chainable row-independent stages into
  segments; each segment runs as ONE jitted program over a device-resident
  column dict.  Intermediates never materialize on host; only columns the
  output Table (or a terminal's host finalizer) actually needs transfer
  back.  Non-chainable stages (``RandomSplitter``, SQL, string-domain
  tokenizers, GBT — see ``gbt_stage.py``) break the chain and run
  stagewise between segments.

- **Compile sharing.**  The segment runner is THE kernel registry's
  shared plan-static jit (``kernels/registry.py`` — one ``jax.jit``
  whose static argument is the tuple of per-stage ``(fn, static)``
  pairs and whose params are runtime device arrays, device-put once at
  plan build — no per-call re-transfer, and NOT baked as XLA
  constants).  Two plans with the same stage types, column names, and
  shapes — e.g. the per-fold pipelines of a CrossValidator, or
  consecutive hot-swapped model generations — therefore share one
  compiled executable per (schema, bucket), and so do the OTHER
  consumers of the same surface: the serving executors and the models'
  standalone transforms dispatch identical single-stage plans, with
  compile/cache-hit accounting on ``kernels.registry.kernel_stats``.

- **Bit-exactness.**  Every ported kernel mirrors the stage's stagewise
  arithmetic expression at the same f32 precision (host-side exact-compare
  stages carry f32 edge *surrogates* — see ``vector_ops.py``), rows pad
  to the same power-of-two buckets the stagewise predict entry points
  use, and every chained op is row-independent, so the fused output is
  bit-exact with the stagewise path.  Terminal dot products additionally
  route through a context-stable contraction
  (``models/common/linear.py::_stable_margins``): a k=1 matvec would
  accumulate differently standalone vs inside a fused program.

- **Dtype hygiene.**  Host float64 columns silently retrace every jitted
  transform and double the transfer bytes; segment entry normalizes
  floating columns to :attr:`ChainConfig.dtype` (f32 by default) and
  integer/bool columns to int32 on the HOST, so an f64 and an f32 input
  table hit the same compiled program and move half the bytes.
"""

from __future__ import annotations

import threading

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..data.table import Table
from ..kernels.registry import dispatch as _kernel_dispatch
from ..kernels.registry import dispatch_count  # noqa: F401  (re-export)
from ..obs.trace import tracer
from ..utils.padding import DEFAULT_MIN_BUCKET, pad_rows_to_bucket

__all__ = ["StageKernel", "ChainConfig", "CompiledSegment",
           "CompiledPipeline", "UnsafeColumnValues", "apply_kernel",
           "apply_kernel_or_none", "as_matrix", "numeric_entry",
           "compile_pipeline", "run_kernel",
           "chain_disabled", "dispatch_count", "f32_ceil", "f32_floor"]


def as_matrix(col):
    """Chain-side mirror of ``linalg.stack_vectors``'s 1-D promotion: a
    scalar column is n samples of dim 1, not one n-dim row.  Kernels use
    this instead of spelling the reshape locally so the invariant lives
    in one place (works on device and host arrays alike)."""
    return col.reshape(-1, 1) if col.ndim == 1 else col


def numeric_entry(schema, col: str, *, exact_compare: bool = False):
    """The ``(shape, dtype)`` schema entry when ``col`` is
    chain-admissible — present and plain numeric (object/string columns
    stay stagewise) — else ``None``.  This is THE protocol admissibility
    rule; kernels call it instead of respelling the kind check.

    ``exact_compare=True`` additionally rejects float64 columns: segment
    entry rounds them to f32, and a kernel whose OUTPUT is an exact
    comparison decision (threshold crossing, bucket index, vocabulary
    equality) could round a value across the boundary the host-f64
    stagewise compare respects — the f32 threshold surrogates
    (:func:`f32_ceil`/:func:`f32_floor`) are only exact for values that
    are already f32.  Such stages decline to chain on f64 columns and
    run stagewise at full precision instead (continuous kernels keep
    chaining: their contract is value-exactness at f32, which f64 entry
    rounding satisfies by construction)."""
    entry = schema.get(col)
    if entry is None or entry[1].kind not in "fiub":
        return None
    if exact_compare and entry[1].kind == "f" and entry[1].itemsize > 4:
        return None
    return entry


# --------------------------------------------------------------------------
# protocol
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class StageKernel:
    """One stage's pure device kernel.

    ``fn(static, params, cols) -> {produced name: array}`` must be a
    MODULE-LEVEL function (its identity is the jit cache key — a per-call
    closure would defeat cross-plan compile sharing); everything the fn
    reads beyond the column dict goes through ``static`` (hashable,
    shape/name-level) or ``params`` (pytree of arrays, device-put once at
    plan build and passed as runtime jit arguments).

    ``post`` (host, optional) marks a chain TERMINAL: it receives the
    host copies of this stage's produced columns and returns the final
    output columns (e.g. a linear model's f64 decision/raw mapping).  A
    terminal's device outputs are staging values only, so nothing may
    consume them in-segment — the segment ends at the terminal.

    ``pre`` (host, optional) validates raw input columns (e.g.
    Wide&Deep's categorical id range check).  It runs on the segment's
    HOST entry columns, so a stage with a ``pre`` only chains while every
    column named in ``pre_cols`` is a segment-entry passthrough (columns
    produced mid-segment exist only on device).
    """

    fn: Callable[[tuple, Any, Dict[str, Any]], Dict[str, Any]]
    static: tuple
    params: Any
    consumes: Tuple[str, ...]
    produces: Tuple[str, ...]
    post: Optional[Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]]] = None
    pre: Optional[Callable[[Dict[str, np.ndarray]], None]] = None
    pre_cols: Tuple[str, ...] = ()


@dataclass(frozen=True)
class ChainConfig:
    """Plan-build configuration (defaults match the stagewise predict
    entry points, so fused and stagewise pad to identical shapes)."""

    dtype: Any = np.float32
    min_bucket: int = DEFAULT_MIN_BUCKET


# --------------------------------------------------------------------------
# enable/disable switch (tests and the bench A/B baseline)
# --------------------------------------------------------------------------

_STATE = threading.local()


def _enabled() -> bool:
    return getattr(_STATE, "enabled", True)


class chain_disabled:
    """Context manager forcing the stagewise path — the bench A/B baseline
    and the bit-exactness oracle in tests."""

    def __enter__(self):
        self._prev = _enabled()
        _STATE.enabled = False
        return self

    def __exit__(self, *exc):
        _STATE.enabled = self._prev
        return False


# --------------------------------------------------------------------------
# exact f32 comparison surrogates
# --------------------------------------------------------------------------

def f32_ceil(x: np.ndarray) -> np.ndarray:
    """Smallest float32 >= x (elementwise).  For any f32 value ``v`` and
    f64 threshold ``t``: ``t <= v  ⟺  f32_ceil(t) <= v`` — there is no
    f32 value strictly between ``t`` and ``f32_ceil(t)``.  This is what
    lets the host-f64 exact-compare stages (Bucketizer, KBinsDiscretizer)
    run their searchsorted semantics bit-exactly inside an f32 segment."""
    x = np.asarray(x, np.float64)
    c = x.astype(np.float32)
    low = c.astype(np.float64) < x
    out = c.copy()
    out[low] = np.nextafter(c[low], np.float32(np.inf))
    return out


def f32_floor(x: np.ndarray) -> np.ndarray:
    """Largest float32 <= x (elementwise): ``v > t  ⟺  v > f32_floor(t)``
    for f32 ``v``."""
    x = np.asarray(x, np.float64)
    c = x.astype(np.float32)
    high = c.astype(np.float64) > x
    out = c.copy()
    out[high] = np.nextafter(c[high], np.float32(-np.inf))
    return out


# --------------------------------------------------------------------------
# the shared segment runner — ONE jit for every plan
# --------------------------------------------------------------------------
# The runner itself (the plan-static jit with the rounding barrier) moved
# to kernels/registry.py: it is THE repo-wide dispatch surface now, shared
# with the serving executors and the models' own predict entry points, so
# the same (plan, schema, bucket) warmed by any consumer is a compile-cache
# hit for the others.  This module keeps the chain-facing helpers.


def run_kernel(kernel: StageKernel, table: Table, *,
               params: Any = None, dtype=np.float32,
               min_bucket: int = DEFAULT_MIN_BUCKET,
               op: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Run ONE stage's kernel as a single-stage plan through the shared
    registry dispatch (normalize -> pre -> bucket-pad -> dispatch ->
    fetch -> post).  ``params`` overrides ``kernel.params`` with
    already-device-resident arrays (the serving executors device-put
    once per generation instead of re-transferring per request); ``op``
    labels the registry's per-op counters.

    Raises :class:`UnsafeColumnValues` when a consumed integer column
    carries values outside the f32-exact range — callers fall back to
    their legacy host path for that call (see
    :func:`apply_kernel_or_none`)."""
    host = {n: _normalize_col(table[n], dtype) for n in kernel.consumes}
    if kernel.pre is not None:
        kernel.pre(host)
    with tracer.span("bucket_pad", cat="kernel", op=op):
        padded, n = pad_rows_to_bucket(tuple(host.values()),
                                       min_bucket=min_bucket)
        cols = dict(zip(host, padded))
    out = _kernel_dispatch(((kernel.fn, kernel.static),),
                           (kernel.params if params is None else params,),
                           cols, op=op)
    # device_execute: the np.asarray fetch IS the completion fence (the
    # StepTimer probe pattern — device_get on the host side of the
    # dispatch boundary, never a block inside a step fn), so this span
    # covers queue + device compute + transfer of the produced columns
    with tracer.span("device_execute", cat="kernel", op=op,
                     bucket=int(next(iter(cols.values())).shape[0])
                     if cols else 0):
        fetched = {name: np.asarray(out[name])[:n]
                   for name in kernel.produces}
    if kernel.post is not None:
        fetched.update(kernel.post(fetched))
    return fetched


def apply_kernel(kernel: StageKernel, table: Table, *,
                 dtype=np.float32,
                 min_bucket: int = DEFAULT_MIN_BUCKET) -> Dict[str, np.ndarray]:
    """Run ONE stage's kernel stagewise (a single-stage segment).

    Ported stages whose legacy transform was host-f64 numpy route their
    standalone ``transform`` through this, so the stagewise and fused
    paths literally share one compiled expression — bit-exactness between
    them is by construction, and the stage's offline transform gains the
    bucket-padded zero-retrace behavior of the predict entry points."""
    return run_kernel(kernel, table, dtype=dtype, min_bucket=min_bucket)


#: integers beyond +-2^24 are not exactly representable in the f32 the
#: kernels compare/promote with (and 2^31 would overflow the int32 cast);
#: a batch carrying them falls back stagewise rather than silently
#: diverging from the host-f64 path
_INT_EXACT_BOUND = 1 << 24


class UnsafeColumnValues(Exception):
    """Batch values the f32 segment cannot represent exactly — the caller
    falls back to the stagewise path for THIS call (plan stays valid)."""


def _normalize_col(arr: np.ndarray, dtype) -> np.ndarray:
    """Host-side dtype hygiene: floating -> config dtype, int/bool ->
    int32.  Casting BEFORE device_put halves the transfer bytes for f64
    inputs and makes f64-vs-f32 callers share one compiled program."""
    arr = np.asarray(arr)
    if arr.dtype.kind == "f" and arr.dtype != np.dtype(dtype):
        return arr.astype(dtype)
    if arr.dtype.kind in "iu":
        if arr.size and (int(arr.min()) < -_INT_EXACT_BOUND
                         or int(arr.max()) > _INT_EXACT_BOUND):
            raise UnsafeColumnValues(
                f"integer column values exceed +-2^24 "
                f"({int(arr.min())}..{int(arr.max())})")
        if arr.dtype != np.dtype(np.int32):
            return arr.astype(np.int32)
    elif arr.dtype.kind == "b":
        return arr.astype(np.int32)
    return arr


def apply_kernel_or_none(kernel: Optional[StageKernel], table: Table,
                         **kwargs) -> Optional[Dict[str, np.ndarray]]:
    """:func:`apply_kernel` that answers ``None`` instead of raising when
    the kernel is absent or this batch's values are f32-unsafe — the
    standalone stage transforms branch to their legacy host math on
    ``None``."""
    if kernel is None:
        return None
    try:
        return apply_kernel(kernel, table, **kwargs)
    except UnsafeColumnValues:
        return None


def raw_schema(table: Table) -> tuple:
    """Hashable (name, trailing shape, RAW dtype) signature.  Plan caches
    key on this — not on the device-normalized view — because kernel
    admissibility depends on the input float width (exact-compare stages
    decline f64, see :func:`numeric_entry`); an f64 and an f32 view of
    the same flow need different plans, whose matching segments still
    share jit executables through the plan-static segment runner."""
    return tuple((n, s, dt.str) for n, (s, dt)
                 in sorted(table.schema().items()))


def _device_schema(table: Table, dtype) -> tuple:
    """The normalized (name, trailing shape, device dtype) signature a
    plan is keyed on — f64 and f32 views of the same data collide."""
    sig = []
    for name, (shape, dt) in table.schema().items():
        if dt.kind == "f":
            dt = np.dtype(dtype)
        elif dt.kind in "iub":
            dt = np.dtype(np.int32)
        sig.append((name, shape, dt.str))
    return tuple(sig)


# --------------------------------------------------------------------------
# compiled plan
# --------------------------------------------------------------------------

class CompiledSegment:
    """A maximal run of chainable stages compiled as one program.

    ``run`` normalizes + pads the entry columns on host, makes ONE jitted
    call, fetches only the columns the output (or a terminal's host
    finalizer) needs, and reassembles the Table in the stagewise column
    order.  Entry columns that no kernel replaces are reattached from the
    ORIGINAL host arrays — bit-exact passthrough with zero transfer."""

    def __init__(self, stages: Sequence, kernels: Sequence[StageKernel],
                 out_names: Sequence[str], config: ChainConfig):
        self.stages = list(stages)
        self.kernels = list(kernels)
        self.config = config
        self.plan = tuple((k.fn, k.static) for k in kernels)
        # device_put once: params ride every call as device-resident args
        self.params = tuple(jax.device_put(k.params) for k in kernels)
        produced: set = set()
        for k in kernels:
            produced.update(k.produces)
        self.produced = produced
        # columns that must cross host->device: everything any kernel
        # consumes that an earlier kernel did not itself produce
        entry: List[str] = []
        seen: set = set()
        for k in kernels:
            for name in k.consumes:
                if name not in seen and name not in entry:
                    entry.append(name)
            seen.update(k.produces)
        self.entry_cols = tuple(entry)
        for k in kernels:
            missing = [c for c in k.pre_cols if c not in self.entry_cols]
            if missing:
                # fail at plan build, not with a KeyError on the first
                # serving request: pre() only ever sees host entry columns
                raise ValueError(
                    f"StageKernel pre_cols {missing} are not entry columns "
                    f"of their segment — a host pre hook can only validate "
                    f"columns some kernel in the segment consumes from the "
                    f"segment input")
        self.out_names = tuple(out_names)
        terminal = kernels[-1] if kernels and kernels[-1].post else None
        # device->host fetch set: final columns a kernel produced, plus
        # the terminal's staging outputs its host finalizer reads
        fetch = [n for n in self.out_names if n in produced]
        if terminal is not None:
            fetch += [n for n in terminal.produces if n not in fetch]
        self.fetch_cols = tuple(fetch)
        self.posts = [k.post for k in kernels if k.post]
        self.pres = [k.pre for k in kernels if k.pre]

    @property
    def num_stages(self) -> int:
        return len(self.stages)

    def transfer_bytes(self, num_rows: int) -> Tuple[int, int]:
        """(host->device, device->host) bytes this segment moves for a
        ``num_rows`` batch — exact shape math for the bench accounting."""
        itemsize = np.dtype(self.config.dtype).itemsize

        def _nbytes(names, schema):
            total = 0
            for n in names:
                shape, dt = schema.get(n, ((), np.dtype(self.config.dtype)))
                width = int(np.prod(shape)) if shape else 1
                size = itemsize if dt.kind == "f" else 4
                total += num_rows * width * size
            return total

        return (_nbytes(self.entry_cols, self._entry_schema),
                _nbytes(self.fetch_cols, self._out_schema))

    def bind_schemas(self, entry_schema: dict, out_schema: dict) -> None:
        self._entry_schema = dict(entry_schema)
        self._out_schema = dict(out_schema)

    def run(self, table: Table) -> Table:
        cfg = self.config
        try:
            host = {n: _normalize_col(table[n], cfg.dtype)
                    for n in self.entry_cols}
        except UnsafeColumnValues:
            # this batch carries integers f32 cannot represent exactly —
            # run the segment's own stages stagewise (per-call; the plan
            # stays valid for safe batches)
            for stage in self.stages:
                (table,) = stage.transform(table)
            return table
        for pre in self.pres:
            pre(host)
        n = table.num_rows
        if host:
            padded, n = pad_rows_to_bucket(
                tuple(host.values()), min_bucket=cfg.min_bucket)
            cols = dict(zip(host, padded))
        else:
            cols = {}
        out = _kernel_dispatch(self.plan, self.params, cols)
        fetched = {name: np.asarray(out[name])[:n]
                   for name in self.fetch_cols}
        for post in self.posts:
            fetched.update(post(fetched))
        final: Dict[str, np.ndarray] = {}
        for name in self.out_names:
            final[name] = (fetched[name] if name in fetched
                           else table[name])
        return Table(final)


class _HostStage:
    """A non-chainable stage in the plan: runs its own transform
    (possibly multiplying tables, e.g. RandomSplitter)."""

    def __init__(self, stage):
        self.stage = stage

    def run_all(self, tables: List[Table]) -> List[Table]:
        out: List[Table] = []
        for t in tables:
            out.extend(self.stage.transform(t))
        return out


class CompiledPipeline:
    """The fused execution plan: segments interleaved with stagewise
    fallback stages, applied table-wise (a multi-output host stage fans
    the flow out; later items map over every table)."""

    def __init__(self, items: List, config: ChainConfig,
                 schema_key: tuple):
        self.items = items
        self.config = config
        self.schema_key = schema_key

    @property
    def segments(self) -> List[CompiledSegment]:
        return [i for i in self.items if isinstance(i, CompiledSegment)]

    @property
    def num_fused_stages(self) -> int:
        return sum(s.num_stages for s in self.segments)

    @property
    def worthwhile(self) -> bool:
        """Fusing pays once any segment merges >= 2 stages; a plan of
        singletons is the stagewise path with extra bookkeeping."""
        return any(s.num_stages >= 2 for s in self.segments)

    def describe(self) -> List[Tuple[str, int]]:
        """[('segment', n_stages) | ('stage', 1)] in pipeline order —
        what the chain-break tests assert segment boundaries on."""
        return [("segment", i.num_stages) if isinstance(i, CompiledSegment)
                else ("stage", 1) for i in self.items]

    def transform(self, *inputs) -> List[Table]:
        tables = list(inputs)
        for item in self.items:
            if isinstance(item, CompiledSegment):
                tables = [item.run(t) for t in tables]
            else:
                tables = item.run_all(tables)
        return tables


def compile_pipeline(pipeline_model, example: Table, *,
                     dtype=np.float32,
                     min_bucket: int = DEFAULT_MIN_BUCKET) -> CompiledPipeline:
    """Compile a fitted ``PipelineModel`` into a fused plan.

    Walks the stage list with ``example`` (any table carrying the request
    schema — row VALUES only steer non-chainable fallback stages), asking
    each stage for its kernel at the current schema and greedily grouping
    maximal chainable runs into :class:`CompiledSegment`\\s.  A terminal
    kernel (one with a host ``post``) closes its segment; a stage without
    a kernel breaks the chain and runs stagewise.
    """
    config = ChainConfig(dtype=dtype, min_bucket=min_bucket)
    items: List = []
    current = example
    run_stages: List = []
    run_kernels: List[StageKernel] = []
    run_entry: Table = example
    produced_in_run: set = set()

    def flush(out_table: Table) -> None:
        nonlocal run_stages, run_kernels, produced_in_run
        if not run_stages:
            return
        seg = CompiledSegment(run_stages, run_kernels,
                              out_table.column_names, config)
        seg.bind_schemas(run_entry.schema(), out_table.schema())
        items.append(seg)
        run_stages, run_kernels, produced_in_run = [], [], set()

    for stage in pipeline_model.stages:
        kernel = None
        if hasattr(stage, "transform_kernel"):
            try:
                kernel = stage.transform_kernel(current.schema())
            except NotImplementedError:
                kernel = None
        if kernel is not None and kernel.pre is not None and \
                any(c in produced_in_run for c in kernel.pre_cols):
            # host pre-validation needs raw entry columns; a mid-segment
            # input only exists on device — close the running segment so
            # its outputs host-materialize and this stage opens a FRESH
            # segment whose entry columns pre() can see (it stays fused,
            # just across a segment boundary, instead of silently
            # skipping validation or dropping to per-stage dispatch)
            flush(current)
        next_table = stage.transform(current)[0]
        if kernel is not None:
            if not run_stages:
                run_entry = current
            run_stages.append(stage)
            run_kernels.append(kernel)
            produced_in_run.update(kernel.produces)
            current = next_table
            if kernel.post is not None:       # terminal closes the segment
                flush(current)
        else:
            flush(current)
            items.append(_HostStage(stage))
            current = next_table
    flush(current)
    return CompiledPipeline(items, config,
                            _device_schema(example, dtype))
