"""Model selection: ParamGridBuilder + CrossValidator / TrainValidationSplit.

Beyond-reference surface (the flink-ml snapshot has no model selection;
the capability is table stakes for a pipeline framework — the Spark ML
`CrossValidator` shape, expressed over this repo's Stage/Param API).

Design notes, TPU-first: each candidate fit is an independent jitted
program over the SAME fold tensors, so fold tables are sliced once on the
host and reused across the whole grid; nothing here adds device state of
its own.  Scoring goes through any evaluator stage whose ``transform``
emits a single-row metrics Table (the `models/evaluation` family).
"""

from __future__ import annotations

import itertools

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..data.table import Table
from ..params.param import BoolParam, FloatParam, IntParam, Param, \
    ParamValidators, StringParam
from ..params.shared import HasSeed
from .stage import AlgoOperator, Estimator, Model

__all__ = ["ParamGridBuilder", "CrossValidator", "CrossValidatorModel",
           "TrainValidationSplit"]


class ParamGridBuilder:
    """Cartesian product of per-param value lists (the Spark ML idiom)::

        grid = (ParamGridBuilder()
                .add_grid(LogisticRegression.REG, [0.0, 0.01, 0.1])
                .add_grid(LogisticRegression.MAX_ITER, [10, 50])
                .build())          # 6 param maps
    """

    def __init__(self):
        self._grid: List[Tuple[Param, Sequence[Any]]] = []

    def add_grid(self, param: Param, values: Sequence[Any]
                 ) -> "ParamGridBuilder":
        if not isinstance(param, Param):
            raise TypeError(f"add_grid needs a Param, got {type(param)}")
        if len(values) == 0:
            raise ValueError(f"empty value list for {param.name}")
        # repeated add_grid for a param REPLACES its values (the Spark
        # behavior) instead of silently multiplying duplicate candidates
        self._grid = [(p, v) for p, v in self._grid if p is not param]
        self._grid.append((param, list(values)))
        return self

    def build(self) -> List[Dict[Param, Any]]:
        if not self._grid:
            return [{}]
        params = [p for p, _ in self._grid]
        return [dict(zip(params, combo))
                for combo in itertools.product(
                    *(vals for _, vals in self._grid))]


def _declares(stage, param: Param) -> bool:
    """Does this stage's class hierarchy declare THIS param object?
    (Identity over the MRO — name collisions between unrelated params
    never match; shared Has* mixin params match every inheriting stage.)
    A nested Pipeline declares whatever its descendants declare."""
    from .pipeline import Pipeline

    if isinstance(stage, Pipeline):
        return any(_declares(s, param) for s in stage.stages)
    return any(v is param for klass in type(stage).__mro__
               for v in vars(klass).values())


def _bind_in_children(children, param: Param, value) -> bool:
    from .pipeline import Pipeline

    hit = False
    for child in children:
        if isinstance(child, Pipeline):
            hit |= _bind_in_children(child.stages, param, value)
        elif _declares(child, param):
            child.set(param, value)
            hit = True
    return hit


def _clone_with(stage, param_map: Dict[Any, Any], _grid_params=None):
    """Fresh stage with ``stage``'s params plus ``param_map`` overrides.

    A Pipeline candidate clones its ESTIMATOR children (nested pipelines
    recursively) and any transformer/model child that declares a bound
    grid param (so ``child.set`` on a candidate never mutates the
    caller's original pipeline, and candidates don't share one mutable
    stage).  A fitted Model clones as a shallow copy with its own param
    map — its fitted data is shared by reference (fit never mutates it;
    re-instantiating would drop it).  Grid-untouched transformer/model
    children are reused as-is.  Grid keys bind by param-object IDENTITY
    on every declaring descendant (a shared ``Has*`` mixin param
    therefore reaches all stages inheriting it); to pin a value to one
    top-level child, use a ``(child_index, Param)`` tuple key.  A key
    binding nowhere is an error."""
    from .pipeline import Pipeline
    from .stage import Model

    # The full set of grid params steers transformer cloning through
    # nested-pipeline recursion (where param_map is empty but the outer
    # _bind_in_children will still reach the descendants).
    grid_params = (_grid_params if _grid_params is not None else
                   [key[1] if isinstance(key, tuple) else key
                    for key in param_map])

    def _clone_transformer(t):
        if isinstance(t, Model):
            # keep the fitted data (re-instantiating would drop it):
            # shallow-copy the instance and give it an independent param
            # map so grid binds never reach the caller's original
            import copy

            clone = copy.copy(t)
            clone.__dict__["_param_map"] = dict(t.get_param_map())
            return clone
        clone = type(t)()
        clone.copy_params_from(t)
        return clone

    if isinstance(stage, Pipeline):
        children = [
            _clone_with(s, {}, grid_params)
            if isinstance(s, (Pipeline, Estimator))
            else _clone_transformer(s)
            if any(_declares(s, p) for p in grid_params)
            else s
            for s in stage.stages]
        clone = Pipeline(children)
        clone.copy_params_from(stage)
        for key, value in param_map.items():
            if isinstance(key, tuple):
                idx, param = key
                target = children[idx]
                if not (_declares(target, param)
                        and _bind_in_children([target], param, value)):
                    raise ValueError(
                        f"pipeline stage {idx} does not declare "
                        f"{param.name!r}")
            elif not _bind_in_children(children, key, value):
                raise ValueError(
                    f"grid param {key.name!r} matches no pipeline stage")
        return clone
    clone = type(stage)()
    clone.copy_params_from(stage)
    for key, value in param_map.items():
        if isinstance(key, tuple):
            raise ValueError(
                "(child_index, Param) grid keys only apply to Pipeline "
                "estimators")
        clone.set(key, value)   # set() resolves by name and validates
    return clone


def _score(evaluator, table: Table, metric: Optional[str]) -> float:
    """One scalar from an evaluator stage's single-row metrics Table."""
    (out,) = evaluator.transform(table)
    names = out.column_names
    if metric is None:
        if len(names) != 1:
            raise ValueError(
                f"evaluator emitted metrics {names}; set metricName to "
                "pick one")
        metric = names[0]
    if metric not in names:
        raise ValueError(f"metric {metric!r} not in evaluator output "
                         f"{names}")
    return float(np.asarray(out[metric])[0])


class _SelectorBase(HasSeed, Estimator["CrossValidatorModel"]):
    """Shared machinery: candidate grid x fold loop -> best model."""

    METRIC_NAME = StringParam(
        "metricName",
        "Column of the evaluator's metrics Table to optimize (None: the "
        "evaluator must emit exactly one).", default=None,
        validator=ParamValidators.always_true())
    LARGER_IS_BETTER = BoolParam(
        "largerIsBetter", "Maximize the metric (else minimize).",
        default=True)

    def __init__(self, estimator=None, evaluator=None, param_grid=None):
        super().__init__()
        self._estimator = estimator
        self._evaluator = evaluator
        self._param_grid = param_grid or [{}]

    # estimator/evaluator/grid are python objects, not serializable params
    def set_estimator(self, est):
        self._estimator = est
        return self

    def set_evaluator(self, ev):
        self._evaluator = ev
        return self

    def set_param_grid(self, grid: List[Dict[Param, Any]]):
        self._param_grid = list(grid) or [{}]
        return self

    def set_metric_name(self, name: str):
        return self.set(_SelectorBase.METRIC_NAME, name)

    def set_larger_is_better(self, larger: bool):
        return self.set(_SelectorBase.LARGER_IS_BETTER, bool(larger))

    def _check(self):
        if self._estimator is None or self._evaluator is None:
            raise ValueError(
                f"{type(self).__name__} needs set_estimator and "
                "set_evaluator")

    def _splits(self, table: Table) -> List[Tuple[Table, Table]]:
        raise NotImplementedError

    def fit(self, *inputs) -> "CrossValidatorModel":
        (table,) = inputs
        self._check()
        splits = self._splits(table)
        larger = self.get(_SelectorBase.LARGER_IS_BETTER)
        metric = self.get(_SelectorBase.METRIC_NAME)

        avg_metrics: List[float] = []
        for param_map in self._param_grid:
            scores = []
            for train, val in splits:
                candidate = _clone_with(self._estimator, param_map)
                model = candidate.fit(train)
                # Pipeline candidates score through the fused chain
                # (api/chain.py): every fold's model has the same stage
                # types / column names / shapes, so the plan-static
                # segment jit compiles ONCE for the whole grid x fold
                # sweep — fold params ride as runtime device args.
                # (tests/test_model_selection.py asserts zero new XLA
                # lowerings after the first fold and fold metrics
                # identical to the stagewise path.)
                (pred,) = model.transform(val)
                scores.append(_score(self._evaluator, pred, metric))
            avg_metrics.append(float(np.mean(scores)))

        best_idx = int(np.argmax(avg_metrics) if larger
                       else np.argmin(avg_metrics))
        best_est = _clone_with(self._estimator, self._param_grid[best_idx])
        best_model = best_est.fit(table)   # refit on ALL rows

        out = CrossValidatorModel()
        out.copy_params_from(self)
        out.best_model = best_model
        out.best_index = best_idx
        out.best_params = self._param_grid[best_idx]
        out.avg_metrics = avg_metrics
        return out


class CrossValidator(_SelectorBase):
    """k-fold cross validation over a candidate param grid: every
    candidate trains k times (fold i held out for scoring), the best
    average metric wins, and the winner refits on the full table."""

    NUM_FOLDS = IntParam("numFolds", "Number of folds.", default=3,
                         validator=ParamValidators.gt_eq(2))

    def set_num_folds(self, k: int):
        return self.set(CrossValidator.NUM_FOLDS, k)

    def get_num_folds(self) -> int:
        return self.get(CrossValidator.NUM_FOLDS)

    def _splits(self, table: Table) -> List[Tuple[Table, Table]]:
        k = self.get_num_folds()
        n = table.num_rows
        if n < k:
            raise ValueError(f"{n} rows cannot make {k} folds")
        shuffled = table.shuffle(self.get_seed())
        bounds = np.linspace(0, n, k + 1).astype(int)
        out = []
        for i in range(k):
            lo, hi = bounds[i], bounds[i + 1]
            val = shuffled.slice(lo, hi)
            if lo == 0:
                train = shuffled.slice(hi, n)
            elif hi == n:
                train = shuffled.slice(0, lo)
            else:
                train = shuffled.slice(0, lo).concat(shuffled.slice(hi, n))
            out.append((train, val))
        return out


class TrainValidationSplit(_SelectorBase):
    """Single seeded train/validation split (the cheap cousin of
    CrossValidator for large tables: each candidate trains once)."""

    TRAIN_RATIO = FloatParam(
        "trainRatio", "Fraction of rows in the training split.",
        default=0.75, validator=ParamValidators.in_range(0.0, 1.0))

    def set_train_ratio(self, r: float):
        return self.set(TrainValidationSplit.TRAIN_RATIO, r)

    def _splits(self, table: Table) -> List[Tuple[Table, Table]]:
        n = table.num_rows
        cut = int(n * self.get(TrainValidationSplit.TRAIN_RATIO))
        if not 0 < cut < n:
            raise ValueError(
                f"trainRatio leaves an empty split for {n} rows")
        shuffled = table.shuffle(self.get_seed())
        return [(shuffled.slice(0, cut), shuffled.slice(cut, n))]


class CrossValidatorModel(Model):
    """Wraps the winning refitted model; transform delegates to it.
    Persistence delegates to the best model (reload with that model's
    class — the selector itself holds non-serializable python stages)."""

    def __init__(self):
        super().__init__()
        self.best_model = None
        self.best_index: int = -1
        self.best_params: Dict[Param, Any] = {}
        self.avg_metrics: List[float] = []

    def transform(self, *inputs) -> List[Table]:
        if self.best_model is None:
            raise ValueError("CrossValidatorModel has no best model; fit "
                             "a CrossValidator first")
        return self.best_model.transform(*inputs)

    def save(self, path: str) -> None:
        if self.best_model is None:
            raise ValueError("nothing to save")
        self.best_model.save(path)