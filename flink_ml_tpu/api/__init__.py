from .chain import (  # noqa: F401
    StageKernel,
    chain_disabled,
    compile_pipeline,
)
from .stage import AlgoOperator, Estimator, Model, Stage, Transformer  # noqa: F401
from .graph import Graph, GraphBuilder, GraphModel, TableId  # noqa: F401
from .pipeline import Pipeline, PipelineModel  # noqa: F401
from .model_selection import (  # noqa: F401
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    TrainValidationSplit,
)
