"""The Stage hierarchy: Estimator / AlgoOperator / Transformer / Model.

TPU-native re-design of ``flink-ml-api/.../api/core/`` (``Stage.java:34-45``,
``Estimator.java:31-38``, ``AlgoOperator.java:31-38``,
``Transformer.java:31-32``, ``Model.java:31-51``).

Differences from the reference, by design:
- Stages operate on in-memory columnar :class:`~flink_ml_tpu.data.table.Table`
  objects (host numpy columns, shardable onto a device mesh) instead of lazy
  Flink ``Table``s — fit/transform are eager, the laziness the reference needs
  for graph construction is supplied by ``jax.jit`` inside each stage.
- ``load`` is a classmethod taking only a path (no execution environment —
  JAX owns the devices globally).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Generic, List, Optional, TypeVar

from ..params.with_params import WithParams
from ..utils import persist

M = TypeVar("M", bound="Model")

__all__ = ["Stage", "AlgoOperator", "Transformer", "Model", "Estimator"]


class Stage(WithParams, ABC):
    """Base node of a pipeline.  Contract (``Stage.java:34-45``): subclasses
    are constructible with no args, support ``save(path)`` and a classmethod
    ``load(path)``."""

    def save(self, path: str) -> None:
        """Default: persist params-only stages via metadata alone
        (``ReadWriteUtils.saveMetadata``).  Stages with model data override
        and additionally write ``{path}/data``."""
        persist.save_metadata(self, path)

    @classmethod
    def load(cls, path: str) -> "Stage":
        stage = persist.load_stage_param(path)
        if not isinstance(stage, cls):
            raise IOError(f"Stage at {path} is a {type(stage).__name__}, "
                          f"not a {cls.__name__}")
        return stage


class AlgoOperator(Stage):
    """A stage that maps tables to tables (``AlgoOperator.java:31-38``)."""

    @abstractmethod
    def transform(self, *inputs) -> List:
        """Apply to one or more tables, returning one or more tables."""

    def transform_one(self, table):
        """Convenience for the common single-in/single-out case."""
        return self.transform(table)[0]


class Transformer(AlgoOperator):
    """Marker specialization (``Transformer.java:31-32``): a one-pass,
    model-free or model-backed table mapping."""


class Model(Transformer, Generic[M]):
    """A Transformer with explicit model data (``Model.java:31-51``)."""

    def set_model_data(self, *inputs) -> "Model":
        raise NotImplementedError(
            f"{type(self).__name__} does not support setModelData")

    def get_model_data(self) -> List:
        raise NotImplementedError(
            f"{type(self).__name__} does not support getModelData")


class Estimator(Stage, Generic[M]):
    """Fits tables into a Model (``Estimator.java:31-38``)."""

    @abstractmethod
    def fit(self, *inputs) -> M:
        ...
