"""Broadcast variables.

Capability mirror of ``flink-ml-lib/.../common/broadcast/`` (SURVEY §2.8):
the reference needs ~1,900 lines (receiver operators, cache-or-block
wrappers with mailbox yields, spill-to-disk replay, co-location keys) to make
a small stream fully available to every parallel instance of an operator
before it runs.  On a TPU mesh the same capability is *replication*: a
broadcast variable is a pytree device_put with ``PartitionSpec()`` — every
device holds the full copy, XLA broadcasts it once over ICI, and any jitted
function can close over it.

``with_broadcast`` keeps the reference's API shape
(``BroadcastUtils.withBroadcastStream(inputs, broadcastMap, userFn)``,
``BroadcastUtils.java:67-119``): materialize the named tables onto the mesh,
expose them through a context, run the user function.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

from ..parallel.mesh import replicate
from .table import Table

__all__ = ["BroadcastContext", "with_broadcast"]


class BroadcastContext:
    """Named replicated variables (analog of ``BroadcastContext.java:34-113``,
    whose JVM-singleton map becomes instance state — no global registry or
    mailbox blocking is needed when materialization is eager)."""

    def __init__(self, variables: Mapping[str, Any]):
        self._variables = dict(variables)

    def get_broadcast_variable(self, name: str) -> Any:
        """The analog of ``RichFunction.getBroadcastVariable(name)``
        (``BroadcastStreamingRuntimeContext.java``)."""
        if name not in self._variables:
            raise KeyError(
                f"No broadcast variable {name!r}; available: "
                f"{sorted(self._variables)}")
        return self._variables[name]

    def names(self):
        return sorted(self._variables)


def _materialize(value: Any, mesh) -> Any:
    """Table -> replicated dict of device arrays; array/pytree -> replicated
    as-is (numeric object columns are densified)."""
    if isinstance(value, Table):
        cols = {}
        for name in value.column_names:
            col = value[name]
            if col.dtype == object:
                from ..linalg import stack_vectors
                col = stack_vectors(col)
            cols[name] = col
        return replicate(cols, mesh)
    return replicate(value, mesh)


def with_broadcast(fn: Callable[..., Any],
                   broadcast: Mapping[str, Any],
                   *inputs,
                   mesh=None) -> Any:
    """Run ``fn(*inputs, ctx)`` with ``broadcast`` (name -> Table or array
    pytree) replicated across the mesh.

    Mirror of ``BroadcastUtils.withBroadcastStream``'s contract: the
    variables are fully materialized before ``fn`` executes (the reference
    blocks or spills pending inputs to achieve this; eager device_put makes
    it trivially true here).
    """
    ctx = BroadcastContext(
        {name: _materialize(value, mesh) for name, value in broadcast.items()})
    return fn(*inputs, ctx)
