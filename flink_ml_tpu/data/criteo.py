"""Criteo-format TSV ingest: raw click logs -> the mixed training layout.

The BASELINE.md north star is Criteo-1TB LogisticRegression; this module
owns the first leg of that pipeline: parsing ``label \\t I1..I13 \\t
C1..C26`` lines into the framework's mixed convention (13 dense f32
slots + 26 hashed categorical int32 slots with implicit value 1.0) that
``sgd_fit_outofcore(mixed=True)`` / ``LogisticRegression.fit_outofcore``
consume directly, or that a ``DataCacheWriter`` persists for replayed
epochs.

Parsing runs through ``native/criteo.cpp`` (one pass over a byte chunk,
FNV-1a hashing folded in) with a bit-identical pure-Python fallback.
Categorical tokens hash as ``C{field}={token}`` — the FeatureHasher salt
convention — into ``[n_reserved, n_reserved + hash_space)`` so hashed
slots can never alias the dense weight slots.  Empty categorical fields
hash the empty token, giving each field a stable "missing" slot.
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, Iterator, Optional, Sequence, Tuple

import numpy as np

from ..utils.native_lib import load_native_lib

__all__ = ["CriteoTSVReader", "parse_chunk"]

N_DENSE = 13
N_CAT = 26

_FNV_OFFSET = 14695981039346656037
_FNV_PRIME = 1099511628211
_FNV_MASK = (1 << 64) - 1


def _fnv1a_bytes(data: bytes, h: int = _FNV_OFFSET) -> int:
    """Raw-bytes FNV-1a (matches ``text._fnv1a`` on ASCII, and matches the
    native parser on arbitrary bytes — no utf-8 round-trip that could
    raise on undecodable tokens)."""
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _FNV_MASK
    return h


_CAT_SALTS = [_fnv1a_bytes(b"C%d=" % (f + 1)) for f in range(N_CAT)]


def _int_field(raw: bytes) -> float:
    """The native parser's integer rules, exactly: optional '-', then
    digits only; empty, non-digit, or > 18 digits -> 0.0."""
    if not raw:
        return 0.0
    neg = raw[:1] == b"-"
    body = raw[1:] if neg else raw
    if not body.isdigit() or len(body) > 18:
        return 0.0
    v = int(body)
    return float(-v if neg else v) if v else 0.0

_LIB = None
_LIB_TRIED = False


def _native_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    lib = load_native_lib("criteo")
    if lib is not None:
        lib.ct_parse.restype = ctypes.c_int64
        lib.ct_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p]
    _LIB = lib
    return _LIB


def _py_parse_chunk(data: bytes, max_rows: int, hash_space: int,
                    n_reserved: int):
    """Pure-Python twin of ``ct_parse`` (bit-identical output)."""
    dense = np.zeros((max_rows, N_DENSE), np.float32)
    cat = np.zeros((max_rows, N_CAT), np.int32)
    label = np.zeros((max_rows,), np.float32)
    rows = 0
    consumed = 0
    pos = 0
    while rows < max_rows:
        eol = data.find(b"\n", pos)
        if eol < 0:
            break
        fields = data[pos:eol].split(b"\t")
        if len(fields) == 40:
            label[rows] = 1.0 if fields[0][:1] == b"1" else 0.0
            for f in range(N_DENSE):
                dense[rows, f] = _int_field(fields[1 + f])
            for f in range(N_CAT):
                h = _fnv1a_bytes(fields[14 + f], _CAT_SALTS[f])
                cat[rows, f] = n_reserved + (h % hash_space)
            rows += 1
        pos = eol + 1
        consumed = pos
    return dense[:rows], cat[:rows], label[:rows], consumed


def parse_chunk(data: bytes, max_rows: int, hash_space: int,
                n_reserved: int = N_DENSE
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Parse whole lines from ``data`` (up to ``max_rows``); returns
    (dense (r, 13) f32, cat (r, 26) int32, label (r,) f32, bytes_consumed).
    A trailing partial line is left unconsumed for the caller to carry
    into its next chunk."""
    if hash_space <= 0:
        raise ValueError(f"hash_space must be positive, got {hash_space}")
    if n_reserved + hash_space > 1 << 31:
        raise ValueError(
            f"n_reserved + hash_space = {n_reserved + hash_space} exceeds "
            "int32 index range (2^31); use a smaller hash space")
    lib = _native_lib()
    if lib is None:
        return _py_parse_chunk(data, max_rows, hash_space, n_reserved)
    dense = np.zeros((max_rows, N_DENSE), np.float32)
    cat = np.zeros((max_rows, N_CAT), np.int32)
    label = np.zeros((max_rows,), np.float32)
    consumed = ctypes.c_int64(0)
    rows = lib.ct_parse(data, len(data), max_rows, hash_space, n_reserved,
                        dense.ctypes.data, cat.ctypes.data,
                        label.ctypes.data, ctypes.byref(consumed))
    return dense[:rows], cat[:rows], label[:rows], int(consumed.value)


class CriteoTSVReader:
    """Iterator of mixed-layout batch dicts over one Criteo TSV file or a
    SEQUENCE of files (the Criteo-1TB corpus is day_0..day_23; they
    stream back-to-back in the given order, batches crossing file
    boundaries): ``{"{col}_dense": (b, 13) f32, "{col}_indices": (b, 26)
    int32, "label": (b,) f32}`` — exactly what
    ``fit_outofcore(mixed=True)`` and ``DataCacheWriter.append`` take.
    Construct a fresh reader per epoch (the ``make_reader`` protocol).

    ``num_features`` for the downstream trainer is
    ``n_reserved + hash_space``.
    """

    def __init__(self, path: "str | bytes | os.PathLike | Sequence[str]",
                 batch_rows: int, hash_space: int,
                 n_reserved: int = N_DENSE, features_col: str = "features",
                 label_col: str = "label", chunk_bytes: int = 1 << 24,
                 workers: int = 0):
        if batch_rows <= 0:
            raise ValueError(f"batch_rows must be positive: {batch_rows}")
        # one path or a sequence (the Criteo-1TB corpus is day_0..day_23
        # files; they stream back-to-back in the given order)
        self.paths = ([path] if isinstance(path, (str, bytes, os.PathLike))
                      else list(path))
        if not self.paths:
            raise ValueError("need at least one path")
        self.batch_rows = batch_rows
        self.hash_space = hash_space
        self.n_reserved = n_reserved
        self.features_col = features_col
        self.label_col = label_col
        self.chunk_bytes = max(chunk_bytes, 1 << 12)
        # workers=0: auto (one parse thread per core beyond the first,
        # capped; 1-core hosts parse inline).  The reference's data plane
        # is parallel by construction — every operator runs at
        # parallelism P with P readers (``Iterations.java:188-209``);
        # here the analog is byte-range sharding of the day-files across
        # a thread pool (ct_parse releases the GIL through ctypes, so
        # threads scale on real cores).  Output order is DETERMINISTIC
        # (ranges re-assemble in file order) so cursor-based resume and
        # seeded shuffles stay exact regardless of worker count.
        if workers < 0:
            raise ValueError(f"workers must be >= 0, got {workers}")
        self.workers = (min(8, max(1, (os.cpu_count() or 1) - 1))
                        if workers == 0 else workers)

    @property
    def num_features(self) -> int:
        return self.n_reserved + self.hash_space

    def _rows(self) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        if self.workers > 1:
            yield from self._rows_parallel()
            return
        for path in self.paths:
            yield from self._file_rows(path)

    # -- parallel range-sharded parse --------------------------------------

    def _range_tasks(self, range_bytes: int = 32 << 20):
        """Split the file set into byte-range tasks.  Range boundaries are
        arbitrary; each task starts after the first newline past its start
        (unless at file offset 0) and runs through the first newline past
        its end, so every line belongs to exactly one task."""
        for path in self.paths:
            size = os.path.getsize(path)
            start = 0
            while start < size:
                yield (path, start, min(start + range_bytes, size))
                start += range_bytes

    def _parse_range(self, path, start: int, end: int
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Parse [start, end)'s lines (ownership rule above) into one
        concatenated (dense, cat, label) triple."""
        ds, cs, ys = [], [], []
        with open(path, "rb") as f:
            f.seek(max(0, start - 1))
            tail = b""
            # a range owns lines whose FIRST byte lies in [start, end); if
            # byte start-1 is a newline, start IS a line start and nothing
            # is skipped
            at_line_start = start == 0 or f.read(1) == b"\n"
            f.seek(start)
            if not at_line_start:
                # skip the partial line owned by the previous range
                while True:
                    probe = f.read(1 << 16)
                    if not probe:
                        return (np.zeros((0, N_DENSE), np.float32),
                                np.zeros((0, N_CAT), np.int32),
                                np.zeros((0,), np.float32))
                    nl = probe.find(b"\n")
                    if nl >= 0:
                        start += nl + 1
                        break
                    start += len(probe)
                if start >= end:
                    # the whole range sat inside one line owned by the
                    # previous range
                    return (np.zeros((0, N_DENSE), np.float32),
                            np.zeros((0, N_CAT), np.int32),
                            np.zeros((0,), np.float32))
                f.seek(start)   # re-read from the owned line start
            pos_in_file = start
            while True:
                data = tail
                take = end - pos_in_file
                if take > 0:
                    chunk = f.read(min(self.chunk_bytes, take))
                    if chunk:
                        data = tail + chunk
                        pos_in_file += len(chunk)
                    else:
                        take = 0
                if take <= 0:
                    if not data:
                        break  # ended exactly on a line boundary
                    # past end: the tail may hold several complete (e.g.
                    # malformed-short) lines plus the range's owned final
                    # partial line.  Complete that last line by extending
                    # through the FIRST newline past the current bytes
                    # (never further — later lines belong to the next
                    # range), then drain everything.
                    if not data.endswith(b"\n"):
                        while True:
                            extra = f.read(1 << 16)
                            if not extra:   # EOF without trailing newline
                                data = (data + b"\n" if data.strip()
                                        else b"")
                                break
                            nl = extra.find(b"\n")
                            if nl >= 0:
                                data += extra[:nl + 1]
                                break
                            data += extra
                    pos = 0
                    while pos < len(data):
                        d, c, y, consumed = parse_chunk(
                            data[pos:], max(1, (len(data) - pos) // 40),
                            self.hash_space, self.n_reserved)
                        if consumed == 0:
                            break
                        pos += consumed
                        if len(y):
                            ds.append(d); cs.append(c); ys.append(y)
                    break
                max_rows = max(1, len(data) // 40)
                d, c, y, consumed = parse_chunk(
                    data, max_rows, self.hash_space, self.n_reserved)
                if len(y):
                    ds.append(d); cs.append(c); ys.append(y)
                tail = data[consumed:]
        if not ds:
            return (np.zeros((0, N_DENSE), np.float32),
                    np.zeros((0, N_CAT), np.int32),
                    np.zeros((0,), np.float32))
        return (np.concatenate(ds), np.concatenate(cs), np.concatenate(ys))

    def _rows_parallel(self
                       ) -> Iterator[Tuple[np.ndarray, np.ndarray,
                                           np.ndarray]]:
        """Ordered assembly over a thread pool: a sliding window of
        in-flight range tasks bounds memory at ~2x workers ranges."""
        from concurrent.futures import ThreadPoolExecutor

        tasks = self._range_tasks()
        with ThreadPoolExecutor(max_workers=self.workers,
                                thread_name_prefix="criteo-parse") as pool:
            window: list = []
            for task in tasks:
                window.append(pool.submit(self._parse_range, *task))
                if len(window) >= 2 * self.workers:
                    dense, cat, label = window.pop(0).result()
                    if len(label):
                        yield dense, cat, label
            for fut in window:
                dense, cat, label = fut.result()
                if len(label):
                    yield dense, cat, label

    def _file_rows(self, path
                   ) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        tail = b""
        with open(path, "rb") as f:
            while True:
                chunk = f.read(self.chunk_bytes)
                if not chunk:
                    break
                data = tail + chunk
                pos = 0
                # drain the chunk in as few calls as possible: a Criteo
                # line is >= 40 bytes (40 separators), so len//40 rows
                # always covers the chunk — repeated small-batch calls
                # would re-slice (copy) the remaining bytes quadratically
                max_rows = max(self.batch_rows, len(data) // 40)
                while True:
                    dense, cat, label, consumed = parse_chunk(
                        data[pos:], max_rows, self.hash_space,
                        self.n_reserved)
                    if consumed == 0:   # no whole line left in the chunk
                        break
                    pos += consumed     # advances past skipped bad lines too
                    if len(label):
                        yield dense, cat, label
                tail = data[pos:]
        if tail.strip():
            # final line without trailing newline
            dense, cat, label, _ = parse_chunk(
                tail + b"\n", self.batch_rows, self.hash_space,
                self.n_reserved)
            if len(label):
                yield dense, cat, label

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        B = self.batch_rows
        pend_d, pend_c, pend_l = [], [], []
        pending = 0
        for dense, cat, label in self._rows():
            pend_d.append(dense)
            pend_c.append(cat)
            pend_l.append(label)
            pending += len(label)
            if pending < B:
                continue
            # concatenate ONCE, then emit offset slices: re-concatenating
            # the leftover per batch would copy O(remaining) per yield
            # (quadratic when a parse chunk holds many batches)
            d = np.concatenate(pend_d)
            c = np.concatenate(pend_c)
            y = np.concatenate(pend_l)
            off = 0
            while pending - off >= B:
                yield self._batch(d[off:off + B], c[off:off + B],
                                  y[off:off + B])
                off += B
            pend_d, pend_c, pend_l = [d[off:]], [c[off:]], [y[off:]]
            pending -= off
        if pending:
            yield self._batch(np.concatenate(pend_d),
                              np.concatenate(pend_c),
                              np.concatenate(pend_l))

    def _batch(self, dense, cat, label) -> Dict[str, np.ndarray]:
        return {
            f"{self.features_col}_dense": dense,
            f"{self.features_col}_indices": cat,
            self.label_col: label,
        }
