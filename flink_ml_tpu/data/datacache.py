"""Host-side segmented epoch cache — out-of-core data for iterations.

Capability mirror of the reference's data cache (SURVEY §2.7):
- ``DataCacheWriter`` (``datacache/nonkeyed/DataCacheWriter.java:36-145``):
  append-only segmented log, here of **columnar array batches** instead of
  serialized records — batches land on disk as raw column byte ranges so a
  reader can hand zero-copy memmap slices straight to ``jax.device_put``.
- ``DataCacheReader`` (``DataCacheReader.java:35-139``): an iterator over
  fixed-size row batches, resumable from a cursor (the reference's
  ``(segmentIdx, offset)`` becomes a global row position), with native
  readahead of the next batch (posix_fadvise via native/datacache.cpp) so
  the TPU never waits on disk.
- ``DataCacheSnapshot`` (``DataCacheSnapshot.java:50-224``): persists either
  segment paths (shared filesystem) or embedded bytes into a checkpoint
  directory; ``recover`` rebuilds local segments from embedded bytes.

The native library is built lazily from ``native/`` (plain ``make``); every
operation falls back to pure numpy/memmap when it is unavailable.
"""

from __future__ import annotations

import ctypes
import json
import os
import shutil

from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..utils.native_lib import load_native_lib

__all__ = ["DataCacheWriter", "DataCacheReader", "DataCacheSnapshot", "Segment"]


def _col_filename(name: str) -> str:
    """THE column file naming scheme — writer, reader and snapshot all
    resolve through here."""
    return f"col.{name}.bin"

_LIB = None
_LIB_TRIED = False


def _native_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native IO library; None -> fallback."""
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    lib = load_native_lib("datacache")
    if lib is not None:
        lib.dc_read.restype = ctypes.c_int64
        lib.dc_read.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                ctypes.c_int64, ctypes.c_void_p]
        lib.dc_write.restype = ctypes.c_int64
        lib.dc_write.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                 ctypes.c_int64, ctypes.c_int]
        lib.dc_file_size.restype = ctypes.c_int64
        lib.dc_file_size.argtypes = [ctypes.c_char_p]
        lib.dc_prefetch.restype = None
        lib.dc_prefetch.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                    ctypes.c_int64]
        lib.dc_prefetch_drain.restype = None
        lib.dc_prefetch_pending.restype = ctypes.c_int64
    _LIB = lib
    return _LIB


class Segment:
    """One on-disk segment: a directory of per-column raw binary files +
    rows count (the analog of ``Segment(path, count, size)``,
    ``datacache/nonkeyed/Segment.java``)."""

    def __init__(self, directory: str, rows: int,
                 schema: Dict[str, Tuple[Tuple[int, ...], str]]):
        self.directory = directory
        self.rows = rows
        self.schema = schema  # name -> (row_shape, dtype_str)

    def column_path(self, name: str) -> str:
        return os.path.join(self.directory, _col_filename(name))

    def nbytes(self) -> int:
        total = 0
        for name, (shape, dtype) in self.schema.items():
            row = int(np.prod(shape, dtype=np.int64)) if shape else 1
            total += self.rows * row * np.dtype(dtype).itemsize
        return total

    def to_json(self) -> Dict[str, Any]:
        return {"directory": self.directory, "rows": self.rows,
                "schema": {k: [list(s), d] for k, (s, d) in self.schema.items()}}

    @staticmethod
    def from_json(doc: Dict[str, Any]) -> "Segment":
        schema = {k: (tuple(s), d) for k, (s, d) in doc["schema"].items()}
        return Segment(doc["directory"], int(doc["rows"]), schema)


class DataCacheWriter:
    """Append columnar batches; rotate segments at ``segment_rows``.

    ``workers > 1`` writes whole segments on a background thread pool
    (the reference's data plane writes with operator parallelism P,
    ``Iterations.java:188-209``; here the analog is segment-parallel
    pwrite, which overlaps disk IO with the producer's parse/decode and
    scales on multi-queue storage).  Batches buffer in memory until a
    segment fills, bounded to ``workers + 2`` segments in flight; the
    manifest still lists segments in arrival order, so the reader's view
    is identical for any worker count."""

    def __init__(self, directory: str, segment_rows: int = 1 << 20,
                 workers: int = 1, borrow_batches: bool = False):
        if segment_rows <= 0:
            raise ValueError("segment_rows must be positive")
        if workers < 1:
            raise ValueError("workers must be >= 1")
        # borrow_batches=True skips the defensive copy the parallel path
        # otherwise makes of every buffered slice: valid ONLY when the
        # producer never mutates a batch after append() (e.g. it yields
        # fresh arrays, like CriteoTSVReader) — on a single core the copy
        # costs more than the write overlap buys.  Note: borrowed slices
        # are VIEWS, so each in-flight segment pins its producer arrays'
        # full base buffers until the background write lands — peak RSS
        # scales with the producer's chunk size, not just segment size.
        self._borrow = borrow_batches
        self.directory = directory
        self.segment_rows = segment_rows
        os.makedirs(directory, exist_ok=True)
        # Refuse a dirty directory: appending after a previous run's bytes
        # would silently serve stale leading rows (the reference likewise
        # refuses to overwrite existing persistence paths).
        leftovers = [name for name in os.listdir(directory)
                     if name.startswith("seg-") or name == "manifest.json"]
        if leftovers:
            raise ValueError(
                f"Cache directory {directory!r} already contains "
                f"{sorted(leftovers)[:3]}...; use a fresh directory")
        self._schema: Optional[Dict[str, Tuple[Tuple[int, ...], str]]] = None
        self._segments: List[Segment] = []
        self._current_rows = 0
        self._current_dir: Optional[str] = None
        self._finished = False
        self._broken = False
        self._workers = workers
        self._pool = None
        self._futures: List = []        # (segment_index, Future[Segment])
        self._pending: List = []        # buffered arrays for current seg
        self._pending_rows = 0
        self._next_seg = 0
        if workers > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="datacache-write")

    def _check_schema(self, batch: Dict[str, np.ndarray]) -> None:
        schema = {name: (tuple(arr.shape[1:]), str(arr.dtype))
                  for name, arr in batch.items()}
        if self._schema is None:
            self._schema = schema
        elif schema != self._schema:
            raise ValueError(
                f"Batch schema {schema} does not match cache schema "
                f"{self._schema}")

    def _open_segment(self) -> None:
        idx = len(self._segments)
        self._current_dir = os.path.join(self.directory, f"seg-{idx:05d}")
        os.makedirs(self._current_dir, exist_ok=True)
        self._current_rows = 0

    def _rotate(self) -> None:
        if self._current_dir is not None and self._current_rows > 0:
            self._segments.append(
                Segment(self._current_dir, self._current_rows, self._schema))
        self._current_dir = None

    def append(self, batch: Dict[str, Any]) -> None:
        if self._finished:
            raise RuntimeError("writer already finished")
        if self._broken:
            raise RuntimeError(
                "writer is broken: a previous append failed mid-write, the "
                "current segment may hold partial column bytes")
        batch = {k: np.ascontiguousarray(v) for k, v in batch.items()}
        rows = next(iter(batch.values())).shape[0]
        for name, arr in batch.items():
            if arr.shape[0] != rows:
                raise ValueError("Ragged batch: columns disagree on rows")
        self._check_schema(batch)
        if self._pool is not None:
            self._append_parallel(batch, rows)
            return

        written = 0
        lib = _native_lib()
        try:
            while written < rows:
                if self._current_dir is None:
                    self._open_segment()
                take = min(rows - written,
                           self.segment_rows - self._current_rows)
                for name, arr in batch.items():
                    chunk = np.ascontiguousarray(arr[written:written + take])
                    path = self.column_path_for_current(name)
                    if lib is not None:
                        r = lib.dc_write(path.encode(), chunk.ctypes.data,
                                         chunk.nbytes, 1)
                        if r != chunk.nbytes:
                            raise IOError(f"native write failed for {path}")
                    else:
                        with open(path, "ab") as f:
                            f.write(chunk.tobytes())
                written += take
                self._current_rows += take
                if self._current_rows >= self.segment_rows:
                    self._rotate()
        except Exception:
            # Columns written before the failing one hold partial bytes for
            # this chunk; retrying would silently shift every later row.
            self._broken = True
            raise

    def column_path_for_current(self, name: str) -> str:
        return os.path.join(self._current_dir, _col_filename(name))

    # -- segment-parallel path (workers > 1) -------------------------------

    def _append_parallel(self, batch: Dict[str, np.ndarray],
                         rows: int) -> None:
        written = 0
        while written < rows:
            take = min(rows - written, self.segment_rows - self._pending_rows)
            # COPY the slice (unless borrowing): append() returns before
            # the background write runs, so a view into a caller-reused
            # buffer would let the next batch's bytes land in this segment
            self._pending.append(
                {k: (v[written:written + take] if self._borrow
                     else v[written:written + take].copy())
                 for k, v in batch.items()})
            self._pending_rows += take
            written += take
            if self._pending_rows >= self.segment_rows:
                self._submit_segment()

    def _submit_segment(self) -> None:
        if not self._pending_rows:
            return
        seg_idx = self._next_seg
        self._next_seg += 1
        parts, rows = self._pending, self._pending_rows
        self._pending, self._pending_rows = [], 0
        # backpressure: bound in-flight segments (memory = buffered
        # arrays); block on the OLDEST unfinished write, pruning finished
        # futures so neither the list nor the wait degenerates
        pending = [(i, f) for i, f in self._futures if not f.done()]
        done = [(i, f) for i, f in self._futures if f.done()]
        try:
            for _, f in done:
                f.result()   # surface write errors promptly
            self._futures = done + pending  # keep results for finish()
            while len(pending) >= self._workers + 2:
                pending[0][1].result()
                pending = [(i, f) for i, f in pending if not f.done()]
        except Exception:
            # same contract as the serial path: a failed segment write
            # leaves partial column bytes on disk — refuse retries
            self._broken = True
            raise
        self._futures.append(
            (seg_idx, self._pool.submit(self._write_segment, seg_idx,
                                        parts, rows)))

    def _write_segment(self, seg_idx: int, parts: List[Dict[str, np.ndarray]],
                       rows: int) -> Segment:
        seg_dir = os.path.join(self.directory, f"seg-{seg_idx:05d}")
        os.makedirs(seg_dir, exist_ok=True)
        lib = _native_lib()
        for name in self._schema:
            path = os.path.join(seg_dir, _col_filename(name))
            if lib is not None:
                for part in parts:
                    chunk = np.ascontiguousarray(part[name])
                    r = lib.dc_write(path.encode(), chunk.ctypes.data,
                                     chunk.nbytes, 1)
                    if r != chunk.nbytes:
                        raise IOError(f"native write failed for {path}")
            else:
                with open(path, "ab") as f:
                    for part in parts:
                        f.write(np.ascontiguousarray(part[name]).tobytes())
        return Segment(seg_dir, rows, self._schema)

    def finish(self) -> List[Segment]:
        """Seal the cache and write the manifest
        (``DataCacheWriter.finish``)."""
        if not self._finished:
            if self._pool is not None:
                self._submit_segment()
                try:
                    segs = {i: f.result() for i, f in self._futures}
                except Exception:
                    self._broken = True
                    self._pool.shutdown(wait=True)
                    raise
                self._pool.shutdown(wait=True)
                self._segments = [segs[i] for i in sorted(segs)]
            else:
                self._rotate()
            self._finished = True
            manifest = {
                "segments": [s.to_json() for s in self._segments],
                "schema": ({k: [list(s), d]
                            for k, (s, d) in self._schema.items()}
                           if self._schema else {}),
            }
            with open(os.path.join(self.directory, "manifest.json"), "w") as f:
                json.dump(manifest, f)
        return list(self._segments)


def load_segments(directory: str) -> List[Segment]:
    with open(os.path.join(directory, "manifest.json")) as f:
        manifest = json.load(f)
    return [Segment.from_json(doc) for doc in manifest["segments"]]


class DataCacheReader:
    """Iterate fixed-size row batches across segments; resumable via the
    ``cursor`` property (global row index).  With the native library, the
    next batch's byte ranges are prefetched into page cache while the caller
    consumes the current one."""

    def __init__(self, source, batch_rows: int, cursor: int = 0,
                 prefetch: bool = True):
        if batch_rows <= 0:
            raise ValueError("batch_rows must be positive")
        self.segments = (load_segments(source) if isinstance(source, str)
                         else list(source))
        if not self.segments:
            raise ValueError("DataCacheReader got an empty cache")
        self.batch_rows = batch_rows
        self.total_rows = sum(s.rows for s in self.segments)
        if not 0 <= cursor <= self.total_rows:
            raise ValueError(f"cursor {cursor} out of range "
                             f"[0, {self.total_rows}]")
        self._cursor = cursor
        self._prefetch = prefetch
        self._maps: Dict[Tuple[int, str], np.memmap] = {}

    @property
    def cursor(self) -> int:
        return self._cursor

    def seek(self, cursor: int) -> None:
        if not 0 <= cursor <= self.total_rows:
            raise ValueError(f"cursor {cursor} out of range")
        self._cursor = cursor

    def _segment_at(self, row: int) -> Tuple[int, int]:
        """global row -> (segment index, row within segment)."""
        offset = row
        for i, seg in enumerate(self.segments):
            if offset < seg.rows:
                return i, offset
            offset -= seg.rows
        return len(self.segments) - 1, self.segments[-1].rows

    def _column_map(self, seg_idx: int, name: str) -> np.memmap:
        key = (seg_idx, name)
        if key not in self._maps:
            seg = self.segments[seg_idx]
            shape, dtype = seg.schema[name]
            self._maps[key] = np.memmap(
                seg.column_path(name), dtype=np.dtype(dtype), mode="r",
                shape=(seg.rows,) + shape)
        return self._maps[key]

    def _prefetch_range(self, start_row: int, rows: int) -> None:
        lib = _native_lib()
        if lib is None or rows <= 0 or start_row >= self.total_rows:
            return
        seg_idx, in_seg = self._segment_at(start_row)
        remaining = min(rows, self.total_rows - start_row)
        while remaining > 0 and seg_idx < len(self.segments):
            seg = self.segments[seg_idx]
            take = min(remaining, seg.rows - in_seg)
            for name, (shape, dtype) in seg.schema.items():
                row_bytes = (int(np.prod(shape, dtype=np.int64)) if shape
                             else 1) * np.dtype(dtype).itemsize
                lib.dc_prefetch(seg.column_path(name).encode(),
                                in_seg * row_bytes, take * row_bytes)
            remaining -= take
            seg_idx += 1
            in_seg = 0

    def read_batch(self) -> Optional[Dict[str, np.ndarray]]:
        """Next batch (dict of arrays, <= batch_rows on the tail), advancing
        the cursor; None at end of cache."""
        if self._cursor >= self.total_rows:
            return None
        rows = min(self.batch_rows, self.total_rows - self._cursor)
        out: Dict[str, List[np.ndarray]] = {}
        start = self._cursor
        seg_idx, in_seg = self._segment_at(start)
        remaining = rows
        while remaining > 0:
            seg = self.segments[seg_idx]
            take = min(remaining, seg.rows - in_seg)
            for name in seg.schema:
                out.setdefault(name, []).append(
                    np.asarray(self._column_map(seg_idx, name)
                               [in_seg:in_seg + take]))
            remaining -= take
            seg_idx += 1
            in_seg = 0
        self._cursor += rows
        if self._prefetch:
            self._prefetch_range(self._cursor, self.batch_rows)
        return {name: (parts[0] if len(parts) == 1
                       else np.concatenate(parts, axis=0))
                for name, parts in out.items()}

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            batch = self.read_batch()
            if batch is None:
                return
            yield batch

    # Stream-source protocol for iterate() checkpointing (the analog of
    # ReplayOperator snapshotting its reader position).
    def snapshot(self) -> Dict[str, Any]:
        return {"cursor": self._cursor}

    def restore(self, snap: Dict[str, Any]) -> None:
        self.seek(int(snap["cursor"]))


class ShuffledCacheReader:
    """Per-epoch block-shuffled view over a data cache — the documented
    "vary segment order per epoch" posture for out-of-core SGD, packaged
    with exact resume.

    Full fixed-size row blocks of ``batch_rows`` are visited in a seeded
    permutation of ``(seed, epoch)``; the trailing partial block (if any)
    is always visited last so batch shapes stay static for the one
    compiled step program.  Construct one per epoch — pass an
    epoch-aware ``make_reader(epoch=...)`` to ``sgd_fit_outofcore`` and
    it supplies the epoch, which keeps the permutation reconstructible
    on checkpoint resume (the cursor protocol's ``seek`` jumps to a
    VISIT position, ``cursor // batch_rows``, not a file offset — the
    permutation plus the visit index IS the stream position).

    ``epoch_varying = True`` declares the per-epoch variance to
    ``sgd_fit_outofcore``'s decoded replay cache — a one-batch digest
    guard cannot prove a permutation identical (two epochs can lead
    with the same block yet differ after it), so declaring beats
    detecting.  ``block_order`` additionally makes the stream
    BLOCK-ADDRESSABLE: the i-th yielded batch is block
    ``block_order[i]``, and a given block's rows (hence its decoded
    form) are identical in every epoch — the contract the streamer's
    block-keyed decode cache relies on to give per-epoch reshuffling
    AND decode-once together.

    Shuffling defeats the sequential fadvise readahead, so each read
    prefetches the NEXT visit's block instead."""

    epoch_varying = True

    def __init__(self, source, batch_rows: int, *, seed: int = 0,
                 epoch: int = 0, prefetch: bool = True):
        self._inner = DataCacheReader(source, batch_rows=batch_rows,
                                      prefetch=False)
        self.batch_rows = batch_rows
        self.total_rows = self._inner.total_rows
        self._do_prefetch = prefetch
        full = self.total_rows // batch_rows
        order = np.random.default_rng(
            np.random.SeedSequence([int(seed), int(epoch)])
        ).permutation(full)
        if self.total_rows % batch_rows:
            order = np.concatenate([order, [full]])
        self._order = order.astype(np.int64)
        self._visit = 0

    @property
    def block_order(self) -> Tuple[int, ...]:
        """This epoch's visit order: the i-th yielded batch is block
        ``block_order[i]`` (block b = rows ``[b*batch_rows,
        (b+1)*batch_rows)`` of the cache, ragged block last)."""
        return tuple(int(b) for b in self._order)

    @property
    def cursor(self) -> int:
        """Rows handed out so far (visit position x batch_rows, capped)."""
        return min(self._visit * self.batch_rows, self.total_rows)

    def seek(self, cursor: int) -> None:
        if not 0 <= cursor <= self.total_rows:
            raise ValueError(f"cursor {cursor} out of range")
        if cursor < self.total_rows and cursor % self.batch_rows:
            # this class's cursor protocol only ever produces visit
            # boundaries (or total_rows); silently flooring an arbitrary
            # row position would drop up to batch_rows-1 rows (ADVICE r4)
            raise ValueError(
                f"cursor {cursor} is not a visit boundary (multiple of "
                f"batch_rows={self.batch_rows}) or total_rows; "
                "ShuffledCacheReader seeks by whole visits")
        self._visit = (len(self._order) if cursor >= self.total_rows
                       else cursor // self.batch_rows)

    def read_batch(self) -> Optional[Dict[str, np.ndarray]]:
        if self._visit >= len(self._order):
            return None
        block = int(self._order[self._visit])
        self._inner.seek(block * self.batch_rows)
        batch = self._inner.read_batch()
        self._visit += 1
        if self._do_prefetch and self._visit < len(self._order):
            nxt = int(self._order[self._visit])
            self._inner._prefetch_range(nxt * self.batch_rows,
                                        self.batch_rows)
        return batch

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            batch = self.read_batch()
            if batch is None:
                return
            yield batch

    def snapshot(self) -> Dict[str, Any]:
        return {"cursor": self.cursor}

    def restore(self, snap: Dict[str, Any]) -> None:
        self.seek(int(snap["cursor"]))


class DataCacheSnapshot:
    """Persist/recover a cache into a checkpoint directory
    (``DataCacheSnapshot.java:50-224``): path-only references when the cache
    is on a shared filesystem, embedded bytes otherwise."""

    @staticmethod
    def write(segments: List[Segment], target: str, *,
              embed: bool = False, cursor: int = 0) -> None:
        os.makedirs(target, exist_ok=True)
        doc = {
            "embed": embed,
            "cursor": cursor,
            "segments": [s.to_json() for s in segments],
        }
        if embed:
            payload_dir = os.path.join(target, "payload")
            os.makedirs(payload_dir, exist_ok=True)
            for i, seg in enumerate(segments):
                for name in seg.schema:
                    shutil.copyfile(
                        seg.column_path(name),
                        os.path.join(payload_dir, f"{i:05d}." + _col_filename(name)))
        with open(os.path.join(target, "snapshot.json"), "w") as f:
            json.dump(doc, f)

    @staticmethod
    def recover(target: str, restore_dir: Optional[str] = None
                ) -> Tuple[List[Segment], int]:
        with open(os.path.join(target, "snapshot.json")) as f:
            doc = json.load(f)
        segments = [Segment.from_json(d) for d in doc["segments"]]
        if doc["embed"]:
            if restore_dir is None:
                raise ValueError("embedded snapshot needs a restore_dir")
            os.makedirs(restore_dir, exist_ok=True)
            restored = []
            for i, seg in enumerate(segments):
                seg_dir = os.path.join(restore_dir, f"seg-{i:05d}")
                os.makedirs(seg_dir, exist_ok=True)
                for name in seg.schema:
                    shutil.copyfile(
                        os.path.join(target, "payload",
                                     f"{i:05d}." + _col_filename(name)),
                        os.path.join(seg_dir, _col_filename(name)))
                restored.append(Segment(seg_dir, seg.rows, seg.schema))
            segments = restored
        return segments, int(doc["cursor"])
