from .datacache import (  # noqa: F401
    DataCacheReader,
    DataCacheSnapshot,
    DataCacheWriter,
    ShuffledCacheReader,
)
from .prefetch import PrefetchStats, prefetch_to_device  # noqa: F401
from .replay_cache import DecodedReplayCache, default_ram_budget  # noqa: F401
from .stream import CountWindows, EventTimeWindows, windows_of  # noqa: F401
from .table import Table  # noqa: F401
from .wal import WindowLog  # noqa: F401
