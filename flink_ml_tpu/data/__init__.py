from .prefetch import prefetch_to_device  # noqa: F401
from .table import Table  # noqa: F401
