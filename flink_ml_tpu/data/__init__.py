from .table import Table  # noqa: F401
