"""Unbounded-stream substrate: windowed sources shared by every online
estimator.

The reference makes unbounded iteration a first-class entry point
(``Iterations.iterateUnboundedStreams``, ``Iterations.java:118-127``) and
windows bounded streams with ``EndOfStreamWindows``
(``common/datastream/EndOfStreamWindows.java:36-71``).  The TPU-native
mapping (``data/table.py``): a bounded stream is a Table, an unbounded
stream is an iterator of Tables, and *windowing* is this module — one shared
implementation of count/event-time tumbling windows with watermark-style
close and a snapshot/restore cursor, instead of each online model
reimplementing its own batching.

Consumers: OnlineLogisticRegression, OnlineKMeans, OnlineStandardScaler all
go through :func:`windows_of`; the cursor protocol matches what
``iterate``'s checkpointing expects of a data source (the
``DataCacheReader`` surface: ``snapshot()``/``restore()``).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional

import numpy as np

from .table import Table

__all__ = ["CountWindows", "EventTimeWindows", "cursor_adapter",
           "ensure_cursor_source", "windows_of"]


class CountWindows:
    """Tumbling count windows over a stream of rows.

    ``source`` is a Table (bounded: rows are windowed in order and the final
    partial window flushes at end-of-stream — the ``EndOfStreamWindows``
    close) or an iterable of Tables (unbounded feed: incoming tables are
    re-chunked to exactly ``window_rows``, buffering across table
    boundaries; whatever remains when the feed ends flushes as the last
    window).
    """

    def __init__(self, source: Any, window_rows: int):
        if window_rows <= 0:
            raise ValueError(f"window_rows must be positive, got {window_rows}")
        self.window_rows = window_rows
        self._table = source if isinstance(source, Table) else None
        self._feed = None if self._table is not None else source
        self._cursor = 0          # rows (table) / windows emitted (feed)
        self._skip = 0            # feed windows to discard after restore

    # -- iteration -----------------------------------------------------------
    def __iter__(self) -> Iterator[Table]:
        if self._table is not None:
            yield from self._iter_table()
        else:
            yield from self._iter_feed(skip=self._skip)

    def _iter_table(self) -> Iterator[Table]:
        n = self._table.num_rows
        while self._cursor < n:
            end = min(self._cursor + self.window_rows, n)
            window = self._table.slice(self._cursor, end)
            self._cursor = end
            yield window

    def _iter_feed(self, skip: int) -> Iterator[Table]:
        pending: Optional[Table] = None
        emitted = 0

        def emit(window: Table):
            nonlocal emitted
            emitted += 1
            self._cursor = emitted
            return window

        for t in self._feed:
            pending = t if pending is None else pending.concat(t)
            while pending.num_rows >= self.window_rows:
                window = pending.take(self.window_rows)
                pending = pending.slice(self.window_rows, pending.num_rows)
                if emitted < skip:
                    emitted += 1
                    continue
                yield emit(window)
        if pending is not None and pending.num_rows > 0 and emitted >= skip:
            yield emit(pending)   # end-of-stream watermark: flush the tail

    # -- cursor protocol -----------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        return {"cursor": self._cursor}

    def restore(self, snap: Dict[str, Any]) -> None:
        cursor = int(snap["cursor"])
        if self._table is not None:
            if not 0 <= cursor <= self._table.num_rows:
                raise ValueError(f"cursor {cursor} out of range")
            self._cursor = cursor
        else:
            # feed cursors fast-forward by re-windowing and discarding —
            # exact for replayable feeds; live feeds need a WindowLog tee
            # (data/wal.py) for loss-free restore
            self._skip = cursor


class EventTimeWindows:
    """Tumbling event-time windows: each row joins the window
    ``[k*size, (k+1)*size)`` holding its timestamp; a window closes when the
    watermark — the max timestamp seen minus ``allowed_lateness`` — passes
    its end (rows later than that are dropped, the streaming-engine late-data
    rule).  All still-open windows flush in time order at end-of-stream.

    ``source`` is a Table or an iterable of Tables carrying ``time_col``.

    Cursor caveat: ``snapshot``/``restore`` count EMITTED windows and
    fast-forward by re-iterating the source — exact only when the source
    replays deterministically from the start (a Table, a file, a cache).
    For a genuinely live feed, wrap the window stream in
    :class:`flink_ml_tpu.data.wal.WindowLog`, whose write-ahead log
    replays consumed-but-uncheckpointed windows without touching the
    source (the ``Checkpoints.java`` analog).
    """

    def __init__(self, source: Any, time_col: str, window_size: float,
                 allowed_lateness: float = 0.0):
        if window_size <= 0:
            raise ValueError("window_size must be positive")
        self._source = [source] if isinstance(source, Table) else source
        self.time_col = time_col
        self.window_size = float(window_size)
        self.allowed_lateness = float(allowed_lateness)
        self._emitted = 0

    def _window_key(self, ts: np.ndarray) -> np.ndarray:
        return np.floor(ts / self.window_size).astype(np.int64)

    def __iter__(self) -> Iterator[Table]:
        open_windows: Dict[int, Table] = {}
        watermark = -np.inf
        emitted = 0
        skip = self._emitted

        def close_ready():
            nonlocal emitted
            for key in sorted(open_windows):
                if (key + 1) * self.window_size <= watermark:
                    window = open_windows.pop(key)
                    emitted += 1
                    if emitted > skip:
                        self._emitted = emitted
                        yield window
                else:
                    break  # later windows end even later

        for t in self._source:
            ts = np.asarray(t[self.time_col], np.float64)
            if len(ts) == 0:
                continue
            keys = self._window_key(ts)
            # a row is late iff its window ALREADY closed (window end behind
            # the watermark); rows for still-open windows always join them
            live = (keys + 1) * self.window_size > watermark
            for key in np.unique(keys[live]):
                rows = Table({c: np.asarray(t[c])[live & (keys == key)]
                              for c in t.column_names})
                open_windows[key] = (rows if key not in open_windows
                                     else open_windows[key].concat(rows))
            watermark = max(watermark,
                            float(ts.max()) - self.allowed_lateness)
            yield from close_ready()
        # end of stream: the watermark jumps to +inf, closing everything
        watermark = np.inf
        yield from close_ready()

    def snapshot(self) -> Dict[str, Any]:
        return {"emitted": self._emitted}

    def restore(self, snap: Dict[str, Any]) -> None:
        self._emitted = int(snap["emitted"])


def windows_of(source: Any, window_rows: int) -> Iterator[Table]:
    """THE shared online-model ingest: a Table is count-windowed into
    ``window_rows`` chunks; an iterable of Tables passes through AS-IS (a
    live feed's framing IS its windowing — each yielded Table is one
    window); a Count/EventTimeWindows is consumed as-is, so callers can hand
    a re-chunked or time-windowed stream straight to any online
    estimator."""
    if isinstance(source, Table):
        return iter(CountWindows(source, window_rows))
    return iter(source)


def ensure_cursor_source(source: Any, window_rows: int):
    """THE checkpoint-source preparation shared by the online estimators:
    a bare Table auto-wraps in :class:`CountWindows` (it has no cursor of
    its own), and anything without ``snapshot``/``restore`` is rejected —
    resume would otherwise silently re-train already-consumed windows."""
    if isinstance(source, Table):
        source = CountWindows(source, window_rows)
    if not (hasattr(source, "snapshot") and hasattr(source, "restore")):
        raise ValueError(
            "checkpointed streaming fit needs a source with a cursor "
            "(snapshot/restore): resume would otherwise silently re-train "
            "already-consumed windows.  Use CountWindows / "
            "EventTimeWindows / DataCacheReader, or wrap a live feed in "
            "flink_ml_tpu.data.wal.WindowLog")
    return source


def cursor_adapter(source: Any, payloads):
    """Iterable whose items come from ``payloads()`` (a zero-arg generator
    factory) while ``snapshot``/``restore`` delegate to ``source`` — THE
    shim the checkpointed online estimators hand to ``iterate`` so the
    stream cursor rides the checkpoint (one copy; OnlineLogisticRegression
    and OnlineKMeans both route through it)."""

    class _CursorAdapter:
        def __iter__(self):
            return payloads()

        def __getattr__(self, name):
            if name in ("snapshot", "restore"):
                return getattr(source, name)  # AttributeError if absent
            raise AttributeError(name)

    return _CursorAdapter()
