"""Host->device prefetch — the feed that keeps the MXU from waiting on IO.

The reference streams records through Flink's network stack with built-in
backpressure (SURVEY §2.10); on TPU the analog problem is keeping the device
fed: ``device_put`` of batch N+1 (and the host-side read/decode behind it)
must overlap the jitted step on batch N, or every step pays
HBM-transfer + disk latency serially.

``prefetch_to_device`` wraps any host-batch iterator with a bounded
background thread: the thread pulls host batches (hitting the data cache's
fadvise readahead, `data/datacache.py`), schedules the async ``device_put``,
and parks the in-flight device buffers in a depth-bounded queue — classic
double buffering at ``depth=2``, deeper if decode jitter demands it.  The
bound is the backpressure: the reader never runs more than ``depth`` batches
ahead of the consumer, so host RAM stays flat on out-of-core epochs.
"""

from __future__ import annotations

import queue
import threading

from typing import Any, Callable, Iterable, Iterator, Optional

import jax

__all__ = ["prefetch_to_device"]

_END = object()


def prefetch_to_device(batches: Iterable[Any], *, depth: int = 2,
                       sharding: Optional[Any] = None,
                       transform: Optional[Callable[[Any], Any]] = None
                       ) -> Iterator[Any]:
    """Iterate device-resident copies of ``batches``, staying ``depth``
    batches ahead of the consumer.

    ``sharding`` (e.g. a ``NamedSharding`` or a pytree of them matching the
    batch structure) is passed to ``device_put``; ``transform`` runs on the
    host thread before the transfer (decode/pad/astype — keeps that work off
    the consumer thread too).

    Exceptions raised by the source iterator or the transform are re-raised
    at the consuming ``next()`` call.
    """
    if depth < 1:
        raise ValueError(f"depth must be >= 1, got {depth}")
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def put_or_abandon(item) -> None:
        """Stop-aware put: never parks forever if the consumer walked away
        (an untimed put here would leak the thread + queued device buffers)."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def worker():
        try:
            for batch in batches:
                if stop.is_set():
                    return
                if transform is not None:
                    batch = transform(batch)
                batch = (jax.device_put(batch, sharding)
                         if sharding is not None else jax.device_put(batch))
                put_or_abandon(batch)
            put_or_abandon(_END)
        except BaseException as exc:  # noqa: BLE001 — re-raised at consumer
            put_or_abandon(exc)

    thread = threading.Thread(target=worker, daemon=True,
                              name="flink-ml-tpu-prefetch")
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
