"""Host->device prefetch — the feed that keeps the MXU from waiting on IO.

The reference streams records through Flink's network stack with built-in
backpressure (SURVEY §2.10); on TPU the analog problem is keeping the device
fed: ``device_put`` of batch N+1 (and the host-side read/decode behind it)
must overlap the jitted step on batch N, or every step pays
HBM-transfer + disk latency serially.

``prefetch_to_device`` wraps any host-batch iterator with a bounded
pipeline: a reader thread pulls host batches (hitting the data cache's
fadvise readahead, `data/datacache.py`), ``workers`` threads run the
decode ``transform`` (ordered reassembly — results stay in source order),
and a putter thread schedules the async ``device_put``, parking in-flight
device buffers in a depth-bounded queue — classic double buffering at
``depth=2``, deeper if decode jitter demands it.  The bound is the
backpressure: the reader never runs more than ``depth + in-flight
transforms`` batches ahead of the consumer, so host RAM stays flat on
out-of-core epochs.

``stats`` (a :class:`PrefetchStats`) attributes the pipeline's time:
cumulative seconds spent reading host batches, transforming, in
``device_put``, and how long the CONSUMER sat waiting on an empty queue
(the infeed gap — if this is ~0 the device is the bottleneck, not the
ingest).  This is the instrumentation VERDICT r2 asked for: it separates
host-decode from transfer from compute so the out-of-core benchmark can
attribute its overhead.
"""

from __future__ import annotations

import queue
import threading
import time

from concurrent import futures

from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Iterator, Optional

import jax

__all__ = ["prefetch_to_device", "PrefetchStats"]

_END = object()


@dataclass
class PrefetchStats:
    """Cumulative pipeline timing (seconds) and batch count.  Single
    writer per field (each stage runs on one thread; transform workers
    accumulate under the lock)."""
    read_s: float = 0.0        # source iterator next()
    transform_s: float = 0.0   # decode/pad (sum over workers)
    put_s: float = 0.0         # device_put scheduling
    wait_s: float = 0.0        # consumer blocked on empty queue
    batches: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    def as_dict(self) -> dict:
        return {"read_s": round(self.read_s, 4),
                "transform_s": round(self.transform_s, 4),
                "put_s": round(self.put_s, 4),
                "consumer_wait_s": round(self.wait_s, 4),
                "batches": self.batches}


def prefetch_to_device(batches: Iterable[Any], *, depth: int = 2,
                       sharding: Optional[Any] = None,
                       transform: Optional[Callable[[Any], Any]] = None,
                       workers: int = 1,
                       put_workers: int = 1,
                       stats: Optional[PrefetchStats] = None,
                       put_fn: Optional[Callable[[Any, Any], Any]] = None
                       ) -> Iterator[Any]:
    """Iterate device-resident copies of ``batches``, staying ``depth``
    batches ahead of the consumer.

    ``sharding`` (e.g. a ``NamedSharding`` or a pytree of them matching the
    batch structure) is passed to ``device_put``; ``transform`` runs on
    ``workers`` background threads before the transfer (decode/pad/astype —
    keeps that work off the consumer thread; results are reassembled in
    source order, so worker count never changes what the consumer sees).

    ``put_workers`` issues the transfers themselves from that many
    threads — on transports where a single ``device_put`` is
    latency-bound but concurrent transfer RPCs pipeline (the axon
    tunnel question ``scripts/put_overlap_probe.py`` measures),
    parallel puts hide most of the per-batch latency.  Results are
    reassembled in source order, so the consumer sees the same stream
    at any worker count.

    Exceptions raised by the source iterator or the transform are re-raised
    at the consuming ``next()`` call.

    ``put_fn(batch, sharding)`` overrides the transfer itself (default
    ``jax.device_put``) — multi-host callers pass an assembly that builds
    non-fully-addressable global arrays from each process's local batch
    (``jax.make_array_from_process_local_data``).
    """
    if depth < 1:
        raise ValueError(f"depth must be >= 1, got {depth}")
    if workers < 1:
        raise ValueError(f"workers must be >= 1, got {workers}")
    if put_workers < 1:
        raise ValueError(f"put_workers must be >= 1, got {put_workers}")
    st = stats or PrefetchStats()

    def put(batch, sh):
        # honor the documented 2-arg put_fn contract on BOTH branches
        if put_fn is not None:
            return put_fn(batch, sh)
        return jax.device_put(batch, sh) if sh is not None \
            else jax.device_put(batch)
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def put_or_abandon(dst: queue.Queue, item) -> None:
        """Stop-aware put: never parks forever if the consumer walked away
        (an untimed put here would leak the thread + queued device buffers)."""
        while not stop.is_set():
            try:
                dst.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def timed_transform(batch):
        t0 = time.perf_counter()
        out = transform(batch) if transform is not None else batch
        with st._lock:
            st.transform_s += time.perf_counter() - t0
        return out

    if workers == 1 and put_workers == 1:
        def worker():
            try:
                src = iter(batches)
                while True:
                    t0 = time.perf_counter()
                    try:
                        batch = next(src)
                    except StopIteration:
                        break
                    st.read_s += time.perf_counter() - t0
                    if stop.is_set():
                        return
                    batch = timed_transform(batch)
                    t0 = time.perf_counter()
                    batch = put(batch, sharding)
                    st.put_s += time.perf_counter() - t0
                    put_or_abandon(q, batch)
                put_or_abandon(q, _END)
            except BaseException as exc:  # noqa: BLE001 — re-raised at consumer
                put_or_abandon(q, exc)

        threads = [threading.Thread(target=worker, daemon=True,
                                    name="flink-ml-tpu-prefetch")]
    else:
        from concurrent.futures import ThreadPoolExecutor

        pool = ThreadPoolExecutor(max_workers=workers,
                                  thread_name_prefix="flink-ml-tpu-decode")
        fq: queue.Queue = queue.Queue(maxsize=depth + workers + put_workers)
        # ordered reassembly shared by the putter pool: seq -> device
        # batch, flushed to q in source order as the prefix completes
        flush_lock = threading.Lock()
        pending: dict = {}
        flush_state = {"next": 0, "total": None, "finished": False}

        def _flush_ready_locked():
            """Emit the completed prefix (and the terminal _END once the
            reader's total is known and reached).  Caller holds
            flush_lock; q puts under the lock are safe — the consumer
            drains q independently, so progress is guaranteed."""
            while flush_state["next"] in pending:
                put_or_abandon(q, pending.pop(flush_state["next"]))
                flush_state["next"] += 1
            if (flush_state["total"] is not None
                    and flush_state["next"] >= flush_state["total"]
                    and not flush_state["finished"]):
                flush_state["finished"] = True
                put_or_abandon(q, _END)

        def reader():
            seq = 0
            try:
                src = iter(batches)
                while True:
                    t0 = time.perf_counter()
                    try:
                        batch = next(src)
                    except StopIteration:
                        break
                    st.read_s += time.perf_counter() - t0
                    if stop.is_set():
                        return
                    put_or_abandon(
                        fq, (seq, pool.submit(timed_transform, batch)))
                    seq += 1
                with flush_lock:
                    flush_state["total"] = seq
                    _flush_ready_locked()   # covers the empty stream
            except BaseException as exc:  # noqa: BLE001
                # deliver the error IN STREAM ORDER: it enters the
                # reassembly at the next seq, so every batch already
                # read and decoded reaches the consumer first (callers
                # that checkpoint from the last consumed batch rely on
                # this)
                with flush_lock:
                    pending[seq] = exc
                    flush_state["total"] = seq + 1
                    _flush_ready_locked()
            for _ in range(put_workers):
                put_or_abandon(fq, _END)

        def get_or_abandon(src: queue.Queue):
            """Stop-aware get: the putter must exit when the consumer
            walks away, or it leaks for process lifetime."""
            while not stop.is_set():
                try:
                    return src.get(timeout=0.1)
                except queue.Empty:
                    continue
            return _END

        def putter():
            while True:
                item = get_or_abandon(fq)
                if item is _END:
                    return
                seq, fut = item
                # stop-aware future wait, mirroring put/get_or_abandon:
                # an abandoned consumer must not leave this thread
                # blocked behind a hung transform.  Poll done-ness
                # rather than catching TimeoutError from result() —
                # futures.TimeoutError IS the builtin TimeoutError on
                # 3.11+, so a transform failing with e.g.
                # socket.timeout must still propagate, not spin.
                while not stop.is_set() and not fut.done():
                    futures.wait([fut], timeout=0.1)
                if stop.is_set():
                    fut.cancel()
                    return
                try:
                    batch = fut.result()
                    t0 = time.perf_counter()
                    entry = put(batch, sharding)
                    with st._lock:
                        st.put_s += time.perf_counter() - t0
                except BaseException as exc:  # noqa: BLE001
                    # transform/put errors ride the reassembly at their
                    # own seq: every earlier batch is delivered first,
                    # exactly like the reader's error path
                    entry = exc
                with flush_lock:
                    pending[seq] = entry
                    _flush_ready_locked()
                if isinstance(entry, BaseException):
                    return

        threads = [threading.Thread(target=reader, daemon=True,
                                    name="flink-ml-tpu-prefetch-read")]
        threads += [threading.Thread(target=putter, daemon=True,
                                     name=f"flink-ml-tpu-prefetch-put-{i}")
                    for i in range(put_workers)]

    for t in threads:
        t.start()
    try:
        while True:
            t0 = time.perf_counter()
            item = q.get()
            st.wait_s += time.perf_counter() - t0
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            st.batches += 1
            yield item
    finally:
        stop.set()
        if workers > 1 or put_workers > 1:
            pool.shutdown(wait=False, cancel_futures=True)
