"""Host->device prefetch — the feed that keeps the MXU from waiting on IO.

The reference streams records through Flink's network stack with built-in
backpressure (SURVEY §2.10); on TPU the analog problem is keeping the device
fed: ``device_put`` of batch N+1 (and the host-side read/decode behind it)
must overlap the jitted step on batch N, or every step pays
HBM-transfer + disk latency serially.

``prefetch_to_device`` wraps any host-batch iterator with a bounded
pipeline: a reader thread pulls host batches (hitting the data cache's
fadvise readahead, `data/datacache.py`), ``workers`` threads run the
decode ``transform`` (ordered reassembly — results stay in source order),
and a putter thread schedules the async ``device_put``, parking in-flight
device buffers in a depth-bounded queue — classic double buffering at
``depth=2``, deeper if decode jitter demands it.  The bound is the
backpressure: the reader never runs more than ``depth + in-flight
transforms`` batches ahead of the consumer, so host RAM stays flat on
out-of-core epochs.

``stats`` (a :class:`PrefetchStats`) attributes the pipeline's time:
cumulative seconds spent reading host batches, transforming, in
``device_put``, and how long the CONSUMER sat waiting on an empty queue
(the infeed gap — if this is ~0 the device is the bottleneck, not the
ingest).  This is the instrumentation VERDICT r2 asked for: it separates
host-decode from transfer from compute so the out-of-core benchmark can
attribute its overhead.

``chunks=W`` turns the pipeline's unit of work from one batch into a
CHUNK of ``W`` consecutive batches stacked along a new leading axis —
the feed side of chunked-scan dispatch: the consumer runs one jitted
``lax.scan`` over the chunk, so ``W`` optimizer steps cost one host
dispatch, and the ``device_put`` of chunk N+1 still overlaps compute on
chunk N (the same double buffering, one level up).  The final short
chunk pads by repeating its last batch; the per-chunk validity mask
(1.0 for real batches) makes the pad steps inert in a masked scan.
Chunk mode yields ``(chunk, mask, n_valid)`` triples — ``chunk`` the
stacked device pytree, ``mask`` a device ``(W,)`` f32, ``n_valid`` the
host-side real-batch count (no device sync needed to count steps).
"""

from __future__ import annotations

import queue
import threading
import time

from concurrent import futures

from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np

__all__ = ["prefetch_to_device", "PrefetchStats", "masked_chunk_scan",
           "chunk_consumer_plan"]

_END = object()


@dataclass
class PrefetchStats:
    """Cumulative pipeline timing (seconds) and batch count.  Single
    writer per field (each stage runs on one thread; transform workers
    accumulate under the lock).

    In ``chunks=W`` mode ``transform_s`` covers decode AND chunk
    assembly (both run in the decode workers); ``assemble_s`` breaks out
    the stack/pad/mask portion, ``put_s``/``wait_s`` become per-CHUNK
    transfer/wait time, and ``chunks`` counts dispatched chunks
    (``batches`` keeps counting real batches)."""
    read_s: float = 0.0        # source iterator next()
    transform_s: float = 0.0   # decode/pad (sum over workers)
    put_s: float = 0.0         # device_put scheduling
    wait_s: float = 0.0        # consumer blocked on empty queue
    batches: int = 0
    assemble_s: float = 0.0    # chunk stack/pad/mask (within transform_s)
    chunks: int = 0
    chunk_size: Optional[int] = None   # W in chunks=W mode, else None
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    def pad_fraction(self) -> float:
        """Fraction of dispatched chunk steps that were padding (the final
        short chunk repeats its last batch): ``(chunks*W - batches) /
        (chunks*W)``.  0.0 outside chunk mode or before any chunk."""
        if not self.chunks or not self.chunk_size:
            return 0.0
        slots = self.chunks * self.chunk_size
        return (slots - self.batches) / slots

    def as_dict(self) -> dict:
        d = {"read_s": round(self.read_s, 4),
             "transform_s": round(self.transform_s, 4),
             "put_s": round(self.put_s, 4),
             "consumer_wait_s": round(self.wait_s, 4),
             "batches": self.batches}
        if self.chunks:
            d["chunk_assemble_s"] = round(self.assemble_s, 4)
            d["chunks"] = self.chunks
            d["pad_fraction"] = round(self.pad_fraction(), 4)
        return d

    def publish(self, group) -> None:
        """Write the current stats into a ``utils.metrics.MetricGroup`` as
        gauges (the observability follow-up to the chunked-dispatch layer:
        internal fields become scrapeable endpoint metrics).  Gauge names
        match :meth:`as_dict` plus ``chunks_emitted`` / ``put_overlap_s``
        aliases for the per-chunk view; safe to call repeatedly — gauges
        are overwritten in place."""
        group.gauge("read_s").set(round(self.read_s, 4))
        group.gauge("transform_s").set(round(self.transform_s, 4))
        group.gauge("put_overlap_s").set(round(self.put_s, 4))
        group.gauge("consumer_wait_s").set(round(self.wait_s, 4))
        group.gauge("batches").set(self.batches)
        group.gauge("chunks_emitted").set(self.chunks)
        group.gauge("pad_fraction").set(round(self.pad_fraction(), 4))
        group.gauge("chunk_assemble_s").set(round(self.assemble_s, 4))


def _grouped(batches: Iterable[Any], size: int) -> Iterator[list]:
    """Consecutive ``size``-item groups of ``batches`` (final group
    short).  A mid-group source error propagates immediately — items
    already read in the broken group are dropped, which keeps the error
    in stream order from the consumer's point of view."""
    group: list = []
    for item in batches:
        group.append(item)
        if len(group) == size:
            yield group
            group = []
    if group:
        yield group


def _assemble_chunk(items: list, size: int):
    """Stack ``items`` (pytrees of equal-shaped leaves) along a new
    leading axis, padding short chunks by repeating the last item;
    returns ``(chunk, mask (size,) f32, n_valid)``."""
    n_valid = len(items)
    if n_valid < size:
        items = items + [items[-1]] * (size - n_valid)
    chunk = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *items)
    mask = np.zeros((size,), np.float32)
    mask[:n_valid] = 1.0
    return chunk, mask, n_valid


def masked_chunk_scan(step: Callable, state: Any, loss_sum, chunk, mask,
                      probe=None):
    """THE consumer half of ``chunks=W``: run ``step(state, *batch) ->
    (new_state, loss)`` over every stacked batch of ``chunk`` as one
    ``lax.scan``, freezing ``state`` and skipping the loss accumulation
    on masked (padded) steps — dead steps are exact no-ops, which is
    what makes any two ``W`` values bit-exact on the same stream.  One
    copy of the freeze/accumulate logic shared by the sgd and WideDeep
    streaming fits (callers jit + donate the ``(state, loss_sum)``
    carry); the hosted ``iterate`` chunk loop carries extra epoch/vote
    structure and stays separate.

    ``probe`` (a :class:`~flink_ml_tpu.obs.StepProbe`, ISSUE 13)
    optionally rides the carry recording the per-step ``loss`` — it is
    frozen on dead steps exactly like the state, so the recorded series
    is W-independent; callers fetch it in one batched transfer at the
    chunk boundary and pass a ``reset()`` probe into the next dispatch.
    ``probe=None`` keeps the 2-tuple carry byte-identical to the
    pre-probe program (the W-bit-exactness contract rides on program
    identity, not just the math)."""
    import jax.numpy as jnp

    if probe is None:
        def scan_step(carry, xs):
            state, loss_sum = carry
            *batch, m = xs
            new_state, loss = step(state, *batch)
            valid = m > 0
            state = jax.tree_util.tree_map(
                lambda n, o: jnp.where(valid, n, o), new_state, state)
            loss_sum = loss_sum + jnp.where(valid, loss, 0.0)
            return (state, loss_sum), None

        (state, loss_sum), _ = jax.lax.scan(scan_step, (state, loss_sum),
                                            tuple(chunk) + (mask,))
        return state, loss_sum

    def probed_step(carry, xs):
        state, loss_sum, probe = carry
        *batch, m = xs
        new_state, loss = step(state, *batch)
        valid = m > 0
        state = jax.tree_util.tree_map(
            lambda n, o: jnp.where(valid, n, o), new_state, state)
        loss_sum = loss_sum + jnp.where(valid, loss, 0.0)
        probe = jax.tree_util.tree_map(
            lambda n, o: jnp.where(valid, n, o),
            probe.record(loss=loss), probe)
        return (state, loss_sum, probe), None

    (state, loss_sum, probe), _ = jax.lax.scan(
        probed_step, (state, loss_sum, probe), tuple(chunk) + (mask,))
    return state, loss_sum, probe


def chunk_consumer_plan(mesh, specs, W: int, prefetch_depth: int):
    """THE shared consumer wiring for ``chunks=W`` prefetch (one copy
    for every adopter — sgd and WideDeep both use it): returns
    ``(sharding, depth)`` where ``sharding`` describes the ``(chunk,
    mask)`` pair — each per-batch PartitionSpec in ``specs`` gains a
    leading (unsharded) chunk axis, the validity mask replicates — and
    ``depth`` converts the caller's per-batch ``prefetch_depth`` into
    chunks (``ceil(prefetch_depth / W)``).  NOTE the floor: staging
    cannot drop below ONE chunk, so chunked mode keeps ``W`` batches
    staged plus ``W`` in compute regardless of ``prefetch_depth`` —
    memory-constrained deployments bound the footprint by lowering
    ``steps_per_dispatch``, not ``prefetch_depth``."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = (tuple(NamedSharding(mesh, P(None, *p)) for p in specs),
                NamedSharding(mesh, P()))
    return sharding, max(1, -(-prefetch_depth // W))


def prefetch_to_device(batches: Iterable[Any], *, depth: int = 2,
                       sharding: Optional[Any] = None,
                       transform: Optional[Callable[[Any], Any]] = None,
                       workers: int = 1,
                       put_workers: int = 1,
                       stats: Optional[PrefetchStats] = None,
                       put_fn: Optional[Callable[[Any, Any], Any]] = None,
                       chunks: Optional[int] = None,
                       metric_group: Optional[Any] = None,
                       retry_policy: Optional[Any] = None
                       ) -> Iterator[Any]:
    """Iterate device-resident copies of ``batches``, staying ``depth``
    UNITS OF WORK ahead of the consumer — a unit is one batch, or one
    ``chunks=W``-batch chunk in chunk mode (so staging memory scales
    with ``depth * W`` batches there; chunking callers size ``depth``
    in chunks, typically 1).

    ``sharding`` (e.g. a ``NamedSharding`` or a pytree of them matching the
    batch structure) is passed to ``device_put``; ``transform`` runs on
    ``workers`` background threads before the transfer (decode/pad/astype —
    keeps that work off the consumer thread; results are reassembled in
    source order, so worker count never changes what the consumer sees).

    ``put_workers`` issues the transfers themselves from that many
    threads — on transports where a single ``device_put`` is
    latency-bound but concurrent transfer RPCs pipeline (the axon
    tunnel question ``scripts/put_overlap_probe.py`` measures),
    parallel puts hide most of the per-batch latency.  Results are
    reassembled in source order, so the consumer sees the same stream
    at any worker count.

    Exceptions raised by the source iterator or the transform are re-raised
    at the consuming ``next()`` call.

    ``put_fn(batch, sharding)`` overrides the transfer itself (default
    ``jax.device_put``) — multi-host callers pass an assembly that builds
    non-fully-addressable global arrays from each process's local batch
    (``jax.make_array_from_process_local_data``).

    ``chunks=W`` (an int >= 1; default None = classic per-batch yields)
    groups every ``W`` consecutive (transformed) batches into one
    stacked chunk (see module docstring); ``sharding`` then describes
    the ``(chunk, mask)`` pair — stacked leaves carry a leading chunk
    axis — and the iterator yields ``(chunk, mask, n_valid)`` triples.
    ``chunks=1`` keeps one batch per chunk but still emits the stacked
    triple form, so a ``W=1`` consumer runs the SAME scan program as
    ``W>1`` (the bit-exact fallback).  Incompatible with ``put_fn``
    (process-local assembly is per-batch); multi-process callers use
    ``chunks=None``.

    ``metric_group`` (a ``utils.metrics.MetricGroup``) publishes the
    cumulative :class:`PrefetchStats` as live gauges — chunks emitted, pad
    fraction, put-overlap time, per-stage seconds — refreshed at every
    yielded item and once more at stream end, so a fit's ingest pipeline
    is observable through the same registry as its epoch metrics.

    ``retry_policy`` (a ``robustness.retry.RetryPolicy``) retries the
    SOURCE pull on classified-transient errors with exponential backoff
    — a flaky read costs a sleep on the reader thread (overlapped by
    whatever is already staged), not the fit.  ``batches`` is wrapped in
    a ``RetryingIterator`` at the raw-source level (below chunk
    grouping), so object-shaped sources retry in place and cursor-backed
    generator sources re-iterate at their cursor; a bare generator that
    dies on a transient fails LOUDLY (``StreamRetryUnsupported``) rather
    than truncating silently.  The source must not consume an item on a
    failed pull (raise-before-read, the ``FaultPlan.wrap_source``
    contract) or be idempotent at the failed position; fatal errors
    still propagate in stream order.
    """
    if depth < 1:
        raise ValueError(f"depth must be >= 1, got {depth}")
    if workers < 1:
        raise ValueError(f"workers must be >= 1, got {workers}")
    if put_workers < 1:
        raise ValueError(f"put_workers must be >= 1, got {put_workers}")
    if chunks is not None and chunks < 1:
        raise ValueError(f"chunks must be >= 1 (or None), got {chunks}")
    if chunks is not None and put_fn is not None:
        raise ValueError(
            "chunks= does not compose with put_fn (process-local "
            "assembly is per-batch); use chunks=None on process-"
            "spanning meshes")
    st = stats or PrefetchStats()
    if chunks is not None:
        st.chunk_size = chunks
    if retry_policy is not None:
        # wrap the RAW source, below the chunk grouping: retrying above
        # a generator adapter would read StopIteration off its dead
        # frame and silently truncate (robustness.retry.RetryingIterator
        # docs); StopIteration itself is never classified retryable, so
        # end-of-stream passes through the policy untouched
        from ..robustness.retry import RetryingIterator

        batches = RetryingIterator(batches, retry_policy)

    if chunks is not None:
        item_transform = transform
        batches = _grouped(batches, chunks)

        def transform(group):  # noqa: F811 — chunk-mode transform
            items = ([item_transform(b) for b in group]
                     if item_transform is not None else list(group))
            t0 = time.perf_counter()
            assembled = _assemble_chunk(items, chunks)
            with st._lock:
                st.assemble_s += time.perf_counter() - t0
                st.chunks += 1
            return assembled

    def put(batch, sh):
        if chunks is not None:
            chunk, mask, n_valid = batch
            payload = (chunk, mask)
            moved = jax.device_put(payload, sh) if sh is not None \
                else jax.device_put(payload)
            return moved + (n_valid,)
        # honor the documented 2-arg put_fn contract on BOTH branches
        if put_fn is not None:
            return put_fn(batch, sh)
        return jax.device_put(batch, sh) if sh is not None \
            else jax.device_put(batch)
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def put_or_abandon(dst: queue.Queue, item) -> None:
        """Stop-aware put: never parks forever if the consumer walked away
        (an untimed put here would leak the thread + queued device buffers)."""
        while not stop.is_set():
            try:
                dst.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def timed_transform(batch):
        t0 = time.perf_counter()
        out = transform(batch) if transform is not None else batch
        with st._lock:
            st.transform_s += time.perf_counter() - t0
        return out

    if workers == 1 and put_workers == 1:
        def worker():
            try:
                src = iter(batches)
                while True:
                    t0 = time.perf_counter()
                    try:
                        batch = next(src)
                    except StopIteration:
                        break
                    st.read_s += time.perf_counter() - t0
                    if stop.is_set():
                        return
                    batch = timed_transform(batch)
                    t0 = time.perf_counter()
                    batch = put(batch, sharding)
                    st.put_s += time.perf_counter() - t0
                    put_or_abandon(q, batch)
                put_or_abandon(q, _END)
            except BaseException as exc:  # noqa: BLE001 — re-raised at consumer
                put_or_abandon(q, exc)

        threads = [threading.Thread(target=worker, daemon=True,
                                    name="flink-ml-tpu-prefetch")]
    else:
        from concurrent.futures import ThreadPoolExecutor

        pool = ThreadPoolExecutor(max_workers=workers,
                                  thread_name_prefix="flink-ml-tpu-decode")
        fq: queue.Queue = queue.Queue(maxsize=depth + workers + put_workers)
        # ordered reassembly shared by the putter pool: seq -> device
        # batch, flushed to q in source order as the prefix completes
        flush_lock = threading.Lock()
        pending: dict = {}
        flush_state = {"next": 0, "total": None, "finished": False,
                       "draining": False}
        # latched once an in-stream error entry is FLUSHED: the consumer
        # will raise at that seq, so later transfers are pure waste —
        # putters check this before waiting on decodes / issuing puts
        failed = threading.Event()

        def _collect_ready_locked() -> list:
            """Pop the completed prefix (appending the terminal _END once
            the reader's total is known and reached).  Caller holds
            flush_lock; no queue puts happen here — the blocking puts run
            OUTSIDE the lock so put concurrency survives backpressure (a
            full q must stall only the emitter, not every putter trying
            to register a completion)."""
            ready: list = []
            while flush_state["next"] in pending:
                entry = pending.pop(flush_state["next"])
                if isinstance(entry, BaseException):
                    failed.set()
                ready.append(entry)
                flush_state["next"] += 1
            if (flush_state["total"] is not None
                    and flush_state["next"] >= flush_state["total"]
                    and not flush_state["finished"]):
                flush_state["finished"] = True
                ready.append(_END)
            return ready

        def _flush_ready():
            """Emit every ready entry to q in source order.  Exactly one
            thread drains at a time (the ``draining`` flag): a second
            completer registers its entry and leaves — the active drainer
            re-collects after each emit round, so nothing is stranded —
            and the single-drainer rule is what preserves source order
            now that the puts happen outside flush_lock."""
            flush_lock.acquire()
            try:
                if flush_state["draining"]:
                    return
                flush_state["draining"] = True
                try:
                    while True:
                        ready = _collect_ready_locked()
                        if not ready:
                            return
                        flush_lock.release()
                        try:
                            for entry in ready:
                                put_or_abandon(q, entry)
                        finally:
                            flush_lock.acquire()
                finally:
                    flush_state["draining"] = False
            finally:
                flush_lock.release()

        def reader():
            seq = 0
            try:
                src = iter(batches)
                while True:
                    t0 = time.perf_counter()
                    try:
                        batch = next(src)
                    except StopIteration:
                        break
                    st.read_s += time.perf_counter() - t0
                    if stop.is_set():
                        return
                    if failed.is_set():
                        break   # consumer will raise; stop reading ahead
                    put_or_abandon(
                        fq, (seq, pool.submit(timed_transform, batch)))
                    seq += 1
                with flush_lock:
                    flush_state["total"] = seq
                _flush_ready()   # covers the empty stream
            except BaseException as exc:  # noqa: BLE001
                # deliver the error IN STREAM ORDER: it enters the
                # reassembly at the next seq, so every batch already
                # read and decoded reaches the consumer first (callers
                # that checkpoint from the last consumed batch rely on
                # this)
                with flush_lock:
                    pending[seq] = exc
                    flush_state["total"] = seq + 1
                _flush_ready()
            for _ in range(put_workers):
                put_or_abandon(fq, _END)

        def get_or_abandon(src: queue.Queue):
            """Stop-aware get: the putter must exit when the consumer
            walks away, or it leaks for process lifetime."""
            while not stop.is_set():
                try:
                    return src.get(timeout=0.1)
                except queue.Empty:
                    continue
            return _END

        def putter():
            while True:
                # a flushed in-stream error means the consumer raises at
                # that seq: stop pulling work — every further device_put
                # would transfer batches nobody will ever read
                if failed.is_set():
                    return
                item = get_or_abandon(fq)
                if item is _END:
                    return
                seq, fut = item
                # stop-aware future wait, mirroring put/get_or_abandon:
                # an abandoned consumer must not leave this thread
                # blocked behind a hung transform.  Poll done-ness
                # rather than catching TimeoutError from result() —
                # futures.TimeoutError IS the builtin TimeoutError on
                # 3.11+, so a transform failing with e.g.
                # socket.timeout must still propagate, not spin.
                while not stop.is_set() and not failed.is_set() \
                        and not fut.done():
                    futures.wait([fut], timeout=0.1)
                if stop.is_set() or failed.is_set():
                    fut.cancel()
                    return
                try:
                    batch = fut.result()
                    if failed.is_set():   # error flushed during decode
                        return
                    t0 = time.perf_counter()
                    entry = put(batch, sharding)
                    with st._lock:
                        st.put_s += time.perf_counter() - t0
                except BaseException as exc:  # noqa: BLE001
                    # transform/put errors ride the reassembly at their
                    # own seq: every earlier batch is delivered first,
                    # exactly like the reader's error path
                    entry = exc
                with flush_lock:
                    pending[seq] = entry
                _flush_ready()
                if isinstance(entry, BaseException):
                    return

        threads = [threading.Thread(target=reader, daemon=True,
                                    name="flink-ml-tpu-prefetch-read")]
        threads += [threading.Thread(target=putter, daemon=True,
                                     name=f"flink-ml-tpu-prefetch-put-{i}")
                    for i in range(put_workers)]

    for t in threads:
        t.start()
    try:
        while True:
            t0 = time.perf_counter()
            item = q.get()
            st.wait_s += time.perf_counter() - t0
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            st.batches += item[2] if chunks is not None else 1
            if metric_group is not None:
                st.publish(metric_group)
            yield item
    finally:
        stop.set()
        # Quiesce the pipeline threads before returning control: an
        # abandoned-but-alive reader still holds the SOURCE iterator, and
        # a supervised fit (robustness.resilient_fit) re-attempts over
        # the same live source — a zombie reader would race the new
        # attempt's pulls (observed: windows silently consumed between
        # WAL replay and the live tail).  The join is bounded: a reader
        # parked inside a blocking live-source pull cannot be
        # interrupted — it dies at its next stop check; sources feeding
        # supervised fits should deliver or fail, not park forever.
        for t in threads:
            t.join(timeout=5.0)
            if t.is_alive():
                import logging

                logging.getLogger("flink_ml_tpu.robustness").warning(
                    "prefetch thread %s still alive after close "
                    "(blocked in a live-source pull?); it will exit at "
                    "its next stop check", t.name)
        if metric_group is not None:
            st.publish(metric_group)
        if workers > 1 or put_workers > 1:
            pool.shutdown(wait=False, cancel_futures=True)
