"""Write-ahead window log — exactly-once ingest for LIVE (non-replayable)
unbounded feeds.

The reference logs in-flight feedback records into each pending checkpoint
so a restore loses nothing even mid-superstep
(``flink-ml-iteration/.../checkpoint/Checkpoints.java:43-211``).  The
TPU-native iteration has no feedback channel to log — but a live feed has
the same exposure at the INGEST edge: windows consumed between the last
checkpoint cut and a crash are gone, because a true live source cannot be
re-iterated.  :class:`WindowLog` closes that hole at window granularity:

- every window pulled from the live source is persisted (atomic
  write-then-rename) BEFORE it is handed to the consumer;
- ``snapshot()`` returns the count of windows consumed — the cursor the
  iteration checkpoint stores (`iteration/core.py` feed envelopes);
- on restore, windows logged beyond the cursor replay FIRST (in order),
  then the live source resumes.  A crash with no checkpoint at all simply
  replays the whole log — the no-cut case heals too.

The irreducible race is a crash between pulling a window from the source
and the rename making it durable: that window is lost (the source moved
on).  The reference has the same exposure for records in flight between
the feedback channel and ``Checkpoints.append``; both designs make the
vulnerable span a few microseconds rather than a whole checkpoint
interval.

Storage: ``win-{i:08d}.npz`` per window under ``directory``; older
entries are truncated on snapshot once they fall behind the
``keep_snapshots`` most recent cuts (every kept cut must still be able to
restore).

Durability cost (measured r4, single-core bench host, 256-row f32
windows): ~1100 windows/s with the per-window file+dir fsync pair
(~2.6 ms/window overhead; ~2700 w/s with fsync stubbed out).  Online
windows arrive at device-step rate — orders of magnitude below that — so
the per-window fsync stays; batching the dirfsync would only matter past
~1k windows/s.  bench.py re-measures this each round
(``notes.wal_windows_per_sec``).
"""

from __future__ import annotations

import logging
import os
import tempfile
import zipfile

from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from .table import Table
from ..obs.trace import tracer
from ..robustness.durability import CorruptStateError
from ..robustness.faults import fault_point

__all__ = ["WindowLog", "WindowBatchReader"]

log = logging.getLogger("flink_ml_tpu.robustness")


def _win_name(i: int) -> str:
    return f"win-{i:08d}.npz"


class WindowLog:
    """Durable tee over an iterable of window Tables (see module doc).

    One directory belongs to ONE logical stream: pointing a fresh run at a
    dirty directory replays the leftover windows (that is the crash-heal
    path; for a genuinely new stream, use a new directory).
    """

    def __init__(self, source: Any, directory: str, *,
                 keep_snapshots: int = 2, retry_policy: Optional[Any] = None):
        if keep_snapshots < 1:
            raise ValueError("keep_snapshots must be >= 1")
        self._source = source
        self._dir = directory
        self._keep = keep_snapshots
        #: a robustness.retry.RetryPolicy: transient append failures
        #: (flaky NFS, injected faults) cost a backoff sleep, not the run
        self._retry = retry_policy
        os.makedirs(directory, exist_ok=True)
        self._consumed = 0           # windows handed to the consumer
        self._start = 0              # restore position
        self._snap_positions: List[int] = []
        # next log index = 1 + highest persisted window (gaps below come
        # from truncation; a stale tmp file from a mid-write crash is
        # ignored and overwritten)
        existing = [int(name[4:-4]) for name in os.listdir(directory)
                    if name.startswith("win-") and name.endswith(".npz")]
        self._next_log = max(existing) + 1 if existing else 0

    # -- iteration ---------------------------------------------------------
    def __iter__(self) -> Iterator[Table]:
        i = self._start
        # replay phase: logged-but-unacknowledged windows
        while i < self._next_log:
            path = os.path.join(self._dir, _win_name(i))
            if not os.path.exists(path):
                raise ValueError(
                    f"window {i} missing from log {self._dir!r}: the "
                    "restore cursor predates the truncation horizon "
                    "(keep_snapshots too small for this checkpoint lag)")
            try:
                with np.load(path, allow_pickle=True) as data:
                    window = Table({k: data[k] for k in data.files})
            except (zipfile.BadZipFile, EOFError, OSError,
                    ValueError, KeyError) as exc:
                if i == self._next_log - 1:
                    # torn TAIL entry: the crash hit mid-append, so this
                    # window never reached the consumer — drop it and
                    # resume live exactly where the log truly ends (the
                    # same few-microsecond exposure as the module doc's
                    # pull-to-rename race, now detected instead of fatal)
                    log.warning(
                        "window log %s: truncating torn tail entry %d "
                        "(%r)", self._dir, i, exc)
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    self._next_log = i
                    break
                raise CorruptStateError(
                    f"window {i} of log {self._dir!r} is corrupt ({exc!r}) "
                    "but is NOT the tail — windows beyond it were already "
                    "consumed, so truncating would silently drop data; "
                    "restore from a checkpoint past this window or start "
                    "a fresh log directory") from exc
            i += 1
            self._consumed = i
            yield window
        # live phase: write-ahead, then hand over
        for window in self._source:
            with tracer.span("wal_append", cat="train",
                             window=self._next_log):
                if self._retry is not None:
                    self._retry.call(self._persist, self._next_log, window)
                else:
                    self._persist(self._next_log, window)
            self._next_log += 1
            self._consumed = self._next_log
            yield window

    def _persist(self, i: int, window: Table) -> None:
        cols = {k: np.asarray(window[k]) for k in window.column_names}
        fd, tmp = tempfile.mkstemp(dir=self._dir, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **cols)
                f.flush()
                os.fsync(f.fileno())   # durable BEFORE the consumer sees it
            # fault seam: control faults (transient -> retried by the
            # policy above, ENOSPC -> fatal) raise here; data faults
            # damage tmp so the rename commits a torn tail entry — the
            # case the replay-side truncation above exists for
            fault_point("wal.append", tmp)
            os.replace(tmp, os.path.join(self._dir, _win_name(i)))
            dirfd = os.open(self._dir, os.O_RDONLY)
            try:
                os.fsync(dirfd)        # the rename itself must survive too
            finally:
                os.close(dirfd)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # -- cursor protocol (what iterate()'s checkpoint stores) --------------
    def snapshot(self) -> Dict[str, Any]:
        pos = self._consumed
        self._snap_positions.append(pos)
        if len(self._snap_positions) > self._keep:
            horizon = self._snap_positions[-self._keep]
            self._truncate_below(horizon)
        return {"consumed": pos}

    def restore(self, snap: Dict[str, Any]) -> None:
        self._consumed = self._start = int(snap["consumed"])

    def _truncate_below(self, horizon: int) -> None:
        for name in os.listdir(self._dir):
            if (name.startswith("win-") and name.endswith(".npz")
                    and int(name[4:-4]) < horizon):
                try:
                    os.unlink(os.path.join(self._dir, name))
                except OSError:
                    pass


class WindowBatchReader:
    """Adapts a :class:`WindowLog` (or any iterable of window Tables)
    into the ``sgd_fit_outofcore`` reader protocol for CONTINUOUS
    training: one window = one optimizer batch, every window carrying
    exactly ``batch_rows`` rows (the training-stream contract — a ragged
    window raises instead of silently padding, because the WAL replay
    and the offline-equivalence acceptance both assume a fixed grid).

    Speaks the checkpoint fast-forward half of the cursor protocol
    (``seek`` + ``batch_rows``): ``seek(k * batch_rows)`` maps the row
    cursor back onto the log's WINDOW cursor via ``WindowLog.restore``,
    so a resumed fit replays exactly the logged-but-unacknowledged
    windows past its restored step — the exactly-once ingest edge of the
    train-while-serve loop (``flink_ml_tpu/online/driver.py``).  It does
    NOT claim ``total_rows``: the stream is unbounded, so the decoded
    replay cache must never engage.

    ``max_windows`` bounds the run (benches/tests); the bound is an
    ABSOLUTE window index, so a resumed reader still stops at the same
    stream position.
    """

    def __init__(self, log: Any, batch_rows: int, *,
                 max_windows: Optional[int] = None):
        if batch_rows < 1:
            raise ValueError("batch_rows must be >= 1")
        self._log = log
        self.batch_rows = int(batch_rows)
        self._max = max_windows
        self._start = 0
        self._stream: Optional[Iterator[Any]] = None

    def _plain_stream(self) -> Iterator[Any]:
        """ONE cached iterator over a non-restorable source: seek and
        iteration must share it — discarding from a throwaway
        ``iter()`` of a re-iterable (list/tuple) source would lose the
        position silently and re-train old windows under shifted
        indices."""
        if self._stream is None:
            self._stream = iter(self._log)
        return self._stream

    def seek(self, rows: int) -> None:
        if rows % self.batch_rows:
            raise ValueError(
                f"seek({rows}) is not a multiple of batch_rows="
                f"{self.batch_rows}: window-granular streams only "
                "reposition at window boundaries")
        idx = rows // self.batch_rows
        if hasattr(self._log, "restore"):
            self._log.restore({"consumed": idx})
        else:
            # plain iterable: discard-to-position on the SHARED stream
            # (a live source's consumed windows are gone regardless);
            # seeking backward cannot be honored — fail loudly
            if idx < self._start:
                raise ValueError(
                    f"seek({rows}) rewinds a non-restorable source "
                    f"(position {self._start * self.batch_rows}); wrap "
                    "the feed in a WindowLog for replayable resume")
            it = self._plain_stream()
            for _ in range(idx - self._start):
                if next(it, None) is None:
                    break
        self._start = idx

    def __iter__(self) -> Iterator[Dict[str, Any]]:
        pos = self._start
        src = (self._log if hasattr(self._log, "restore")
               else self._plain_stream())
        for window in src:
            if self._max is not None and pos >= self._max:
                return
            if window.num_rows != self.batch_rows:
                raise ValueError(
                    f"window {pos} carries {window.num_rows} rows, the "
                    f"training stream is pinned to batch_rows="
                    f"{self.batch_rows}; continuous fits need a fixed "
                    "window grid (re-window the source)")
            pos += 1
            yield window.to_dict()