"""RAM-resident replay of a decoded batch stream.

The reference's ``ReplayOperator`` makes bounded inputs cheap to iterate:
round 0 passes records through while writing them to a ``DataCacheWriter``;
every later round re-reads the cache instead of re-running the upstream
pipeline (``iteration/operator/ReplayOperator.java:62-311``).  On TPU the
expensive upstream work is not the read — it is the host *decode* that
turns raw cached rows into device-ready arrays (pad + dtype casts + the
ELL routing build, ``ops/ell_scatter.py``).  r4 measurement: at the bench
shape the decode costs ~4 s/epoch while the device step costs ~25 ms —
the out-of-core epoch rate is decode-bound, not math-bound.

:class:`DecodedReplayCache` is the TPU-native analog, one level higher
than the reference's, and serves two access patterns:

- **Positional (record/replay)** — epoch-stable streams: the *first*
  epoch tees each decoded batch (a tuple of fixed-shape numpy arrays)
  into host RAM up to a byte budget; later epochs replay the cached
  prefix directly into the device-put stage and only re-decode the tail
  that did not fit.  Because the out-of-core trainers require fixed
  batch shapes anyway (one compiled step program for the whole stream),
  every cached batch has identical nbytes and the budget maps 1:1 to a
  batch-count prefix.  ``offer`` + ``finish`` + ``replay``.
- **Block-keyed** — epoch-VARYING but block-addressable streams
  (``ShuffledCacheReader``): entries key by BLOCK id instead of stream
  position, ``get`` works without any ``finish`` phase, and every epoch
  serves cached blocks in that epoch's fresh permutation while
  decoding+offering the misses — reshuffling and decode-once compose.
  ``offer`` + ``get`` + ``set_anchor`` (the per-epoch contract-check
  digest).

Thread-safety: ``offer`` may be called from multiple decode workers in
any order (the prefetch pool reassembles source order downstream, but the
tee happens inside the transform).  ``finish`` computes the longest
contiguous prefix from batch 0 that landed under the budget and drops any
stragglers, so positional replay order is always exactly source order.
"""

from __future__ import annotations

import hashlib
import threading

from typing import Dict, Iterator, Optional, Sequence, Tuple

import numpy as np

__all__ = ["DecodedReplayCache", "batch_fingerprint", "default_ram_budget"]


def default_ram_budget(fraction: float = 0.25,
                       cap_bytes: int = 32 << 30) -> int:
    """Budget for the decoded cache when the caller does not pin one:
    ``fraction`` of *currently available* host RAM, capped.  Reads
    ``/proc/meminfo`` (Linux); where that is unavailable the budget
    falls back to a conservative 1 GiB — over-budgeting on an unknown
    host risks the OOM kill that out-of-core training exists to avoid."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
                    return int(min(avail * fraction, cap_bytes))
    except OSError:
        pass
    return min(1 << 30, cap_bytes)


def _is_disk_backed(a) -> bool:
    """True when the array's ultimate base is an ``np.memmap`` — its
    bytes live in the page cache, not anonymous RAM."""
    while isinstance(a, np.ndarray):
        if isinstance(a, np.memmap):
            return True
        a = a.base
    return False


def _retained(a: np.ndarray) -> np.ndarray:
    """The array as the cache should hold it.  Disk-backed views are
    materialized (the budget must count real RAM and replay must not
    fault pages back in).  RAM views whose ultimate base is more than
    2x the view's bytes are COPIED: zero-copy retention would keep the
    whole base alive while the budget counts only the view (ADVICE r4).
    Exact-sized views and decode-fresh arrays stay zero-copy."""
    if _is_disk_backed(a):
        return np.array(a)
    a = np.asarray(a)
    # walk to the OUTERMOST ndarray in the base chain: for frombuffer
    # arrays the chain ends in a non-ndarray buffer (bytes, mmap), and
    # that outermost ndarray spans it — comparing its nbytes still
    # detects the small-view-of-big-buffer case
    base = a
    while isinstance(base.base, np.ndarray):
        base = base.base
    if base is not a and base.nbytes > 2 * a.nbytes:
        return np.array(a)
    return a


def batch_fingerprint(batch) -> bytes:
    """Order-stable digest of a raw host batch (a dict of arrays, or any
    sequence of arrays).  Used by the replay guard in
    ``sgd_fit_outofcore``: under ``cache_decoded="auto"`` the first raw
    batch of every replay epoch is re-read and compared against the
    recorded epoch's digest, so a reader that legitimately varies its
    stream per epoch (re-shuffled segment order, per-epoch sampling)
    drops the cache instead of silently training on frozen epoch-0
    data."""
    h = hashlib.blake2b(digest_size=16)
    items = (sorted(batch.items()) if isinstance(batch, dict)
             else list(enumerate(batch)))
    for key, value in items:
        a = np.ascontiguousarray(value)
        h.update(str(key).encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.digest()


class DecodedReplayCache:
    """Cache-what-fits store of decoded batches, addressed positionally
    (record/replay prefix) or by block id (see module doc)."""

    def __init__(self, ram_budget_bytes: int):
        if ram_budget_bytes < 0:
            raise ValueError(
                f"ram_budget_bytes must be >= 0, got {ram_budget_bytes}")
        self.budget = int(ram_budget_bytes)
        self._entries: Dict[int, Tuple[np.ndarray, ...]] = {}
        self._bytes = 0
        self._full = False          # budget hit: stop accepting
        self._lock = threading.Lock()
        self._prefix: Optional[int] = None   # set by finish()
        self.n_batches: Optional[int] = None
        # digest of the recording epoch's first RAW batch (pre-decode),
        # set by the recording caller; replay guards compare against it
        self.fingerprint: Optional[bytes] = None
        # additional raw digests at power-of-two stream indices (set by
        # the recording caller): replay guards on SEEKABLE readers probe
        # the largest recorded index <= n_batches-1 as a second,
        # mid-stream determinism check — a one-batch digest cannot catch
        # a reader that shuffles everything after its first batch
        # (ADVICE r4).  Distinct keys per writer; dict ops are atomic.
        self.probe_fingerprints: Dict[int, bytes] = {}
        # block-keyed mode: the first cached block's id — later epochs
        # re-digest that block's raw bytes to catch readers that violate
        # the per-block-determinism contract
        self.anchor_key: Optional[int] = None

    # ------------------------------------------------------------ record

    def offer(self, index: int, arrays: Sequence[np.ndarray]) -> None:
        """Tee decoded batch ``index``.  Drops (permanently disables
        further storing) once the cumulative size would exceed the
        budget — transient overshoot is bounded by the number of
        concurrent decode workers, never by the stream length.

        Decode-fresh arrays (and views of them) are retained zero-copy;
        disk-backed views (``np.memmap`` slices that passed through the
        decode uncopied — dense columns already in their target dtype)
        are materialized into RAM here, otherwise the budget would count
        pages that occupy no RAM and "replay" would still fault batches
        in from disk."""
        if self._full or self._prefix is not None:
            return
        stored = tuple(_retained(a) for a in arrays)
        size = sum(int(a.nbytes) for a in stored)
        with self._lock:
            if self._full:
                return
            if self._bytes + size > self.budget:
                self._full = True
                return
            self._bytes += size
            self._entries[index] = stored

    def finish(self, n_batches: int) -> None:
        """End of the recording epoch: keep the longest contiguous prefix
        from batch 0, free everything else."""
        with self._lock:
            prefix = 0
            while prefix in self._entries:
                prefix += 1
            for i in list(self._entries):
                if i >= prefix:
                    self._bytes -= sum(
                        int(a.nbytes) for a in self._entries[i])
                    del self._entries[i]
            self._prefix = prefix
            self.n_batches = int(n_batches)

    def set_anchor(self, key: int, fingerprint: bytes) -> None:
        """Record the contract-check anchor (first offered block) once;
        atomic so concurrent decode workers cannot pair one worker's key
        with another's digest."""
        with self._lock:
            if self.anchor_key is None:
                self.anchor_key = key
                self.fingerprint = fingerprint

    # ------------------------------------------------------ keyed lookup

    def get(self, key: int) -> Optional[Tuple[np.ndarray, ...]]:
        """Keyed access, usable WITHOUT :meth:`finish` — the block-keyed
        mode (``sgd_fit_outofcore`` over block-addressable shuffled
        readers) keys entries by BLOCK id rather than stream position:
        every epoch serves cached blocks and decodes+offers the rest, so
        there is no record/replay phase boundary and no prefix."""
        return self._entries.get(key)

    def __len__(self) -> int:
        return len(self._entries)

    # ------------------------------------------------------------ replay

    @property
    def ready(self) -> bool:
        return self._prefix is not None

    @property
    def prefix_batches(self) -> int:
        """Batches replayable from RAM (valid after :meth:`finish`)."""
        if self._prefix is None:
            raise RuntimeError("cache not finished; no prefix yet")
        return self._prefix

    @property
    def cached_bytes(self) -> int:
        return self._bytes

    def replay(self, start: int = 0) -> Iterator[Tuple[np.ndarray, ...]]:
        """Yield cached batches ``start..prefix`` in source order."""
        if self._prefix is None:
            raise RuntimeError("cache not finished; cannot replay")
        for i in range(start, self._prefix):
            yield self._entries[i]
