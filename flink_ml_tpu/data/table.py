"""Columnar in-memory Table — the framework's data substrate.

The reference stages exchange lazy Flink ``Table``s over a streaming engine.
The TPU-native substrate is instead a host-resident **columnar batch**: named
numpy columns of equal length, cheap to slice into per-device shards and to
feed to jitted steps.  Vector-valued columns are plain 2-D arrays, so the
whole feature matrix lands on the MXU without row-wise marshalling.

Bounded streams map to a Table (all rows known); unbounded streams map to an
iterator of Tables (see ``flink_ml_tpu.data.stream``).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, Iterator, List, Mapping, Optional, Sequence, Tuple

import numpy as np

__all__ = ["Table"]


class Table:
    """An ordered mapping ``name -> column`` where every column is a numpy
    array with the same leading dimension (rows)."""

    def __init__(self, columns: Mapping[str, Any]):
        cols: Dict[str, np.ndarray] = {}
        num_rows: Optional[int] = None
        for name, values in columns.items():
            arr = np.asarray(values)
            if arr.ndim == 0:
                raise ValueError(f"Column {name!r} must be at least 1-D")
            if num_rows is None:
                num_rows = arr.shape[0]
            elif arr.shape[0] != num_rows:
                raise ValueError(
                    f"Column {name!r} has {arr.shape[0]} rows, expected {num_rows}")
            cols[name] = arr
        self._columns = cols
        self._num_rows = num_rows or 0

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_rows(rows: Iterable[Sequence[Any]], names: Sequence[str]) -> "Table":
        """Build from row tuples (the shape of the reference's
        ``tEnv.fromDataStream`` test fixtures, e.g. ``KMeansTest.java:58-66``)."""
        rows = list(rows)
        columns: Dict[str, List[Any]] = {n: [] for n in names}
        for row in rows:
            if len(row) != len(names):
                raise ValueError(f"Row {row!r} does not match schema {names!r}")
            for name, value in zip(names, row):
                columns[name].append(value)
        return Table({n: np.asarray(v) for n, v in columns.items()})

    @staticmethod
    def empty_like(other: "Table") -> "Table":
        return Table({n: c[:0] for n, c in other._columns.items()})

    # -- schema -------------------------------------------------------------
    @property
    def column_names(self) -> List[str]:
        return list(self._columns.keys())

    @property
    def num_rows(self) -> int:
        return self._num_rows

    def __len__(self) -> int:
        return self._num_rows

    def __contains__(self, name: str) -> bool:
        return name in self._columns

    def schema(self) -> Dict[str, Tuple[Tuple[int, ...], np.dtype]]:
        return {n: (c.shape[1:], c.dtype) for n, c in self._columns.items()}

    # -- access -------------------------------------------------------------
    def column(self, name: str) -> np.ndarray:
        if name not in self._columns:
            raise KeyError(
                f"No column {name!r}; available: {self.column_names}")
        return self._columns[name]

    def __getitem__(self, name: str) -> np.ndarray:
        return self.column(name)

    def rows(self) -> Iterator[Tuple[Any, ...]]:
        names = self.column_names
        for i in range(self._num_rows):
            yield tuple(self._columns[n][i] for n in names)

    # -- transformation -----------------------------------------------------
    def select(self, *names: str) -> "Table":
        return Table({n: self.column(n) for n in names})

    def with_column(self, name: str, values: Any) -> "Table":
        cols = dict(self._columns)
        cols[name] = np.asarray(values)
        return Table(cols)

    def drop(self, *names: str) -> "Table":
        return Table({n: c for n, c in self._columns.items() if n not in names})

    def rename(self, mapping: Mapping[str, str]) -> "Table":
        return Table({mapping.get(n, n): c for n, c in self._columns.items()})

    def take(self, n: int) -> "Table":
        return Table({name: c[:n] for name, c in self._columns.items()})

    def slice(self, start: int, stop: int) -> "Table":
        return Table({name: c[start:stop] for name, c in self._columns.items()})

    def select_rows(self, indices: Any) -> "Table":
        """Row subset/reorder by integer index array (or boolean mask)."""
        idx = np.asarray(indices)
        if idx.dtype == bool:
            idx = np.flatnonzero(idx)
        return Table({name: c[idx] for name, c in self._columns.items()})

    def shuffle(self, seed: int = 0) -> "Table":
        perm = np.random.default_rng(seed).permutation(self._num_rows)
        return Table({name: c[perm] for name, c in self._columns.items()})

    def concat(self, other: "Table") -> "Table":
        if set(self.column_names) != set(other.column_names):
            raise ValueError("Cannot concat tables with different schemas")
        return Table({
            n: np.concatenate([c, other.column(n)], axis=0)
            for n, c in self._columns.items()
        })

    # -- batching / sharding ------------------------------------------------
    def pad_to_multiple(self, multiple: int) -> Tuple["Table", np.ndarray]:
        """Pad rows (repeating row 0) so num_rows % multiple == 0; returns the
        padded table plus a float mask (1 for real rows).  Static shapes are
        what keep XLA from recompiling per batch."""
        from ..utils.padding import pad_rows_with_mask

        mask = np.ones((self._num_rows,), dtype=np.float32)
        cols = {}
        for n, c in self._columns.items():
            cols[n], mask = pad_rows_with_mask(c, multiple)
        if not cols:
            return self, mask
        return Table(cols), mask

    def batches(self, batch_size: int, *, drop_remainder: bool = False
                ) -> Iterator["Table"]:
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        for start in range(0, self._num_rows, batch_size):
            batch = self.slice(start, min(start + batch_size, self._num_rows))
            if drop_remainder and batch.num_rows < batch_size:
                return
            yield batch

    def to_dict(self) -> Dict[str, np.ndarray]:
        return dict(self._columns)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        schema = ", ".join(
            f"{n}:{c.dtype.name}{list(c.shape[1:]) if c.ndim > 1 else ''}"
            for n, c in self._columns.items())
        return f"Table[{self._num_rows} rows; {schema}]"
