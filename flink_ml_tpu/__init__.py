"""flink_ml_tpu — a TPU-native ML pipeline framework.

Brand-new design with the capabilities of Apache Flink ML (reference
snapshot: huangchengmin97/flink-ml): Estimator/Transformer/Model/Pipeline API
with typed params and directory save/load, an iterative training runtime with
epoch semantics and checkpoint/resume, and an algorithm library — built
TPU-first on JAX/XLA: jitted SPMD epoch steps over a device mesh, HBM-resident
feedback state, ICI collectives for aggregation.
"""

from .api.stage import AlgoOperator, Estimator, Model, Stage, Transformer
from .api.graph import Graph, GraphBuilder, GraphModel, TableId
from .api.model_selection import (CrossValidator,
                                  CrossValidatorModel,
                                  ParamGridBuilder,
                                  TrainValidationSplit)
from .api.pipeline import Pipeline, PipelineModel
from .data.table import Table
from .linalg import DenseVector, SparseVector, Vectors
from .distance import DistanceMeasure
from .params.param import (
    BoolParam,
    DoubleArrayParam,
    DoubleParam,
    FloatArrayParam,
    FloatParam,
    IntArrayParam,
    IntParam,
    InvalidParamError,
    LongParam,
    Param,
    ParamValidators,
    StringArrayParam,
    StringParam,
    VectorParam,
)
from .params.with_params import WithParams

__version__ = "0.1.0"

__all__ = [
    "AlgoOperator", "Estimator", "Model", "Stage", "Transformer",
    "CrossValidator", "CrossValidatorModel", "ParamGridBuilder",
    "TrainValidationSplit",
    "Pipeline", "PipelineModel", "Table",
    "Graph", "GraphBuilder", "GraphModel", "TableId",
    "DenseVector", "SparseVector", "Vectors", "DistanceMeasure",
    "Param", "ParamValidators", "WithParams", "InvalidParamError",
    "BoolParam", "IntParam", "LongParam", "FloatParam", "DoubleParam",
    "StringParam", "IntArrayParam", "FloatArrayParam", "DoubleArrayParam",
    "StringArrayParam", "VectorParam",
    "__version__",
]
