"""WithParams mixin: param discovery, get/set, JSON round-trip.

Re-design of ``param/WithParams.java:74-142`` +
``util/ParamUtils.java:41-88``.  The reference scans public-final
``Param<?>`` fields reflectively (including interfaces and superclasses);
here we walk the MRO and collect ``Param`` class attributes, which covers the
same "params inherited from mixin interfaces" behavior.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional, Type, TypeVar, Union

from .param import InvalidParamError, Param

S = TypeVar("S", bound="WithParams")

__all__ = ["WithParams"]


_PARAMS_CACHE: Dict[type, Dict[str, Param]] = {}


def _declared_params(cls: type) -> Dict[str, Param]:
    """All Param descriptors reachable on ``cls`` via the MRO, keyed by
    param name (mirror of ``ParamUtils.getPublicFinalParamFields``,
    ``util/ParamUtils.java:63-88``).  Cached per class — param sets are
    static after class creation and this runs on every get/set."""
    cached = _PARAMS_CACHE.get(cls)
    if cached is None:
        cached = {}
        for klass in reversed(cls.__mro__):
            for value in vars(klass).values():
                if isinstance(value, Param):
                    cached[value.name] = value
        _PARAMS_CACHE[cls] = cached
    return cached


class WithParams:
    """Base mixin giving any class a typed, validated param map.

    The live values are stored per-instance in ``_param_map``
    (Param -> value), initialised with defaults the way
    ``ParamUtils.initializeMapWithDefaultValues`` does
    (``util/ParamUtils.java:41-52``).
    """

    _param_map: Dict[Param, Any]

    def __init__(self) -> None:
        self._ensure_param_map()

    # -- discovery ----------------------------------------------------------
    def _ensure_param_map(self) -> Dict[Param, Any]:
        if "_param_map" not in self.__dict__:
            self.__dict__["_param_map"] = {
                p: p.default_value for p in _declared_params(type(self)).values()
            }
        return self.__dict__["_param_map"]

    @classmethod
    def params(cls) -> Dict[str, Param]:
        return _declared_params(cls)

    def get_param(self, name: str) -> Optional[Param]:
        """Mirror of ``WithParams.getParam(String)`` (``WithParams.java:60-68``)."""
        return _declared_params(type(self)).get(name)

    # -- get/set ------------------------------------------------------------
    def _resolve(self, param: Union[Param, str]) -> Param:
        if isinstance(param, str):
            resolved = self.get_param(param)
            if resolved is None:
                raise InvalidParamError(
                    f"Parameter {param!r} is not defined on {type(self).__name__}")
            return resolved
        return param

    def set(self: S, param: Union[Param, str], value: Any) -> S:
        """Validate and set; returns self for chaining.  Null values are
        validated too, matching ``WithParams.java:91-95`` which rejects null
        at set time unless the validator accepts it."""
        param = self._resolve(param)
        declared = self.get_param(param.name)
        if declared is None or declared != param:
            raise InvalidParamError(
                f"Parameter {param.name!r} is not defined on {type(self).__name__}")
        if value is None:
            if not _nullable(declared):
                raise InvalidParamError(
                    f"Parameter {declared.name}'s value should not be null")
            self._ensure_param_map()[declared] = None
        else:
            self._ensure_param_map()[declared] = declared.validate(value)
        return self

    def get(self, param: Union[Param, str]) -> Any:
        """Mirror of ``WithParams.get`` (``WithParams.java:102-116``): raises if
        the param has no value and no default."""
        param = self._resolve(param)
        param_map = self._ensure_param_map()
        if param not in param_map:
            raise InvalidParamError(
                f"Parameter {param.name!r} is not defined on {type(self).__name__}")
        value = param_map[param]
        if value is None and param.default_value is None and not _nullable(param):
            raise InvalidParamError(
                f"Parameter {param.name}'s value should not be null")
        return value

    def get_param_map(self) -> Dict[Param, Any]:
        return self._ensure_param_map()

    def param_items(self) -> Iterator:
        return iter(self._ensure_param_map().items())

    # -- JSON ---------------------------------------------------------------
    def params_to_json(self) -> Dict[str, Any]:
        """name -> json value, mirror of the paramMap section written by
        ``ReadWriteUtils.saveMetadata`` (``util/ReadWriteUtils.java:77-96``)."""
        return {
            p.name: p.json_encode(v) for p, v in self._ensure_param_map().items()
        }

    def params_from_json(self, payload: Dict[str, Any]) -> None:
        for name, raw in payload.items():
            param = self.get_param(name)
            if param is None:
                continue  # forward-compatible: unknown params are skipped
            self._ensure_param_map()[param] = (
                None if raw is None else param.json_decode(raw))

    def copy_params_from(self: S, other: "WithParams") -> S:
        for param, value in other.param_items():
            mine = self.get_param(param.name)
            if mine is not None:
                self._ensure_param_map()[mine] = value
        return self


def _nullable(param: Param) -> bool:
    # A param whose validator accepts None is considered nullable.
    try:
        return bool(param.validator(None))
    except Exception:
        return False
