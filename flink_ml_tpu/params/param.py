"""Typed, validated, JSON-codable hyperparameters.

TPU-native re-design of the reference param system
(``flink-ml-api/.../param/Param.java:33-79`` and the twelve typed param
classes).  The reference discovers params by reflecting over public-final
``Param<?>`` fields (``util/ParamUtils.java:41-88``); here params are plain
class attributes (descriptors) discovered by walking the MRO — no reflection
tricks needed in Python.
"""

from __future__ import annotations

import numpy as np

from typing import Any, Callable, Generic, Optional, Sequence, TypeVar

T = TypeVar("T")

__all__ = [
    "Param",
    "IntParam",
    "LongParam",
    "FloatParam",
    "DoubleParam",
    "BoolParam",
    "StringParam",
    "IntArrayParam",
    "FloatArrayParam",
    "DoubleArrayParam",
    "StringArrayParam",
    "VectorParam",
    "ParamValidator",
    "ParamValidators",
    "InvalidParamError",
]


class InvalidParamError(ValueError):
    """Raised when a param value fails validation (reference throws
    IllegalArgumentException from ``WithParams.set``, ``WithParams.java:74-95``)."""


ParamValidator = Callable[[Any], bool]


class ParamValidators:
    """Factory of validators mirroring ``param/ParamValidators.java:27-90``."""

    @staticmethod
    def always_true() -> ParamValidator:
        return lambda value: True

    @staticmethod
    def gt(lower: float) -> ParamValidator:
        return lambda value: value is not None and value > lower

    @staticmethod
    def gt_eq(lower: float) -> ParamValidator:
        return lambda value: value is not None and value >= lower

    @staticmethod
    def lt(upper: float) -> ParamValidator:
        return lambda value: value is not None and value < upper

    @staticmethod
    def lt_eq(upper: float) -> ParamValidator:
        return lambda value: value is not None and value <= upper

    @staticmethod
    def in_range(lower: float, upper: float,
                 lower_inclusive: bool = True,
                 upper_inclusive: bool = True) -> ParamValidator:
        def check(value: Any) -> bool:
            if value is None:
                return False
            lo_ok = value >= lower if lower_inclusive else value > lower
            hi_ok = value <= upper if upper_inclusive else value < upper
            return lo_ok and hi_ok
        return check

    @staticmethod
    def in_array(allowed: Sequence[Any]) -> ParamValidator:
        allowed_set = list(allowed)
        return lambda value: value in allowed_set

    @staticmethod
    def not_null() -> ParamValidator:
        return lambda value: value is not None

    @staticmethod
    def non_empty_array() -> ParamValidator:
        return lambda value: value is not None and len(value) > 0


class Param(Generic[T]):
    """A named, typed, validated hyperparameter.

    Mirrors ``param/Param.java:33-58`` (name / clazz / description / default /
    validator) plus ``jsonEncode``/``jsonDecode`` (``Param.java:66-79``).

    Params double as Python descriptors so ``stage.max_iter`` reads the
    current value while ``MyParams.MAX_ITER`` (class access) yields the Param
    object itself for use with ``get``/``set``.
    """

    value_type: type = object

    def __init__(self, name: str, description: str = "",
                 default: Optional[T] = None,
                 validator: Optional[ParamValidator] = None):
        self.name = name
        self.description = description
        self.validator = validator or ParamValidators.always_true()
        if default is not None:
            default = self.coerce(default)
            if not self.validator(default):
                raise InvalidParamError(
                    f"Invalid default value {default!r} for param {name!r}")
        self.default_value = default

    # -- value handling -----------------------------------------------------
    def coerce(self, value: Any) -> T:
        """Normalise a user-supplied value to the canonical runtime type."""
        return value

    def validate(self, value: Any) -> T:
        value = self.coerce(value)
        if not self.validator(value):
            raise InvalidParamError(
                f"Parameter {self.name} is given an invalid value {value!r}")
        return value

    # -- JSON ---------------------------------------------------------------
    def json_encode(self, value: T) -> Any:
        return value

    def json_decode(self, payload: Any) -> T:
        return self.coerce(payload)

    # -- descriptor protocol ------------------------------------------------
    def __set_name__(self, owner: type, attr_name: str) -> None:
        self._attr_name = attr_name

    def __get__(self, obj: Any, objtype: Optional[type] = None):
        if obj is None:
            return self
        return obj.get(self)

    def __set__(self, obj: Any, value: Any) -> None:
        obj.set(self, value)

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"{type(self).__name__}({self.name!r}, default={self.default_value!r})"

    def __hash__(self) -> int:
        return hash((type(self).__name__, self.name))

    def __eq__(self, other: Any) -> bool:
        return (isinstance(other, Param) and other.name == self.name
                and type(other) is type(self))


class IntParam(Param[int]):
    value_type = int

    def coerce(self, value: Any) -> int:
        if isinstance(value, bool):
            raise InvalidParamError(f"Param {self.name} expects int, got bool")
        return int(value)


class LongParam(IntParam):
    """Alias — Python ints are arbitrary precision (reference LongParam)."""


class FloatParam(Param[float]):
    value_type = float

    def coerce(self, value: Any) -> float:
        return float(value)


class DoubleParam(FloatParam):
    """Alias — Python floats are doubles (reference DoubleParam)."""


class BoolParam(Param[bool]):
    value_type = bool

    def coerce(self, value: Any) -> bool:
        if not isinstance(value, (bool, np.bool_)):
            raise InvalidParamError(f"Param {self.name} expects bool, got {value!r}")
        return bool(value)


class StringParam(Param[str]):
    value_type = str

    def coerce(self, value: Any) -> str:
        if value is None:
            return value
        if not isinstance(value, str):
            raise InvalidParamError(f"Param {self.name} expects str, got {value!r}")
        return value


class _ArrayParam(Param[tuple]):
    element_coerce: Callable[[Any], Any] = staticmethod(lambda x: x)

    def coerce(self, value: Any) -> tuple:
        if value is None:
            return value
        if isinstance(value, (str, bytes)):
            raise InvalidParamError(
                f"Param {self.name} expects a sequence, got {value!r} "
                "(wrap single values in a list)")
        if isinstance(value, np.ndarray):
            value = value.tolist()
        return tuple(type(self).element_coerce(v) for v in value)

    def json_encode(self, value: tuple) -> Any:
        return None if value is None else list(value)


class IntArrayParam(_ArrayParam):
    element_coerce = staticmethod(int)


class FloatArrayParam(_ArrayParam):
    element_coerce = staticmethod(float)


class DoubleArrayParam(FloatArrayParam):
    pass


class StringArrayParam(_ArrayParam):
    element_coerce = staticmethod(str)


class VectorParam(Param[np.ndarray]):
    """Dense vector-valued param (reference ``VectorParam`` over DenseVector)."""

    value_type = np.ndarray

    def coerce(self, value: Any) -> np.ndarray:
        if value is None:
            return value
        return np.asarray(value, dtype=np.float64)

    def json_encode(self, value: np.ndarray) -> Any:
        return None if value is None else np.asarray(value).tolist()

    def json_decode(self, payload: Any) -> np.ndarray:
        return None if payload is None else np.asarray(payload, dtype=np.float64)
