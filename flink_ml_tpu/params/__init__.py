from .param import *  # noqa: F401,F403
from .with_params import WithParams  # noqa: F401
from .shared import *  # noqa: F401,F403
