"""Shared ``Has*`` param mixins.

Mirrors the reference's shared mixin interfaces in
``flink-ml-lib/.../common/param/`` (HasDistanceMeasure, HasFeaturesCol,
HasPredictionCol, HasSeed, HasMaxIter) and extends the set with the params
the linear/streaming estimators in BASELINE.json need.
"""

from __future__ import annotations

from .param import (
    BoolParam,
    FloatParam,
    IntParam,
    ParamValidators,
    StringArrayParam,
    StringParam,
)
from .with_params import WithParams

__all__ = [
    "HasDistanceMeasure",
    "HasFeaturesCol",
    "HasLabelCol",
    "HasWeightCol",
    "HasPredictionCol",
    "HasRawPredictionCol",
    "HasSeed",
    "HasMaxIter",
    "HasTol",
    "HasLearningRate",
    "HasRegParam",
    "HasElasticNet",
    "HasGlobalBatchSize",
    "HasBatchStrategy",
    "HasOutputCol",
    "HasInputCols",
    "HasOutputCols",
]


class HasDistanceMeasure(WithParams):
    """``common/param/HasDistanceMeasure.java`` — metric name resolved through
    the DistanceMeasure registry (§2.1 distance)."""

    DISTANCE_MEASURE = StringParam(
        "distanceMeasure", "Distance measure name.", default="euclidean",
        validator=ParamValidators.in_array(["euclidean", "cosine", "manhattan"]))

    def get_distance_measure(self) -> str:
        return self.get(HasDistanceMeasure.DISTANCE_MEASURE)

    def set_distance_measure(self, value: str):
        return self.set(HasDistanceMeasure.DISTANCE_MEASURE, value)


class HasFeaturesCol(WithParams):
    FEATURES_COL = StringParam(
        "featuresCol", "Features column name.", default="features",
        validator=ParamValidators.not_null())

    def get_features_col(self) -> str:
        return self.get(HasFeaturesCol.FEATURES_COL)

    def set_features_col(self, value: str):
        return self.set(HasFeaturesCol.FEATURES_COL, value)


class HasLabelCol(WithParams):
    LABEL_COL = StringParam(
        "labelCol", "Label column name.", default="label",
        validator=ParamValidators.not_null())

    def get_label_col(self) -> str:
        return self.get(HasLabelCol.LABEL_COL)

    def set_label_col(self, value: str):
        return self.set(HasLabelCol.LABEL_COL, value)


class HasWeightCol(WithParams):
    WEIGHT_COL = StringParam(
        "weightCol", "Sample-weight column name (optional).", default=None)

    def get_weight_col(self):
        return self.get(HasWeightCol.WEIGHT_COL)

    def set_weight_col(self, value: str):
        return self.set(HasWeightCol.WEIGHT_COL, value)


class HasPredictionCol(WithParams):
    PREDICTION_COL = StringParam(
        "predictionCol", "Prediction column name.", default="prediction",
        validator=ParamValidators.not_null())

    def get_prediction_col(self) -> str:
        return self.get(HasPredictionCol.PREDICTION_COL)

    def set_prediction_col(self, value: str):
        return self.set(HasPredictionCol.PREDICTION_COL, value)


class HasRawPredictionCol(WithParams):
    RAW_PREDICTION_COL = StringParam(
        "rawPredictionCol", "Raw prediction (margin / probability) column name.",
        default="rawPrediction")

    def get_raw_prediction_col(self) -> str:
        return self.get(HasRawPredictionCol.RAW_PREDICTION_COL)

    def set_raw_prediction_col(self, value: str):
        return self.set(HasRawPredictionCol.RAW_PREDICTION_COL, value)


class HasSeed(WithParams):
    """``common/param/HasSeed.java`` — default differs from the reference
    (System.nanoTime) so runs are reproducible unless overridden."""

    SEED = IntParam("seed", "PRNG seed.", default=0)

    def get_seed(self) -> int:
        return self.get(HasSeed.SEED)

    def set_seed(self, value: int):
        return self.set(HasSeed.SEED, value)


class HasMaxIter(WithParams):
    MAX_ITER = IntParam(
        "maxIter", "Maximum number of iterations.", default=20,
        validator=ParamValidators.gt(0))

    def get_max_iter(self) -> int:
        return self.get(HasMaxIter.MAX_ITER)

    def set_max_iter(self, value: int):
        return self.set(HasMaxIter.MAX_ITER, value)


class HasTol(WithParams):
    TOL = FloatParam(
        "tol", "Convergence tolerance on the iteration criterion.",
        default=1e-6, validator=ParamValidators.gt_eq(0))

    def get_tol(self) -> float:
        return self.get(HasTol.TOL)

    def set_tol(self, value: float):
        return self.set(HasTol.TOL, value)


class HasLearningRate(WithParams):
    LEARNING_RATE = FloatParam(
        "learningRate", "Step size for gradient updates.", default=0.1,
        validator=ParamValidators.gt(0))

    def get_learning_rate(self) -> float:
        return self.get(HasLearningRate.LEARNING_RATE)

    def set_learning_rate(self, value: float):
        return self.set(HasLearningRate.LEARNING_RATE, value)


class HasRegParam(WithParams):
    REG = FloatParam(
        "reg", "L2 regularization strength.", default=0.0,
        validator=ParamValidators.gt_eq(0))

    def get_reg(self) -> float:
        return self.get(HasRegParam.REG)

    def set_reg(self, value: float):
        return self.set(HasRegParam.REG, value)


class HasElasticNet(WithParams):
    ELASTIC_NET = FloatParam(
        "elasticNet", "Elastic-net mixing: 0 = pure L2, 1 = pure L1.",
        default=0.0, validator=ParamValidators.in_range(0.0, 1.0))

    def get_elastic_net(self) -> float:
        return self.get(HasElasticNet.ELASTIC_NET)

    def set_elastic_net(self, value: float):
        return self.set(HasElasticNet.ELASTIC_NET, value)


class HasGlobalBatchSize(WithParams):
    GLOBAL_BATCH_SIZE = IntParam(
        "globalBatchSize",
        "Global (across all devices) mini-batch size.  None = auto: 32, "
        "except mixed/sparse hashed linear fits size the batch so the ELL "
        "scatter kernel's layout fits its HBM budget "
        "(sgd.resolve_global_batch_size).",
        default=None,
        validator=lambda v: v is None or v > 0)

    def get_global_batch_size(self) -> int:
        return self.get(HasGlobalBatchSize.GLOBAL_BATCH_SIZE)

    def set_global_batch_size(self, value: int):
        return self.set(HasGlobalBatchSize.GLOBAL_BATCH_SIZE, value)


class HasNumFeatures(WithParams):
    NUM_FEATURES = IntParam(
        "numFeatures",
        "Feature-space size for hashed sparse input (pair columns); 0 = "
        "derive from the data (dense input or SparseVector.size).",
        default=0, validator=ParamValidators.gt_eq(0))

    def get_num_features(self) -> int:
        return self.get(HasNumFeatures.NUM_FEATURES)

    def set_num_features(self, value: int):
        return self.set(HasNumFeatures.NUM_FEATURES, value)


class HasBatchStrategy(WithParams):
    BATCH_STRATEGY = StringParam(
        "batchStrategy", "Mini-batch strategy.", default="count",
        validator=ParamValidators.in_array(["count"]))

    def get_batch_strategy(self) -> str:
        return self.get(HasBatchStrategy.BATCH_STRATEGY)


class HasOutputCol(WithParams):
    OUTPUT_COL = StringParam("outputCol", "Output column name.",
                             default="output")

    def get_output_col(self) -> str:
        return self.get(HasOutputCol.OUTPUT_COL)

    def set_output_col(self, value: str):
        return self.set(HasOutputCol.OUTPUT_COL, value)


class HasInputCols(WithParams):
    INPUT_COLS = StringArrayParam("inputCols", "Input column names.",
                                  default=None)

    def get_input_cols(self):
        return self.get(HasInputCols.INPUT_COLS)

    def set_input_cols(self, *cols: str):
        return self.set(HasInputCols.INPUT_COLS, cols)


class HasOutputCols(WithParams):
    OUTPUT_COLS = StringArrayParam("outputCols", "Output column names.",
                                   default=None)

    def get_output_cols(self):
        return self.get(HasOutputCols.OUTPUT_COLS)

    def set_output_cols(self, *cols: str):
        return self.set(HasOutputCols.OUTPUT_COLS, cols)
