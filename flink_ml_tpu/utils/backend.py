"""Process-level JAX backend control.

One shared implementation of the "reset to an n-device virtual CPU
platform" dance used by the driver's multi-chip dryrun, the two-process
distributed tests, and the multi-host example.  The ordering constraints
are sharp enough that three hand-rolled copies had already drifted apart:

- ``jax_num_cpu_devices`` has a validator that raises ``RuntimeError``
  when a backend is already initialized and the value changes, so any
  live backend must be torn down *before* the config update.
- With the axon TPU relay registered but unreachable, the first device
  use (``jax.devices()``, or anything that initializes a backend) blocks
  for many minutes in backend init, so nothing here may touch devices
  until the platform is pinned to CPU.  ``JAX_PLATFORMS`` in the
  environment does not help: the environment's sitecustomize consumes it
  before user code runs.

Capability parity note: this is the stand-in for the reference's
MiniCluster test harness (flink-ml-tests
``.../iteration/UnboundedStreamIterationITCase.java:71``), which brings
up N task managers in one JVM; here N virtual CPU devices stand in for N
TPU chips.
"""

from __future__ import annotations


def force_virtual_cpu(n_devices: int, *, verify: bool = True) -> None:
    """Pin this process to an ``n_devices``-device virtual CPU platform.

    Safe to call whether or not a backend (CPU or the axon TPU relay) is
    already initialized, and guaranteed never to touch the possibly-dead
    TPU relay: the check + teardown operate on the backend registry only.

    ``verify=False`` skips the final device-count check, leaving the
    backend *uninitialized* — required when ``jax.distributed.initialize``
    runs next, since it refuses to start after any device use.
    """
    import jax
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        from jax.extend.backend import clear_backends

        clear_backends()
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except AttributeError:
        # Older JAX has no jax_num_cpu_devices config; XLA_FLAGS is read
        # lazily at CPU-client creation, and the backend registry was just
        # cleared above, so the env route reaches the next client.
        import os
        import re

        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       os.environ.get("XLA_FLAGS", ""))
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    if verify and len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"requested {n_devices} virtual CPU devices, "
            f"got {len(jax.devices())}")
