"""Framework configuration.

Mirror of the reference's config surface (SURVEY §5): it owns exactly one
option, ``iteration.data-cache.path`` with a random-tmp fallback
(``config/IterationOptions.java:29-37``, resolved at
``operator/OperatorUtils.java:109-117``); everything else rides host-runtime
config.  Here: a dataclass with env-var overrides (``FLINK_ML_TPU_*``), a
process-wide instance, and the same tmp-dir fallback semantics.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile

from typing import Optional

__all__ = ["FrameworkConfig", "get_config", "set_config", "resolve_cache_dir"]

_ENV_PREFIX = "FLINK_ML_TPU_"


@dataclasses.dataclass
class FrameworkConfig:
    # The analog of iteration.data-cache.path (IterationOptions.java:29-37).
    data_cache_path: Optional[str] = None
    # Default checkpoint interval (epochs) when estimators enable it.
    checkpoint_interval: int = 1
    # matmul dtype policy for estimators that support it ("float32"|"bfloat16")
    compute_dtype: str = "float32"
    # INFO-log period for iteration metrics listeners (0 = silent)
    log_every_epochs: int = 0
    # Root of the persistent AOT executable / autotune-decision cache
    # (kernels/aot.py).  None (default) disables it: dispatch compiles
    # in-process exactly as before.  Env: FLINK_ML_TPU_AOT_CACHE_PATH.
    aot_cache_path: Optional[str] = None

    @staticmethod
    def from_env(base: Optional["FrameworkConfig"] = None) -> "FrameworkConfig":
        cfg = dataclasses.replace(base) if base else FrameworkConfig()
        for field in dataclasses.fields(cfg):
            env_key = _ENV_PREFIX + field.name.upper()
            if env_key in os.environ:
                raw = os.environ[env_key]
                current = getattr(cfg, field.name)
                if field.type in ("int", int) or isinstance(current, int):
                    setattr(cfg, field.name, int(raw))
                else:
                    setattr(cfg, field.name, raw)
        return cfg


_CONFIG: Optional[FrameworkConfig] = None


def get_config() -> FrameworkConfig:
    global _CONFIG
    if _CONFIG is None:
        _CONFIG = FrameworkConfig.from_env()
    return _CONFIG


def set_config(config: FrameworkConfig) -> None:
    global _CONFIG
    _CONFIG = config


def resolve_cache_dir() -> str:
    """Configured path or a fresh random tmp dir
    (``OperatorUtils.java:109-117`` semantics)."""
    configured = get_config().data_cache_path
    if configured:
        os.makedirs(configured, exist_ok=True)
        return configured
    return tempfile.mkdtemp(prefix="flink_ml_tpu_cache_")
