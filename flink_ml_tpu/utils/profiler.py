"""Profiler hooks — the TPU answer to the reference's latency tracking.

The reference's only tracing is Flink LatencyMarker stats in the per-round
wrapper (SURVEY §5).  Here: thin wrappers over ``jax.profiler`` producing
Perfetto/XPlane traces of the jitted epoch steps, plus named trace
annotations for host-side phases.
"""

from __future__ import annotations

import contextlib
import time

from typing import Iterator, Optional

import jax

__all__ = ["trace", "annotate", "StepTimer"]


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device+host profile into ``log_dir`` (view with Perfetto /
    tensorboard).  Usage: ``with profiler.trace("/tmp/prof"): fit()``."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named host annotation that shows up on the trace timeline."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Wall-clock timer with a device fence: ``device_get`` of a probe value
    is the only reliable completion barrier on the axon tunnel (see
    bench.py), so ``stop(probe_array)`` fetches it before reading the
    clock."""

    def __init__(self) -> None:
        self._t0: Optional[float] = None
        self.laps = []

    def start(self) -> "StepTimer":
        self._t0 = time.perf_counter()
        return self

    def stop(self, probe=None) -> float:
        if probe is not None:
            jax.device_get(probe)
        if self._t0 is None:
            raise RuntimeError("StepTimer.stop() before start()")
        elapsed = time.perf_counter() - self._t0
        self.laps.append(elapsed)
        self._t0 = None
        return elapsed
