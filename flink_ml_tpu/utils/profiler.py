"""Profiler hooks — the TPU answer to the reference's latency tracking.

The reference's only tracing is Flink LatencyMarker stats in the per-round
wrapper (SURVEY §5).  Here: thin wrappers over ``jax.profiler`` producing
Perfetto/XPlane traces of the jitted epoch steps, plus named trace
annotations for host-side phases.
"""

from __future__ import annotations

import contextlib
import time

from typing import Any, Callable, Iterator, Optional, Tuple

import jax

__all__ = ["trace", "annotate", "StepTimer", "fenced_call"]


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """Capture a device+host profile into ``log_dir`` (view with Perfetto /
    tensorboard).  Usage: ``with profiler.trace("/tmp/prof"): fit()``."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named host annotation that shows up on the trace timeline."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Wall-clock timer with a device fence: ``device_get`` of a probe value
    is the only reliable completion barrier on the axon tunnel (see
    bench.py), so ``stop(probe_array)`` fetches it before reading the
    clock."""

    def __init__(self) -> None:
        self._t0: Optional[float] = None
        self.laps = []

    def start(self) -> "StepTimer":
        self._t0 = time.perf_counter()
        return self

    def stop(self, probe=None) -> float:
        if probe is not None:
            jax.device_get(probe)
        if self._t0 is None:
            raise RuntimeError("StepTimer.stop() before start()")
        elapsed = time.perf_counter() - self._t0
        self.laps.append(elapsed)
        self._t0 = None
        return elapsed


def _default_probe(result: Any) -> Any:
    """The completion probe when the caller names none: the first array
    leaf of the result — fetching ANY output waits for the whole
    dispatch on every backend this repo targets (the axon tunnel
    included, where ``block_until_ready`` does NOT reliably block —
    see bench.py's timing-methodology notes)."""
    for leaf in jax.tree_util.tree_leaves(result):
        if hasattr(leaf, "shape"):
            return leaf
    return None


def fenced_call(fn: Callable, *args: Any,
                probe_of: Optional[Callable[[Any], Any]] = None,
                **kwargs: Any) -> Tuple[Any, float]:
    """THE device-fenced wall-timing idiom (ISSUE 13 satellite), one
    copy: run ``fn(*args, **kwargs)``, fence completion by
    ``device_get``-ing a probe from the result (``probe_of(result)``,
    default: first array leaf), and return ``(result, seconds)``.

    This is what bench.py's leg timings and the tracing layer's
    device-execute spans ride, replacing the hand-rolled
    ``perf_counter -> call -> np.asarray(...) -> perf_counter`` copies;
    the graftlint ``unfenced-timing`` pass flags the hand-rolled form
    when the fence is missing.  Never call this from inside a jitted
    step/scan body — the fence belongs on the host side of the dispatch
    boundary (the ``StepTimer`` stance)."""
    timer = StepTimer().start()
    result = fn(*args, **kwargs)
    probe = probe_of(result) if probe_of is not None \
        else _default_probe(result)
    return result, timer.stop(probe)
