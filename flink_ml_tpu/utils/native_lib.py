"""Shared build-and-load policy for the native libraries in ``native/``.

One place owns the rules — invoke make incrementally on every first load
(a no-op when fresh, guarantees .cpp edits are picked up; a stale .so
would silently serve old native code otherwise), tolerate a failed make
when a previously built .so exists, and degrade to ``None`` (callers keep
their pure-Python fallback) when the toolchain is absent.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

__all__ = ["load_native_lib", "NATIVE_DIR"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
NATIVE_DIR = os.path.join(_REPO_ROOT, "native")


_MAKE_RAN = False


# The lock lives OUTSIDE native/build: `make clean` rm -rf's build/, and
# unlinking a held lock file would let a second process lock a fresh inode
# and compile concurrently — the exact race the lock prevents.
_LOCK_PATH = os.path.join(NATIVE_DIR, ".make.lock")


def _run_make_locked() -> None:
    """make under an exclusive file lock: concurrent processes (the
    multi-host workers, parallel test runs) must not race two compilers
    onto the same .so — the loser would dlopen a half-written library."""
    import fcntl

    with open(_LOCK_PATH, "w") as lock_file:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        try:
            subprocess.run(["make", "-C", NATIVE_DIR], check=True,
                           capture_output=True, timeout=120)
        finally:
            fcntl.flock(lock_file, fcntl.LOCK_UN)


def load_native_lib(lib_name: str) -> Optional[ctypes.CDLL]:
    """Build (if needed) and load ``native/build/lib{lib_name}.so``;
    ``None`` means no native path (caller falls back).  Callers cache the
    result and declare their own symbol signatures.  One ``make all``
    builds every target, so the subprocess runs once per process no
    matter how many libraries load."""
    global _MAKE_RAN
    so_path = os.path.join(NATIVE_DIR, "build", f"lib{lib_name}.so")
    if not _MAKE_RAN and os.path.exists(os.path.join(NATIVE_DIR,
                                                     "Makefile")):
        _MAKE_RAN = True
        try:
            _run_make_locked()
        except Exception:
            if not os.path.exists(so_path):
                return None
    try:
        # shared lock around dlopen: a concurrent process rebuilding the
        # library (exclusive lock) writes -o straight onto this path, and
        # loading mid-write would tear the mapping
        import fcntl

        with open(_LOCK_PATH, "w") as lock_file:
            fcntl.flock(lock_file, fcntl.LOCK_SH)
            try:
                return ctypes.CDLL(so_path)
            finally:
                fcntl.flock(lock_file, fcntl.LOCK_UN)
    except OSError:
        return None
