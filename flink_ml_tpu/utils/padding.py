"""The canonical pad-to-multiple-with-mask invariant (numpy only — shared by
the Table substrate, the mesh sharding helpers, and the estimators)."""

from __future__ import annotations

import threading

from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["FixedRowBatcher", "pad_rows_with_mask", "bucket_rows",
           "bucket_sizes", "pad_rows_to_bucket", "pad_rows_to_block",
           "require_block_rows", "DEFAULT_MIN_BUCKET"]

#: Smallest row bucket the shared predict paths pad to.  Every batch size in
#: [1, 8] compiles the same program, and each further power of two adds one
#: compile — the bucket ladder the serving warm-up walks.
DEFAULT_MIN_BUCKET = 8

#: Largest batch the shared predict paths bucket-pad.  Above this, padding
#: to the next power of two would cost up to 2x the FLOPs and peak device
#: memory of the exact shape — a bad trade for huge OFFLINE tables, whose
#: single exact-shape compile is amortized over the whole call anyway.
#: Online serving batches sit far below this (``max_batch_rows``), so the
#: zero-retrace guarantee is unaffected.
DEFAULT_BUCKET_CAP = 1 << 16


class FixedRowBatcher:
    """The out-of-core fixed-row protocol, shared by
    ``sgd_fit_outofcore`` / ``kmeans_fit_outofcore`` /
    ``WideDeep.fit_outofcore``: the FIRST batch pins the row count
    (rounded up to ``multiple`` for data-axis divisibility), later
    batches must not grow, and short batches (the ragged tail) zero-pad
    — callers give padded rows weight/mask 0.

    Thread-safe: with multi-worker prefetch decode two first batches can
    race; the lock makes exactly one pin win (a mis-sized winner — only
    possible when a cursorless reader's final partial batch decodes
    first — still fails loudly as a growing batch)."""

    def __init__(self, multiple: int):
        if multiple <= 0:
            raise ValueError("multiple must be positive")
        self._multiple = multiple
        self._rows: list = []
        self._lock = threading.Lock()

    @property
    def rows(self) -> Optional[int]:
        return self._rows[0] if self._rows else None

    def pin(self, rows: int) -> None:
        """Pin the fixed row count (rounded up to the multiple); no-op if
        already pinned."""
        with self._lock:
            if not self._rows:
                self._rows.append(rows + (-rows) % self._multiple)

    def pad(self, arrays: Sequence[np.ndarray],
            have: Optional[int] = None) -> Tuple[np.ndarray, ...]:
        """Zero-pad every array's leading dim to the pinned row count
        (pinning from this batch if none is pinned yet)."""
        have = int(arrays[0].shape[0]) if have is None else int(have)
        self.pin(have)
        rows = self._rows[0]
        if have > rows:
            raise ValueError(
                f"reader produced a growing batch ({have} rows after "
                f"{rows}); fixed-size batches are required")
        if have == rows:
            return tuple(arrays)
        return tuple(
            np.concatenate(
                [a, np.zeros((rows - have,) + a.shape[1:], a.dtype)])
            for a in arrays)


def bucket_rows(n: int, *, min_bucket: int = DEFAULT_MIN_BUCKET) -> int:
    """The power-of-two row bucket ``n`` rows pad to (floored at
    ``min_bucket``).  Bucketing is what makes predict paths compile a
    BOUNDED set of programs: every distinct request/batch size in
    ``(bucket/2, bucket]`` hits the same jitted executable, so steady-state
    traffic of mixed sizes triggers zero retraces after one warm-up pass
    over the ladder."""
    if min_bucket <= 0:
        raise ValueError("min_bucket must be positive")
    if n <= min_bucket:
        return min_bucket
    return 1 << (int(n) - 1).bit_length()


def bucket_sizes(max_rows: int,
                 min_bucket: int = DEFAULT_MIN_BUCKET) -> Tuple[int, ...]:
    """The full bucket ladder covering every batch of ``1..max_rows`` rows
    (ascending powers of two) — what a serving warm-up must compile for the
    endpoint to promise zero steady-state retraces."""
    if max_rows <= 0:
        raise ValueError("max_rows must be positive")
    sizes = []
    b = bucket_rows(1, min_bucket=min_bucket)
    top = bucket_rows(max_rows, min_bucket=min_bucket)
    while b <= top:
        sizes.append(b)
        b <<= 1
    return tuple(sizes)


def pad_rows_to_bucket(arrays: Sequence[np.ndarray], *,
                       min_bucket: int = DEFAULT_MIN_BUCKET,
                       max_bucket_rows: Optional[int] = DEFAULT_BUCKET_CAP
                       ) -> Tuple[Tuple[np.ndarray, ...], int]:
    """Zero-pad every array's leading dim to the shared power-of-two bucket;
    returns ``(padded_arrays, n_real_rows)`` — the caller slices device
    results back to ``[:n]``.  Safe for every ROW-INDEPENDENT predict
    computation (margins, per-row argmin, tree routing, MLP forward): pad
    rows never influence real rows, and zero is a valid filler for both
    float features and int id/bin columns (id 0 always exists).

    Batches above ``max_bucket_rows`` (None = unlimited) keep their exact
    shape: the up-to-2x pad cost only buys retrace-freedom for REPEATED
    mixed sizes, which huge one-shot offline tables don't have."""
    n = int(arrays[0].shape[0])
    if max_bucket_rows is not None and n > max_bucket_rows:
        return tuple(np.asarray(a) for a in arrays), n
    bucket = bucket_rows(n, min_bucket=min_bucket)
    if n == bucket:
        return tuple(np.asarray(a) for a in arrays), n
    return tuple(
        np.concatenate(
            [a, np.zeros((bucket - n,) + a.shape[1:], a.dtype)])
        for a in arrays), n


def require_block_rows(n: int, block: int, *, op: str = "kernel") -> None:
    """THE registered-kernel block invariant (the kernel registry's shared
    padding contract, see ``kernels/registry.py``): a blocked device
    kernel's row count must be an exact multiple of its grid block.
    Kernels call this instead of respelling the check, so every violation
    names the same rule and the same fix."""
    if block <= 0:
        raise ValueError(f"{op}: block must be positive, got {block}")
    if n % block:
        raise ValueError(
            f"{op}: n={n} must be a multiple of block={block} — pad rows "
            "with utils.padding.pad_rows_to_block (maskless zero-fill "
            "contract) or pad_rows_with_mask(multiple=block) (masked "
            "contract)")


def pad_rows_to_block(arrays: Sequence[np.ndarray], block: int,
                      ) -> Tuple[Tuple[np.ndarray, ...], int]:
    """The MASKLESS kernel padding contract: zero-pad every array's leading
    dim up to a multiple of ``block``; returns ``(padded, n_real_rows)``.

    Pad rows are exact zeros BY CONTRACT — a registered maskless kernel
    (e.g. ``ops/kmeans_pallas.py``'s stats kernels) relies on zero filler
    having an analytically removable effect (its ``pad_correction``)
    instead of carrying a mask operand.  Kernels that do take a mask use
    :func:`pad_rows_with_mask` with ``multiple=block`` instead; either
    way the divisibility rule is :func:`require_block_rows` — one
    documented invariant for every registered kernel."""
    if block <= 0:
        raise ValueError("block must be positive")
    n = int(arrays[0].shape[0])
    pad = (-n) % block
    if pad == 0:
        return tuple(np.asarray(a) for a in arrays), n
    return tuple(
        np.concatenate(
            [a, np.zeros((pad,) + a.shape[1:], a.dtype)])
        for a in arrays), n


def pad_rows_with_mask(arr, multiple: int,
                       fill: str = "first_row") -> Tuple[np.ndarray, np.ndarray]:
    """Pad rows so ``rows % multiple == 0``; returns ``(padded, mask)`` with
    a float32 mask of 1 for real rows — the MASKED kernel padding contract
    (:func:`require_block_rows` documents the divisibility rule both
    contracts share).

    ``fill="first_row"`` repeats row 0 — safe when every consumer weights
    rows by the mask.  ``fill="zero"`` pads exact-zero rows — required by the
    maskless Pallas KMeans path (``ops/kmeans_pallas.py``), whose padding
    correction assumes zero filler (the :func:`pad_rows_to_block`
    contract)."""
    if multiple <= 0:
        raise ValueError("multiple must be positive")
    if fill not in ("first_row", "zero"):
        raise ValueError(f"fill must be 'first_row' or 'zero', got {fill!r}")
    arr = np.asarray(arr)
    n = arr.shape[0]
    mask = np.ones((n,), dtype=np.float32)
    remainder = n % multiple
    if remainder == 0 or n == 0:
        return arr, mask
    pad = multiple - remainder
    if fill == "zero":
        filler = np.zeros((pad,) + arr.shape[1:], dtype=arr.dtype)
    else:
        filler = np.repeat(arr[:1], pad, axis=0)
    padded = np.concatenate([arr, filler], axis=0)
    mask = np.concatenate([mask, np.zeros((pad,), dtype=np.float32)])
    return padded, mask
