"""The canonical pad-to-multiple-with-mask invariant (numpy only — shared by
the Table substrate, the mesh sharding helpers, and the estimators)."""

from __future__ import annotations

import threading

from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["FixedRowBatcher", "pad_rows_with_mask"]


class FixedRowBatcher:
    """The out-of-core fixed-row protocol, shared by
    ``sgd_fit_outofcore`` / ``kmeans_fit_outofcore`` /
    ``WideDeep.fit_outofcore``: the FIRST batch pins the row count
    (rounded up to ``multiple`` for data-axis divisibility), later
    batches must not grow, and short batches (the ragged tail) zero-pad
    — callers give padded rows weight/mask 0.

    Thread-safe: with multi-worker prefetch decode two first batches can
    race; the lock makes exactly one pin win (a mis-sized winner — only
    possible when a cursorless reader's final partial batch decodes
    first — still fails loudly as a growing batch)."""

    def __init__(self, multiple: int):
        if multiple <= 0:
            raise ValueError("multiple must be positive")
        self._multiple = multiple
        self._rows: list = []
        self._lock = threading.Lock()

    @property
    def rows(self) -> Optional[int]:
        return self._rows[0] if self._rows else None

    def pin(self, rows: int) -> None:
        """Pin the fixed row count (rounded up to the multiple); no-op if
        already pinned."""
        with self._lock:
            if not self._rows:
                self._rows.append(rows + (-rows) % self._multiple)

    def pad(self, arrays: Sequence[np.ndarray],
            have: Optional[int] = None) -> Tuple[np.ndarray, ...]:
        """Zero-pad every array's leading dim to the pinned row count
        (pinning from this batch if none is pinned yet)."""
        have = int(arrays[0].shape[0]) if have is None else int(have)
        self.pin(have)
        rows = self._rows[0]
        if have > rows:
            raise ValueError(
                f"reader produced a growing batch ({have} rows after "
                f"{rows}); fixed-size batches are required")
        if have == rows:
            return tuple(arrays)
        return tuple(
            np.concatenate(
                [a, np.zeros((rows - have,) + a.shape[1:], a.dtype)])
            for a in arrays)


def pad_rows_with_mask(arr, multiple: int,
                       fill: str = "first_row") -> Tuple[np.ndarray, np.ndarray]:
    """Pad rows so ``rows % multiple == 0``; returns ``(padded, mask)`` with
    a float32 mask of 1 for real rows.

    ``fill="first_row"`` repeats row 0 — safe when every consumer weights
    rows by the mask.  ``fill="zero"`` pads exact-zero rows — required by the
    maskless Pallas KMeans path (``ops/kmeans_pallas.py``), whose padding
    correction assumes zero filler."""
    if multiple <= 0:
        raise ValueError("multiple must be positive")
    if fill not in ("first_row", "zero"):
        raise ValueError(f"fill must be 'first_row' or 'zero', got {fill!r}")
    arr = np.asarray(arr)
    n = arr.shape[0]
    mask = np.ones((n,), dtype=np.float32)
    remainder = n % multiple
    if remainder == 0 or n == 0:
        return arr, mask
    pad = multiple - remainder
    if fill == "zero":
        filler = np.zeros((pad,) + arr.shape[1:], dtype=arr.dtype)
    else:
        filler = np.repeat(arr[:1], pad, axis=0)
    padded = np.concatenate([arr, filler], axis=0)
    mask = np.concatenate([mask, np.zeros((pad,), dtype=np.float32)])
    return padded, mask
