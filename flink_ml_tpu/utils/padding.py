"""The canonical pad-to-multiple-with-mask invariant (numpy only — shared by
the Table substrate, the mesh sharding helpers, and the estimators)."""

from __future__ import annotations

from typing import Tuple

import numpy as np

__all__ = ["pad_rows_with_mask"]


def pad_rows_with_mask(arr, multiple: int,
                       fill: str = "first_row") -> Tuple[np.ndarray, np.ndarray]:
    """Pad rows so ``rows % multiple == 0``; returns ``(padded, mask)`` with
    a float32 mask of 1 for real rows.

    ``fill="first_row"`` repeats row 0 — safe when every consumer weights
    rows by the mask.  ``fill="zero"`` pads exact-zero rows — required by the
    maskless Pallas KMeans path (``ops/kmeans_pallas.py``), whose padding
    correction assumes zero filler."""
    if multiple <= 0:
        raise ValueError("multiple must be positive")
    if fill not in ("first_row", "zero"):
        raise ValueError(f"fill must be 'first_row' or 'zero', got {fill!r}")
    arr = np.asarray(arr)
    n = arr.shape[0]
    mask = np.ones((n,), dtype=np.float32)
    remainder = n % multiple
    if remainder == 0 or n == 0:
        return arr, mask
    pad = multiple - remainder
    if fill == "zero":
        filler = np.zeros((pad,) + arr.shape[1:], dtype=arr.dtype)
    else:
        filler = np.repeat(arr[:1], pad, axis=0)
    padded = np.concatenate([arr, filler], axis=0)
    mask = np.concatenate([mask, np.zeros((pad,), dtype=np.float32)])
    return padded, mask
