"""The canonical pad-to-multiple-with-mask invariant (numpy only — shared by
the Table substrate, the mesh sharding helpers, and the estimators)."""

from __future__ import annotations

from typing import Tuple

import numpy as np

__all__ = ["pad_rows_with_mask"]


def pad_rows_with_mask(arr, multiple: int) -> Tuple[np.ndarray, np.ndarray]:
    """Pad rows (repeating row 0) so ``rows % multiple == 0``; returns
    ``(padded, mask)`` with a float32 mask of 1 for real rows.  Row 0 is a
    safe filler because every consumer weights rows by the mask."""
    if multiple <= 0:
        raise ValueError("multiple must be positive")
    arr = np.asarray(arr)
    n = arr.shape[0]
    mask = np.ones((n,), dtype=np.float32)
    remainder = n % multiple
    if remainder == 0 or n == 0:
        return arr, mask
    pad = multiple - remainder
    padded = np.concatenate([arr, np.repeat(arr[:1], pad, axis=0)], axis=0)
    mask = np.concatenate([mask, np.zeros((pad,), dtype=np.float32)])
    return padded, mask
