"""Stage persistence: directory layout + reflective load.

Re-design of ``util/ReadWriteUtils.java``.  The on-disk convention is kept
compatible in spirit with the reference (``ReadWriteUtils.java:112-223``):

    {path}/metadata        JSON: {className, timestamp, paramMap, extra...}
    {path}/data/           model data files (.npz instead of Kryo streams)
    {path}/stages/NN       pipeline children, zero-padded directory names

``load_stage`` resolves the saved class name with importlib and dispatches to
the class's ``load`` classmethod (the analog of the reflective static-load in
``ReadWriteUtils.java:294-314``).
"""

from __future__ import annotations

import importlib
import json
import os
import time
import zipfile

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..robustness.faults import fault_point

__all__ = [
    "save_metadata",
    "load_metadata",
    "save_pipeline",
    "load_pipeline",
    "load_stage",
    "load_stage_param",
    "get_data_path",
    "save_model_arrays",
    "load_model_arrays",
]


def _class_name(obj_or_cls: Any) -> str:
    cls = obj_or_cls if isinstance(obj_or_cls, type) else type(obj_or_cls)
    return f"{cls.__module__}.{cls.__qualname__}"


def _resolve_class(class_name: str) -> type:
    module_name, _, qualname = class_name.rpartition(".")
    module = importlib.import_module(module_name)
    obj: Any = module
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj


def _resolve_saved_class(path: str, meta: Dict[str, Any]) -> type:
    """Resolve ``meta["className"]`` for the stage saved at ``path``,
    converting the raw importlib/getattr failure modes (module renamed,
    class deleted, metadata truncated) into a diagnosable ``IOError``
    naming the path and the stored class name — the model registry's
    hot-load path depends on these being actionable."""
    class_name = meta.get("className")
    if not class_name:
        raise IOError(
            f"Metadata at {path} has no className entry; the directory is "
            "not a saved stage (or the metadata file is truncated)")
    try:
        return _resolve_class(class_name)
    except (ImportError, AttributeError, ValueError) as exc:
        raise IOError(
            f"Cannot load stage at {path}: the stored class "
            f"{class_name!r} is not importable ({exc}).  The class was "
            "renamed/removed since the stage was saved, or the save came "
            "from a different code version.") from exc


def save_metadata(stage, path: str, extra: Optional[Dict[str, Any]] = None) -> None:
    """Mirror of ``ReadWriteUtils.saveMetadata`` (``ReadWriteUtils.java:77-96``).

    Unlike the reference (which refuses to overwrite), saving over an existing
    directory is allowed but the metadata file is always rewritten atomically.
    """
    os.makedirs(path, exist_ok=True)
    meta = dict(extra or {})
    meta["className"] = _class_name(stage)
    meta["timestamp"] = int(time.time() * 1000)
    meta["paramMap"] = stage.params_to_json()
    tmp = os.path.join(path, ".metadata.tmp")
    with open(tmp, "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)
    os.replace(tmp, os.path.join(path, "metadata"))


def load_metadata(path: str, expected_class: Optional[type] = None) -> Dict[str, Any]:
    """Mirror of ``ReadWriteUtils.loadMetadata`` (``ReadWriteUtils.java:139-166``).

    A truncated/corrupted ``metadata`` file surfaces as the same
    diagnosable ``IOError`` (path + hint) that ``_resolve_saved_class``
    established — never a raw ``json.JSONDecodeError`` the registry's
    hot-load path can't act on."""
    meta_path = os.path.join(path, "metadata")
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except json.JSONDecodeError as exc:
        raise IOError(
            f"Metadata at {meta_path} is not valid JSON ({exc}); the "
            "file is truncated or corrupted — the save was interrupted "
            "or the bytes were damaged; re-save the stage or restore "
            "from a valid copy") from exc
    if expected_class is not None:
        expected = _class_name(expected_class)
        if meta.get("className") != expected:
            raise IOError(
                f"Metadata at {path} was saved by {meta.get('className')}, "
                f"expected {expected}")
    return meta


def stage_path(path: str, index: int) -> str:
    """``{path}/stages/%02d`` zero-padded child dir
    (``ReadWriteUtils.java:168-182``)."""
    return os.path.join(path, "stages", f"{index:02d}")


def save_pipeline(pipeline, stages: Sequence[Any], path: str) -> None:
    """Mirror of ``ReadWriteUtils.savePipeline`` (``ReadWriteUtils.java:184-198``)."""
    save_metadata(pipeline, path, {"numStages": len(stages)})
    for i, stage in enumerate(stages):
        stage.save(stage_path(path, i))


def load_pipeline(path: str, expected_class: Optional[type] = None) -> List[Any]:
    """Mirror of ``ReadWriteUtils.loadPipeline`` (``ReadWriteUtils.java:211-223``)."""
    meta = load_metadata(path, expected_class)
    num_stages = int(meta["numStages"])
    return [load_stage(stage_path(path, i)) for i in range(num_stages)]


def load_stage(path: str):
    """Reflective dispatch to the saved class's ``load``
    (``ReadWriteUtils.java:294-314``)."""
    meta = load_metadata(path)
    cls = _resolve_saved_class(path, meta)
    load_fn = getattr(cls, "load", None)
    if load_fn is None:
        raise IOError(f"Class {meta['className']} does not implement load()")
    return cls.load(path)


def load_stage_param(path: str):
    """Instantiate via no-arg constructor + restore params
    (``ReadWriteUtils.java:258-280``) — for stages whose state is purely
    their params."""
    meta = load_metadata(path)
    cls = _resolve_saved_class(path, meta)
    stage = cls()
    stage.params_from_json(meta.get("paramMap", {}))
    return stage


def get_data_path(path: str) -> str:
    """``{path}/data`` (``ReadWriteUtils.java:112-118``)."""
    return os.path.join(path, "data")


def save_model_arrays(path: str, name: str, arrays: Dict[str, np.ndarray]) -> str:
    """Write model data as a compressed npz under ``{path}/data/{name}.npz``
    (replaces the reference's Kryo FileSink, ``KMeansModel.java:184-199``).

    Atomic like :func:`save_metadata` (write tmp -> ``os.replace``): a
    crash mid-save can never leave a half-written model the serving
    registry would try to load."""
    data_dir = get_data_path(path)
    os.makedirs(data_dir, exist_ok=True)
    out = os.path.join(data_dir, f"{name}.npz")
    tmp = os.path.join(data_dir, f".{name}.npz.tmp")
    with open(tmp, "wb") as f:
        np.savez(f, **{k: np.asarray(v) for k, v in arrays.items()})
        f.flush()
    fault_point("persist.write", tmp)
    os.replace(tmp, out)
    return out


def load_model_arrays(path: str, name: str) -> Dict[str, np.ndarray]:
    """Inverse of :func:`save_model_arrays`
    (replaces ``KMeansModel.load``'s Kryo FileSource, ``KMeansModel.java:202-213``).

    The npz's zip CRCs are a free integrity check: truncated or
    bit-flipped model data raises a diagnosable ``IOError`` naming the
    file — never silently-wrong params."""
    npz = os.path.join(get_data_path(path), f"{name}.npz")
    try:
        with np.load(npz) as data:
            return {k: data[k] for k in data.files}
    except (zipfile.BadZipFile, EOFError, ValueError, KeyError) as exc:
        raise IOError(
            f"Model data at {npz} failed to load ({exc!r}); the file is "
            "truncated or corrupted — the save was interrupted or the "
            "bytes were damaged; re-save the model or restore from a "
            "valid copy") from exc
