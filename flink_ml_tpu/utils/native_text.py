"""ctypes binding for the native batch text hasher (native/texthash.cpp).

The pure-Python FNV-1a in ``models/feature/text.py`` loops per byte per
token in the interpreter; for corpus-scale HashingTF that loop IS the
featurization cost.  This binding concatenates all tokens into one buffer
and hands the whole batch to C++ (bit-identical hash values).  Every entry
point degrades to ``None`` when the toolchain/library is unavailable so
callers keep their pure-Python fallback.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Sequence

import numpy as np

from .native_lib import load_native_lib

__all__ = ["fnv1a_batch", "hashing_tf", "native_available"]

_LIB = None
_LIB_TRIED = False


def _native_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    lib = load_native_lib("texthash")
    if lib is not None:
        lib.th_fnv1a_batch.restype = None
        lib.th_fnv1a_batch.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p]
        lib.th_hashing_tf.restype = None
        lib.th_hashing_tf.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int, ctypes.c_void_p]
    _LIB = lib
    return _LIB


def native_available() -> bool:
    return _native_lib() is not None


def _pack(strings: Sequence) -> tuple:
    """Concatenate utf-8 encodings + (n+1,) int64 offsets.

    ASCII batches (the overwhelming case) take a one-join one-encode fast
    path where byte offsets equal character offsets; ``str.isascii`` is a
    C-speed scan, so neither branch encodes any string twice."""
    as_str = [str(s) for s in strings]
    joined = "".join(as_str)
    offsets = np.zeros(len(as_str) + 1, np.int64)
    if joined.isascii():              # byte len == char len
        data = joined.encode("utf-8")
        np.cumsum(np.fromiter(map(len, as_str), np.int64,
                              count=len(as_str)), out=offsets[1:])
    else:
        encoded = [s.encode("utf-8") for s in as_str]
        data = b"".join(encoded)
        np.cumsum(np.fromiter(map(len, encoded), np.int64,
                              count=len(encoded)), out=offsets[1:])
    return data, offsets


def fnv1a_batch(strings: Sequence) -> Optional[np.ndarray]:
    """64-bit FNV-1a of each string's utf-8 form; None when no native lib
    (caller falls back to the Python loop)."""
    lib = _native_lib()
    if lib is None:
        return None
    data, offsets = _pack(strings)
    out = np.empty(len(strings), np.uint64)
    lib.th_fnv1a_batch(data, offsets.ctypes.data, len(strings),
                       out.ctypes.data)
    return out


def hashing_tf(docs, m: int, binary: bool) -> Optional[np.ndarray]:
    """The full HashingTF document-term fill for ``docs`` (iterable of
    token lists) into an (n_docs, m) float64 matrix; None when no lib."""
    lib = _native_lib()
    if lib is None:
        return None
    tokens: List = []
    counts = np.empty(len(docs), np.int64)
    for i, doc in enumerate(docs):
        toks = np.ravel(np.asarray(doc, dtype=object))
        counts[i] = len(toks)
        tokens.extend(toks)
    data, offsets = _pack(tokens)
    out = np.zeros((len(docs), m), np.float64)
    lib.th_hashing_tf(data, offsets.ctypes.data, counts.ctypes.data,
                      len(docs), m, 1 if binary else 0, out.ctypes.data)
    return out
