"""Metrics: counters/gauges/timers + per-epoch iteration metrics.

Mirror of the reference's observability surface (SURVEY §5): Flink metric
groups + INFO logs at alignment events
(``AbstractWrapperOperator.java:161-177``,
``RegularHeadOperatorRecordProcessor.java:107,159``).  Here a
``MetricGroup`` is a plain nested registry, and
``IterationMetricsListener`` hooks the hosted epoch loop to record wall
time, records/sec and any scalar outputs — the analog of the per-round
latency stats in ``perround/AbstractPerRoundWrapperOperator.java:500-553``.
"""

from __future__ import annotations

import logging
import time

from typing import Any, Dict, List, Optional

import numpy as np

from ..iteration.body import EpochContext, IterationListener

__all__ = ["MetricGroup", "Counter", "Gauge", "IterationMetricsListener"]

logger = logging.getLogger("flink_ml_tpu")


class Counter:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0

    def inc(self, amount: int = 1) -> None:
        self.value += amount


class Gauge:
    __slots__ = ("value",)

    def __init__(self, value: Any = None) -> None:
        self.value = value

    def set(self, value: Any) -> None:
        self.value = value


class MetricGroup:
    """Nested name -> metric registry (``group.add_group("epoch").counter(
    "records")`` mirrors Flink's ``getMetricGroup().addGroup(...)``)."""

    def __init__(self, name: str = "root"):
        self.name = name
        self._groups: Dict[str, "MetricGroup"] = {}
        self._metrics: Dict[str, Any] = {}

    def add_group(self, name: str) -> "MetricGroup":
        return self._groups.setdefault(name, MetricGroup(name))

    def counter(self, name: str) -> Counter:
        return self._metrics.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        return self._metrics.setdefault(name, Gauge())

    def snapshot(self, prefix: str = "") -> Dict[str, Any]:
        """Flatten to {dotted.name: value}."""
        out: Dict[str, Any] = {}
        for name, metric in self._metrics.items():
            out[f"{prefix}{name}"] = metric.value
        for name, group in self._groups.items():
            out.update(group.snapshot(f"{prefix}{name}."))
        return out


class IterationMetricsListener(IterationListener):
    """Per-epoch wall-clock + throughput recorder for hosted iterations.

    ``records_per_epoch`` (if given) yields records/sec; scalar epoch outputs
    are logged as ``epoch_metric``.  ``log_every`` INFO-logs progress the way
    the reference logs epoch alignment.
    """

    def __init__(self, records_per_epoch: Optional[int] = None,
                 log_every: int = 0,
                 group: Optional[MetricGroup] = None):
        self.group = group or MetricGroup("iteration")
        self.records_per_epoch = records_per_epoch
        self.log_every = log_every
        self.epoch_seconds: List[float] = []
        self.epoch_metrics: List[float] = []
        self._last = time.perf_counter()
        self._epochs = self.group.counter("epochs")
        self._records = self.group.counter("records")
        self._rate = self.group.gauge("records_per_sec")

    def on_epoch_watermark_incremented(self, epoch: int,
                                       context: EpochContext) -> None:
        now = time.perf_counter()
        elapsed = now - self._last
        self._last = now
        self.epoch_seconds.append(elapsed)
        self._epochs.inc()
        if self.records_per_epoch:
            self._records.inc(self.records_per_epoch)
            self._rate.set(self.records_per_epoch / max(elapsed, 1e-9))
        if context.outputs is not None and np.ndim(context.outputs) == 0:
            self.epoch_metrics.append(float(context.outputs))
        if self.log_every and (epoch + 1) % self.log_every == 0:
            logger.info(
                "epoch %d: %.4fs/epoch%s%s", epoch, elapsed,
                (f", {self._rate.value:.0f} rec/s" if self.records_per_epoch
                 else ""),
                (f", metric={self.epoch_metrics[-1]:.6g}"
                 if self.epoch_metrics else ""))

    def on_iteration_terminated(self, context: EpochContext) -> None:
        total = sum(self.epoch_seconds)
        self.group.gauge("total_seconds").set(total)
        if self.log_every:
            logger.info("iteration finished: %d epochs in %.3fs",
                        len(self.epoch_seconds), total)
