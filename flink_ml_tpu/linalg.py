"""Minimal linear-algebra surface: dense/sparse vectors + factories.

Mirror of ``flink-ml-api/.../linalg/`` (``DenseVector.java:27-67``,
``Vectors.java``).  On TPU a "vector" is just a row of a batched 2-D array;
these classes exist for API parity (single-row construction, save/load of
model data) and normalise everything to numpy float64 on the host, with
conversion helpers to device-friendly dtypes.

The reference's custom serializer (``DenseVectorSerializer.java``) is
replaced by npz persistence in :mod:`flink_ml_tpu.utils.persist`.
"""

from __future__ import annotations

from typing import Any, Iterable, Sequence, Union

import numpy as np

__all__ = ["Vector", "DenseVector", "SparseVector", "Vectors",
           "stack_vectors", "stack_sparse_vectors"]


class Vector:
    """Abstract vector contract (``linalg/Vector.java``): size/get/to_array."""

    def size(self) -> int:
        raise NotImplementedError

    def get(self, i: int) -> float:
        raise NotImplementedError

    def to_array(self) -> np.ndarray:
        raise NotImplementedError


class DenseVector(Vector):
    """Dense double vector (``linalg/DenseVector.java:27-67``)."""

    __slots__ = ("values",)

    def __init__(self, values: Union[Sequence[float], np.ndarray]):
        self.values = np.asarray(values, dtype=np.float64).reshape(-1)

    def size(self) -> int:
        return int(self.values.shape[0])

    def get(self, i: int) -> float:
        return float(self.values[i])

    def to_array(self) -> np.ndarray:
        return self.values

    def __array__(self, dtype=None):
        return self.values if dtype is None else self.values.astype(dtype)

    def __len__(self) -> int:
        return self.size()

    def __getitem__(self, i: int) -> float:
        return float(self.values[i])

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, DenseVector) and np.array_equal(
            self.values, other.values)

    def __hash__(self) -> int:
        return hash(self.values.tobytes())

    def __repr__(self) -> str:
        return f"DenseVector({self.values.tolist()})"


class SparseVector(Vector):
    """COO sparse vector — not present in the reference snapshot but part of
    the Flink ML linalg surface; provided for completeness.  Densifies for
    device compute (TPUs want dense tiles)."""

    __slots__ = ("n", "indices", "values")

    def __init__(self, n: int, indices: Sequence[int], values: Sequence[float]):
        self.n = int(n)
        self.indices = np.asarray(indices, dtype=np.int64).reshape(-1)
        self.values = np.asarray(values, dtype=np.float64).reshape(-1)
        if self.indices.shape != self.values.shape:
            raise ValueError("indices and values must have the same length")
        if self.indices.size and (self.indices.min() < 0
                                  or self.indices.max() >= self.n):
            raise ValueError("index out of range")

    def size(self) -> int:
        return self.n

    def get(self, i: int) -> float:
        hits = np.nonzero(self.indices == i)[0]
        return float(self.values[hits[0]]) if hits.size else 0.0

    def to_array(self) -> np.ndarray:
        dense = np.zeros((self.n,), dtype=np.float64)
        dense[self.indices] = self.values
        return dense

    def to_dense(self) -> DenseVector:
        return DenseVector(self.to_array())

    def __repr__(self) -> str:
        return (f"SparseVector(n={self.n}, indices={self.indices.tolist()}, "
                f"values={self.values.tolist()})")


class Vectors:
    """Factory methods (``linalg/Vectors.java``)."""

    @staticmethod
    def dense(*values: float) -> DenseVector:
        if len(values) == 1 and isinstance(values[0], (list, tuple, np.ndarray)):
            return DenseVector(values[0])
        return DenseVector(values)

    @staticmethod
    def sparse(n: int, indices: Sequence[int], values: Sequence[float]) -> SparseVector:
        return SparseVector(n, indices, values)


def stack_sparse_vectors(column: Iterable["SparseVector"],
                         nnz: int = 0) -> tuple:
    """Normalise a column of :class:`SparseVector` into the device-facing
    fixed-active-count form: ``(indices (n, nnz) int32, values (n, nnz)
    float32, dim)``.  Rows with fewer actives pad with ``(index 0, value
    0.0)`` — a zero value contributes nothing to any gather-based score or
    scatter-based gradient, so padding is free of masking.

    This is what makes the hashed high-dim path (Criteo-shape, 2^20+ dims)
    expressible: the dense ``stack_vectors`` form would materialise an
    ``(n, 2^20)`` matrix.  TPUs want static shapes, hence fixed nnz (pass
    ``nnz`` to force a count >= the max actives; 0 = use the max)."""
    vecs = list(column)
    n = len(vecs)
    max_active = max((v.indices.shape[0] for v in vecs), default=0)
    if nnz <= 0:
        nnz = max(max_active, 1)
    elif max_active > nnz:
        raise ValueError(
            f"nnz={nnz} is smaller than the densest row ({max_active} "
            "active entries)")
    indices = np.zeros((n, nnz), np.int32)
    values = np.zeros((n, nnz), np.float32)
    dim = 0
    for i, v in enumerate(vecs):
        k = v.indices.shape[0]
        indices[i, :k] = v.indices
        values[i, :k] = v.values
        dim = max(dim, v.size())
    return indices, values, dim


def stack_vectors(column: Iterable[Any]) -> np.ndarray:
    """Normalise a features column (array of DenseVector / lists / 2-D array)
    into one contiguous ``(rows, dim)`` float array — the device-facing form."""
    if isinstance(column, np.ndarray) and column.dtype != object:
        arr = np.asarray(column, dtype=np.float64)
        # A 1-D numeric column is n scalar samples -> (n, 1), NOT one n-dim row.
        return arr.reshape(-1, 1) if arr.ndim == 1 else arr
    rows = [np.asarray(getattr(v, "values", v), dtype=np.float64).reshape(-1)
            for v in column]
    if not rows:
        return np.zeros((0, 0), dtype=np.float64)
    return np.stack(rows)
