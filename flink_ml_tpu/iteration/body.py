"""Iteration body contract: result type, listeners, epoch context.

Re-design of the reference's iteration API surface
(``IterationBody.java:54-98``, ``IterationBodyResult.java:28-76``,
``IterationListener.java:30-74``, ``IterationConfig.java:22-66``).

The body is a function, not a graph: ``body(state, epoch, data) ->
IterationBodyResult``.  ``state`` is the feedback-variable pytree — the
TPU-native feedback edge is simply that this pytree never leaves HBM between
epochs (donated jit buffers), replacing the reference's StateFun
FeedbackChannel + Tail/Head operators.
"""

from __future__ import annotations

import enum

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax

__all__ = [
    "IterationBodyResult",
    "IterationListener",
    "EpochContext",
    "OperatorLifeCycle",
    "IterationConfig",
    "Workset",
    "active_fraction",
    "normalize_body_result",
]


class OperatorLifeCycle(enum.Enum):
    """``IterationConfig.OperatorLifeCycle`` (``IterationConfig.java:22-66``):
    ALL_ROUND state is carried across epochs; PER_ROUND state is functionally
    re-initialised every epoch (the analog of the reference physically
    scrubbing per-round operator state,
    ``perround/AbstractPerRoundWrapperOperator.java:579-650``)."""

    ALL_ROUND = "all_round"
    PER_ROUND = "per_round"


@dataclass
class IterationConfig:
    """Mirror of ``IterationConfig.java`` extended with the TPU-native knobs
    the driver loop needs."""

    lifecycle: OperatorLifeCycle = OperatorLifeCycle.ALL_ROUND
    max_epochs: Optional[int] = None
    # "hosted": python epoch loop around a jitted step (listeners, streaming
    #           data, checkpoints). "fused": whole loop on device via
    #           lax.scan/while_loop (no per-epoch host round-trip at all).
    # "auto": fused when there are no listeners/checkpoints/streaming data.
    mode: str = "auto"
    jit: bool = True
    # Donate the state buffers to the jitted step so the feedback pytree is
    # updated in place in HBM (flat memory across epochs).
    donate_state: bool = True
    # Hosted-mode dispatch amortization: scan this many epochs per jit
    # dispatch (device-resident data only).  Listener callbacks,
    # termination-vote syncs, and checkpoint cuts move to CHUNK
    # boundaries; results stay bit-exact vs steps_per_dispatch=1 (a
    # terminated vote freezes the carried state inside the scan).  1 =
    # the classic one-dispatch-per-epoch loop.
    steps_per_dispatch: int = 1

    def __post_init__(self):
        if self.mode not in ("auto", "hosted", "fused"):
            raise ValueError(f"Unknown iteration mode {self.mode!r}")
        if self.steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1, got "
                f"{self.steps_per_dispatch}")


@dataclass
class Workset:
    """Device-resident active set riding the iteration carry — the delta-
    iteration workset of Ewen et al. (*Spinning Fast Iterative Data
    Flows*) rebuilt TPU-native: where the reference streams the changed
    elements through a feedback edge each superstep, here the workset is
    a mask over device-resident data that never leaves HBM.

    - ``mask``: per-element activity, float32 0/1 (or bool) arrays — a
      single array or a pytree of them (ALS masks users AND items).  An
      element with mask 0 is *provably settled this round*: the body must
      reuse its cached contribution instead of recomputing it.
    - ``bounds``: optional per-element bound state the body uses to decide
      settlement (Hamerly upper/lower distance bounds for KMeans, cached
      assignments, movement deltas, ...).  Rides the carry — and therefore
      chunk-boundary checkpoints — untouched by the driver.

    The driver terminates when :func:`active_fraction` falls to
    ``workset_tol`` (default exactly zero): an empty workset is the
    reference's empty-workset termination criterion
    (``SharedProgressAligner``'s zero-feedback-records rule applied to the
    delta iteration's solution-set updates).
    """

    mask: Any
    bounds: Any = None


def _workset_flatten(ws: Workset):
    return (ws.mask, ws.bounds), None


def _workset_unflatten(_, children):
    return Workset(*children)


jax.tree_util.register_pytree_node(Workset, _workset_flatten,
                                   _workset_unflatten)


def active_fraction(workset: Workset):
    """Global fraction of active elements, as a traced scalar: total mask
    mass over total element count across every mask leaf.  Under a jitted
    SPMD program with sharded masks XLA inserts the cross-device psum —
    every shard sees the same replicated scalar, so the while_loop exit
    decision is mesh-consistent by construction."""
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(workset.mask)
    total = sum(x.size for x in leaves)
    if total == 0:
        return jnp.asarray(0.0, jnp.float32)
    act = sum(jnp.sum(x.astype(jnp.float32)) for x in leaves)
    return act / jnp.asarray(float(total), jnp.float32)


@dataclass
class IterationBodyResult:
    """(feedback, outputs, termination) — mirror of
    ``IterationBodyResult.java:28-76``.

    - ``feedback``: next-epoch variable state (pytree).
    - ``outputs``: per-epoch emission (pytree or None).
    - ``termination``: optional scalar vote. Truthy / nonzero means "records
      still flowing — continue"; the iteration terminates on a zero vote,
      mirroring the aligner's zero-feedback-records rule
      (``SharedProgressAligner.java:277-300``).
    """

    feedback: Any
    outputs: Any = None
    termination: Optional[Any] = None


def _result_flatten(res: IterationBodyResult):
    return (res.feedback, res.outputs, res.termination), None


def _result_unflatten(_, children):
    return IterationBodyResult(*children)


jax.tree_util.register_pytree_node(
    IterationBodyResult, _result_flatten, _result_unflatten)


def normalize_body_result(result: Any) -> IterationBodyResult:
    """Accept ``IterationBodyResult`` or a bare state pytree (which may
    itself be a tuple — bare returns are never unpacked: outputs/termination
    require the explicit result type, so a tuple-shaped state can't be
    silently misread as (feedback, outputs))."""
    if isinstance(result, IterationBodyResult):
        return result
    return IterationBodyResult(result)


@dataclass
class EpochContext:
    """Handed to listeners between epochs (hosted mode) — the analog of the
    ``IterationListener.Context`` + Collector pair."""

    epoch: int
    state: Any
    outputs: Any = None
    terminated: bool = False
    side: dict = field(default_factory=dict)

    def output(self, key: str, value: Any) -> None:
        """Side-output channel (the analog of ``ctx.output(OutputTag, v)``)."""
        self.side.setdefault(key, []).append(value)


class IterationListener:
    """Epoch-watermark callbacks (``IterationListener.java:30-74``).

    In hosted mode these fire on the host between jitted epoch steps — the
    exact analog of ``onEpochWatermarkIncremented`` firing after the
    superstep-alignment barrier (which, in SPMD, *is* the jitted step
    boundary)."""

    def on_epoch_watermark_incremented(self, epoch: int,
                                       context: EpochContext) -> None:
        pass

    def on_checkpoint_saved(self, epoch: int,
                            context: EpochContext) -> None:
        """Fires right after a checkpoint cut lands (hosted mode only —
        fused iterations cannot checkpoint mid-run).  THE hook the
        continuous-learning publish listener rides: at this point the
        (state, source cursor) pair is durable, so a publish of exactly
        this state composes with crash recovery into exactly-once —
        a crash after the cut re-publishes the same step idempotently
        (``online/publish.py``)."""
        pass

    def on_iteration_terminated(self, context: EpochContext) -> None:
        pass


class FnListener(IterationListener):
    """Adapter: wrap plain callables as a listener."""

    def __init__(self,
                 on_epoch: Optional[Callable[[int, EpochContext], None]] = None,
                 on_terminated: Optional[Callable[[EpochContext], None]] = None):
        self._on_epoch = on_epoch
        self._on_terminated = on_terminated

    def on_epoch_watermark_incremented(self, epoch, context):
        if self._on_epoch:
            self._on_epoch(epoch, context)

    def on_iteration_terminated(self, context):
        if self._on_terminated:
            self._on_terminated(context)
