"""Mid-training checkpoint/resume of iteration state.

The reference achieves exactly-once over a cyclic graph with coordinator/
barrier alignment plus a feedback-records-in-flight log (§3.4,
``checkpoint/Checkpoints.java:43-211``).  In the TPU-native design there are
no in-flight records: an epoch boundary is a consistent cut by construction
(the jitted step is the barrier), so a checkpoint is simply

    (epoch counter, state pytree, optional data-source cursor)

written atomically between epochs.  Exactly-once equivalence becomes
*deterministic replay*: state + epoch + cursor + RNG key fully determine the
rest of training (tested, not assumed — see tests/test_checkpoint.py).

Durability is VALIDATED (robustness PR): every checkpoint directory
carries a per-file CRC32 manifest and an atomic commit marker
(``robustness/durability.py`` — write payload -> manifest -> marker ->
rename), so a torn write, a bit flip, or a crash mid-save is *detected*
at restore time.  ``CheckpointManager.latest()`` scans newest->oldest,
quarantines invalid cuts (``<dir>.corrupt``) and returns the newest
VALID one instead of crashing on — or worse, silently restoring — bad
state.
"""

from __future__ import annotations

import json
import os
import shutil
import time
import zipfile

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..obs.trace import tracer
from ..robustness.durability import (
    CorruptStateError,
    commit_dir,
    quarantine,
    verify_dir,
)
from ..robustness.faults import fault_point

__all__ = ["save_pytree", "load_pytree", "CheckpointManager",
           "CheckpointConfig", "mesh_shape_meta", "require_fleet_compat"]


def mesh_shape_meta(mesh, participant_count: Optional[int] = None
                    ) -> Dict[str, Any]:
    """The fleet-identity metadata every elastic-aware cut carries: the
    writing mesh's axis sizes plus the reduction participant count.  A
    restore onto a DIFFERENT fleet consults this to know what it is
    re-sharding from (``require_fleet_compat``) — a cut without it can
    only safely restore onto a fleet of the original shape."""
    meta: Dict[str, Any] = {
        "mesh_shape": {str(a): int(mesh.shape[a]) for a in mesh.axis_names}}
    if participant_count is not None:
        meta["participant_count"] = int(participant_count)
    return meta


def require_fleet_compat(meta: Dict[str, Any], *, saved_participants: int,
                         current_participants: int, path: str = "") -> None:
    """Gate a cross-fleet restore on the cut carrying mesh-shape
    metadata.  ``CheckpointManager.latest()`` historically assumed a cut
    from the same mesh shape; with elastic fleets a cut can legally
    restore onto a different one — but ONLY when the manifest records
    what fleet wrote it (``mesh_shape``/``participant_count``, attached
    by the elastic-aware fits).  A legacy cut restored onto a different
    fleet raises a diagnosable :class:`CorruptStateError` instead of a
    silent wrong-shape restore."""
    if saved_participants == current_participants:
        return
    if meta.get("mesh_shape") is None \
            and meta.get("participant_count") is None:
        where = f" at {path}" if path else ""
        raise CorruptStateError(
            f"checkpoint{where} holds reducer state for "
            f"{saved_participants} participant(s) but is being restored "
            f"onto a fleet of {current_participants}, and the cut "
            "predates mesh-shape metadata (no 'mesh_shape'/"
            "'participant_count' in its manifest) — refusing the "
            "wrong-shape restore; restore onto a fleet of the original "
            "size, or re-cut the checkpoint with an elastic-aware fit")

_LEAF = "__leaf__"


def _encode_key(key: Any) -> Any:
    """Dict keys keep their python type through JSON (json.dump would
    silently stringify int/bool keys, corrupting the pytree structure)."""
    if isinstance(key, str):
        return key
    if isinstance(key, bool):
        return {"__bool__": key}
    if isinstance(key, int):
        return {"__int__": key}
    if isinstance(key, float):
        return {"__float__": key}
    raise TypeError(f"Unsupported dict key type in checkpoint state: {key!r}")


def _decode_key(node: Any) -> Any:
    if isinstance(node, str):
        return node
    for tag in ("__bool__", "__int__", "__float__"):
        if tag in node:
            return node[tag]
    raise ValueError(f"Corrupt checkpoint key: {node!r}")


def _encode_structure(tree: Any, leaves: List[np.ndarray]) -> Any:
    """JSON-able structure skeleton with leaf placeholders.  Supports dict /
    list / tuple / namedtuple / None containers — the practical shapes of
    training state (incl. optax NamedTuple optimizer states) — plus the
    iteration runtime's :class:`~.body.Workset` (a workset iteration's
    hosted carry is ``(state, Workset)``, so the active-set mask and bound
    state round-trip through crash-recovery cuts bit-exactly)."""
    from .body import Workset

    if tree is None:
        return None
    if isinstance(tree, Workset):
        return {"__workset__": [_encode_structure(tree.mask, leaves),
                                _encode_structure(tree.bounds, leaves)]}
    if isinstance(tree, dict):
        return {"__dict__": [[_encode_key(k), _encode_structure(v, leaves)]
                             for k, v in tree.items()]}
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):
        cls = type(tree)
        return {"__namedtuple__": f"{cls.__module__}.{cls.__qualname__}",
                "fields": [[f, _encode_structure(v, leaves)]
                           for f, v in zip(tree._fields, tree)]}
    if isinstance(tree, tuple):
        return {"__tuple__": [_encode_structure(v, leaves) for v in tree]}
    if isinstance(tree, list):
        return {"__list__": [_encode_structure(v, leaves) for v in tree]}
    idx = len(leaves)
    leaves.append(np.asarray(tree))
    return {_LEAF: idx, "__scalar__": np.ndim(tree) == 0
            and not isinstance(tree, (np.ndarray, jax.Array))}


def _resolve_namedtuple(qualified: str):
    import importlib

    module_name, _, qualname = qualified.rpartition(".")
    obj: Any = importlib.import_module(module_name)
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj


def _decode_structure(node: Any, leaves: Dict[int, np.ndarray]) -> Any:
    from .body import Workset

    if node is None:
        return None
    if "__workset__" in node:
        mask_node, bounds_node = node["__workset__"]
        return Workset(_decode_structure(mask_node, leaves),
                       _decode_structure(bounds_node, leaves))
    if "__dict__" in node:
        return {_decode_key(k): _decode_structure(v, leaves)
                for k, v in node["__dict__"]}
    if "__namedtuple__" in node:
        values = {f: _decode_structure(v, leaves) for f, v in node["fields"]}
        cls = _resolve_namedtuple(node["__namedtuple__"])
        return cls(**values)
    if "__tuple__" in node:
        return tuple(_decode_structure(v, leaves) for v in node["__tuple__"])
    if "__list__" in node:
        return [_decode_structure(v, leaves) for v in node["__list__"]]
    leaf = leaves[node[_LEAF]]
    if node.get("__scalar__"):
        return leaf.item()
    return leaf


def _leaf_to_host(x: Any) -> Any:
    """Device leaf -> host value, multi-host safe: a jax.Array sharded over a
    multi-host mesh is NOT fully addressable (``jax.device_get`` would
    throw), so its global value is assembled with a ``process_allgather``
    collective — which every process must enter (it compiles to an
    all-gather over DCN/ICI)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return x


def save_pytree(path: str, tree: Any,
                meta: Optional[Dict[str, Any]] = None) -> None:
    """Atomically persist a pytree: arrays into one npz, structure + metadata
    into a JSON sidecar.  Device arrays are fetched to host first (one
    blocking transfer; callers wanting async snapshots copy the state with
    ``jax.device_get`` beforehand).

    Multi-host: every process participates in assembling the global value
    (collective), then ONLY process 0 touches the filesystem — no directory
    races — and a cross-host barrier makes the checkpoint visible to all
    processes before anyone proceeds (the directory must be on a filesystem
    shared by all hosts, the standard pod setup)."""
    multi = jax.process_count() > 1
    leaves: List[np.ndarray] = []
    host_tree = jax.device_get(jax.tree_util.tree_map(_leaf_to_host, tree))
    skeleton = _encode_structure(host_tree, leaves)
    if multi and jax.process_index() != 0:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"save_pytree:{path}")
        return
    tmp = path + ".tmp"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    np.savez(os.path.join(tmp, "leaves.npz"),
             **{f"leaf_{i}": leaf for i, leaf in enumerate(leaves)})
    with open(os.path.join(tmp, "structure.json"), "w") as f:
        json.dump({"skeleton": skeleton, "meta": meta or {}}, f)
    # commit protocol: CRC manifest -> (fault seam) -> COMMITTED marker,
    # all BEFORE the rename publishes the directory.  An injected crash
    # here leaves an uncommitted tmp (never trusted); an injected
    # torn/flip fault leaves a committed-but-invalid checkpoint that
    # verify_dir catches at restore (robustness/durability.py).
    commit_dir(tmp, fault_scope="checkpoint.write")
    if os.path.exists(path):
        # Overwrite dance keeping a valid copy at every instant: demote the
        # old checkpoint to .old, promote tmp, then drop .old.  A crash in
        # the window leaves either {path} or {path}.old readable —
        # load_pytree falls back to .old.
        old = path + ".old"
        if os.path.exists(old):
            shutil.rmtree(old)
        os.replace(path, old)
        os.replace(tmp, path)
        shutil.rmtree(old)
    else:
        os.replace(tmp, path)
    if multi:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"save_pytree:{path}")


def load_pytree(path: str) -> Tuple[Any, Dict[str, Any]]:
    """Validate (manifest CRCs + commit marker — legacy pre-manifest
    saves pass through) then decode.  Decode-time corruption that slips
    past a legacy save's missing manifest still surfaces as a
    diagnosable :class:`~..robustness.durability.CorruptStateError`
    naming the path, never as silently wrong state."""
    if not os.path.exists(os.path.join(path, "structure.json")) \
            and os.path.exists(os.path.join(path + ".old", "structure.json")):
        path = path + ".old"  # crashed mid-overwrite; previous copy is valid
    verify_dir(path)
    try:
        with open(os.path.join(path, "structure.json")) as f:
            doc = json.load(f)
        with np.load(os.path.join(path, "leaves.npz")) as data:
            leaves = {int(k.split("_", 1)[1]): data[k] for k in data.files}
        return _decode_structure(doc["skeleton"], leaves), doc.get("meta", {})
    except (json.JSONDecodeError, zipfile.BadZipFile, KeyError, EOFError,
            ValueError, FileNotFoundError) as exc:
        # FileNotFoundError: a legacy (pre-manifest) dir can pass
        # verify_dir yet be missing a payload file — a partial save,
        # quarantinable like any other corruption
        raise CorruptStateError(
            f"checkpoint at {path} failed to decode ({exc!r}); the save "
            "is truncated or corrupted — restore from an earlier "
            "checkpoint") from exc


class CheckpointConfig:
    def __init__(self, directory: str, interval: int = 1, max_to_keep: int = 2,
                 async_save: bool = False):
        if interval <= 0:
            raise ValueError("checkpoint interval must be positive")
        self.directory = directory
        self.interval = interval
        self.max_to_keep = max_to_keep
        # Overlap the device->host fetch + disk write with the next epoch's
        # compute (the iteration driver snapshots a device-side copy first so
        # donation can't invalidate the buffers being read).
        self.async_save = async_save


class CheckpointManager:
    """Epoch-granular checkpoint store: ``{dir}/ckpt-{epoch:08d}/``.

    The write is atomic (tmp dir + rename), so a crash mid-write leaves the
    previous checkpoint intact — the analog of the reference aborting a
    pending ``Checkpoints`` log on failure (``Checkpoints.java:179-211``)."""

    def __init__(self, config: CheckpointConfig):
        self.config = config
        os.makedirs(config.directory, exist_ok=True)
        self._pending: Optional["threading.Thread"] = None
        self._pending_error: Optional[BaseException] = None
        #: set by :meth:`latest` — the supervisor/bench read these to
        #: compute MTTR (detect -> restore complete) and steps replayed
        self.last_restore_at: Optional[float] = None
        self.last_restored_step: Optional[int] = None
        #: timestamp source for ``last_restore_at``; resilient_fit
        #: overwrites it with ITS clock so MTTR never mixes clock domains
        self.clock: Callable[[], float] = time.perf_counter

    def _ckpt_path(self, epoch: int) -> str:
        return os.path.join(self.config.directory, f"ckpt-{epoch:08d}")

    def list_epochs(self) -> List[int]:
        out = []
        for name in os.listdir(self.config.directory):
            if name.startswith("ckpt-") and not name.endswith(".tmp"):
                try:
                    out.append(int(name.split("-", 1)[1]))
                except ValueError:
                    continue
        return sorted(out)

    def should_save(self, epoch: int) -> bool:
        return epoch % self.config.interval == 0

    def save(self, epoch: int, state: Any,
             extra: Optional[Dict[str, Any]] = None) -> str:
        path = self._ckpt_path(epoch)
        meta = {"epoch": epoch}
        if extra:
            meta.update(extra)
        # the cut's slot key IS the trainer's global step for streaming
        # fits — the `step` correlation id a later delta publish carries
        with tracer.span("checkpoint_write", cat="train", step=int(epoch)):
            save_pytree(path, state, meta)
        self._gc()
        return path

    def save_async(self, epoch: int, state: Any,
                   extra: Optional[Dict[str, Any]] = None) -> None:
        """Kick the device->host fetch + write to a background thread.  At
        most one save is in flight; callers must pass state buffers that the
        training loop will NOT donate/overwrite (a device-side copy)."""
        import threading

        self.wait()

        def work():
            try:
                self.save(epoch, state, extra)
            except BaseException as e:  # surfaced on next wait()
                self._pending_error = e

        self._pending = threading.Thread(target=work, daemon=True)
        self._pending.start()

    def wait(self) -> None:
        """Block until the in-flight async save (if any) lands; re-raise its
        error.  Called before restore and at iteration end."""
        if self._pending is not None:
            self._pending.join()
            self._pending = None
        if self._pending_error is not None:
            error, self._pending_error = self._pending_error, None
            raise error

    def latest(self) -> Optional[Tuple[int, Any, Dict[str, Any]]]:
        """The newest VALID checkpoint, scanning newest->oldest.  A cut
        that fails validation/decoding (torn write, bit flip, crash
        mid-commit) is quarantined (``<dir>.corrupt`` — kept for
        forensics, invisible to future scans) and the scan falls back to
        the previous one; only when NO valid checkpoint exists does this
        return None.  The self-healing contract resilient_fit rides: a
        corrupted newest checkpoint costs replayed steps, never the
        run.

        The returned ``meta`` may carry the writing fleet's identity
        (``mesh_shape``/``participant_count`` — :func:`mesh_shape_meta`,
        attached by elastic-aware fits).  Restoring onto a *different*
        fleet is the caller's re-shard job; callers must gate it with
        :func:`require_fleet_compat` so a legacy cut (no fleet
        metadata) fails diagnosably instead of restoring wrong-shaped
        state."""
        self.wait()
        for epoch in reversed(self.list_epochs()):
            path = self._ckpt_path(epoch)
            try:
                state, meta = load_pytree(path)
            except CorruptStateError:
                quarantine(path)
                continue
            self.last_restore_at = self.clock()
            self.last_restored_step = int(meta["epoch"])
            return int(meta["epoch"]), state, meta
        return None

    def restore_latest(self) -> Optional[Tuple[int, Any, Dict[str, Any]]]:
        return self.latest()

    def _gc(self) -> None:
        keep = self.config.max_to_keep
        if keep <= 0:
            return
        if jax.process_count() > 1 and jax.process_index() != 0:
            return  # process 0 owns the directory (save_pytree writes there)
        for epoch in self.list_epochs()[:-keep]:
            shutil.rmtree(self._ckpt_path(epoch), ignore_errors=True)
