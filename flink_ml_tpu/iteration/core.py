"""The iteration runtime: ``iterate`` — jitted SPMD epoch loops.

Re-design of ``Iterations.java:104-286`` + the whole operator/wrapper
machinery it drives.  80% of the reference's 16k-line iteration module exists
to retrofit cycles, BSP epoch alignment, per-round state and exactly-once
checkpointing onto an acyclic streaming engine (SURVEY §7).  On TPU none of
that machinery is needed:

- feedback edge      -> the state pytree stays in HBM (donated jit buffers),
                        replacing FeedbackChannel + Head/Tail operators
- epoch watermark    -> the jitted step boundary *is* the superstep barrier
                        (SPMD alignment is implicit), replacing
                        OperatorEpochWatermarkTracker + SharedProgressAligner
- termination vote   -> a device scalar reduced inside the step (psum over
                        the mesh), replacing SubtaskAlignedEvent/
                        GloballyAlignedEvent RPC
- replayed inputs    -> device-resident arrays are "replayed" for free each
                        epoch (they never left HBM), replacing ReplayOperator's
                        disk cache re-reads
- per-round state    -> functional re-initialisation per epoch, replacing
                        reflective state-backend scrubbing

Two execution modes:
- **fused**: the entire loop compiles to one XLA program (lax.scan or
  lax.while_loop) — zero host round-trips per epoch; listeners can't fire.
- **hosted**: python loop around a jitted step — per-epoch listener
  callbacks, streaming data sources, and checkpoint/resume.
"""

from __future__ import annotations

import dataclasses

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Iterator, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .body import (
    EpochContext,
    IterationBodyResult,
    IterationConfig,
    IterationListener,
    OperatorLifeCycle,
    Workset,
    active_fraction,
    normalize_body_result,
)
from .checkpoint import CheckpointConfig, CheckpointManager

__all__ = ["iterate", "IterationResult"]

BodyFn = Callable[..., Any]


@dataclass
class IterationResult:
    """Final state + collected outputs (the analog of the iteration's output
    streams after ``OutputOperator`` unwrapping).

    ``workset`` is the final :class:`Workset` of a workset iteration (None
    otherwise).  ``side["epoch_trace"]`` (criteria-driven fused loops and
    per-epoch hosted loops) holds the per-epoch convergence curves —
    ``{"active_fraction": (num_epochs,), "termination": (num_epochs,)}``
    host arrays — that would otherwise die inside the fused while_loop."""

    state: Any
    outputs: Any
    num_epochs: int
    side: dict
    workset: Any = None


def _private_copy(state: Any) -> Any:
    """Copy the caller's state pytree before the loop donates its buffers —
    donation must consume *our* copy, never arrays the caller still holds."""
    return jax.tree_util.tree_map(
        lambda x: x.copy() if isinstance(x, jax.Array) else jnp.asarray(x),
        state)


def _vote_continue(vote: Any) -> bool:
    """Reference rule: continue while the criteria stream is non-empty /
    feedback record count nonzero (``SharedProgressAligner.java:277-300``)."""
    return bool(jax.device_get(vote))


class Replayed:
    """Marks a bounded input replayed identically every epoch (the analog of
    ``ReplayableDataStreamList.replay(...)``).  On TPU a replayed input is
    simply device-resident — replay costs nothing."""

    def __init__(self, value: Any):
        self.value = value


class PerEpoch:
    """Marks a per-epoch source: a callable ``f(epoch) -> pytree`` or an
    iterable consumed one item per epoch (the analog of
    ``ReplayableDataStreamList.notReplay(...)`` / an unbounded stream).
    Exhaustion of any PerEpoch iterator ends the iteration."""

    def __init__(self, source: Any):
        self.source = source


class _Feed:
    """One normalized leaf source."""

    def __init__(self, raw: Any):
        self.static = None
        self.fn = None
        self.it: Optional[Iterator] = None
        if callable(raw):
            self.fn = raw
        elif hasattr(raw, "__next__"):
            self.it = raw
        elif hasattr(raw, "__iter__") and not isinstance(raw, (dict, tuple,
                                                              list, str)):
            # keep the original object reachable for snapshot/restore
            self.source = raw
            self.it = iter(raw)
        else:
            self.static = raw
        if not hasattr(self, "source"):
            self.source = raw


class _DataProvider:
    """Adapts the ``data`` argument to a per-epoch feed.

    - None                  -> body gets data=None every epoch
    - pytree of arrays      -> replayed: same device-resident pytree each epoch
    - callable / iterator   -> per-epoch source (exhaustion = stream end)
    - Replayed(x)/PerEpoch(s) markers, possibly MIXED one level deep inside a
      dict/tuple/list — the ``ReplayableDataStreamList`` analog: e.g.
      ``{"train": Replayed(points), "stream": PerEpoch(reader)}``
    """

    def __init__(self, data: Any):
        self.exhausted = False
        self._container: Optional[type] = None
        self._keys = None
        self._feeds = None
        self._single: Optional[_Feed] = None

        data = self._unwrap(data)
        if isinstance(data, _Feed):
            self._single = data
            return
        if isinstance(data, dict) and any(
                isinstance(v, (Replayed, PerEpoch)) for v in data.values()):
            self._container = dict
            self._keys = list(data.keys())
            self._feeds = [self._unwrap(data[k], force=True)
                           for k in self._keys]
            return
        if isinstance(data, (tuple, list)) and any(
                isinstance(v, (Replayed, PerEpoch)) for v in data):
            self._container = type(data)
            self._feeds = [self._unwrap(v, force=True) for v in data]
            return
        # plain pytree (or None): replayed static data
        self._single = _Feed(None)
        self._single.static = data
        self._single.source = data

    @staticmethod
    def _unwrap(value: Any, force: bool = False):
        if isinstance(value, Replayed):
            feed = _Feed(None)
            feed.static = value.value
            feed.source = value.value
            return feed
        if isinstance(value, PerEpoch):
            return _Feed(value.source)
        if force:
            feed = _Feed(None)
            feed.static = value
            feed.source = value
            return feed
        if value is None or isinstance(value, (dict, tuple, list)) \
                or hasattr(value, "shape"):
            return value
        return _Feed(value)

    def _all_feeds(self):
        if self._single is not None:
            return [self._single]
        return self._feeds

    @property
    def is_static(self) -> bool:
        return all(f.fn is None and f.it is None for f in self._all_feeds())

    def _pull(self, feed: _Feed, epoch: int) -> Any:
        if feed.it is not None:
            try:
                return next(feed.it)
            except StopIteration:
                self.exhausted = True
                return None
        if feed.fn is not None:
            return feed.fn(epoch)
        return feed.static

    def __call__(self, epoch: int) -> Any:
        if self._single is not None:
            return self._pull(self._single, epoch)
        values = [self._pull(f, epoch) for f in self._feeds]
        if self.exhausted:
            return None
        if self._container is dict:
            return dict(zip(self._keys, values))
        return self._container(values)

    def snapshot(self) -> Optional[dict]:
        # Single-feed caches keep the source's raw snapshot format (what
        # checkpoints have always stored); multi-feed providers wrap the
        # per-feed snapshots in an index-keyed envelope.
        feeds = self._all_feeds()
        if self._single is not None:
            src = self._single.source
            live = self._single.fn is not None or self._single.it is not None
            return src.snapshot() if live and hasattr(src, "snapshot") else None
        snaps = {}
        for i, feed in enumerate(feeds):
            live = feed.fn is not None or feed.it is not None
            if live and hasattr(feed.source, "snapshot"):
                snaps[str(i)] = feed.source.snapshot()
        return {"__feeds__": snaps} if snaps else None

    def restore(self, snap: dict) -> None:
        if "__feeds__" in snap:
            for i, feed in enumerate(self._all_feeds()):
                sub = snap["__feeds__"].get(str(i))
                if sub is not None and hasattr(feed.source, "restore"):
                    feed.source.restore(sub)
            return
        # raw single-source snapshot (incl. checkpoints from older runs)
        if self._single is not None and hasattr(self._single.source, "restore"):
            self._single.source.restore(snap)


def _call_body(body: BodyFn, state, epoch, data) -> IterationBodyResult:
    if data is None:
        return normalize_body_result(body(state, epoch))
    return normalize_body_result(body(state, epoch, data))


def iterate(
    body: BodyFn,
    initial_state: Any,
    data: Any = None,
    *,
    config: Optional[IterationConfig] = None,
    max_epochs: Optional[int] = None,
    steps_per_dispatch: Optional[int] = None,
    listeners: Sequence[IterationListener] = (),
    per_round_init: Optional[Callable[[], Any]] = None,
    per_round: Optional[Sequence[str]] = None,
    workset: Optional[Workset] = None,
    workset_tol: float = 0.0,
    checkpoint: Optional[Union[CheckpointConfig, CheckpointManager]] = None,
    resume: bool = False,
) -> IterationResult:
    """Run an iteration (the analog of
    ``Iterations.iterateBoundedStreamsUntilTermination``,
    ``Iterations.java:149-170``).

    ``body(state, epoch[, data]) -> IterationBodyResult | state |
    (state[, outputs[, termination]])``.  Epoch semantics mirror
    ``Iterations.java:69-83``: state entering epoch ``e`` produces the state
    for epoch ``e+1`` (the feedback edge increments the epoch).

    **Mixed lifecycle** (``per_round=``): the ``IterationBody.forEachRound``
    analog (``IterationBody.java:73-91``) — name top-level keys of a dict
    state that are re-initialised from ``initial_state`` at the start of
    every epoch while the rest of the state is carried.  Where the reference
    builds a per-round sub-graph whose operators are recreated and scrubbed
    each round (``BoundedMixedLifeCycleStreamIterationITCase``), here the
    named subtree simply re-enters each epoch at its initial value — the
    final result keeps the LAST round's values (what ``forEachRound``'s
    output forwarding yields).  Works in both fused and hosted modes.

    Termination: ``max_epochs`` reached, OR the body's ``termination`` vote
    is zero/false, OR an iterator data source is exhausted, OR — workset
    iterations — the active fraction falls to ``workset_tol``.

    **Workset iterations** (``workset=``): pass the initial
    :class:`Workset` (device-resident active-set mask + optional
    per-element bound state) and a body with the extended signature
    ``body(state, workset, epoch[, data]) ->`` result whose feedback is
    ``(new_state, new_workset)``.  The mask/bounds pytree rides the
    ``lax.scan``/``lax.while_loop`` carry with the state — in hosted mode
    it also rides chunk-boundary checkpoints (GR_STATE_KEY-style), so
    ``resilient_fit`` crash-resume restores mask, bounds, AND the rounds
    run bit-exactly.  The driver terminates when
    :func:`~.body.active_fraction` drops to ``workset_tol`` (default:
    exactly zero — the reference's empty-workset criterion), AND-ed with
    any explicit body vote.  Incompatible with ``per_round=`` and the
    PER_ROUND lifecycle (those re-init state each round; a workset is
    cross-round by definition).

    ``steps_per_dispatch=W`` (hosted mode, device-resident data): scan
    ``W`` epochs per jit dispatch — one host round-trip (and one
    termination-vote sync) per ``W`` epochs instead of per epoch.
    Listener callbacks and checkpoint cuts fire at chunk boundaries
    (``on_epoch_watermark_incremented`` once per chunk, with the last
    completed epoch's context).  Bit-exact vs ``W=1``: a mid-chunk
    termination vote freezes the carried state for the rest of the
    chunk, so the returned state is the voting epoch's feedback exactly
    as in the per-epoch loop.  Ignored (with per-epoch stepping) for
    per-epoch data sources, unjitted bodies, and PER_ROUND lifecycles.
    """
    config = config or IterationConfig()
    if max_epochs is not None:
        config = dataclasses.replace(config, max_epochs=max_epochs)
    if steps_per_dispatch is not None:
        config = dataclasses.replace(config,
                                     steps_per_dispatch=steps_per_dispatch)

    if per_round:
        if not isinstance(initial_state, dict):
            raise TypeError(
                "per_round= names top-level dict keys; state is "
                f"{type(initial_state).__name__}")
        missing = [k for k in per_round if k not in initial_state]
        if missing:
            raise KeyError(f"per_round keys {missing} not in state "
                           f"{list(initial_state)}")
        reset_subtree = {k: _private_copy(initial_state[k])
                        for k in per_round}
        inner_body = body

        def body(state, epoch, *rest):  # noqa: F811
            # Re-entering each epoch at the initial value IS the per-round
            # re-init; at epoch 0 this is a no-op by construction.
            return _call_body(inner_body, {**state, **reset_subtree},
                              epoch, rest[0] if rest else None)

    frac_fn = None
    if workset is not None:
        if not isinstance(workset, Workset):
            raise TypeError(
                f"workset= expects a Workset, got {type(workset).__name__}")
        if per_round or config.lifecycle == OperatorLifeCycle.PER_ROUND:
            raise ValueError(
                "workset iterations are incompatible with per-round "
                "re-initialisation (the workset is cross-round state)")
        ws_body, ws_tol = body, float(workset_tol)

        def body(carry, epoch, *rest):  # noqa: F811
            # The workset rides the carry NEXT TO the user state; the
            # continue-vote is "records still flowing" = active elements
            # remain, AND-ed with any explicit body vote.
            state, ws = carry
            res = normalize_body_result(
                ws_body(state, ws, epoch, *rest) if rest
                else ws_body(state, ws, epoch))
            new_state, new_ws = res.feedback
            cont = active_fraction(new_ws) > ws_tol
            if res.termination is not None:
                cont = jnp.logical_and(
                    cont,
                    jnp.asarray(res.termination).astype(bool).reshape(()))
            return IterationBodyResult((new_state, new_ws), res.outputs,
                                       cont)

        initial_state = (initial_state, workset)
        frac_fn = lambda carry: active_fraction(carry[1])  # noqa: E731

    provider = _DataProvider(data)
    # NOTE: distinct from the per_round= KEY LIST above — this is the
    # whole-state PER_ROUND lifecycle flag from IterationConfig.
    per_round_lifecycle = config.lifecycle == OperatorLifeCycle.PER_ROUND
    if per_round_lifecycle and per_round_init is None:
        # Default per-round re-init: restart every epoch from initial_state.
        init_copy = initial_state
        per_round_init = lambda: init_copy  # noqa: E731

    mode = config.mode
    if mode == "auto":
        fusible = (provider.is_static and not listeners and checkpoint is None
                   and not per_round_lifecycle and config.jit
                   and config.max_epochs is not None)
        if fusible:
            # Criteria-driven fused loops keep only the LAST epoch's outputs
            # (a while_loop can't stack a dynamic number of them) — auto must
            # not silently change output semantics, so probe for a vote and
            # fall back to hosted when one exists.  Explicit mode="fused"
            # opts into last-output semantics.  A workset iteration always
            # votes (the active-fraction criterion), so it stays fusible
            # whenever the body emits NO outputs — then there are no output
            # semantics to lose and the fused while_loop (plus its epoch
            # trace) is the point of the feature.
            probe = jax.eval_shape(
                lambda s, e: _call_body(body, s, e, provider(0)),
                initial_state, jax.ShapeDtypeStruct((), jnp.int32))
            fusible = (probe.termination is None
                       or (workset is not None and probe.outputs is None))
        mode = "fused" if fusible else "hosted"

    if mode == "fused":
        result = _iterate_fused(body, initial_state, provider, config,
                                frac_fn=frac_fn)
    else:
        result = _iterate_hosted(body, initial_state, provider, config,
                                 listeners, per_round_lifecycle,
                                 per_round_init, checkpoint, resume,
                                 frac_fn=frac_fn)
    if workset is not None:
        final_state, final_ws = result.state
        result = dataclasses.replace(result, state=final_state,
                                     workset=final_ws)
    return result


# ---------------------------------------------------------------------------
# fused: whole loop is one XLA program
# ---------------------------------------------------------------------------

def _iterate_fused(body: BodyFn, initial_state, provider: _DataProvider,
                   config: IterationConfig, *,
                   frac_fn: Optional[Callable[[Any], Any]] = None
                   ) -> IterationResult:
    if not provider.is_static:
        raise ValueError("fused mode requires device-resident (static) data")
    if config.max_epochs is None:
        raise ValueError("fused mode requires max_epochs")
    if config.donate_state:
        initial_state = _private_copy(initial_state)
    data = provider(0)
    max_epochs = config.max_epochs

    # Probe the body's output structure without running it.
    probe = jax.eval_shape(
        lambda s, e: _call_body(body, s, e, data),
        initial_state, jax.ShapeDtypeStruct((), jnp.int32))
    has_criteria = probe.termination is not None

    if not has_criteria:
        # Fixed epoch count: lax.scan stacks per-epoch outputs.
        @partial(jax.jit, donate_argnums=(0,) if config.donate_state else ())
        def run(state, data):
            def scan_step(state, epoch):
                res = _call_body(body, state, epoch, data)
                return res.feedback, res.outputs

            return jax.lax.scan(scan_step, state,
                                jnp.arange(max_epochs, dtype=jnp.int32))

        final_state, outputs = run(initial_state, data)
        return IterationResult(final_state, outputs, max_epochs, {})

    # Criteria-driven: lax.while_loop; keeps only the last outputs.
    if probe.outputs is not None:
        import warnings

        warnings.warn(
            "fused iteration with a termination criterion keeps only the "
            "LAST epoch's outputs (a while_loop cannot stack a dynamic "
            "number of them); use mode='hosted' (or carry a fixed-size "
            "buffer in state) to keep the full per-epoch output log",
            stacklevel=3)
    zero_out = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), probe.outputs)

    # Per-epoch convergence curves survive the fused loop in a
    # fixed-size NaN-prefilled StepProbe riding the carry (obs/probe.py
    # — the generalization of the sgd.py loss-log pattern this loop used
    # to hand-roll): a while_loop keeps only its final carry, so
    # anything per-epoch must be indexed into a (max_epochs,) buffer on
    # device.  NaN tail = epochs never run; the probe cursor tracks
    # rounds actually recorded.
    from ..obs.probe import StepProbe

    trace0 = StepProbe.create(("active_fraction", "termination"),
                              max_epochs)

    @partial(jax.jit, donate_argnums=(0,) if config.donate_state else ())
    def run(state, data):
        def cond(carry):
            _, _, epoch, keep_going, _ = carry
            return jnp.logical_and(keep_going, epoch < max_epochs)

        def step(carry):
            state, _, epoch, _, trace = carry
            res = _call_body(body, state, epoch, data)
            vote = jnp.asarray(res.termination)
            keep_going = vote.astype(bool).reshape(())
            frac = (frac_fn(res.feedback) if frac_fn is not None
                    else jnp.asarray(jnp.nan, jnp.float32))
            trace = trace.record_at(
                epoch, active_fraction=frac,
                termination=vote.astype(jnp.float32).reshape(()))
            return res.feedback, res.outputs, epoch + 1, keep_going, trace

        return jax.lax.while_loop(
            cond, step, (state, zero_out, jnp.asarray(0, jnp.int32),
                         jnp.asarray(True), trace0))

    final_state, outputs, num_epochs, _, trace = run(initial_state, data)
    # on a process-spanning mesh the loop counter comes back as a
    # non-fully-addressable replicated scalar; read this host's replica
    from ..parallel.mesh import fetch_replicated

    n_run = int(np.asarray(fetch_replicated(num_epochs)))
    side = {"epoch_trace": trace.fetch(
        get=lambda v: np.asarray(fetch_replicated(v)))}
    return IterationResult(final_state, outputs, n_run, side)


# ---------------------------------------------------------------------------
# hosted: python epoch loop around a jitted step
# ---------------------------------------------------------------------------

def _iterate_hosted(body: BodyFn, initial_state, provider: _DataProvider,
                    config: IterationConfig,
                    listeners: Sequence[IterationListener],
                    per_round_lifecycle: bool, per_round_init,
                    checkpoint, resume: bool, *,
                    frac_fn: Optional[Callable[[Any], Any]] = None
                    ) -> IterationResult:
    donating = (config.jit and config.donate_state
                and not per_round_lifecycle)
    if config.jit:
        # Donating the state argument keeps HBM flat across epochs: the new
        # feedback pytree reuses the old buffers (the in-place feedback edge).
        step = jax.jit(
            lambda s, e, d: _call_body(body, s, e, d),
            donate_argnums=(0,) if donating else ())
    else:
        step = lambda s, e, d: _call_body(body, s, e, d)  # noqa: E731

    # Chunked dispatch (steps_per_dispatch=W > 1): one jitted lax.scan
    # runs W epochs per host round-trip — per-epoch data sources can't
    # chunk (the host pulls between epochs), and unjitted/per-round
    # bodies keep the classic loop.
    W = config.steps_per_dispatch
    chunked = (W > 1 and config.jit and provider.is_static
               and not per_round_lifecycle)
    if chunked:
        @partial(jax.jit, static_argnums=(3,),
                 donate_argnums=(0,) if donating else ())
        def chunk_step(state, e0, data, w: int):
            def scan_step(carry, epoch):
                state, alive = carry
                res = _call_body(body, state, epoch, data)
                # a dead step (post-vote) freezes the carry, so the
                # returned state is the VOTING epoch's feedback — the
                # exact per-epoch-loop semantics
                new_state = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(alive, n, o),
                    res.feedback, state)
                vote = (jnp.asarray(res.termination)
                        .astype(bool).reshape(())
                        if res.termination is not None
                        else jnp.asarray(True))
                return ((new_state, jnp.logical_and(alive, vote)),
                        (res.outputs, alive))
            (state, alive), (outs, ran) = jax.lax.scan(
                scan_step, (state, jnp.asarray(True)),
                e0 + jnp.arange(w, dtype=jnp.int32))
            return state, alive, outs, ran

    manager: Optional[CheckpointManager] = None
    if isinstance(checkpoint, CheckpointManager):
        manager = checkpoint
    elif isinstance(checkpoint, CheckpointConfig):
        manager = CheckpointManager(checkpoint)

    # Does any listener actually consume the checkpoint hook?  Only then
    # must an async save land before the hook fires (its contract is
    # durability); listeners that never override it keep the full
    # async-save overlap.
    wants_ckpt_hook = any(
        type(lst).on_checkpoint_saved
        is not IterationListener.on_checkpoint_saved
        for lst in listeners)

    state = _private_copy(initial_state) if donating else initial_state
    start_epoch = 0
    resumed_terminated = False
    if manager is not None and resume:
        restored = manager.restore_latest()
        if restored is not None:
            start_epoch, state, meta = restored
            resumed_terminated = bool(meta.get("terminated"))
            snap = meta.get("source_snapshot")
            if snap:
                provider.restore(snap)
    if resumed_terminated:
        # The checkpointed run had already voted to terminate at this epoch:
        # re-running the body would diverge from the uninterrupted run.
        ctx = EpochContext(epoch=start_epoch, state=state, terminated=True)
        for listener in listeners:
            listener.on_iteration_terminated(ctx)
        return IterationResult(state, [], start_epoch,
                               {"termination_reason": "criteria"})

    outputs_log = []
    side: dict = {}
    # Per-epoch convergence curves (per-epoch stepping only): device
    # scalars collected WITHOUT syncing — one batched fetch at the end.
    # Covers the epochs run in THIS call (a resumed run's earlier curve
    # lives with the earlier call).
    trace_frac: list = []
    trace_term: list = []
    epoch = start_epoch
    terminated_reason = "max_epochs"
    from ..robustness.faults import fault_point

    try:
        while config.max_epochs is None or epoch < config.max_epochs:
            # fault seam: lets the chaos suite kill a hosted iteration
            # mid-run at a chosen epoch even when the data is static
            # (stream sources are instead wrapped at the pull —
            # robustness.FaultPlan.wrap_source)
            fault_point("iterate.epoch")
            epoch_data = provider(epoch)
            if provider.exhausted:
                terminated_reason = "stream_end"
                break
            if chunked:
                from ..parallel.mesh import fetch_replicated

                w = (W if config.max_epochs is None
                     else min(W, config.max_epochs - epoch))
                state, alive, outs, ran = chunk_step(
                    state, jnp.asarray(epoch, jnp.int32), epoch_data, w)
                # ONE host sync per chunk: which scan steps ran, and
                # whether the vote says continue
                ran_h = np.asarray(fetch_replicated(ran)).astype(bool)
                alive_h = bool(np.asarray(fetch_replicated(alive)))
                n_run = int(ran_h.sum())
                last_outputs = None
                if outs is not None:
                    for i in range(w):
                        if ran_h[i]:
                            last_outputs = jax.tree_util.tree_map(
                                lambda x, i=i: x[i], outs)
                            outputs_log.append(last_outputs)
                epoch += n_run
                ctx = EpochContext(epoch=epoch - 1, state=state,
                                   outputs=last_outputs, side=side)
                for listener in listeners:
                    listener.on_epoch_watermark_incremented(epoch - 1, ctx)
                stop = not alive_h
                if manager is not None and (
                        stop or any(manager.should_save(e) for e in
                                    range(epoch - n_run + 1, epoch + 1))):
                    extra = {"terminated": stop}
                    snap = provider.snapshot()
                    if snap:
                        extra["source_snapshot"] = snap
                    if getattr(manager.config, "async_save", False):
                        to_save = (_private_copy(state) if donating
                                   else state)
                        manager.save_async(epoch, to_save, extra)
                        if wants_ckpt_hook:
                            manager.wait()   # hook promises durability
                    else:
                        manager.save(epoch, state, extra)
                    if wants_ckpt_hook:
                        for listener in listeners:
                            listener.on_checkpoint_saved(epoch - 1, ctx)
                if stop:
                    terminated_reason = "criteria"
                    break
                continue
            if per_round_lifecycle and epoch > start_epoch:
                state = per_round_init()
            res = step(state, jnp.asarray(epoch, jnp.int32), epoch_data)
            state = res.feedback
            if res.outputs is not None:
                outputs_log.append(res.outputs)
            if frac_fn is not None:
                # Eager tiny op on the fresh feedback buffers — dispatched
                # before the next donating step call, so donation can't
                # invalidate what it reads; no host sync here.
                trace_frac.append(frac_fn(state))
                trace_term.append(res.termination)

            ctx = EpochContext(epoch=epoch, state=state, outputs=res.outputs,
                               side=side)
            for listener in listeners:
                listener.on_epoch_watermark_incremented(epoch, ctx)

            epoch += 1
            stop = (res.termination is not None
                    and not _vote_continue(res.termination))
            if manager is not None and (manager.should_save(epoch) or stop):
                # The vote travels with the checkpoint: resuming from a
                # checkpoint of a terminated run must not re-run the body.
                extra = {"terminated": stop}
                snap = provider.snapshot()
                if snap:
                    extra["source_snapshot"] = snap
                if getattr(manager.config, "async_save", False):
                    # Only copy when the loop donates the live buffers the
                    # background thread would otherwise read.
                    to_save = _private_copy(state) if donating else state
                    manager.save_async(epoch, to_save, extra)
                    if wants_ckpt_hook:
                        manager.wait()   # hook promises durability
                else:
                    manager.save(epoch, state, extra)
                if wants_ckpt_hook:
                    for listener in listeners:
                        listener.on_checkpoint_saved(epoch - 1, ctx)
            if stop:
                terminated_reason = "criteria"
                break
    except BaseException:
        # Land any in-flight async save so the newest checkpoint isn't torn
        # by interpreter exit; swallow its error — the loop's own exception
        # is the one the caller must see.
        if manager is not None:
            try:
                manager.wait()
            except Exception:
                pass
        raise

    if manager is not None:
        manager.wait()  # land any in-flight async save before returning

    final_ctx = EpochContext(epoch=epoch, state=state, terminated=True,
                             side=side)
    for listener in listeners:
        listener.on_iteration_terminated(final_ctx)

    side["termination_reason"] = terminated_reason
    if trace_frac:
        side["epoch_trace"] = {
            "active_fraction": np.asarray(
                jax.device_get(trace_frac), np.float32),
            "termination": np.asarray(
                jax.device_get(trace_term), np.float32),
        }
    return IterationResult(state, outputs_log, epoch, side)
