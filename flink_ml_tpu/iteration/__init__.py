from .body import (  # noqa: F401
    EpochContext,
    FnListener,
    IterationBodyResult,
    IterationConfig,
    IterationListener,
    OperatorLifeCycle,
    Workset,
    active_fraction,
)
from .checkpoint import (  # noqa: F401
    CheckpointConfig,
    CheckpointManager,
    load_pytree,
    save_pytree,
)
from .core import IterationResult, PerEpoch, Replayed, iterate  # noqa: F401
