"""Deterministic fault injection — failure as a reproducible test input.

A :class:`FaultPlan` schedules faults against named **scopes** — the
instrumented seams of the stack::

    source.pull        data-source pulls (plan.wrap_source(reader))
    checkpoint.write   checkpoint commit (iteration/checkpoint.py)
    wal.append         window-log appends (data/wal.py)
    persist.write      stage model-array saves (utils/persist.py)
    serving.load       registry model loads (serving/registry.py)
    serving.warm_up    executor warm-up (serving/executor.py)
    serving.predict    executor predict calls

Each scope keeps an invocation counter; a fault fires when the counter
hits a scheduled index.  Explicit schedules (:meth:`FaultPlan.inject`)
and seeded random ones (:meth:`FaultPlan.inject_random`) are both fully
deterministic — same plan, same faults, so every recovery test replays
bit-identically.  MLFabric's stance applies: training must tolerate a
lossy substrate rather than assume a perfect one, and the only way to
*test* that is to make the substrate lossy on demand.

Fault kinds:

- ``"transient"`` — raises :class:`InjectedTransientError` (an
  ``IOError`` with ``transient = True``, the marker
  :func:`~.retry.default_classify` treats as retryable) *before* the
  wrapped operation runs, so a retry is lossless;
- ``"crash"`` — raises :class:`InjectedCrash`: the simulated process
  death the supervisor (:func:`~.supervisor.resilient_fit`) heals;
- ``"enospc"`` — raises :class:`InjectedDiskFullError`
  (``errno.ENOSPC``; classified fatal, not retryable);
- ``"torn"`` / ``"flip"`` — **data** faults at file scopes: the bytes
  just written are truncated / bit-flipped *before* the commit rename,
  producing a committed-but-invalid artifact that only manifest/CRC
  validation (:mod:`.durability`) can catch;
- ``"preempt"`` / ``"join"`` — **membership** faults (elastic PR):
  raise :class:`InjectedPreemption` / :class:`InjectedJoin` at the
  seam, which the elastic coordinator's chunk-boundary ``poll``
  (``parallel/elastic.py``) translates into a deterministic
  leave/join transition.  Seedable like every other kind
  (:meth:`FaultPlan.inject_random` works unchanged), and — because
  :meth:`FaultPlan.fire` runs BEFORE the wrapped operation —
  ``wrap_source``-style wrappers stay lossless across a resize: a
  membership fault never consumes an item;
- ``"chip_down"`` / ``"chip_flap"`` — **fleet** faults (serving
  failover, ISSUE 20): raise :class:`InjectedChipDown` /
  :class:`InjectedChipFlap` at the scheduler's DISPATCH boundary
  (``serving.dispatch`` — fired before ``predict`` runs, so the
  picked micro-batch is requeued intact and the schedule stays
  lossless/replayable).  The attached
  :class:`~flink_ml_tpu.serving.failover.FailoverDriver` translates
  the raise into a deterministic chip-death (``chip_flap`` adds a
  scheduled recovery) exactly like the membership pair above.

Control faults (transient/crash/enospc, the membership pair, and the
fleet pair) are valid at every scope; data faults only where a file
path reaches the injection point.
"""

from __future__ import annotations

import errno
import os

from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = [
    "FaultPlan", "InjectedChipDown", "InjectedChipFlap", "InjectedCrash",
    "InjectedDiskFullError", "InjectedJoin", "InjectedPreemption",
    "InjectedTransientError", "corrupt_file", "fault_point", "active_plan",
]


class InjectedTransientError(IOError):
    """A retryable injected fault (``transient = True`` is the marker
    :func:`~.retry.default_classify` keys on)."""

    transient = True


class InjectedCrash(RuntimeError):
    """Simulated process death: not retryable at the call site (a retry
    loop must NOT swallow it), recoverable by the supervisor via
    checkpoint restore + replay."""


class InjectedDiskFullError(OSError):
    def __init__(self, message: str):
        super().__init__(errno.ENOSPC, message)


class InjectedPreemption(RuntimeError):
    """A membership fault: the scheduler reclaimed a worker.  Raised at
    the seam BEFORE the wrapped operation (nothing is consumed — the
    lossless ``wrap_source`` contract holds across a resize) and
    translated by the elastic coordinator's ``poll`` into a
    deterministic leave transition; it is NOT a retryable error and
    must never be swallowed by a retry loop."""


class InjectedJoin(RuntimeError):
    """The membership fault dual of :class:`InjectedPreemption`: a new
    worker asks to join.  Same raise-before-the-operation contract;
    translated by the coordinator's ``poll`` into a join transition."""


class InjectedChipDown(RuntimeError):
    """A fleet fault: one serving chip died.  Raised at the DISPATCH
    boundary BEFORE the micro-batch's predict runs (nothing is served,
    nothing is lost — the scheduler requeues the picked requests with
    their futures intact) and translated by the attached failover
    driver into a deterministic chip-death transition; NOT retryable at
    the call site and never swallowed by a retry loop."""


class InjectedChipFlap(RuntimeError):
    """The flapping dual of :class:`InjectedChipDown`: the chip dies and
    comes back shortly after (a deterministic number of health polls
    later).  Same raise-before-dispatch lossless contract; the failover
    driver's hysteresis is what keeps the flap from thrashing
    placements."""


_CONTROL_KINDS = ("transient", "crash", "enospc", "preempt", "join",
                  "chip_down", "chip_flap")
_DATA_KINDS = ("torn", "flip")


def _flip_offset(path: str, size: int, draw: int) -> int:
    """A seeded offset guaranteed to hit PAYLOAD bytes.  Zip containers
    (npz) get a flip inside the largest member's CRC-covered data — a
    blind offset could land in header/directory slack the reader
    tolerates, making the 'corruption' a silent no-op; other formats get
    the middle third (clear of magic bytes and trailers)."""
    import zipfile

    try:
        with zipfile.ZipFile(path) as zf:
            info = max(zf.infolist(), key=lambda z: z.compress_size,
                       default=None)
        if info is not None and info.compress_size > 0:
            with open(path, "rb") as f:
                f.seek(info.header_offset)
                hdr = f.read(30)
            name_len = hdr[26] | (hdr[27] << 8)
            extra_len = hdr[28] | (hdr[29] << 8)
            start = info.header_offset + 30 + name_len + extra_len
            return start + draw % info.compress_size
    except (zipfile.BadZipFile, OSError, IndexError):
        pass
    span = max(1, size // 3)
    return size // 3 + draw % span


def corrupt_file(path: str, mode: str = "flip", seed: int = 0) -> None:
    """Deterministically damage ``path`` in place: ``"flip"`` XORs one
    byte at a seeded offset in the file's middle third (the payload
    region — container formats like zip tolerate flips in their header/
    directory slack, which would make the corruption a no-op), ``"torn"``
    truncates to a seeded fraction (a torn write's committed prefix).
    The standalone helper tests and bench use to corrupt
    *already-committed* artifacts."""
    size = os.path.getsize(path)
    if size == 0:
        raise ValueError(f"cannot corrupt empty file {path!r}")
    # LCG on the seed: cheap, deterministic, no RNG object needed
    draw = seed * 2654435761 + 12345
    if mode == "flip":
        offset = _flip_offset(path, size, draw)
        with open(path, "r+b") as f:
            f.seek(offset)
            byte = f.read(1)
            f.seek(offset)
            f.write(bytes([byte[0] ^ 0xFF]))
    elif mode == "torn":
        # keep at least one byte, drop at least one: a prefix, never all
        keep = max(1, min(size - 1, draw % size))
        with open(path, "r+b") as f:
            f.truncate(keep)
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")


@dataclass
class _FaultSpec:
    scope: str
    indices: Tuple[int, ...]
    kind: str
    remaining: int


@dataclass
class FaultPlan:
    """A reproducible schedule of faults over scoped invocation counters.

    Activate with ``with plan: ...`` (sets the process-wide active plan
    the :func:`fault_point` seams consult — worker threads inside the
    block see it too), or pass the plan explicitly where an API takes
    one (``plan.wrap_source``).  ``fires`` records every fault that
    actually fired as ``(scope, index, kind)`` — the audit log recovery
    tests and the bench's steps-replayed accounting read."""

    seed: int = 0
    _specs: List[_FaultSpec] = field(default_factory=list)
    _counters: Dict[str, int] = field(default_factory=dict)
    fires: List[Tuple[str, int, str]] = field(default_factory=list)

    # -- scheduling --------------------------------------------------------
    def inject(self, scope: str, *, at: int, kind: str = "transient",
               times: int = 1) -> "FaultPlan":
        """Fire ``kind`` at invocation ``at`` of ``scope`` (0-based), and
        at each subsequent invocation until it has fired ``times`` times
        — ``times=2`` at a retried call site exercises back-to-back
        transient failures."""
        if kind not in _CONTROL_KINDS + _DATA_KINDS:
            raise ValueError(f"unknown fault kind {kind!r}")
        if times < 1:
            raise ValueError("times must be >= 1")
        self._specs.append(_FaultSpec(
            scope, tuple(range(at, at + times)), kind, times))
        return self

    def inject_random(self, scope: str, *, rate: float, horizon: int,
                      kind: str = "transient") -> "FaultPlan":
        """Seeded Bernoulli schedule: each of the first ``horizon``
        invocations of ``scope`` fires with probability ``rate``.  The
        draw depends only on ``(seed, scope, kind)`` — same plan, same
        fault indices, run after run."""
        if not 0.0 <= rate <= 1.0:
            raise ValueError("rate must be in [0, 1]")
        import numpy as np
        import zlib

        # crc32, not hash(): str hashing is salted per-process, which
        # would make the schedule unreproducible across runs
        key = zlib.crc32(f"{self.seed}:{scope}:{kind}".encode())
        draws = np.random.default_rng(key).random(horizon)
        indices = tuple(int(i) for i in np.nonzero(draws < rate)[0])
        if indices:
            self._specs.append(_FaultSpec(scope, indices, kind,
                                          len(indices)))
        return self

    def scheduled(self, scope: str) -> List[Tuple[int, str]]:
        """The (index, kind) schedule for ``scope`` — what WILL fire."""
        out = [(i, s.kind) for s in self._specs if s.scope == scope
               for i in s.indices]
        return sorted(out)

    # -- firing ------------------------------------------------------------
    def fire(self, scope: str, path: Optional[str] = None) -> None:
        """One invocation of ``scope``: bump the counter and fire any
        scheduled fault.  Control faults raise; data faults damage
        ``path`` in place and return (the caller then commits the
        damaged bytes — the torn-write model)."""
        idx = self._counters.get(scope, 0)
        self._counters[scope] = idx + 1
        for spec in self._specs:
            if (spec.scope != scope or spec.remaining <= 0
                    or idx not in spec.indices):
                continue
            spec.remaining -= 1
            self.fires.append((scope, idx, spec.kind))
            if spec.kind == "transient":
                raise InjectedTransientError(
                    f"injected transient fault at {scope}[{idx}]")
            if spec.kind == "crash":
                raise InjectedCrash(
                    f"injected crash at {scope}[{idx}]")
            if spec.kind == "enospc":
                raise InjectedDiskFullError(
                    f"injected ENOSPC at {scope}[{idx}]")
            if spec.kind == "preempt":
                raise InjectedPreemption(
                    f"injected preemption at {scope}[{idx}]")
            if spec.kind == "join":
                raise InjectedJoin(
                    f"injected join at {scope}[{idx}]")
            if spec.kind == "chip_down":
                raise InjectedChipDown(
                    f"injected chip death at {scope}[{idx}]")
            if spec.kind == "chip_flap":
                raise InjectedChipFlap(
                    f"injected chip flap at {scope}[{idx}]")
            if path is None:
                raise ValueError(
                    f"data fault {spec.kind!r} scheduled at {scope}[{idx}] "
                    "but the injection point carries no file path; data "
                    "faults only apply to file-write scopes")
            corrupt_file(path, mode=spec.kind, seed=self.seed + idx)

    def wrap_source(self, source: Any,
                    scope: str = "source.pull") -> "FaultySource":
        """Wrap an iterable so each pull passes through :meth:`fire`
        BEFORE the underlying ``next`` — a transient fault never consumes
        an item, so retrying the pull is lossless."""
        return FaultySource(source, self, scope)

    # -- activation --------------------------------------------------------
    def __enter__(self) -> "FaultPlan":
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("another FaultPlan is already active")
        _ACTIVE = self
        return self

    def __exit__(self, *exc_info) -> None:
        global _ACTIVE
        _ACTIVE = None


class FaultySource:
    """Iterator wrapper from :meth:`FaultPlan.wrap_source`.  Deliberately
    a class, not a generator: a generator that raises is dead forever,
    while this ``__next__`` can raise a transient fault and then serve
    the SAME item on the retried call."""

    def __init__(self, source: Any, plan: FaultPlan, scope: str):
        self._it = iter(source)
        self._plan = plan
        self._scope = scope

    def __iter__(self) -> Iterator[Any]:
        return self

    def __next__(self) -> Any:
        self._plan.fire(self._scope)
        return next(self._it)


#: The process-wide active plan (``with plan:``).  A plain global, not a
#: thread-local, on purpose: faults must reach the prefetch/serve worker
#: threads spawned inside the activation block.
_ACTIVE: Optional[FaultPlan] = None


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE


def fault_point(scope: str, path: Optional[str] = None) -> None:
    """The injection seam the durability/serving layers call at their
    I/O boundaries.  No active plan (production) = one ``is None`` check
    and out."""
    if _ACTIVE is not None:
        _ACTIVE.fire(scope, path)
