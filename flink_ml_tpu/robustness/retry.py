"""Retry with classified exponential backoff.

The reference rides Flink's restart strategies (fixed-delay /
failure-rate) for transient task failures; the TPU-native stack needs
the same distinction at its I/O seams: a flaky NFS read or a brief
relay drop should cost one backoff sleep, while a corrupt checkpoint or
a schema error must fail fast so the *recovery* layer (restore +
replay, :mod:`.supervisor`) — not a blind retry loop — handles it.

Classification contract (:func:`default_classify`):

| class | examples | retried? |
|---|---|---|
| deadline exceeded | :class:`DeadlineExceededError`, any exc with ``deadline_exceeded = True`` | **no** |
| marked transient | :class:`~.faults.InjectedTransientError`, any exc with ``transient = True`` | yes |
| connection/timeout | ``ConnectionError``, ``TimeoutError`` | yes |
| transient errnos | ``EAGAIN``/``EINTR``/``EIO``/``EBUSY``/``ETIMEDOUT``/``ECONNRESET`` | yes |
| everything else | ``ENOSPC``, corrupt state, ``ValueError``, crashes | no |

Deadline-exceeded outranks the timeout rule on purpose (ISSUE 20): a
hedged or requeued serving request that is already past its SLO
deadline must SHED — the answer is worthless to the caller now, and a
retry would burn survivor capacity exactly when a failover has made
capacity scarce.  :class:`DeadlineExceededError` subclasses
``TimeoutError`` so generic timeout handlers still catch it, but the
``deadline_exceeded`` marker is checked FIRST so no retry loop ever
resurrects it.

The backoff schedule is pure arithmetic over the attempt index
(``base * multiplier**i`` capped at ``max_delay`` — no RNG, no wall
clock), and ``sleep`` is injectable, so tests assert the exact schedule
under a fake clock.
"""

from __future__ import annotations

import errno
import time

from dataclasses import dataclass, field
from typing import Any, Callable, List

__all__ = ["DeadlineExceededError", "RetryPolicy", "RetryingIterator",
           "StreamRetryUnsupported", "default_classify", "retry_call",
           "TRANSIENT_ERRNOS"]

#: errno values worth one more try: the OS said "later", not "never".
TRANSIENT_ERRNOS = frozenset({
    errno.EAGAIN, errno.EINTR, errno.EIO, errno.EBUSY,
    errno.ETIMEDOUT, errno.ECONNRESET,
})


class DeadlineExceededError(TimeoutError):
    """A request blew past its SLO deadline (hedged/requeued serving
    traffic after a failover is the canonical producer).  Fatal, not
    retryable: the ``deadline_exceeded`` marker is classified BEFORE
    the generic-``TimeoutError``-is-retryable rule, because retrying an
    already-worthless answer burns survivor capacity exactly when a
    chip loss has made it scarce — the request must shed instead."""

    deadline_exceeded = True


def default_classify(exc: BaseException) -> bool:
    """True = retryable.  See the module-doc table."""
    if getattr(exc, "deadline_exceeded", False):
        # checked before everything: DeadlineExceededError IS a
        # TimeoutError, and the marker must outrank that retryable rule
        return False
    if getattr(exc, "transient", False):
        return True
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return True
    if isinstance(exc, OSError) and exc.errno in TRANSIENT_ERRNOS:
        return True
    return False


@dataclass
class RetryPolicy:
    """Exponential backoff over classified errors.

    ``call(fn, *args)`` runs ``fn`` up to ``max_attempts`` times,
    sleeping ``delay(i)`` after retryable failure ``i``; a non-retryable
    error (or exhaustion) re-raises the underlying exception unchanged,
    so callers' except clauses keep seeing the real failure type.
    ``attempts``/``slept`` record the policy's lifetime totals (the
    observability hook prefetch stats and tests read)."""

    max_attempts: int = 4
    base_delay: float = 0.05
    multiplier: float = 2.0
    max_delay: float = 2.0
    classify: Callable[[BaseException], bool] = default_classify
    sleep: Callable[[float], None] = time.sleep
    attempts: int = 0
    retries: int = 0
    slept: List[float] = field(default_factory=list)

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.base_delay < 0 or self.max_delay < 0:
            raise ValueError("delays must be non-negative")

    def delay(self, attempt: int) -> float:
        """Backoff after failed attempt ``attempt`` (0-based) — pure
        arithmetic, deterministic under test."""
        return min(self.base_delay * self.multiplier ** attempt,
                   self.max_delay)

    def call(self, fn: Callable, *args: Any, **kwargs: Any) -> Any:
        for attempt in range(self.max_attempts):
            self.attempts += 1
            try:
                return fn(*args, **kwargs)
            except Exception as exc:  # noqa: BLE001 — classified below
                last = attempt == self.max_attempts - 1
                if last or not self.classify(exc):
                    raise
                self.retries += 1
                pause = self.delay(attempt)
                self.slept.append(pause)
                self.sleep(pause)
        raise AssertionError("unreachable")  # pragma: no cover


def retry_call(fn: Callable, *args: Any,
               policy: RetryPolicy = None, **kwargs: Any) -> Any:
    """Functional convenience: ``retry_call(f, x, policy=p)``."""
    return (policy or RetryPolicy()).call(fn, *args, **kwargs)


class StreamRetryUnsupported(RuntimeError):
    """A transient pull failure killed a bare-generator source, which
    cannot be re-iterated: the retried pull would read ``StopIteration``
    off the dead frame and silently truncate the stream — this loud
    error (deliberately NOT classified retryable) is the safe outcome.
    Wrap the raw object-shaped reader instead of a generator over it."""


class RetryingIterator:
    """Reader/iterator proxy whose pulls retry classified-transient
    errors under ``policy``.

    MUST wrap the RAW source, below any generator adapters — a generator
    that lets an exception propagate is dead forever.  Two recovery
    modes, chosen per failure:

    - the current iterator is a plain object iterator (``FaultySource``,
      any class with ``__next__``): it survived the raise, so the retry
      pulls the SAME iterator again;
    - the current iterator is a GENERATOR (e.g. the one
      ``DataCacheReader.__iter__`` returns): its frame is dead, so the
      retry re-iterates the inner object — cursor-backed readers resume
      exactly at the failed batch, because their cursor lives on the
      READER and only advances on a successful pull.  If the inner
      object IS the dead generator (a bare genexpr was wrapped), there
      is nothing to rebuild from and the pull fails loudly with
      :class:`StreamRetryUnsupported` — never a silent truncation.

    Non-iteration attributes (``seek``/``batch_rows``/``block_order``/
    ``epoch_varying``/...) delegate to the inner object, so the cursor
    and shuffle protocols the streaming fits probe for survive the wrap
    (direct protocol calls like ``read_batch()`` are NOT retried — only
    the iteration path is).
    """

    def __init__(self, inner: Any, policy: RetryPolicy):
        self._inner = inner
        self._policy = policy
        self._it = None

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    def __iter__(self) -> "RetryingIterator":
        self._it = iter(self._inner)
        return self

    def _pull_once(self) -> Any:
        import types

        if self._it is None:
            self._it = iter(self._inner)
        try:
            return next(self._it)
        except StopIteration:
            raise
        except Exception as exc:
            if isinstance(self._it, types.GeneratorType):
                rebuilt = iter(self._inner)
                if rebuilt is self._it:
                    raise StreamRetryUnsupported(
                        "transient error inside a bare generator source "
                        f"({exc!r}); a generator cannot be re-iterated "
                        "after an exception — wrap the underlying "
                        "reader object, not a generator over it") from exc
                self._it = rebuilt
            raise

    def __next__(self) -> Any:
        return self._policy.call(self._pull_once)
