"""Self-healing training drivers.

:func:`resilient_fit` supervises any checkpointing fit — the streaming
``sgd_fit_outofcore`` and the hosted ``iterate`` both speak the same
``(checkpoint=..., resume=...)`` kwargs — and turns a recoverable crash
into an automatic restore-and-continue instead of a dead process:

1. run the fit; on a recoverable failure (injected crash, I/O error),
2. back off (classified, deterministic schedule — :class:`~.retry
   .RetryPolicy` arithmetic), then
3. re-run with ``resume=True``: the fit restores from the newest VALID
   checkpoint (``CheckpointManager.latest()`` quarantines corrupt/
   partial cuts and falls back — :mod:`.durability`), re-seeks or
   replays its source past the cursor (seek protocol / WAL windows),
   and continues as if never interrupted.

Because restore + replay are deterministic (the PR 1/PR 3 crash+resume
guarantee, EF reducer state included), the supervised run's final
params are **bit-exact** vs the uninterrupted run — asserted in
tests/test_faults.py, including with a corrupted newest checkpoint in
the fallback path.

The per-restart :class:`RecoveryEvent` records MTTR (detect -> restore
complete, which is where training resumes) measured against the
manager's restore timestamp — the number ``bench.py::bench_recovery``
reports.
"""

from __future__ import annotations

import time

from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

from ..obs.trace import tracer
from .faults import InjectedCrash
from .retry import RetryPolicy

__all__ = ["RecoveryEvent", "RecoveryReport", "resilient_fit",
           "default_recoverable"]


def default_recoverable(exc: BaseException) -> bool:
    """Can a restore-and-replay heal this?  Crashes and I/O failures
    yes; logic errors (bad config, schema mismatch, corrupt *input*
    data raising ValueError) no — re-running those burns restarts on a
    deterministic failure."""
    return isinstance(exc, (InjectedCrash, OSError, IOError,
                            ConnectionError, TimeoutError))


@dataclass
class RecoveryEvent:
    """One detected failure — or planned fleet resize — + the recovery
    that followed.  ``kind`` is ``"crash"`` (unplanned: injected crash,
    I/O failure, worker death) or ``"resize"`` (planned elasticity: a
    membership change detected at a chunk boundary); both ride the same
    restore-and-continue transition, so ``mttr_s`` doubles as the
    resize-pause wall (detect -> restore complete) the elastic bench
    leg reports."""
    error: str
    detected_at: float
    backoff_s: float = 0.0
    restored_step: Optional[int] = None
    mttr_s: Optional[float] = None   # detect -> restore complete
    kind: str = "crash"
    fleet_size: Optional[int] = None  # live workers AFTER the transition


@dataclass
class RecoveryReport:
    """Filled in place by :func:`resilient_fit` (pass ``report=``).
    Crash-elasticity and planned-elasticity share this one report:
    ``restarts`` counts unplanned recoveries, ``resizes`` counts
    planned membership transitions, and both append to ``events``."""
    restarts: int = 0
    resizes: int = 0
    recovered: bool = False
    events: List[RecoveryEvent] = field(default_factory=list)

    def as_dict(self) -> dict:
        return {
            "restarts": self.restarts,
            "resizes": self.resizes,
            "recovered": self.recovered,
            "events": [{
                "error": e.error,
                "kind": e.kind,
                "fleet_size": e.fleet_size,
                "backoff_s": round(e.backoff_s, 4),
                "restored_step": e.restored_step,
                "mttr_s": (round(e.mttr_s, 4)
                           if e.mttr_s is not None else None),
            } for e in self.events],
        }


def resilient_fit(fit: Callable, *args: Any,
                  checkpoint: Any,
                  max_restarts: int = 3,
                  backoff: Optional[RetryPolicy] = None,
                  recoverable: Callable[[BaseException], bool]
                  = default_recoverable,
                  report: Optional[RecoveryReport] = None,
                  clock: Callable[[], float] = time.perf_counter,
                  elastic: Any = None,
                  max_resizes: int = 64,
                  **kwargs: Any) -> Any:
    """Run ``fit(*args, checkpoint=manager, resume=..., **kwargs)`` under
    supervision; returns whatever ``fit`` returns.

    ``fit`` is any callable taking ``checkpoint``/``resume`` keywords —
    ``sgd_fit_outofcore``, ``iterate``, ``WideDeep.fit_outofcore``, or a
    closure that rebuilds per-attempt state (a fresh ``WindowLog`` over
    a live feed) before delegating.  The first attempt runs with
    ``resume=kwargs.get("resume", False)``; every restart forces
    ``resume=True`` so recovery restores from the newest valid cut and
    replays forward.

    ``checkpoint`` (a ``CheckpointConfig`` or ``CheckpointManager``) is
    normalized to ONE manager shared across attempts, so quarantine
    decisions and save-slot history persist through restarts.  Restarts
    back off on the policy's deterministic schedule (attempt i sleeps
    ``backoff.delay(i)``); a failure that ``recoverable`` rejects — or
    restart ``max_restarts + 1`` — re-raises immediately.

    **Elastic fleets** (``elastic=`` — an
    :class:`~flink_ml_tpu.parallel.elastic.ElasticCoordinator`): the
    supervised fit must accept ``membership=``/``mesh=`` keywords
    (``sgd_fit_outofcore`` and ``WideDeep.fit_outofcore`` do) — both
    are injected per attempt, with the mesh rebuilt from the
    coordinator's CURRENT fleet.  Two transitions share this one loop:

    - *planned elasticity*: the fit raises
      :class:`~flink_ml_tpu.parallel.elastic.ResizeRequested` at a
      chunk boundary after cutting a checkpoint; the supervisor records
      a ``kind="resize"`` event (no backoff, no restart budget
      consumed — a resize is not a failure) and re-runs with
      ``resume=True`` on the new mesh, which restores and re-shards the
      carry there.  ``max_resizes`` bounds a pathological churn loop.
    - *crash elasticity*: any recoverable failure additionally asks the
      coordinator for the post-crash fleet
      (:meth:`~flink_ml_tpu.parallel.elastic.ElasticCoordinator
      .on_failure` — lapsed leases reaped, else the deterministic
      victim), so recovery resumes onto the *surviving* fleet through
      exactly the same restore-and-reshard path.
    """
    # local import: checkpoint.py imports robustness.durability, so a
    # top-level import here would cycle through the package __init__
    from ..iteration.checkpoint import CheckpointConfig, CheckpointManager
    from ..parallel.elastic import ResizeRequested

    manager = (CheckpointManager(checkpoint)
               if isinstance(checkpoint, CheckpointConfig) else checkpoint)
    if not isinstance(manager, CheckpointManager):
        raise TypeError(
            "resilient_fit needs a CheckpointConfig/CheckpointManager "
            f"(got {type(checkpoint).__name__}): without durable cuts "
            "there is nothing to recover from")
    # MTTR subtracts the manager's restore stamp from this supervisor's
    # detect stamp — both must come from the SAME clock, including an
    # injected test clock
    manager.clock = clock
    backoff = backoff or RetryPolicy(max_attempts=max_restarts + 1)
    rep = report if report is not None else RecoveryReport()
    resume = bool(kwargs.pop("resume", False))
    restarts = 0
    resizes = 0
    while True:
        if elastic is not None:
            kwargs["membership"] = elastic
            kwargs["mesh"] = elastic.mesh()
        event: Optional[RecoveryEvent] = None
        if rep.events and rep.events[-1].mttr_s is None:
            event = rep.events[-1]
        try:
            result = fit(*args, checkpoint=manager, resume=resume, **kwargs)
        except ResizeRequested as exc:
            _close_event(event, manager, clock)
            if elastic is None:
                # a fit ran with membership= but nobody owns the resize
                raise
            if resizes >= max_resizes:
                raise RuntimeError(
                    f"fleet resized {resizes} times without the fit "
                    "completing (max_resizes) — membership is churning "
                    "faster than training progresses") from exc
            resizes += 1
            rep.resizes = resizes
            elastic.note_resize()
            rep.events.append(RecoveryEvent(
                error=repr(exc)[:200], detected_at=clock(),
                kind="resize", fleet_size=elastic.fleet_size))
            tracer.instant("fleet_resize", cat="train",
                           x_fleet=elastic.fleet_size,
                           x_step=exc.step)
            resume = True
            continue
        except Exception as exc:  # noqa: BLE001 — classified below
            _close_event(event, manager, clock)
            if restarts >= max_restarts or not recoverable(exc):
                raise
            restarts += 1
            rep.restarts = restarts
            pause = backoff.delay(restarts - 1)
            fleet_size = None
            if elastic is not None:
                # worker death: recovery resumes onto the surviving fleet
                elastic.on_failure(exc)
                fleet_size = elastic.fleet_size
            rep.events.append(RecoveryEvent(
                error=repr(exc)[:200], detected_at=clock(),
                backoff_s=pause, fleet_size=fleet_size))
            tracer.instant("recovery_restart", cat="train",
                           x_error=repr(exc)[:80])
            backoff.sleep(pause)
            resume = True
            continue
        _close_event(event, manager, clock)
        rep.recovered = restarts > 0
        return result


def _close_event(event: Optional["RecoveryEvent"], manager: Any,
                 clock: Callable[[], float]) -> None:
    """Stamp the open recovery event with the restore the just-finished
    attempt performed (manager.last_restore_at is set by ``latest()``;
    training resumes the moment it returns)."""
    if event is None:
        return
    restore_at = getattr(manager, "last_restore_at", None)
    if restore_at is not None and restore_at >= event.detected_at:
        event.mttr_s = restore_at - event.detected_at
        event.restored_step = getattr(manager, "last_restored_step", None)
        if event.kind == "resize":
            # the resize-pause span: detect -> restore complete, where
            # training resumes on the new fleet (both stamps from the
            # supervisor's clock — the perf_counter timebase unless a
            # test injected its own)
            tracer.add("resize_pause", event.detected_at, restore_at,
                       cat="train", x_fleet=event.fleet_size,
                       step=event.restored_step)
    else:
        # no checkpoint existed yet: recovery was a cold re-run
        event.mttr_s = clock() - event.detected_at
