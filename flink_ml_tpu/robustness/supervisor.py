"""Self-healing training drivers.

:func:`resilient_fit` supervises any checkpointing fit — the streaming
``sgd_fit_outofcore`` and the hosted ``iterate`` both speak the same
``(checkpoint=..., resume=...)`` kwargs — and turns a recoverable crash
into an automatic restore-and-continue instead of a dead process:

1. run the fit; on a recoverable failure (injected crash, I/O error),
2. back off (classified, deterministic schedule — :class:`~.retry
   .RetryPolicy` arithmetic), then
3. re-run with ``resume=True``: the fit restores from the newest VALID
   checkpoint (``CheckpointManager.latest()`` quarantines corrupt/
   partial cuts and falls back — :mod:`.durability`), re-seeks or
   replays its source past the cursor (seek protocol / WAL windows),
   and continues as if never interrupted.

Because restore + replay are deterministic (the PR 1/PR 3 crash+resume
guarantee, EF reducer state included), the supervised run's final
params are **bit-exact** vs the uninterrupted run — asserted in
tests/test_faults.py, including with a corrupted newest checkpoint in
the fallback path.

The per-restart :class:`RecoveryEvent` records MTTR (detect -> restore
complete, which is where training resumes) measured against the
manager's restore timestamp — the number ``bench.py::bench_recovery``
reports.
"""

from __future__ import annotations

import time

from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

from ..obs.trace import tracer
from .faults import InjectedCrash
from .retry import RetryPolicy

__all__ = ["RecoveryEvent", "RecoveryReport", "resilient_fit",
           "default_recoverable"]


def default_recoverable(exc: BaseException) -> bool:
    """Can a restore-and-replay heal this?  Crashes and I/O failures
    yes; logic errors (bad config, schema mismatch, corrupt *input*
    data raising ValueError) no — re-running those burns restarts on a
    deterministic failure."""
    return isinstance(exc, (InjectedCrash, OSError, IOError,
                            ConnectionError, TimeoutError))


@dataclass
class RecoveryEvent:
    """One detected failure + the recovery that followed."""
    error: str
    detected_at: float
    backoff_s: float = 0.0
    restored_step: Optional[int] = None
    mttr_s: Optional[float] = None   # detect -> restore complete


@dataclass
class RecoveryReport:
    """Filled in place by :func:`resilient_fit` (pass ``report=``)."""
    restarts: int = 0
    recovered: bool = False
    events: List[RecoveryEvent] = field(default_factory=list)

    def as_dict(self) -> dict:
        return {
            "restarts": self.restarts,
            "recovered": self.recovered,
            "events": [{
                "error": e.error,
                "backoff_s": round(e.backoff_s, 4),
                "restored_step": e.restored_step,
                "mttr_s": (round(e.mttr_s, 4)
                           if e.mttr_s is not None else None),
            } for e in self.events],
        }


def resilient_fit(fit: Callable, *args: Any,
                  checkpoint: Any,
                  max_restarts: int = 3,
                  backoff: Optional[RetryPolicy] = None,
                  recoverable: Callable[[BaseException], bool]
                  = default_recoverable,
                  report: Optional[RecoveryReport] = None,
                  clock: Callable[[], float] = time.perf_counter,
                  **kwargs: Any) -> Any:
    """Run ``fit(*args, checkpoint=manager, resume=..., **kwargs)`` under
    supervision; returns whatever ``fit`` returns.

    ``fit`` is any callable taking ``checkpoint``/``resume`` keywords —
    ``sgd_fit_outofcore``, ``iterate``, ``WideDeep.fit_outofcore``, or a
    closure that rebuilds per-attempt state (a fresh ``WindowLog`` over
    a live feed) before delegating.  The first attempt runs with
    ``resume=kwargs.get("resume", False)``; every restart forces
    ``resume=True`` so recovery restores from the newest valid cut and
    replays forward.

    ``checkpoint`` (a ``CheckpointConfig`` or ``CheckpointManager``) is
    normalized to ONE manager shared across attempts, so quarantine
    decisions and save-slot history persist through restarts.  Restarts
    back off on the policy's deterministic schedule (attempt i sleeps
    ``backoff.delay(i)``); a failure that ``recoverable`` rejects — or
    restart ``max_restarts + 1`` — re-raises immediately.
    """
    # local import: checkpoint.py imports robustness.durability, so a
    # top-level import here would cycle through the package __init__
    from ..iteration.checkpoint import CheckpointConfig, CheckpointManager

    manager = (CheckpointManager(checkpoint)
               if isinstance(checkpoint, CheckpointConfig) else checkpoint)
    if not isinstance(manager, CheckpointManager):
        raise TypeError(
            "resilient_fit needs a CheckpointConfig/CheckpointManager "
            f"(got {type(checkpoint).__name__}): without durable cuts "
            "there is nothing to recover from")
    # MTTR subtracts the manager's restore stamp from this supervisor's
    # detect stamp — both must come from the SAME clock, including an
    # injected test clock
    manager.clock = clock
    backoff = backoff or RetryPolicy(max_attempts=max_restarts + 1)
    rep = report if report is not None else RecoveryReport()
    resume = bool(kwargs.pop("resume", False))
    restarts = 0
    while True:
        event: Optional[RecoveryEvent] = None
        if rep.events and rep.events[-1].mttr_s is None:
            event = rep.events[-1]
        try:
            result = fit(*args, checkpoint=manager, resume=resume, **kwargs)
        except Exception as exc:  # noqa: BLE001 — classified below
            _close_event(event, manager, clock)
            if restarts >= max_restarts or not recoverable(exc):
                raise
            restarts += 1
            rep.restarts = restarts
            pause = backoff.delay(restarts - 1)
            rep.events.append(RecoveryEvent(
                error=repr(exc)[:200], detected_at=clock(),
                backoff_s=pause))
            tracer.instant("recovery_restart", cat="train",
                           x_error=repr(exc)[:80])
            backoff.sleep(pause)
            resume = True
            continue
        _close_event(event, manager, clock)
        rep.recovered = restarts > 0
        return result


def _close_event(event: Optional["RecoveryEvent"], manager: Any,
                 clock: Callable[[], float]) -> None:
    """Stamp the open recovery event with the restore the just-finished
    attempt performed (manager.last_restore_at is set by ``latest()``;
    training resumes the moment it returns)."""
    if event is None:
        return
    restore_at = getattr(manager, "last_restore_at", None)
    if restore_at is not None and restore_at >= event.detected_at:
        event.mttr_s = restore_at - event.detected_at
        event.restored_step = getattr(manager, "last_restored_step", None)
    else:
        # no checkpoint existed yet: recovery was a cold re-run
        event.mttr_s = clock() - event.detected_at
