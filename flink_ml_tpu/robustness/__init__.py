"""Fault-injection harness + self-healing training/serving.

The reference's fault-tolerance story is exactly-once over a cyclic
dataflow — coordinator-aligned checkpoints plus a log of in-flight
feedback records (``checkpoint/Checkpoints.java:43-211``).  Our
TPU-native equivalents (epoch-cut checkpoints, the window log,
chunk-boundary cuts) assumed clean I/O; this package makes failure a
first-class, *injectable*, tested input to the whole stack:

- :mod:`.faults` — a seedable, deterministic :class:`FaultPlan` that
  injects transient read errors, torn/corrupted writes, ENOSPC, and
  simulated crashes at chosen invocation indices (same seed, same
  faults — every recovery test is reproducible);
- :mod:`.durability` — per-file CRC32 manifests + an atomic commit
  marker for checkpoint/stage directories, so a torn or bit-flipped
  save is *detected* instead of silently restored;
- :mod:`.retry` — exponential-backoff :class:`RetryPolicy` with
  retryable-vs-fatal classification (deterministic schedule under an
  injected clock), adopted by prefetch source pulls, registry loads,
  and WAL appends;
- :mod:`.supervisor` — :func:`resilient_fit`, the self-healing driver:
  on a (injected or real) recoverable failure it restores from the
  newest *valid* checkpoint (corrupt ones are quarantined), replays
  the source/WAL past the cursor, and continues — final params
  bit-exact vs the uninterrupted run (tests/test_faults.py).
"""

from .faults import (
    FaultPlan,
    InjectedChipDown,
    InjectedChipFlap,
    InjectedCrash,
    InjectedDiskFullError,
    InjectedJoin,
    InjectedPreemption,
    InjectedTransientError,
    corrupt_file,
    fault_point,
)
from .durability import (
    COMMIT_MARKER,
    MANIFEST_NAME,
    CorruptStateError,
    commit_dir,
    is_committed,
    quarantine,
    verify_dir,
    write_commit_marker,
    write_manifest,
)
from .retry import RetryPolicy, default_classify, retry_call
from .supervisor import RecoveryEvent, RecoveryReport, resilient_fit

__all__ = [
    "FaultPlan", "InjectedChipDown", "InjectedChipFlap",
    "InjectedCrash", "InjectedDiskFullError",
    "InjectedJoin", "InjectedPreemption",
    "InjectedTransientError", "corrupt_file", "fault_point",
    "COMMIT_MARKER", "MANIFEST_NAME", "CorruptStateError", "commit_dir",
    "is_committed",
    "quarantine", "verify_dir", "write_commit_marker", "write_manifest",
    "RetryPolicy", "default_classify", "retry_call",
    "RecoveryEvent", "RecoveryReport", "resilient_fit",
]
