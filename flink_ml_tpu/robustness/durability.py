"""Validated, crash-consistent directory commits.

The checkpoint/WAL write path was already atomic at the *rename* level
(tmp dir -> ``os.replace``); what it lacked was a way to tell a GOOD
committed directory from a torn or bit-rotted one before trusting its
bytes with training state.  This module supplies the two missing
pieces, shared by ``iteration/checkpoint.py`` (and usable by any
directory-shaped artifact):

1. **Manifest**: ``manifest.json`` maps every payload file to its
   CRC32 (+ size).  Written LAST among the payload, so a manifest that
   validates proves the payload bytes are the ones the writer hashed.
2. **Commit marker**: an empty ``COMMITTED`` file written (and fsynced)
   after the manifest.  The commit protocol is therefore::

       write payload files -> write manifest -> fsync payload
       -> write COMMITTED -> fsync dir -> os.replace(tmp, final)

   A directory without the marker is a crash-interrupted write (never
   valid); a directory whose CRCs mismatch is torn/corrupt.  Either way
   :func:`verify_dir` raises :class:`CorruptStateError` naming the path
   and the first bad file — and :func:`quarantine` moves the directory
   aside (``<name>.corrupt``) so a newest->oldest scan falls back to
   the previous valid artifact instead of crashing on the bad one.

Directories written before manifests existed (``format`` absent) are
**legacy**: :func:`verify_dir` accepts them by default so old
checkpoints keep restoring; their payload errors surface at decode time
instead.
"""

from __future__ import annotations

import json
import logging
import os
import zlib

from typing import Dict, Iterable, Optional

from .faults import fault_point

__all__ = ["CorruptStateError", "MANIFEST_NAME", "COMMIT_MARKER",
           "file_crc32", "write_manifest", "write_commit_marker",
           "commit_dir", "is_committed", "verify_dir", "quarantine"]

MANIFEST_NAME = "manifest.json"
COMMIT_MARKER = "COMMITTED"

log = logging.getLogger("flink_ml_tpu.robustness")


class CorruptStateError(IOError):
    """A durable artifact failed validation: partial (uncommitted),
    torn, or bit-rotted.  Subclasses ``IOError`` so existing diagnosable
    error handling (``persist._resolve_saved_class`` lineage) catches it
    uniformly."""


def file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return crc
            crc = zlib.crc32(block, crc)


def _payload_files(dirpath: str) -> Iterable[str]:
    for name in sorted(os.listdir(dirpath)):
        if name in (MANIFEST_NAME, COMMIT_MARKER):
            continue
        if os.path.isfile(os.path.join(dirpath, name)):
            yield name


def write_manifest(dirpath: str,
                   files: Optional[Iterable[str]] = None) -> Dict:
    """Hash ``files`` (default: every regular file in ``dirpath``) and
    write ``manifest.json``.  Returns the manifest dict."""
    names = list(files) if files is not None else list(
        _payload_files(dirpath))
    manifest = {"format": 1, "files": {
        name: {"crc32": file_crc32(os.path.join(dirpath, name)),
               "bytes": os.path.getsize(os.path.join(dirpath, name))}
        for name in names}}
    # a torn manifest is SAFE here: the COMMITTED marker is written
    # after it, and verify_dir treats manifest-without-marker as
    # crashed-mid-commit (quarantined) — the marker, not an os.replace,
    # is this protocol's commit point.
    with open(os.path.join(dirpath,   # graftlint: disable=atomic-writes
                           MANIFEST_NAME), "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    return manifest


def write_commit_marker(dirpath: str) -> None:
    """The last write of the commit protocol — its presence asserts the
    manifest (and everything it hashes) fully landed."""
    marker = os.path.join(dirpath, COMMIT_MARKER)
    # zero-byte marker: nothing to tear, fsync'd below — atomic by
    # content, no tmp+replace needed.
    with open(marker, "w") as f:   # graftlint: disable=atomic-writes
        f.flush()
        os.fsync(f.fileno())
    dirfd = os.open(dirpath, os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)


def is_committed(dirpath: str) -> bool:
    return os.path.exists(os.path.join(dirpath, COMMIT_MARKER))


def verify_dir(dirpath: str, *, allow_legacy: bool = True) -> None:
    """Validate the commit protocol for ``dirpath``; raise
    :class:`CorruptStateError` (naming path + first finding) on any
    violation.  Legacy directories (no manifest, no marker) pass when
    ``allow_legacy`` — pre-manifest saves must keep restoring."""
    manifest_path = os.path.join(dirpath, MANIFEST_NAME)
    has_manifest = os.path.exists(manifest_path)
    if not has_manifest and not is_committed(dirpath):
        if allow_legacy:
            return
        raise CorruptStateError(
            f"{dirpath}: no manifest and no commit marker (pre-manifest "
            "legacy save, or not a committed artifact)")
    if has_manifest and not is_committed(dirpath):
        raise CorruptStateError(
            f"{dirpath}: manifest present but no {COMMIT_MARKER} marker — "
            "the writer crashed mid-commit; this artifact was never valid")
    if not has_manifest:
        raise CorruptStateError(
            f"{dirpath}: commit marker present but {MANIFEST_NAME} is "
            "missing — the directory was tampered with or partially lost")
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        entries = manifest["files"]
    except (json.JSONDecodeError, KeyError, TypeError) as exc:
        raise CorruptStateError(
            f"{dirpath}: unreadable {MANIFEST_NAME} ({exc})") from exc
    for name, entry in entries.items():
        path = os.path.join(dirpath, name)
        if not os.path.exists(path):
            raise CorruptStateError(
                f"{dirpath}: manifest lists {name!r} but the file is "
                "missing")
        size = os.path.getsize(path)
        if size != entry["bytes"]:
            raise CorruptStateError(
                f"{dirpath}: {name!r} is {size} bytes, manifest says "
                f"{entry['bytes']} (torn write)")
        crc = file_crc32(path)
        if crc != entry["crc32"]:
            raise CorruptStateError(
                f"{dirpath}: {name!r} CRC32 {crc:#010x} != manifest "
                f"{entry['crc32']:#010x} (corrupted bytes)")


def commit_dir(dirpath: str, *, fault_scope: Optional[str] = None) -> None:
    """Run the tail of the commit protocol on a fully-written payload
    directory: manifest -> (fault injection seam) -> marker.  The fault
    seam sits BETWEEN hashing and the marker so an injected torn/flip
    fault produces exactly the committed-but-invalid artifact the
    validation layer exists to catch."""
    write_manifest(dirpath)
    if fault_scope is not None:
        # data faults damage the largest payload file (the one a real
        # torn write would statistically hit)
        target = max(_payload_files(dirpath),
                     key=lambda n: os.path.getsize(
                         os.path.join(dirpath, n)),
                     default=None)
        fault_point(fault_scope,
                    os.path.join(dirpath, target) if target else None)
    write_commit_marker(dirpath)


def quarantine(dirpath: str) -> str:
    """Move a failed-validation directory aside (``<name>.corrupt``,
    numbered on collision) so directory scans stop tripping on it while
    the bytes stay available for forensics.  Returns the new path."""
    base = dirpath.rstrip(os.sep) + ".corrupt"
    dest = base
    n = 0
    while os.path.exists(dest):
        n += 1
        dest = f"{base}{n}"
    os.rename(dirpath, dest)
    log.warning("quarantined corrupt artifact %s -> %s", dirpath, dest)
    return dest
