"""Span tracing — the request's life story, end to end (ISSUE 13).

The reference's only latency surface is Flink LatencyMarker stats in the
per-round wrapper (SURVEY §5,
``AbstractPerRoundWrapperOperator.java:500-553``) — per-operator
aggregates with no per-request correlation.  :class:`SpanTracer` is the
TPU-native replacement: a **lock-cheap ring-buffered host tracer** whose
spans carry correlation ids, so one exported trace shows
"WAL window N → cut T → delta publish → generation G served request R"
as nested/adjacent events on a shared timeline.

Design stance:

- **Off by default, near-free when off.**  Every instrumentation site
  goes through :meth:`SpanTracer.span` (or guards on
  :attr:`SpanTracer.enabled`); disabled, ``span()`` returns one shared
  no-op context manager — no allocation, no lock, no clock read.  The
  serving/bench A/B (``bench.py::bench_obs``) holds the enabled-path
  overhead under 5% of p99 with ZERO new XLA lowerings (tracing is
  pure host bookkeeping — it never touches a traced program).
- **Bounded memory.**  Completed spans land in a preallocated ring
  (default 64 Ki spans); the lock is held only for the slot bump +
  assignment — never across a clock read or an export.
- **Correlation ids, not parent pointers.**  Spans carry a small dict
  of well-known keys (``request_id``, ``generation``, ``step``,
  ``window``, ``epoch``, ``op``, ``bucket`` — the contract
  ARCHITECTURE.md "Observability" documents); viewers nest by
  (tid, time) containment, and cross-thread causality rides the shared
  ids (a publish's ``generation`` is the served request's
  ``generation``).
- **Device work is fenced, never blocked in step fns.**  Spans that
  claim to cover device execution end on a ``device_get`` of the
  fetched output (the ``utils/profiler.StepTimer`` probe pattern) on
  the HOST side of the dispatch boundary; nothing inside a jitted
  step/scan body ever synchronizes (the graftlint host-sync pass
  covers ``flink_ml_tpu/obs/``).

Exports: Chrome-trace JSON (the ``traceEvents`` array Perfetto and
``chrome://tracing`` load directly) and JSONL (one span per line, the
machine-diffable form).  Both writes are crash-atomic
(tmp -> ``os.replace`` — the PR 5 contract; this module is in the
graftlint atomic-writes durable set).
"""

from __future__ import annotations

import json
import os
import threading
import time

from typing import Any, Dict, Iterator, List, Optional

__all__ = ["Span", "SpanTracer", "tracer", "CORRELATION_KEYS"]

#: the correlation-id contract: instrumentation sites only attach these
#: keys (plus free-form strings prefixed ``x_`` for experiments), so a
#: trace consumer can join spans across threads/subsystems without
#: guessing.  ``request_id`` = one serving request; ``generation`` = the
#: live model generation; ``step`` = the trainer's global step (a
#: checkpoint cut and its publish share it); ``window`` = the WAL
#: window index; ``epoch``/``op``/``bucket`` label loops and dispatch;
#: ``tenant`` = the multi-tenant scheduler's tenant name (ISSUE 14) —
#: queue-wait/serve/shed spans carry it, so one trace shows
#: cross-tenant interleaving on the shared device.
CORRELATION_KEYS = ("request_id", "generation", "step", "window",
                    "epoch", "op", "bucket", "tenant")


class Span:
    """One completed (or instant) event: wall interval on this host's
    ``perf_counter`` timebase plus the correlation-id dict."""

    __slots__ = ("name", "cat", "t0", "dur", "tid", "ph", "ids")

    def __init__(self, name: str, cat: str, t0: float, dur: float,
                 tid: int, ph: str, ids: Dict[str, Any]):
        self.name = name
        self.cat = cat
        self.t0 = t0
        self.dur = dur
        self.tid = tid
        self.ph = ph            # "X" complete | "i" instant
        self.ids = ids

    def as_dict(self) -> Dict[str, Any]:
        out = {"name": self.name, "cat": self.cat,
               "t0_s": self.t0, "dur_s": self.dur,
               "tid": self.tid, "ph": self.ph}
        out.update(self.ids)
        return out


class _NullSpan:
    """The shared disabled-path context manager: every method is a no-op
    and ``note`` chains, so instrumentation sites never branch."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def note(self, **ids) -> "_NullSpan":
        return self


_NULL = _NullSpan()


class _LiveSpan:
    """One in-flight span; ``note(**ids)`` attaches correlation ids
    discovered mid-span (e.g. the generation captured after the batch
    formed)."""

    __slots__ = ("_tracer", "name", "cat", "ids", "_t0")

    def __init__(self, tracer: "SpanTracer", name: str, cat: str,
                 ids: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.ids = ids
        self._t0 = 0.0

    def __enter__(self) -> "_LiveSpan":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self._tracer.add(self.name, self._t0, time.perf_counter(),
                         cat=self.cat, **self.ids)
        return False

    def note(self, **ids) -> "_LiveSpan":
        self.ids.update(ids)
        return self


class SpanTracer:
    """Ring-buffered host span recorder (module doc).  One process-wide
    instance lives at :data:`tracer`; tests and benches may construct
    private ones."""

    def __init__(self, capacity: int = 1 << 16):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.enabled = False
        self._capacity = capacity
        self._buf: List[Optional[Span]] = [None] * capacity
        self._n = 0              # monotonic commit counter
        self._dropped = 0        # spans overwritten by the ring wrap
        self._lock = threading.Lock()
        self._epoch = time.perf_counter()   # export-time origin

    # -- lifecycle ----------------------------------------------------------
    def enable(self, capacity: Optional[int] = None) -> "SpanTracer":
        """Clear and start recording (``capacity`` resizes the ring)."""
        with self._lock:
            if capacity is not None and capacity != self._capacity:
                if capacity <= 0:
                    raise ValueError("capacity must be positive")
                self._capacity = capacity
            self._buf = [None] * self._capacity
            self._n = 0
            self._dropped = 0
            self._epoch = time.perf_counter()
            self.enabled = True
        return self

    def disable(self) -> "SpanTracer":
        """Stop recording; already-captured spans stay exportable."""
        self.enabled = False
        return self

    def clear(self) -> None:
        with self._lock:
            self._buf = [None] * self._capacity
            self._n = 0
            self._dropped = 0

    # -- recording ----------------------------------------------------------
    def span(self, name: str, cat: str = "host", **ids):
        """Context manager timing a code region.  Disabled -> the shared
        no-op (no allocation); enabled -> a live span committed to the
        ring at exit."""
        if not self.enabled:
            return _NULL
        return _LiveSpan(self, name, cat, ids)

    def add(self, name: str, t0: float, t1: float, *, cat: str = "host",
            tid: Optional[int] = None, **ids) -> None:
        """Commit a RETROACTIVE span measured by the caller (``t0``/``t1``
        on the ``perf_counter`` timebase) — how queue-wait is recorded:
        the serve loop stamps it from the request's submit timestamp
        once the batch forms, no tracer work on the submit path."""
        if not self.enabled:
            return
        self._commit(Span(name, cat, t0, max(t1 - t0, 0.0),
                          tid if tid is not None else
                          threading.get_ident(), "X", ids))

    def instant(self, name: str, cat: str = "host", **ids) -> None:
        """Zero-duration marker event (e.g. a shed, a rollback)."""
        if not self.enabled:
            return
        self._commit(Span(name, cat, time.perf_counter(), 0.0,
                          threading.get_ident(), "i", ids))

    def _commit(self, span: Span) -> None:
        # lock-cheap: the lock covers only the slot bump + assignment
        with self._lock:
            idx = self._n % self._capacity
            if self._buf[idx] is not None:
                self._dropped += 1
            self._buf[idx] = span
            self._n += 1

    # -- reading ------------------------------------------------------------
    @property
    def count(self) -> int:
        """Spans committed since enable (monotonic — includes spans the
        ring has since overwritten)."""
        return self._n

    @property
    def dropped(self) -> int:
        return self._dropped

    def spans(self) -> List[Span]:
        """Retained spans, oldest first (ring order)."""
        with self._lock:
            n, cap = self._n, self._capacity
            if n <= cap:
                return [s for s in self._buf[:n] if s is not None]
            head = n % cap
            return [s for s in self._buf[head:] + self._buf[:head]
                    if s is not None]

    def find(self, name: Optional[str] = None, **ids) -> Iterator[Span]:
        """Retained spans matching ``name`` and every given id."""
        for span in self.spans():
            if name is not None and span.name != name:
                continue
            if all(span.ids.get(k) == v for k, v in ids.items()):
                yield span

    # -- export -------------------------------------------------------------
    def _us(self, t: float) -> float:
        return (t - self._epoch) * 1e6

    def chrome_events(self) -> List[Dict[str, Any]]:
        """The Chrome-trace ``traceEvents`` array (what Perfetto /
        ``chrome://tracing`` load): ``ph: "X"`` complete events with
        microsecond ``ts``/``dur`` relative to the tracer's enable
        point, correlation ids under ``args``."""
        pid = os.getpid()
        events = []
        for s in self.spans():
            ev: Dict[str, Any] = {
                "name": s.name, "cat": s.cat, "ph": s.ph,
                "ts": round(self._us(s.t0), 3), "pid": pid, "tid": s.tid,
                "args": dict(s.ids),
            }
            if s.ph == "X":
                ev["dur"] = round(s.dur * 1e6, 3)
            else:
                ev["s"] = "t"          # instant scope: thread
            events.append(ev)
        return events

    def export_chrome(self, path: str) -> int:
        """Write Chrome-trace JSON (atomic: tmp -> ``os.replace``).
        Returns the event count."""
        events = self.chrome_events()
        payload = {"traceEvents": events, "displayTimeUnit": "ms",
                   "otherData": {"dropped_spans": self._dropped}}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return len(events)

    def export_jsonl(self, path: str) -> int:
        """One span per line (machine-diffable; atomic full rewrite)."""
        spans = self.spans()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            for s in spans:
                f.write(json.dumps(s.as_dict()) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return len(spans)


#: THE process-wide tracer every instrumentation site records into.
tracer = SpanTracer()
