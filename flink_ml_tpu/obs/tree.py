"""One metrics tree — every observability surface behind one snapshot.

Six surfaces grew up disjoint in this repo: per-endpoint
``ServingMetrics`` gauges (PR 2), the kernel registry's
``kernel_stats`` with the AOT ledger + ``tuned_ops`` (PRs 10/12),
workset ``epoch_trace`` buffers (PR 9), ``RecoveryReport`` (PR 5),
``warmup_report`` (PR 12) and ``IterationMetricsListener`` — none with
an export format, none correlated.  :class:`MetricsTree` merges them:
providers register under a name, ``snapshot()`` returns ONE nested
dict (JSON-clean: numpy scalars/arrays normalized), and two writers
hang off it:

- :func:`prometheus_text` — the Prometheus text exposition format
  (``# TYPE`` + ``name{...} value`` lines).  Only finite numeric
  scalars export; a NaN gauge is **absent**, never a fake number (the
  never-published ``model_staleness_seconds`` contract — ISSUE 13
  satellite: the old ``-1`` sentinel must not leak into exports as a
  negative age).
- :class:`ObsSampler` — an optional background thread appending one
  JSON line per tick to a time-series file.  Appends are line-framed
  and fsynced; a torn tail from a crash is detected and dropped by
  :func:`read_samples` (the WAL-tail stance, ``data/wal.py``), which
  is the append-side face of the PR 5 durability contract (whole-file
  writes in this package are tmp -> ``os.replace``).
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time

from typing import Any, Callable, Dict, List, Optional

__all__ = ["MetricsTree", "default_tree", "prometheus_text",
           "ObsSampler", "read_samples"]


def _jsonable(value: Any) -> Any:
    """Normalize numpy scalars/arrays (and nested containers) to plain
    Python so the snapshot serializes and diffs cleanly."""
    import numpy as np

    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if isinstance(value, np.ndarray):
        return [_jsonable(v) for v in value.tolist()]
    if isinstance(value, np.generic):
        return value.item()
    return value


class MetricsTree:
    """name -> provider registry; ``snapshot()`` is the one nested dict.

    A provider is anything snapshot-shaped: a zero-arg callable
    returning a dict, a ``MetricGroup`` / ``ServingMetrics`` /
    ``KernelStats`` (their ``snapshot()`` is used), or a plain dict
    (captured by REFERENCE — a live ``stream_info`` keeps updating).
    A provider returning ``None`` is omitted from that snapshot (e.g.
    ``warmup_report`` before the first deploy)."""

    def __init__(self):
        self._providers: Dict[str, Callable[[], Any]] = {}
        self._lock = threading.Lock()

    def register(self, name: str, source: Any) -> "MetricsTree":
        if callable(source) and not hasattr(source, "snapshot"):
            provider = source
        elif hasattr(source, "snapshot"):
            provider = source.snapshot
        elif isinstance(source, dict):
            provider = lambda d=source: d          # noqa: E731 — live ref
        else:
            raise TypeError(
                f"unsnapshotable provider {type(source).__name__}: pass "
                "a callable, a dict, or an object with .snapshot()")
        with self._lock:
            self._providers[name] = provider
        return self

    def unregister(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._providers)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            providers = dict(self._providers)
        out: Dict[str, Any] = {}
        for name in sorted(providers):
            value = providers[name]()
            if value is None:
                continue
            out[name] = _jsonable(value)
        return out


def default_tree(*, endpoint: Any = None, serving: Any = None,
                 scheduler: Any = None, recovery: Any = None,
                 stream_info: Any = None, iteration_result: Any = None,
                 tracer: Any = None, elastic: Any = None,
                 autoscale: Any = None,
                 failover: Any = None) -> MetricsTree:
    """A :class:`MetricsTree` pre-wired to every standard surface that
    exists in this process:

    - ``kernels`` — the process-wide registry ledger (compiles /
      cache hits / dispatch latency, the AOT hit/miss/quarantine ledger,
      ``tuned_ops``) — always registered;
    - ``serving`` — ``endpoint.metrics`` (or a bare ``ServingMetrics``
      via ``serving=``), including its ``kernels.*`` re-export and the
      publish/staleness gauges;
    - ``scheduler`` — a multi-tenant :class:`~flink_ml_tpu.serving.\
scheduler.SharedScheduler`'s subtree (class-labeled shed counters,
      health, and every tenant's own ServingMetrics under
      ``tenants.<name>.*`` — ISSUE 14);
    - ``warmup`` — the live servable's readiness accounting (absent
      until the first deploy);
    - ``recovery`` — a ``RecoveryReport`` (restarts / MTTR events);
    - ``training`` — a live ``stream_info`` dict from
      ``sgd_fit_outofcore`` (impl, dispatch counts, epoch seconds,
      ``step_trace`` when a :class:`~flink_ml_tpu.obs.probe.StepProbe`
      is attached);
    - ``iteration`` — an ``IterationResult``'s ``side`` (the workset
      ``epoch_trace`` + termination reason);
    - ``trace`` — span-tracer volume counters (never the spans
      themselves — those export via the tracer's own writers);
    - ``elastic`` — an
      :class:`~flink_ml_tpu.parallel.elastic.ElasticCoordinator`'s
      fleet gauges (fleet size, membership epoch, join/leave/death/
      suppression counters, resizes) so an operator can correlate a
      loss-curve kink or a step-time shift with the membership
      transition that caused it;
    - ``autoscale`` — an
      :class:`~flink_ml_tpu.autoscale.controller.AutoscaleController`'s
      self-view (ticks, actuations, decision latency, the policy's
      decision ledger, the live placement generation — ISSUE 17), so
      the control plane is observable through the same tree it reads;
    - ``failover`` — a
      :class:`~flink_ml_tpu.serving.failover.FailoverDriver`'s fleet
      view (chips live/down, brownout level, failover/requeue/conflict
      counters, last failover wall — ISSUE 20), so a p99 excursion in
      the same snapshot is attributable to the chip loss that caused
      it.
    """
    from ..kernels.registry import kernel_stats

    tree = MetricsTree()
    tree.register("kernels", kernel_stats)
    metrics = serving
    if endpoint is not None and metrics is None:
        metrics = endpoint.metrics
    if metrics is not None:
        tree.register("serving", metrics)
    if scheduler is not None:
        tree.register("scheduler", scheduler)
    if endpoint is not None:
        tree.register("warmup", lambda: endpoint.warmup_report)
    if recovery is not None:
        tree.register("recovery", recovery.as_dict)
    if stream_info is not None:
        tree.register("training", stream_info)
    if iteration_result is not None:
        tree.register("iteration", lambda: iteration_result.side)
    if tracer is not None:
        tree.register("trace", lambda: {
            "enabled": tracer.enabled, "spans": tracer.count,
            "dropped": tracer.dropped})
    if elastic is not None:
        tree.register("elastic", elastic)
    if autoscale is not None:
        tree.register("autoscale", autoscale)
    if failover is not None:
        tree.register("failover", failover)
    return tree


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(parts: List[str], prefix: str) -> str:
    name = "_".join([prefix] + parts) if prefix else "_".join(parts)
    name = _NAME_OK.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _flatten(tree: Dict[str, Any], parts: List[str],
             out: List[tuple]) -> None:
    for key in sorted(tree):
        value = tree[key]
        # dotted MetricGroup keys split into path segments so
        # serving's "kernels.dispatches" and a nested dict spell the
        # same exported name
        sub = parts + [p for p in str(key).split(".") if p]
        if isinstance(value, dict):
            _flatten(value, sub, out)
        else:
            out.append((sub, value))


def prometheus_text(tree: Dict[str, Any], *,
                    prefix: str = "flink_ml_tpu") -> str:
    """Render a :meth:`MetricsTree.snapshot` (or any nested dict) in the
    Prometheus text exposition format, one gauge per finite numeric
    leaf.  Non-numeric leaves (strings, lists) are skipped — the
    nested snapshot is the full-fidelity export; this is the scrape
    surface.  NaN/inf leaves are ABSENT (a scrape must never see the
    never-published staleness as a number)."""
    leaves: List[tuple] = []
    _flatten(tree, [], leaves)
    lines: List[str] = []
    seen = set()
    for parts, value in leaves:
        if isinstance(value, bool):
            value = int(value)
        if not isinstance(value, (int, float)):
            continue
        if not math.isfinite(value):
            continue
        name = _metric_name(parts, prefix)
        if name in seen:        # a collision keeps the first writer
            continue
        seen.add(name)
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {value}")
    return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# background sampler: JSONL time series
# ---------------------------------------------------------------------------

class ObsSampler:
    """Append one ``{"t": ..., <tree snapshot>}`` JSON line per tick.

    The file is an append-only time series: every line is written whole
    and fsynced before the next tick, so the only crash artifact is a
    torn FINAL line, which :func:`read_samples` detects (json parse or
    missing newline) and drops — the same tail-truncation stance as the
    WAL (``data/wal.py``).  ``start()`` spawns a daemon thread;
    ``sample()`` is also callable directly for tick-on-demand use
    (tests, bench legs)."""

    def __init__(self, tree: MetricsTree, path: str, *,
                 interval_s: float = 1.0,
                 clock: Callable[[], float] = time.time):
        if interval_s <= 0:
            raise ValueError("interval_s must be positive")
        self._tree = tree
        self._path = path
        self._interval = interval_s
        self._clock = clock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples_written = 0

    def sample(self) -> Dict[str, Any]:
        """Take one snapshot and append it durably; returns the line's
        dict (handy for tests/benches)."""
        record = {"t": self._clock()}
        record.update(self._tree.snapshot())
        line = json.dumps(record) + "\n"
        # Line-framed durable append: the whole line lands in ONE write
        # + fsync, so a crash tears at most the final line, which
        # read_samples truncates — the WAL-tail contract.  A tmp ->
        # os.replace of the whole series per tick would be O(n^2).
        with open(self._path, "a") as f:  # graftlint: disable=atomic-writes — line-framed append; torn tail dropped by read_samples (WAL-tail stance)
            f.write(line)
            f.flush()
            os.fsync(f.fileno())
        self.samples_written += 1
        return record

    # -- background thread --------------------------------------------------
    def start(self) -> "ObsSampler":
        if self._thread is not None:
            raise RuntimeError("sampler already started")
        self._stop.clear()

        def loop():
            while not self._stop.wait(self._interval):
                try:
                    self.sample()
                except Exception:   # noqa: BLE001 — sampling must never
                    pass            # kill the host process it observes

        self._thread = threading.Thread(
            target=loop, daemon=True, name="flink-ml-tpu-obs-sampler")
        self._thread.start()
        return self

    def stop(self, *, final_sample: bool = True,
             timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        if final_sample:
            self.sample()


def read_samples(path: str) -> List[Dict[str, Any]]:
    """Parse an :class:`ObsSampler` JSONL series, dropping a torn final
    line (crash mid-append).  A malformed NON-final line raises — like
    the WAL, mid-stream corruption is never silently skipped."""
    samples: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return samples
    with open(path) as f:
        lines = f.read().split("\n")
    # a clean file ends with "\n" -> trailing "" element; anything else
    # in the final slot is a torn tail
    body, tail = lines[:-1], lines[-1]
    for i, line in enumerate(body):
        if not line:
            continue
        try:
            samples.append(json.loads(line))
        except json.JSONDecodeError as exc:
            raise ValueError(
                f"sample {i} of {path!r} is corrupt ({exc}) but is not "
                "the tail — refusing to silently drop mid-series data"
            ) from exc
    if tail:
        # torn tail: framed append means it never completed — drop it
        pass
    return samples
