"""StepProbe — named per-step scalars riding a scan carry (ISSUE 13).

The repo grew this idiom twice by hand: the sgd fused-fit loss log (a
``(max_epochs,)`` NaN-prefilled buffer indexed ``.at[epoch]``) and the
PR 9 workset ``epoch_trace`` (the same buffer, twice, for
active-fraction and termination).  :class:`StepProbe` is the
generalization both now ride: a registered pytree packing K named
channels into ONE ``(capacity, K)`` f32 buffer plus a cursor, so

- **recording is pure device math** (``.at[cursor].set`` of one packed
  row — no host sync inside any step fn; the graftlint host-sync pass
  covers this module), and
- **fetching is one batched transfer**: :meth:`fetch` issues a single
  ``device_get`` of ``(buf, cursor)`` at a chunk/loop boundary and
  splits into per-channel host arrays — never K transfers, never one
  per step.

NaN prefill is the validity encoding: rows past the cursor (steps never
run, the padded tail of a short chunk) read NaN and :meth:`fetch` trims
them.  The probe composes with donation (the chunked fit donates its
carry; :meth:`reset` hands the next dispatch fresh buffers after a
fetch) and with ``masked_chunk_scan``'s dead-step freeze (the probe
rides the same ``jnp.where`` the state does, so padded steps record
nothing and any two ``W`` values stay bit-exact).

Adopters: the fused ``iterate`` epoch trace (``iteration/core.py``)
records ``active_fraction`` + ``termination`` per round;
``sgd_fit_outofcore(step_probe=True)`` records per-step ``loss`` across
the chunked scan and surfaces the concatenated series as
``stream_info["step_trace"]``.  Channel vocabulary is caller-defined —
grad norms, realized compression rungs/bytes, workset active fractions
are all just names.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np

__all__ = ["StepProbe"]


class StepProbe:
    """K named per-step f32 scalars in one ``(capacity, K)`` device
    buffer + an int32 cursor.  Immutable-functional like every carry
    pytree: ``record``/``record_at``/``reset`` return new probes."""

    __slots__ = ("names", "capacity", "buf", "cursor")

    def __init__(self, names: Tuple[str, ...], capacity: int,
                 buf: Any = None, cursor: Any = None):
        import jax.numpy as jnp

        self.names = tuple(names)
        self.capacity = int(capacity)
        if not self.names:
            raise ValueError("StepProbe needs at least one channel name")
        if len(set(self.names)) != len(self.names):
            raise ValueError(f"duplicate channel names: {self.names}")
        if self.capacity <= 0:
            raise ValueError("capacity must be positive")
        self.buf = (buf if buf is not None else
                    jnp.full((self.capacity, len(self.names)), jnp.nan,
                             jnp.float32))
        self.cursor = (cursor if cursor is not None
                       else jnp.asarray(0, jnp.int32))

    # -- construction --------------------------------------------------------
    @classmethod
    def create(cls, names: Sequence[str], capacity: int) -> "StepProbe":
        return cls(tuple(names), capacity)

    # -- device-side recording (pure math, safe inside step fns) -------------
    def _row(self, scalars: Dict[str, Any]):
        import jax.numpy as jnp

        unknown = set(scalars) - set(self.names)
        if unknown:
            raise ValueError(
                f"unknown probe channel(s) {sorted(unknown)}; this probe "
                f"records {self.names}")
        return jnp.stack([
            jnp.asarray(scalars[n], jnp.float32).reshape(())
            if n in scalars else jnp.asarray(jnp.nan, jnp.float32)
            for n in self.names])

    def record(self, **scalars) -> "StepProbe":
        """Write one packed row at the cursor and advance it.  Channels
        not provided stay NaN for this step.  Past-capacity records are
        dropped (the ring-less fixed-buffer contract: callers size
        ``capacity`` to the loop bound — ``W`` steps, ``max_epochs``
        rounds)."""
        import jax.numpy as jnp

        idx = jnp.minimum(self.cursor, self.capacity - 1)
        row = jnp.where(self.cursor < self.capacity,
                        self._row(scalars), self.buf[idx])
        return StepProbe(self.names, self.capacity,
                         self.buf.at[idx].set(row),
                         jnp.minimum(self.cursor + 1, self.capacity))

    def record_at(self, index, **scalars) -> "StepProbe":
        """Write at an explicit step index (the fused while_loop records
        at ``epoch``); the cursor becomes ``max(cursor, index + 1)`` so
        :meth:`fetch` still trims to rounds actually run."""
        import jax.numpy as jnp

        idx = jnp.asarray(index, jnp.int32)
        return StepProbe(self.names, self.capacity,
                         self.buf.at[idx].set(self._row(scalars)),
                         jnp.maximum(self.cursor, idx + 1))

    def reset(self) -> "StepProbe":
        """Fresh NaN buffers, cursor 0 — what a donating chunk loop
        passes into the next dispatch after fetching this one."""
        return StepProbe(self.names, self.capacity)

    # -- host-side fetch (ONE batched transfer) ------------------------------
    def fetch(self, get: Optional[Callable[[Any], Any]] = None
              ) -> Dict[str, np.ndarray]:
        """Fetch every channel in one ``device_get`` of ``(buf, cursor)``
        and trim to recorded steps.  ``get`` overrides the fetcher for
        replicated/multi-host arrays (the iteration driver passes
        ``fetch_replicated``)."""
        if get is None:
            buf, cursor = jax.device_get((self.buf, self.cursor))
        else:
            buf, cursor = get(self.buf), get(self.cursor)
        n = int(np.asarray(cursor))
        buf = np.asarray(buf)[:n]
        return {name: buf[:, i] for i, name in enumerate(self.names)}


def _probe_flatten(p: StepProbe):
    return (p.buf, p.cursor), (p.names, p.capacity)


def _probe_unflatten(aux, children):
    names, capacity = aux
    return StepProbe(names, capacity, *children)


jax.tree_util.register_pytree_node(StepProbe, _probe_flatten,
                                   _probe_unflatten)
