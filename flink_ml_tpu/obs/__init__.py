"""Unified observability (ISSUE 13): span tracing with correlation ids
(:mod:`.trace`), the one-snapshot metrics tree with Prometheus/JSONL
writers (:mod:`.tree`), and the device-side :class:`~.probe.StepProbe`
riding scan carries.  See ARCHITECTURE.md "Observability"."""

from .probe import StepProbe
from .trace import CORRELATION_KEYS, Span, SpanTracer, tracer
from .tree import (
    MetricsTree,
    ObsSampler,
    default_tree,
    prometheus_text,
    read_samples,
)

__all__ = [
    "CORRELATION_KEYS",
    "MetricsTree",
    "ObsSampler",
    "Span",
    "SpanTracer",
    "StepProbe",
    "default_tree",
    "prometheus_text",
    "read_samples",
    "tracer",
]
