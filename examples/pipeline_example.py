"""Feature pipeline: assemble -> scale -> logistic regression -> evaluate."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

from flink_ml_tpu import Pipeline, Table
from flink_ml_tpu.models.classification import LogisticRegression
from flink_ml_tpu.models.evaluation import BinaryClassificationEvaluator
from flink_ml_tpu.models.feature import StandardScaler, VectorAssembler

rng = np.random.default_rng(1)
age = rng.uniform(18, 80, size=1000)
income = rng.normal(50_000, 20_000, size=1000)
label = ((age / 80 + income / 100_000 + rng.normal(scale=0.2, size=1000)) > 1
         ).astype(np.int64)
table = Table({"age": age, "income": income, "label": label})

pipeline = Pipeline([
    VectorAssembler().set_input_cols("age", "income").set_features_col("raw"),
    StandardScaler().set_features_col("raw").set_output_col("features"),
    LogisticRegression().set_max_iter(50).set_learning_rate(0.5),
])
model = pipeline.fit(table)
scored = model.transform(table)[0]

metrics = (BinaryClassificationEvaluator()
           .set_metrics("areaUnderROC", "accuracy").transform(scored)[0])
print("AUC: %.3f  accuracy: %.3f"
      % (metrics["areaUnderROC"][0], metrics["accuracy"][0]))
