"""KMeans end-to-end: fit, predict, save/load.

Run: python examples/kmeans_example.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.clustering import KMeans, KMeansModel

rng = np.random.default_rng(0)
centers = np.array([[0.0, 0.0], [8.0, 8.0], [-8.0, 8.0]])
points = np.concatenate(
    [c + rng.normal(scale=0.5, size=(500, 2)) for c in centers])
table = Table({"features": points})

kmeans = KMeans().set_k(3).set_max_iter(20).set_seed(0)
model = kmeans.fit(table)
predictions = model.transform(table)[0]
print("cluster sizes:", np.bincount(predictions["prediction"]))

model.save("/tmp/kmeans_model")
reloaded = KMeansModel.load("/tmp/kmeans_model")
print("reloaded model predicts identically:",
      np.array_equal(reloaded.transform(table)[0]["prediction"],
                     predictions["prediction"]))
