"""Streaming FTRL: unbounded mini-batch feed with versioned model output."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.classification import OnlineLogisticRegression

rng = np.random.default_rng(2)
w_true = rng.normal(size=16)

def stream(n_batches=100, batch=256):
    for _ in range(n_batches):
        X = rng.normal(size=(batch, 16))
        yield Table({"features": X,
                     "label": (X @ w_true > 0).astype(np.int64)})

model = (OnlineLogisticRegression().set_alpha(0.5)
         .set(OnlineLogisticRegression.MODEL_SAVE_INTERVAL, 10)
         .fit(stream()))
print("model versions emitted:", len(model.version_history))

X = rng.normal(size=(1024, 16))
pred = model.transform(Table({"features": X}))[0]["prediction"]
print("holdout accuracy:", np.mean(pred == (X @ w_true > 0)))
