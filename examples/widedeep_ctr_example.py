"""Wide&Deep CTR (the BASELINE.md stretch config), streamed end-to-end:
synthetic click-log -> data cache -> per-epoch-shuffled out-of-core fit
-> AUC on held-out rows -> save/load.

Run: python examples/widedeep_ctr_example.py
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.data.datacache import DataCacheWriter, ShuffledCacheReader
from flink_ml_tpu.models.evaluation import BinaryClassificationEvaluator
from flink_ml_tpu.models.recommendation import WideDeep, WideDeepModel

rng = np.random.default_rng(0)
N, N_TEST = 1024, 256
VOCAB = [50, 20, 10]

def make_rows(n):
    dense = rng.normal(size=(n, 6)).astype(np.float32)
    cat = np.stack([rng.integers(0, v, size=n) for v in VOCAB],
                   axis=1).astype(np.int32)
    logit = (cat[:, 0] % 7 - 3) * 0.8 + dense[:, 0] * 1.5 + dense[:, 1]
    label = (logit + 0.5 * rng.normal(size=n) > 0).astype(np.float32)
    return dense, cat, label

# --- ingest: click rows land in a segmented columnar cache ------------
tmp = tempfile.mkdtemp(prefix="wdl_example_")
cache = os.path.join(tmp, "cache")
writer = DataCacheWriter(cache, segment_rows=512)
dense, cat, label = make_rows(N)
writer.append({"denseFeatures": dense, "catFeatures": cat, "label": label})
writer.finish()

# --- train: streamed epochs, reshuffled per epoch ---------------------
est = (WideDeep().set_vocab_sizes(VOCAB).set_max_iter(12).set_seed(0))
model = est.fit_outofcore(
    lambda epoch: ShuffledCacheReader(cache, batch_rows=256,
                                      seed=7, epoch=epoch))
print(f"train loss: {model.loss_log[0]:.4f} -> {model.loss_log[-1]:.4f}")

# --- evaluate on held-out rows ----------------------------------------
td, tc, ty = make_rows(N_TEST)
test = Table({"denseFeatures": td, "catFeatures": tc, "label": ty})
scored = model.transform(test)[0]
# `scored` already carries rawPrediction + label under the evaluator's
# default column names
metrics = (BinaryClassificationEvaluator()
           .set_metrics("areaUnderROC").transform(scored))[0]
auc = float(np.asarray(metrics["areaUnderROC"])[0])
print(f"held-out AUC: {auc:.3f}")
assert auc > 0.8

# --- persistence round trip -------------------------------------------
path = os.path.join(tmp, "model")
model.save(path)
reloaded = WideDeepModel.load(path)
again = reloaded.transform(test)[0]
np.testing.assert_allclose(again["rawPrediction"], scored["rawPrediction"],
                           rtol=1e-6)
print("save/load round trip OK")
