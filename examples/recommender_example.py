"""Recommender loop: ALS factorization -> top-k recommendations with
train-pair exclusion -> ranking metrics on held-out interactions."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.evaluation import RankingEvaluator
from flink_ml_tpu.models.recommendation import ALS

rng = np.random.default_rng(0)
N_USERS, N_ITEMS = 120, 40

# two taste groups: users mostly rate items from their own half
rows = []
for u in range(N_USERS):
    group = (np.arange(N_ITEMS // 2) + (u % 2) * (N_ITEMS // 2))
    liked = rng.choice(group, size=10, replace=False)
    for it in liked:
        rows.append((u, int(it), float(rng.uniform(3.5, 5.0))))
    # noise never collides with liked items: a duplicate (user, item)
    # pair would keep a held-out item in train and get it excluded
    noise_pool = np.setdiff1d(np.arange(N_ITEMS), liked)
    for it in rng.choice(noise_pool, size=2, replace=False):
        rows.append((u, int(it), float(rng.uniform(1.0, 2.0))))

users, items, ratings = map(np.asarray, zip(*rows))
# hold out 3 liked items per user for evaluation
holdout = {}
train_mask = np.ones(len(users), bool)
for u in range(N_USERS):
    own = np.flatnonzero((users == u) & (ratings > 3.0))
    held = rng.choice(own, size=3, replace=False)
    holdout[u] = items[held].tolist()
    train_mask[held] = False

train = Table({"user": users[train_mask], "item": items[train_mask],
               "rating": ratings[train_mask]})

model = (ALS().set_rank(8).set_max_iter(12).set_reg_param(0.05)
         .fit(train))
recs = model.recommend_for_users(np.arange(N_USERS), k=10, exclude=train)

truth = np.empty(N_USERS, object)
for u in range(N_USERS):
    truth[u] = holdout[u]
metrics = (RankingEvaluator().set_k(10)
           .transform(Table({"prediction": recs["recommendations"],
                             "label": truth}))[0])
print("recall@10: %.3f  ndcg@10: %.3f  hitRate@10: %.3f"
      % (metrics["recallAtK"][0], metrics["ndcgAtK"][0],
         metrics["hitRateAtK"][0]))
