"""Text classification: tokenize -> stop words -> count vectorize ->
TF-IDF -> logistic regression, all through one Pipeline."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

from flink_ml_tpu import Pipeline, Table
from flink_ml_tpu.models.classification import LogisticRegression
from flink_ml_tpu.models.evaluation import BinaryClassificationEvaluator
from flink_ml_tpu.models.feature import (
    CountVectorizer,
    IDF,
    StopWordsRemover,
    Tokenizer,
)

POSITIVE = ["great", "excellent", "wonderful", "amazing", "love"]
NEGATIVE = ["terrible", "awful", "horrible", "boring", "hate"]
FILLER = ["the", "movie", "was", "plot", "acting", "really", "a", "film"]

rng = np.random.default_rng(0)
docs, labels = [], []
for _ in range(400):
    y = int(rng.random() < 0.5)
    lexicon = POSITIVE if y else NEGATIVE
    words = list(rng.choice(FILLER, size=6)) + \
        list(rng.choice(lexicon, size=rng.integers(1, 4)))
    rng.shuffle(words)
    docs.append(" ".join(words))
    labels.append(y)

table = Table({"features": np.asarray(docs, dtype=object),
               "label": np.asarray(labels, np.float64)})

pipeline = Pipeline([
    Tokenizer().set_output_col("tokens"),
    StopWordsRemover().set_features_col("tokens").set_output_col("kept"),
    CountVectorizer().set_features_col("kept").set_output_col("counts"),
    IDF().set_features_col("counts").set_output_col("tfidf"),
    LogisticRegression().set_features_col("tfidf").set_max_iter(30)
        .set_learning_rate(0.5),
])
model = pipeline.fit(table)
scored = model.transform(table)[0]

metrics = (BinaryClassificationEvaluator()
           .set_metrics("areaUnderROC", "accuracy").transform(scored)[0])
print("vocabulary size:", len(model.stages[2].vocabulary))
print("AUC: %.3f  accuracy: %.3f"
      % (metrics["areaUnderROC"][0], metrics["accuracy"][0]))
