"""Multi-host training demo: 2 OS processes, each holding its own data
shard, jointly fit LogisticRegression (mixed Criteo layout) and KMeans
over one process-spanning mesh.

Run with no arguments: the script spawns itself twice as jax.distributed
participants (2 CPU devices each — the MiniCluster-style local stand-in
for 2 TPU hosts) and prints both processes' identical results.
"""
import os
import socket
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))


def worker(coord: str, nprocs: int, pid: int) -> None:
    from flink_ml_tpu.utils.backend import force_virtual_cpu

    force_virtual_cpu(2, verify=False)  # jax.distributed owns backend init

    import numpy as np

    from flink_ml_tpu import Table
    from flink_ml_tpu.models.clustering import KMeans
    from flink_ml_tpu.parallel import distributed as dist
    from flink_ml_tpu.parallel.mesh import use_mesh

    dist.initialize(coordinator_address=coord, num_processes=nprocs,
                    process_id=pid)
    mesh = dist.global_mesh()

    # each process contributes ITS OWN 512-row shard; the global batch is
    # the concatenation and the gradient reduction crosses hosts
    rng = np.random.default_rng(pid)
    dense = rng.normal(size=(512, 13)).astype(np.float32)
    cat = rng.integers(32, 1 << 16, size=(512, 26)).astype(np.int32)
    label = rng.integers(0, 2, size=512).astype(np.float64)
    cat[:, 0] = np.where(label == 1, 16, 17)

    from flink_ml_tpu.models.common.losses import LOSSES
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_mixed

    state, log = sgd_fit_mixed(
        LOSSES["logistic"], dense, cat, label, None, 1 << 16,
        SGDConfig(learning_rate=0.5, max_epochs=6, tol=0,
                  global_batch_size=128), mesh=mesh)

    # KMeans over per-host shards of the same 3 clusters
    centers = np.asarray([[8.0, 0.0], [-8.0, 8.0], [0.0, -8.0]], np.float32)
    pts = np.concatenate([c + rng.normal(scale=0.4, size=(40, 2))
                          for c in centers]).astype(np.float32)
    with use_mesh(mesh):
        km = KMeans().set_k(3).set_max_iter(15).fit(Table({"features": pts}))
    got = np.sort(np.asarray(km.get_model_data()[0]["centroids"][0]), axis=0)

    print(f"[process {pid}] LR loss {log[0]:.3f}->{log[-1]:.3f}  "
          f"w[16]={state.coefficients[16]:+.3f} w[17]="
          f"{state.coefficients[17]:+.3f}  kmeans c0={got[0].round(1)}")
    dist.barrier("done")


def main() -> None:
    if len(sys.argv) > 1:
        worker(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
        return
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), coord, "2", str(p)],
        env=env) for p in range(2)]
    try:
        for p in procs:
            p.wait(timeout=300)
            assert p.returncode == 0, f"worker exited {p.returncode}"
    finally:
        # one worker dying strands its peer in a collective; never leave
        # an orphan spinning
        for p in procs:
            if p.poll() is None:
                p.kill()
    print("both processes agreed; multi-host fit complete")


if __name__ == "__main__":
    main()
