"""The Criteo north-star pipeline end-to-end on a small synthetic
day-file: raw TSV -> parallel parse (CriteoTSVReader) -> parallel
columnar cache (DataCacheWriter) -> out-of-core mixed-layout
LogisticRegression with instrumented prefetch, then a crash-resumable
second epoch via mid-epoch checkpoints.

Run: python examples/criteo_e2e_pipeline_example.py
"""
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

from flink_ml_tpu.data import PrefetchStats
from flink_ml_tpu.data.criteo import CriteoTSVReader
from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter
from flink_ml_tpu.iteration.checkpoint import CheckpointConfig
from flink_ml_tpu.models.common.losses import logistic_loss
from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

work = tempfile.mkdtemp(prefix="criteo_e2e_")
rng = np.random.default_rng(0)

# --- synthesize a tiny "day file": C1 encodes the label -------------------
rows = 20_000
day = os.path.join(work, "day_0.tsv")
with open(day, "w") as f:
    for _ in range(rows):
        y = int(rng.random() < 0.5)
        ints = "\t".join(str(int(v)) for v in rng.integers(-2, 9, 13))
        toks = [("aaaa1111", "bbbb2222")[y]] + [
            f"{rng.integers(0, 1 << 32):08x}" for _ in range(25)]
        f.write(f"{y}\t{ints}\t" + "\t".join(toks) + "\n")

# --- stage 1+2: parse -> cache (both sides thread-parallel) ---------------
hash_space = 1 << 16
reader = CriteoTSVReader(day, batch_rows=2048, hash_space=hash_space,
                         workers=0)           # 0 = auto (cores - 1)
writer = DataCacheWriter(os.path.join(work, "cache"), segment_rows=8192,
                         workers=2, borrow_batches=True)  # reader yields
                                                          # fresh arrays
t0 = time.perf_counter()
n = 0
for batch in reader:
    writer.append(batch)
    n += len(batch["label"])
writer.finish()
print(f"ingested {n} rows at "
      f"{n / (time.perf_counter() - t0):,.0f} rows/s "
      f"({reader.workers} parse workers)")

# --- stage 3: out-of-core fit with mid-epoch checkpoints ------------------
stats = PrefetchStats()
cfg = SGDConfig(learning_rate=0.5, max_epochs=4, tol=0)
state, losses = sgd_fit_outofcore(
    logistic_loss,
    lambda: DataCacheReader(os.path.join(work, "cache"), batch_rows=2048),
    num_features=13 + hash_space, config=cfg,
    dense_key="features_dense", indices_key="features_indices",
    prefetch_workers=2, prefetch_stats=stats,
    checkpoint=CheckpointConfig(os.path.join(work, "ckpt")),
    checkpoint_every_steps=4)
print("epoch losses:", [round(v, 4) for v in losses])
print("prefetch stages:", stats.as_dict())

# --- resume from the mid-epoch cut (same answer, no recompute) ------------
state2, losses2 = sgd_fit_outofcore(
    logistic_loss,
    lambda: DataCacheReader(os.path.join(work, "cache"), batch_rows=2048),
    num_features=13 + hash_space, config=cfg,
    dense_key="features_dense", indices_key="features_indices",
    checkpoint=CheckpointConfig(os.path.join(work, "ckpt")), resume=True)
assert np.allclose(state2.coefficients, state.coefficients)
print("resume from checkpoint reproduces the converged weights exactly")
