"""The raw iteration API: fused epoch loop with termination criteria."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax.numpy as jnp

from flink_ml_tpu.iteration import (IterationBodyResult, IterationConfig,
                                    iterate)

# Newton iteration for sqrt(2), terminating when converged
def body(x, epoch):
    new_x = 0.5 * (x + 2.0 / x)
    return IterationBodyResult(feedback=new_x, outputs=new_x,
                               termination=jnp.abs(new_x - x) > 1e-6)

result = iterate(body, jnp.asarray(1.0), max_epochs=50,
                 config=IterationConfig(mode="fused"))
print(f"sqrt(2) = {float(result.state):.8f} in {result.num_epochs} epochs")
