"""Pod-posture LogisticRegression: the same Criteo-shaped fit under the
three mesh layouts the trainer plans (run on any 8-device setting — a
v5e-8 pod, or this script's virtual CPU mesh):

- pure data axis: batch sharded, weight replicated; on TPU the
  categorical scatter runs the data-sharded ELL kernel (device-local
  grids + one psum — ``sgd._mixed_update_ell_sharded``).
- dp x model: the weight ITSELF shards over 'model' (the 2^24+
  hash-space posture — hash spaces that must never replicate).
- single device: the classic layout every result must match.

All three produce the same coefficients (the oracle stance the test
suite enforces); what changes is where HBM and the scatter work live.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def main() -> None:
    import jax

    # On a real 8-chip pod set FLINK_ML_TPU_POD=1 to keep the TPU
    # backend; default is the 8-device virtual CPU mesh, decided WITHOUT
    # touching jax.devices() (with the TPU relay registered but down,
    # the first device use blocks for minutes).
    if not os.environ.get("FLINK_ML_TPU_POD"):
        from flink_ml_tpu.utils.backend import force_virtual_cpu

        force_virtual_cpu(8)

    import numpy as np

    from flink_ml_tpu.models.common.losses import LOSSES
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_mixed
    from flink_ml_tpu.parallel.mesh import device_mesh

    rng = np.random.default_rng(0)
    n, nd, nc, d = 4096, 13, 26, 1 << 18
    dense = rng.normal(size=(n, nd)).astype(np.float32)
    cat = rng.integers(nd, d, size=(n, nc)).astype(np.int32)
    y = (dense[:, 0] + 0.2 > 0).astype(np.float64)
    cfg = SGDConfig(learning_rate=0.5, max_epochs=4, tol=0,
                    global_batch_size=512)

    results = {}
    for name, axes in [
        ("data x8", {"data": 8}),
        ("dp4 x model2", {"data": 4, "model": 2}),
        ("single device", {"data": 1}),
    ]:
        devs = jax.devices()[: int(np.prod(list(axes.values())))]
        mesh = device_mesh(axes, devices=devs)
        state, log = sgd_fit_mixed(LOSSES["logistic"], dense, cat, y, None,
                                   d, cfg, mesh=mesh)
        results[name] = state
        print(f"{name:15s} planned={state.planned_impl:8s} "
              f"loss {log[0]:.4f} -> {log[-1]:.4f}")

    ref = results["single device"].coefficients
    for name, state in results.items():
        np.testing.assert_allclose(state.coefficients, ref, atol=1e-5)
    print("all three layouts agree to 1e-5")


if __name__ == "__main__":
    main()
