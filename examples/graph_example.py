"""Graph (DAG) composition: branch, merge, multi-output.

Run: python examples/graph_example.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

from flink_ml_tpu import GraphBuilder, Table
from flink_ml_tpu.models.classification import SoftmaxRegression
from flink_ml_tpu.models.evaluation import MulticlassClassificationEvaluator
from flink_ml_tpu.models.feature import StandardScaler

rng = np.random.default_rng(0)
centers = rng.normal(scale=6.0, size=(3, 4))
y = rng.integers(0, 3, 3000)
X = centers[y] + rng.normal(size=(3000, 4))
table = Table({"features": X, "label": y})

b = GraphBuilder()
src = b.source()
scaled = b.add_stage(StandardScaler().set_output_col("features"), [src])[0]
pred = b.add_stage(SoftmaxRegression().set_max_iter(30), [scaled])[0]
metrics = b.add_stage(
    MulticlassClassificationEvaluator().set_metrics("accuracy"), [pred])[0]
graph = b.build(inputs=[src], outputs=[pred, metrics])

model = graph.fit(table)
predictions, metrics_t = model.transform(table)
print("accuracy:", float(np.asarray(metrics_t["accuracy"])[0]))

model.save("/tmp/graph_model")
from flink_ml_tpu import GraphModel
reloaded = GraphModel.load("/tmp/graph_model")
print("reloaded predicts identically:",
      np.array_equal(np.asarray(reloaded.transform(table)[0]["prediction"]),
                     np.asarray(predictions["prediction"])))
