"""ALS recommendation: explicit ratings, fit + predict + top items.

Run: python examples/als_example.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.recommendation import ALS

rng = np.random.default_rng(0)
n_users, n_items, rank = 200, 100, 6
U = rng.normal(size=(n_users, rank)) / np.sqrt(rank)
V = rng.normal(size=(n_items, rank)) / np.sqrt(rank)
mask = rng.random((n_users, n_items)) < 0.15
u, i = np.nonzero(mask)
ratings = (U @ V.T)[u, i] + 0.05 * rng.normal(size=len(u))

table = Table({"user": u.astype(np.int64), "item": i.astype(np.int64),
               "rating": ratings})
model = (ALS().set_rank(8).set_max_iter(12).set_reg_param(1e-2)
         .fit(table))

pred = np.asarray(model.transform(table)[0]["prediction"])
print("train rmse:", round(float(np.sqrt(np.mean((pred - ratings) ** 2))), 4))

# top-3 items for user 0 (over all items)
items = np.arange(n_items, dtype=np.int64)
scores = np.asarray(model.transform(Table({
    "user": np.zeros(n_items, np.int64), "item": items}))[0]["prediction"])
print("user 0 top items:", items[np.argsort(-scores)[:3]].tolist())
