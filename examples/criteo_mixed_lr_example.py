"""Criteo-shaped CTR training: the mixed dense+categorical layout.

13 dense features ride weight slots [0, 13) through a matvec; 26 hashed
categorical fields (implicit value 1.0) go through the 128-lane blocked
gather/scatter — the framework's fastest LR path on TPU (see
ARCHITECTURE.md 'Performance').  The same Table convention
(`{col}_dense` + `{col}_indices`) also streams from a DataCacheReader
via `fit_outofcore(mixed=True)` for datasets beyond RAM.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.classification import LogisticRegression
from flink_ml_tpu.models.evaluation import BinaryClassificationEvaluator

N, N_DENSE, N_CAT, HASH_DIM = 20_000, 13, 26, 1 << 18

rng = np.random.default_rng(0)
dense = rng.normal(size=(N, N_DENSE)).astype(np.float32)
# hashed indices start at 32: ONE weight vector serves both layouts, with
# dense features owning slots [0, N_DENSE) — a hasher that can emit low
# indices would silently alias categorical features onto dense weights,
# so offset (or mask) your hash range above N_DENSE
cat = rng.integers(32, HASH_DIM, size=(N, N_CAT)).astype(np.int32)
label = rng.integers(0, 2, size=N).astype(np.float64)
# two informative hashed slots: field 0 encodes the class
cat[:, 0] = np.where(label == 1, 16, 17)

table = Table({"features_dense": dense, "features_indices": cat,
               "label": label})

lr = (LogisticRegression()
      .set_num_features(HASH_DIM)       # the hash-space size
      .set_max_iter(8).set_learning_rate(0.5).set_global_batch_size(2048))
model = lr.fit(table)
scored = model.transform(table)[0]

metrics = (BinaryClassificationEvaluator()
           .set_metrics("areaUnderROC", "accuracy").transform(scored)[0])
print("loss log:", [round(float(v), 4) for v in model.loss_log])
print("AUC: %.3f  accuracy: %.3f"
      % (metrics["areaUnderROC"][0], metrics["accuracy"][0]))
