"""Model selection: CrossValidator over a scaler -> LogisticRegression
Pipeline with a hyperparameter grid, then OneVsRest for multiclass.

Run: python examples/model_selection_example.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

from flink_ml_tpu import CrossValidator, ParamGridBuilder, Pipeline, Table
from flink_ml_tpu.models.classification import (LogisticRegression,
                                                OneVsRest)
from flink_ml_tpu.models.evaluation.binary_evaluator import (
    BinaryClassificationEvaluator,
)
from flink_ml_tpu.models.feature.scalers import StandardScaler

rng = np.random.default_rng(0)
n = 1000
X = rng.normal(size=(n, 5)) * np.array([20.0, 0.05, 1.0, 1.0, 1.0])
y = (X[:, 0] / 20 + 20 * X[:, 1] + 0.3 * X[:, 2] > 0).astype(np.float64)
table = Table({"features": X, "label": y})

# --- CV over a pipeline: grid binds into the LR child by param identity --
pipe = Pipeline([
    StandardScaler().set_output_col("features"),
    (LogisticRegression().set_learning_rate(0.5)
     .set_global_batch_size(256)),
])
grid = (ParamGridBuilder()
        .add_grid(LogisticRegression.REG, [0.0, 0.05])
        .add_grid(LogisticRegression.MAX_ITER, [3, 30])
        .build())
evaluator = (BinaryClassificationEvaluator()
             .set_raw_prediction_col("rawPrediction")
             .set_metrics("areaUnderROC"))

cv = CrossValidator(pipe, evaluator, grid).set_num_folds(3).set_seed(7)
model = cv.fit(table)
print("candidate AUCs:", [round(a, 4) for a in model.avg_metrics])
print("best:", {p.name: v for p, v in model.best_params.items()})
pred = np.asarray(model.transform(table)[0]["prediction"]).ravel()
print("refit accuracy:", round(float((pred == y).mean()), 3))

# --- OneVsRest: the binary winner config, lifted to 3 classes -----------
centers = np.array([[3.0, 0.0], [-3.0, 1.5], [0.0, -3.0]])
yk = rng.integers(0, 3, size=900)
Xm = centers[yk] + 0.5 * rng.normal(size=(900, 2))
multi = Table({"features": Xm, "label": yk.astype(np.float64)})
ovr = OneVsRest(LogisticRegression().set_max_iter(30)
                .set_learning_rate(0.5).set_global_batch_size(256)
                .set_raw_prediction_col("rawPrediction"))
m = ovr.fit(multi)
pm = np.asarray(m.transform(multi)[0][m.get_prediction_col()]).ravel()
print("one-vs-rest accuracy:", round(float((pm == yk).mean()), 3))
