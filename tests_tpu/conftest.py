"""TPU-parity tier (VERDICT r3 task 8) — deliberately OUTSIDE tests/ so
the unit suite's conftest (which pins the virtual CPU mesh) never
applies.  Run explicitly before benching:

    python -m pytest tests_tpu/ -m tpu -q

Every test here compiles a Mosaic kernel on tiny shapes and parity-checks
it against its XLA twin (~30 s total on a warm cache), so a
remote-compiler failure (HTTP 500s on some shapes — a known axon mode)
localizes to a named kernel instead of poisoning a timed bench leg.
bench.py runs the same preflight asserts inline; this tier exists to run
them WITHOUT the bench's data-build cost.

If the axon relay is down, the first device use in here blocks for many
minutes — that is the signal to skip benching entirely (bench.py's
subprocess probe handles that case itself).
"""

import numpy as np
import pytest


@pytest.fixture(scope="session")
def tpu():
    import jax

    if jax.default_backend() != "tpu":
        pytest.skip("TPU backend unavailable (axon relay not registered)")
    return jax.devices()[0]


@pytest.fixture
def rng():
    return np.random.default_rng(7)
