"""Tiny-shape Mosaic compile + XLA-twin parity for every kernel the bench
times.  Shapes are the smallest each kernel supports, so a failure here
is a compiler/runtime break, never an OOM or capacity artifact."""

import numpy as np
import pytest

pytestmark = pytest.mark.tpu


def test_ell_scatter_mixed_kernel_parity(tpu, rng):
    import jax.numpy as jnp

    from flink_ml_tpu.ops.ell_scatter import (
        ell_layout,
        ell_scatter_apply,
        ell_scatter_apply_xla,
    )

    d = 128 * 128          # smallest supported table
    cat = rng.integers(0, d, size=(1, 64, 8)).astype(np.int32)
    lay = ell_layout(cat, d)
    u = rng.normal(size=(d // 128, 128)).astype(np.float32)
    w0 = rng.normal(size=d).astype(np.float32)
    got = np.asarray(ell_scatter_apply(
        jnp.asarray(w0), jnp.asarray(u), lay.pos[0], lay.mask[0]))
    want = np.asarray(ell_scatter_apply_xla(
        jnp.asarray(w0), jnp.asarray(u), lay.pos[0], lay.mask[0]))
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_ell_full_step_matches_xla_update(tpu, rng):
    """One whole _mixed_update_ell step (gather + kernel + overflow +
    heavy) against the plain-XLA mixed update — the exact pre-timing
    assert the bench runs, on a 64-row batch."""
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.models.common.losses import LOSSES
    from flink_ml_tpu.models.common.sgd import (
        SGDConfig,
        _mixed_update,
        _mixed_update_ell,
    )
    from flink_ml_tpu.ops.ell_scatter import ell_layout

    d, batch, nnz, nd = 128 * 128, 64, 4, 3
    dense = rng.normal(size=(batch, nd)).astype(np.float32)
    cat = rng.integers(nd, d, size=(1, batch, nnz)).astype(np.int32)
    y = rng.integers(0, 2, size=batch).astype(np.float32)
    wb = np.ones(batch, np.float32)
    lay = ell_layout(cat, d)
    cfg = SGDConfig(learning_rate=0.5, global_batch_size=batch)
    params = {"w": jnp.zeros((d,), jnp.float32),
              "b": jnp.zeros((), jnp.float32)}

    p_ell, v_ell = jax.jit(_mixed_update_ell(LOSSES["logistic"], cfg))(
        params, dense, lay.src[0], lay.pos[0], lay.mask[0],
        lay.ovf_idx[0], lay.ovf_src[0], lay.heavy_idx[0], lay.heavy_cnt[0],
        y, wb)
    p_xla, v_xla = jax.jit(_mixed_update(LOSSES["logistic"], cfg))(
        params, dense, cat[0], y, wb)
    np.testing.assert_allclose(float(v_ell), float(v_xla), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(p_ell["w"]),
                               np.asarray(p_xla["w"]), atol=1e-4)


def test_ell_scatter_values_kernel_parity(tpu, rng):
    """The values-aware layout (sgd_fit_sparse's path) through the same
    kernel."""
    import jax.numpy as jnp

    from flink_ml_tpu.ops.ell_scatter import (
        ell_layout,
        ell_scatter_apply,
        ell_scatter_apply_xla,
    )

    d = 128 * 128
    idx = rng.integers(0, d, size=(1, 64, 8)).astype(np.int32)
    vals = rng.normal(size=(1, 64, 8)).astype(np.float32)
    lay = ell_layout(idx, d, values=vals)
    r = rng.normal(size=65).astype(np.float32)  # extended residual
    u = np.asarray(lay.val[0]) * r[np.asarray(lay.src[0])]
    w0 = rng.normal(size=d).astype(np.float32)
    got = np.asarray(ell_scatter_apply(
        jnp.asarray(w0), jnp.asarray(u), lay.pos[0], lay.mask[0]))
    want = np.asarray(ell_scatter_apply_xla(
        jnp.asarray(w0), jnp.asarray(u), lay.pos[0], lay.mask[0]))
    np.testing.assert_allclose(got, want, atol=1e-4)


@pytest.mark.parametrize("tie_policy", ["first", "split", "fast"])
def test_kmeans_kernel_parity(tpu, rng, tie_policy):
    """kmeans_update_stats (the fused Lloyd's kernel) vs the XLA epoch
    body on one tiny block."""
    import jax.numpy as jnp

    from flink_ml_tpu.ops.kmeans_pallas import kmeans_update_stats

    n, dcol, k = 8192, 8, 4   # one block_n tile
    # Well-separated clusters: this tier tests the Mosaic compile, not
    # matmul tie-breaking — with overlapping random-normal data the TPU's
    # reduced-precision MXU pass flips ~0.1% of near-boundary assignments
    # vs a float64 oracle (observed r4), which is fit-quality noise, not
    # a kernel bug.  20-unit center spacing vs sigma=1 noise makes every
    # margin precision-proof.
    true_c = np.zeros((k, dcol), np.float32)
    true_c[:, 0] = 20.0 * np.arange(k)
    label = rng.integers(0, k, size=n)
    pts = (true_c[label] + rng.normal(size=(n, dcol))).astype(np.float32)
    cents = (true_c + 0.5 * rng.normal(size=(k, dcol))).astype(np.float32)
    sums, counts = kmeans_update_stats(jnp.asarray(pts), jnp.asarray(cents),
                                       block_n=8192, tie_policy=tie_policy)
    # numpy oracle: single-assignment Lloyd's stats (separated clusters
    # have no ties, so all tie policies must agree with it)
    d2 = ((pts[:, None, :] - cents[None, :, :]) ** 2).sum(-1)
    assign = d2.argmin(1)
    want_counts = np.bincount(assign, minlength=k).astype(np.float64)
    want_sums = np.zeros((k, dcol))
    np.add.at(want_sums, assign, pts)
    # counts are the exact-parity guard: any flipped assignment shows up
    # as a whole unit.  sums pass through one default-precision MXU dot
    # (inputs truncated to bf16, ~2^-8 relative), so their tolerance is
    # bf16-scaled: a genuine misassignment would move a sum by >= the
    # 20-unit cluster separation, far past it.
    np.testing.assert_allclose(np.asarray(counts, np.float64), want_counts,
                               atol=1e-3)
    np.testing.assert_allclose(np.asarray(sums, np.float64), want_sums,
                               rtol=2e-3, atol=0.5)


def test_ell_fused_gather_kernel_parity(tpu, rng):
    """Mosaic compile + parity for the EXPERIMENTAL fused-gather kernel
    (per-row one-hot MXU contraction + transpose — the riskiest Mosaic
    surface in the repo; a compile failure here names it cheaply)."""
    import jax.numpy as jnp

    from flink_ml_tpu.ops.ell_scatter import (
        ell_layout,
        ell_scatter_apply_fused,
        ell_scatter_apply_xla,
    )

    d, batch, nnz = 128 * 128, 96, 7
    cat = rng.integers(0, d, size=(1, batch, nnz)).astype(np.int32)
    lay = ell_layout(cat, d)
    r = rng.normal(size=batch).astype(np.float32)
    r_ext = np.concatenate([r, np.zeros(256 - batch % 256, np.float32)])
    w0 = rng.normal(size=d).astype(np.float32)
    u = (-0.35) * jnp.asarray(r_ext)[lay.src[0]]
    want = np.asarray(ell_scatter_apply_xla(
        jnp.asarray(w0), u, lay.pos[0], lay.mask[0]))
    # default precision: the in-kernel one-hot contraction truncates the
    # gathered residuals to bf16 (~2^-8 relative) — bf16-scaled tolerance
    got = np.asarray(ell_scatter_apply_fused(
        jnp.asarray(w0), jnp.asarray(r_ext), lay.src[0], lay.pos[0],
        lay.mask[0], lr=0.35))
    np.testing.assert_allclose(got, want, atol=6e-3)
    # highest precision: exact parity with the XLA gather
    got = np.asarray(ell_scatter_apply_fused(
        jnp.asarray(w0), jnp.asarray(r_ext), lay.src[0], lay.pos[0],
        lay.mask[0], lr=0.35, precision="highest"))
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_ell_margin_kernel_parity(tpu, rng):
    """Mosaic compile + parity for the fused margin kernel (r4: forward
    half of the ELL plan) against the direct gather, both layouts."""
    import jax.numpy as jnp

    from flink_ml_tpu.ops.ell_scatter import ell_layout, ell_margin_fused

    d, batch, nnz, m_len = 128 * 128, 96, 7, 256
    cat = rng.integers(0, d, size=(1, batch, nnz)).astype(np.int32)
    w = rng.normal(size=d).astype(np.float32)
    lay = ell_layout(cat, d)
    want = w[cat[0]].sum(axis=1)
    # default-precision tolerance: nnz=7 bf16-truncated terms of |w|<~4
    # each carry up to ~|w|*2^-8 — worst-case sum ~0.1.  "default" IS the
    # production setting (SGDConfig.ell_precision): exactness there is
    # epoch-level (the residuals are batch-normalized, see sgd.py), while
    # this per-call check sees raw weights
    for prec, tol in (("highest", 1e-4), ("default", 0.1)):
        got = np.asarray(ell_margin_fused(
            jnp.asarray(w), lay.src[0], lay.pos[0], lay.mask[0],
            m_len=m_len, precision=prec))
        np.testing.assert_allclose(got[:batch], want, atol=tol)
    vals = rng.normal(size=(1, batch, nnz)).astype(np.float32)
    layv = ell_layout(cat, d, values=vals)
    wantv = (vals[0] * w[cat[0]]).sum(axis=1)
    got = np.asarray(ell_margin_fused(
        jnp.asarray(w), layv.src[0], layv.pos[0], layv.mask[0],
        m_len=m_len, val=layv.val[0], precision="highest"))
    np.testing.assert_allclose(got[:batch], wantv, atol=1e-4)


def test_routed_table_grad_both_placements_on_device(tpu, rng):
    """The r5 routed table gradients (ops/emb_grad.py): both placements
    must compile and match the scatter-add oracle on the real chip
    (pure-XLA paths, but the sorted-unique scatter flags and the big
    row-gather are exactly what a backend change could break)."""
    import jax.numpy as jnp

    from flink_ml_tpu.ops.emb_grad import emb_grad_route

    vocab, emb = 4096, 8
    cat = rng.integers(0, vocab, size=(2, 64, 4)).astype(np.int64)
    g = rng.normal(size=(256, emb)).astype(np.float32)
    want = np.zeros((vocab, emb), np.float64)
    np.add.at(want, cat[0].reshape(-1), g)
    for placement in ("gather", "scatter"):
        route = emb_grad_route(cat, vocab, placement=placement)
        got = np.asarray(route.apply(
            jnp.asarray(g), *(jnp.asarray(np.asarray(a))
                              for a in route.step_slice(0))))
        np.testing.assert_allclose(got, want.astype(np.float32),
                                   rtol=1e-4, atol=1e-4, err_msg=placement)


def test_als_sorted_neq_on_device(tpu, rng):
    """Sorted MXU normal equations vs the scatter form on the chip
    (dynamic-slice band accumulation + one-hot dot_general under
    'highest' precision)."""
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.models.recommendation.als import (
        NeqPlan, _normal_equations, _normal_equations_sorted)

    n_groups, n_other, nnz, rank = 16, 8, 512, 4
    g = rng.integers(0, n_groups, size=nnz)
    o = rng.integers(0, n_other, size=nnz).astype(np.int32)
    r = rng.normal(size=nnz).astype(np.float32)
    w = np.ones(nnz, np.float32)
    factors = rng.normal(size=(n_other, rank)).astype(np.float32)
    plan = NeqPlan(g, chunk=128)
    with jax.default_matmul_precision("highest"):
        A0, b0, c0 = _normal_equations(
            jnp.asarray(factors), jnp.asarray(g, jnp.int32),
            jnp.asarray(o), jnp.asarray(r), jnp.asarray(w),
            n_groups, False, 1.0)
        A1, b1, c1 = _normal_equations_sorted(
            jnp.asarray(factors), jnp.asarray(plan.sort_pad(o)),
            jnp.asarray(plan.sort_pad(r)), jnp.asarray(plan.sort_pad(w)),
            jnp.asarray(plan.local_rank), jnp.asarray(plan.g_lo),
            n_groups, plan.span, plan.chunk, False, 1.0)
    np.testing.assert_allclose(np.asarray(A1), np.asarray(A0),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(b1), np.asarray(b0),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(c1), np.asarray(c0),
                               rtol=1e-5, atol=1e-5)


def test_gbt_mxu_hist_on_device(tpu, rng):
    """MXU double-one-hot histograms vs segment_sum on the chip."""
    import jax.numpy as jnp

    from flink_ml_tpu.models.common import gbt

    n, d, bins, n_nodes = 256, 4, 16, 4
    binned = jnp.asarray(rng.integers(0, bins, size=(n, d)), jnp.int32)
    ids = jnp.asarray(rng.integers(-1, n_nodes, size=n), jnp.int32)
    g = jnp.asarray(rng.normal(size=n), jnp.float32)
    h = jnp.asarray(rng.random(n) + 0.1, jnp.float32)
    gs, hs = gbt._level_histograms_segsum(binned, ids, g, h, n_nodes, d,
                                          bins)
    gm, hm = gbt._level_histograms_mxu(binned, ids, g, h, n_nodes, d,
                                       bins)
    np.testing.assert_allclose(np.asarray(gm), np.asarray(gs),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(hm), np.asarray(hs),
                               rtol=1e-4, atol=1e-4)
