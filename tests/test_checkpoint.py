"""Checkpoint/resume tests — the analog of the reference's fault-injection
ITCases (``BoundedAllRoundCheckpointITCase.java:76-120``): after a failure +
restore, the final converged values must be exact."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_ml_tpu.iteration import (
    CheckpointConfig,
    CheckpointManager,
    IterationBodyResult,
    IterationConfig,
    iterate,
    load_pytree,
    save_pytree,
)


def test_pytree_round_trip(tmp_path):
    tree = {
        "w": np.arange(6, dtype=np.float32).reshape(2, 3),
        "opt": (np.float64(0.5), [np.int32(3), None]),
        "epoch": 7,
    }
    path = str(tmp_path / "state")
    save_pytree(path, tree, meta={"k": "v"})
    restored, meta = load_pytree(path)
    assert meta["k"] == "v"
    np.testing.assert_array_equal(restored["w"], tree["w"])
    assert restored["opt"][0] == 0.5
    assert restored["opt"][1][0] == 3
    assert restored["opt"][1][1] is None
    assert restored["epoch"] == 7
    assert isinstance(restored["opt"], tuple)


def test_atomic_overwrite(tmp_path):
    path = str(tmp_path / "state")
    save_pytree(path, {"x": np.ones(3)})
    save_pytree(path, {"x": np.zeros(3)})
    restored, _ = load_pytree(path)
    np.testing.assert_array_equal(restored["x"], np.zeros(3))


def test_manager_gc_and_latest(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(str(tmp_path), interval=1,
                                             max_to_keep=2))
    for epoch in range(5):
        mgr.save(epoch, {"v": np.asarray(epoch)})
    assert mgr.list_epochs() == [3, 4]
    epoch, state, _ = mgr.restore_latest()
    assert epoch == 4 and int(state["v"]) == 4


def test_manager_interval(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(str(tmp_path), interval=3))
    assert [e for e in range(7) if mgr.should_save(e)] == [0, 3, 6]
    with pytest.raises(ValueError):
        CheckpointConfig(str(tmp_path), interval=0)


def _run(body, steps, ckpt_dir=None, resume=False, interval=1):
    checkpoint = (CheckpointConfig(ckpt_dir, interval=interval)
                  if ckpt_dir else None)
    return iterate(body, jnp.asarray(1.0), max_epochs=steps,
                   config=IterationConfig(mode="hosted"),
                   checkpoint=checkpoint, resume=resume)


def test_restore_and_converge_exactly(tmp_path):
    # Deterministic replay: run 10 epochs straight vs. crash-at-6 + resume;
    # final state must be bit-identical (the exactly-once equivalence bar).
    def body(x, epoch):
        return IterationBodyResult(x * 1.5 + jnp.asarray(epoch, jnp.float32),
                                   outputs=None)

    full = _run(body, 10)

    ckpt = str(tmp_path / "ckpt")
    # "crash" after 6 epochs
    _run(body, 6, ckpt_dir=ckpt)
    # resume to 10
    resumed = iterate(body, jnp.asarray(1.0), max_epochs=10,
                      config=IterationConfig(mode="hosted"),
                      checkpoint=CheckpointConfig(ckpt), resume=True)
    assert resumed.num_epochs == 10
    assert float(resumed.state) == float(full.state)


def test_resume_restores_stream_cursor(tmp_path):
    # A stateful source exposing snapshot/restore: the data cursor travels
    # with the checkpoint (the analog of ReplayOperator snapshotting its
    # reader position, ReplayOperator.java:194-216).
    class CountingSource:
        def __init__(self):
            self.cursor = 0

        def __call__(self, epoch):
            value = jnp.asarray(float(self.cursor))
            self.cursor += 1
            return value

        def snapshot(self):
            return {"cursor": self.cursor}

        def restore(self, snap):
            self.cursor = snap["cursor"]

    def body(acc, epoch, d):
        return IterationBodyResult(acc + d, outputs=None)

    ckpt = str(tmp_path / "ckpt")
    src = CountingSource()
    iterate(body, jnp.asarray(0.0), src, max_epochs=4,
            config=IterationConfig(mode="hosted"),
            checkpoint=CheckpointConfig(ckpt))
    assert src.cursor == 4

    fresh = CountingSource()  # cursor would restart at 0 without restore
    res = iterate(body, jnp.asarray(0.0), fresh, max_epochs=8,
                  config=IterationConfig(mode="hosted"),
                  checkpoint=CheckpointConfig(ckpt), resume=True)
    # epochs 4..7 consumed cursors 4..7: total = 0+..+7
    assert float(res.state) == sum(range(8))
    assert fresh.cursor == 8


def test_namedtuple_and_intkey_round_trip(tmp_path):
    # optax optimizer states are NamedTuples; int-keyed layer dicts are
    # common — both must survive the round trip with identical structure
    # (structure equality is what makes resumed jit calls hit the cache).
    import optax
    opt = optax.adam(1e-3)
    opt_state = opt.init({"w": jnp.ones((3,))})
    tree = {"opt": opt_state, "layers": {0: np.ones(2), 7: np.zeros(1)}}
    path = str(tmp_path / "state")
    save_pytree(path, tree)
    restored, _ = load_pytree(path)
    assert type(restored["opt"][0]).__name__ == type(opt_state[0]).__name__
    assert set(restored["layers"].keys()) == {0, 7}
    assert (jax.tree_util.tree_structure(restored)
            == jax.tree_util.tree_structure(jax.device_get(tree)))


def test_resume_of_terminated_run_does_not_rerun_body(tmp_path):
    calls = []

    def body(x, epoch):
        calls.append(int(epoch))
        return IterationBodyResult(x * 2, None, epoch < 2)

    ckpt = str(tmp_path / "ckpt")
    r1 = iterate(body, jnp.asarray(1.0), max_epochs=50,
                 config=IterationConfig(mode="hosted", jit=False),
                 checkpoint=CheckpointConfig(ckpt))
    assert r1.side["termination_reason"] == "criteria"
    n_calls = len(calls)
    r2 = iterate(body, jnp.asarray(1.0), max_epochs=50,
                 config=IterationConfig(mode="hosted", jit=False),
                 checkpoint=CheckpointConfig(ckpt), resume=True)
    assert len(calls) == n_calls  # body not re-executed
    assert float(r2.state) == float(r1.state)
    assert r2.side["termination_reason"] == "criteria"


def test_legacy_raw_snapshot_format_restores(tmp_path):
    # Snapshots written before the multi-feed envelope (raw source dicts)
    # must still restore the stream cursor.
    class Src:
        def __init__(self):
            self.cursor = 0

        def __call__(self, epoch):
            v = jnp.asarray(float(self.cursor))
            self.cursor += 1
            return v

        def snapshot(self):
            return {"cursor": self.cursor}

        def restore(self, snap):
            self.cursor = snap["cursor"]

    from flink_ml_tpu.iteration.core import _DataProvider
    src = Src()
    provider = _DataProvider(src)
    provider(0), provider(1)
    assert provider.snapshot() == {"cursor": 2}  # raw format preserved
    fresh = _DataProvider(Src())
    fresh.restore({"cursor": 2})  # legacy raw snapshot
    assert fresh._single.source.cursor == 2


def test_async_checkpointing_matches_sync(tmp_path):
    def body(x, epoch):
        return IterationBodyResult(x * 1.25 + 1.0)

    sync_dir, async_dir = str(tmp_path / "sync"), str(tmp_path / "async")
    r_sync = iterate(body, jnp.asarray(1.0), max_epochs=8,
                     config=IterationConfig(mode="hosted"),
                     checkpoint=CheckpointConfig(sync_dir))
    r_async = iterate(body, jnp.asarray(1.0), max_epochs=8,
                      config=IterationConfig(mode="hosted"),
                      checkpoint=CheckpointConfig(async_dir, async_save=True))
    assert float(r_sync.state) == float(r_async.state)
    # both resume identically
    a = iterate(body, jnp.asarray(1.0), max_epochs=12,
                config=IterationConfig(mode="hosted"),
                checkpoint=CheckpointConfig(sync_dir), resume=True)
    b = iterate(body, jnp.asarray(1.0), max_epochs=12,
                config=IterationConfig(mode="hosted"),
                checkpoint=CheckpointConfig(async_dir, async_save=True),
                resume=True)
    assert float(a.state) == float(b.state)


def test_async_save_error_surfaces(tmp_path):
    mgr = CheckpointManager(CheckpointConfig(str(tmp_path), async_save=True))
    mgr.save_async(1, {("bad", "key"): 1})  # unencodable dict key
    with pytest.raises(TypeError):
        mgr.wait()


def test_elastic_restore_across_mesh_sizes(tmp_path):
    # The reference REJECTS rescaling (parallelism checkState on restore,
    # HeadOperator.java:186-201).  Here checkpoints are placement-free host
    # pytrees: a run checkpointed on the 8-device mesh restores onto a
    # 4-device mesh and converges to the same state.
    import jax

    from flink_ml_tpu.parallel.mesh import device_mesh, shard_batch, use_mesh

    data8 = shard_batch(np.arange(32, dtype=np.float32), device_mesh())

    def body(w, epoch, d):
        return IterationBodyResult(w + jnp.sum(d))

    ckpt = str(tmp_path / "ckpt")
    iterate(body, jnp.asarray(0.0, jnp.float32), data8, max_epochs=3,
            config=IterationConfig(mode="hosted"),
            checkpoint=CheckpointConfig(ckpt))

    # "rescale": resume on a 4-device mesh with re-sharded data
    mesh4 = device_mesh(devices=jax.devices()[:4])
    data4 = shard_batch(np.arange(32, dtype=np.float32), mesh4)
    resumed = iterate(body, jnp.asarray(0.0, jnp.float32), data4,
                      max_epochs=6, config=IterationConfig(mode="hosted"),
                      checkpoint=CheckpointConfig(ckpt), resume=True)
    assert resumed.num_epochs == 6
    assert float(resumed.state) == 6 * np.arange(32).sum()


# ------------------------------------------------- mid-epoch (step) cuts


class _FailingReader:
    """DataCacheReader wrapper that dies after N read_batch calls across the
    whole run (the analog of the reference's FailingMap fault injection,
    ``flink-ml-tests/.../operators/FailingMap.java``)."""

    fail_counter = None  # class-level so the count survives re-creation

    def __init__(self, inner, fail_after):
        self._inner = inner
        self._fail_after = fail_after

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __iter__(self):
        while True:
            if _FailingReader.fail_counter is not None:
                _FailingReader.fail_counter += 1
                if _FailingReader.fail_counter > self._fail_after:
                    raise RuntimeError("injected mid-epoch failure")
            b = self._inner.read_batch()
            if b is None:
                return
            yield b


def _lr_cache(tmp_path, name, n=1536, d=8, seed=7):
    from flink_ml_tpu.data.datacache import DataCacheWriter

    rng = np.random.default_rng(seed)
    true_w = rng.normal(size=(d,))
    cache = str(tmp_path / name)
    writer = DataCacheWriter(cache, segment_rows=512)
    for _ in range(n // 512):
        X = rng.normal(size=(512, d)).astype(np.float32)
        writer.append({"features": X,
                       "label": (X @ true_w > 0).astype(np.float32)})
    writer.finish()
    return cache


def test_outofcore_midepoch_kill_and_resume_exact(tmp_path):
    """A crash mid-pass resumes from the step-granular cut and lands on
    EXACTLY the uninterrupted run's parameters (deterministic replay: the
    exactly-once equivalence the reference gets from its in-flight feedback
    log, ``checkpoint/Checkpoints.java:43-211``)."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _lr_cache(tmp_path, "c1")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=4, tol=0.0)
    # 1536 rows / 256 = 6 batches per epoch

    def reader():
        return DataCacheReader(cache, batch_rows=256)

    ref_state, ref_log = sgd_fit_outofcore(
        logistic_loss, reader, num_features=8, config=cfg)

    # run 2: checkpoint every 2 steps, die mid-epoch-2 (batch 15 overall)
    ckpt = CheckpointConfig(str(tmp_path / "ck"), max_to_keep=3)
    _FailingReader.fail_counter = 0
    # cache_decoded=False: the injection models a process crash via a
    # reader failure, but the decoded replay cache (r4) legitimately stops
    # re-reading the reader after epoch 0 — a real crash would take the
    # RAM cache down with it, so the injected run disables caching
    with pytest.raises(RuntimeError, match="injected"):
        sgd_fit_outofcore(
            logistic_loss, lambda: _FailingReader(reader(), 15),
            num_features=8, config=cfg, cache_decoded=False,
            checkpoint=ckpt, checkpoint_every_steps=2)
    _FailingReader.fail_counter = None

    resumed_state, resumed_log = sgd_fit_outofcore(
        logistic_loss, reader, num_features=8, config=cfg,
        checkpoint=ckpt, checkpoint_every_steps=2, resume=True)

    np.testing.assert_array_equal(resumed_state.coefficients,
                                  ref_state.coefficients)
    assert resumed_state.intercept == ref_state.intercept
    np.testing.assert_array_equal(resumed_log, ref_log)


def test_outofcore_midepoch_resume_without_seek_protocol(tmp_path):
    """Readers without seek/batch_rows (plain generators) fast-forward by
    skipping batches; the result is still exact."""
    from flink_ml_tpu.data.datacache import DataCacheReader
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _lr_cache(tmp_path, "c2")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=3, tol=0.0)

    def gen_reader():
        # strips the DataCacheReader protocol down to a bare generator
        def gen():
            yield from DataCacheReader(cache, batch_rows=256)
        return gen()

    ref_state, _ = sgd_fit_outofcore(
        logistic_loss, gen_reader, num_features=8, config=cfg)

    ckpt = CheckpointConfig(str(tmp_path / "ck2"), max_to_keep=3)
    _FailingReader.fail_counter = 0

    def failing_gen_reader():
        def gen():
            for b in DataCacheReader(cache, batch_rows=256):
                _FailingReader.fail_counter += 1
                if _FailingReader.fail_counter > 9:
                    raise RuntimeError("injected mid-epoch failure")
                yield b
        return gen()

    with pytest.raises(RuntimeError, match="injected"):
        sgd_fit_outofcore(
            logistic_loss, failing_gen_reader, num_features=8, config=cfg,
            checkpoint=ckpt, checkpoint_every_steps=2)
    _FailingReader.fail_counter = None

    resumed_state, _ = sgd_fit_outofcore(
        logistic_loss, gen_reader, num_features=8, config=cfg,
        checkpoint=ckpt, checkpoint_every_steps=2, resume=True)
    np.testing.assert_array_equal(resumed_state.coefficients,
                                  ref_state.coefficients)


def test_outofcore_midepoch_resume_exact_sharded_ell(tmp_path, monkeypatch):
    """Mid-epoch kill/resume exactness through the r4 SHARDED streaming
    ELL path (per-device shard layouts on the 8-device mesh): the resumed
    run must land bit-exactly on the uninterrupted run's parameters."""
    from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter
    from flink_ml_tpu.models.common import sgd
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    rng = np.random.default_rng(11)
    n, nd, nc, d = 1536, 3, 4, 128 * 128
    dense = rng.normal(size=(n, nd)).astype(np.float32)
    cat = rng.integers(0, d, size=(n, nc)).astype(np.int32)
    y = rng.integers(0, 2, size=n).astype(np.float32)
    cache = str(tmp_path / "mixed")
    w = DataCacheWriter(cache, segment_rows=512)
    w.append({"fd": dense, "fi": cat, "label": y})
    w.finish()

    monkeypatch.setattr(sgd, "plan_mixed_impl", lambda *a, **k: "ell")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=3, tol=0.0)
    kw = dict(num_features=d, config=cfg, dense_key="fd", indices_key="fi")

    def reader():
        return DataCacheReader(cache, batch_rows=256)

    ref_state, ref_log = sgd_fit_outofcore(logistic_loss, reader, **kw)
    assert ref_state.planned_impl == "ell-stream"   # sharded on 8 devices

    ckpt = CheckpointConfig(str(tmp_path / "ck"), max_to_keep=3)
    _FailingReader.fail_counter = 0
    # cache_decoded=False for the injected run: see
    # test_outofcore_midepoch_kill_and_resume_exact
    with pytest.raises(RuntimeError, match="injected"):
        sgd_fit_outofcore(
            logistic_loss, lambda: _FailingReader(reader(), 9), **kw,
            cache_decoded=False,
            checkpoint=ckpt, checkpoint_every_steps=2)
    _FailingReader.fail_counter = None

    resumed_state, resumed_log = sgd_fit_outofcore(
        logistic_loss, reader, **kw,
        checkpoint=ckpt, checkpoint_every_steps=2, resume=True)
    np.testing.assert_array_equal(resumed_state.coefficients,
                                  ref_state.coefficients)
    np.testing.assert_array_equal(resumed_log, ref_log)


def test_outofcore_midepoch_resume_exact_shuffled_stream(tmp_path):
    """Kill-and-resume exactness with PER-EPOCH SHUFFLED streaming: the
    epoch-aware factory reconstructs epoch N's permutation on resume, so
    the resumed run replays the exact visit order the crashed run was
    mid-way through (the reason sgd passes the real epoch number instead
    of letting factories count calls)."""
    from flink_ml_tpu.data.datacache import ShuffledCacheReader
    from flink_ml_tpu.models.common.losses import logistic_loss
    from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore

    cache = _lr_cache(tmp_path, "cshuf")
    cfg = SGDConfig(learning_rate=0.4, max_epochs=4, tol=0.0)

    def reader(epoch):
        return ShuffledCacheReader(cache, batch_rows=256, seed=13,
                                   epoch=epoch)

    ref_state, ref_log = sgd_fit_outofcore(
        logistic_loss, reader, num_features=8, config=cfg)

    ckpt = CheckpointConfig(str(tmp_path / "ckshuf"), max_to_keep=3)
    _FailingReader.fail_counter = 0
    with pytest.raises(RuntimeError, match="injected"):
        sgd_fit_outofcore(
            logistic_loss,
            lambda epoch: _FailingReader(reader(epoch), 15),
            num_features=8, config=cfg, cache_decoded=False,
            checkpoint=ckpt, checkpoint_every_steps=2)
    _FailingReader.fail_counter = None

    resumed_state, resumed_log = sgd_fit_outofcore(
        logistic_loss, reader, num_features=8, config=cfg,
        checkpoint=ckpt, checkpoint_every_steps=2, resume=True)

    np.testing.assert_array_equal(resumed_state.coefficients,
                                  ref_state.coefficients)
    assert resumed_state.intercept == ref_state.intercept
    np.testing.assert_array_equal(resumed_log, ref_log)


def test_workset_carry_round_trips_through_checkpoint(tmp_path):
    """ISSUE 9: a workset iteration's hosted carry is (state, Workset) —
    the mask AND the bound pytree must survive the save/load cycle
    bit-exactly (GR_STATE_KEY-style ride-along), including a None
    bounds."""
    from flink_ml_tpu.iteration import Workset, load_pytree, save_pytree

    ws = Workset(
        mask=jnp.asarray([1.0, 0.0, 1.0], jnp.float32),
        bounds={"assign": jnp.asarray([2, 0, 1], jnp.int32),
                "upper": jnp.asarray([0.5, np.inf, 1.25], jnp.float32),
                "lower": jnp.asarray([-np.inf, 0.0, 2.5], jnp.float32)})
    carry = (jnp.arange(4.0), ws)
    save_pytree(str(tmp_path / "ck"), carry, {"epoch": 3})
    restored, meta = load_pytree(str(tmp_path / "ck"))
    assert meta["epoch"] == 3
    state_r, ws_r = restored
    assert isinstance(ws_r, Workset)
    np.testing.assert_array_equal(state_r, np.arange(4.0))
    np.testing.assert_array_equal(ws_r.mask, np.asarray(ws.mask))
    for key in ("assign", "upper", "lower"):
        np.testing.assert_array_equal(ws_r.bounds[key],
                                      np.asarray(ws.bounds[key]))

    bare = Workset(mask=jnp.ones(2, jnp.float32))
    save_pytree(str(tmp_path / "ck2"), bare, {})
    ws2, _ = load_pytree(str(tmp_path / "ck2"))
    assert isinstance(ws2, Workset) and ws2.bounds is None
    np.testing.assert_array_equal(ws2.mask, [1.0, 1.0])
