"""Data cache tests — mirror of ``DataCacheWriteReadTest`` (186 LoC) and
``DataCacheSnapshotTest`` (213 LoC, both FS modes)."""

import numpy as np
import pytest

from flink_ml_tpu.data.datacache import (
    DataCacheReader,
    DataCacheSnapshot,
    DataCacheWriter,
    Segment,
    _native_lib,
    load_segments,
)


def _write_cache(directory, n=100, segment_rows=32, d=3):
    writer = DataCacheWriter(str(directory), segment_rows=segment_rows)
    x = np.arange(n * d, dtype=np.float32).reshape(n, d)
    y = np.arange(n, dtype=np.int64)
    # append in uneven chunks to exercise rotation mid-batch
    for lo, hi in [(0, 10), (10, 45), (45, 100)]:
        writer.append({"x": x[lo:hi], "y": y[lo:hi]})
    return writer.finish(), x, y


def test_write_read_round_trip(tmp_path):
    segments, x, y = _write_cache(tmp_path / "cache")
    assert [s.rows for s in segments] == [32, 32, 32, 4]
    reader = DataCacheReader(segments, batch_rows=17)
    got_x, got_y = [], []
    for batch in reader:
        got_x.append(batch["x"])
        got_y.append(batch["y"])
    np.testing.assert_array_equal(np.concatenate(got_x), x)
    np.testing.assert_array_equal(np.concatenate(got_y), y)
    assert reader.cursor == 100


def test_reader_from_manifest_dir(tmp_path):
    cache_dir = tmp_path / "cache"
    _, x, _ = _write_cache(cache_dir)
    reader = DataCacheReader(str(cache_dir), batch_rows=100)
    batch = reader.read_batch()
    np.testing.assert_array_equal(batch["x"], x)
    assert reader.read_batch() is None


def test_reader_batch_spanning_segments(tmp_path):
    # batch_rows > segment_rows forces concatenation across segments
    segments, x, _ = _write_cache(tmp_path / "cache", segment_rows=16)
    reader = DataCacheReader(segments, batch_rows=50)
    batch = reader.read_batch()
    assert batch["x"].shape == (50, 3)
    np.testing.assert_array_equal(batch["x"], x[:50])


def test_cursor_resume(tmp_path):
    # The reference resumes a reader from (segmentIdx, offset)
    # (DataCacheReader.java:35-139); here the cursor is a global row.
    segments, x, _ = _write_cache(tmp_path / "cache")
    r1 = DataCacheReader(segments, batch_rows=30)
    r1.read_batch()
    snap = r1.snapshot()
    assert snap == {"cursor": 30}

    r2 = DataCacheReader(segments, batch_rows=30)
    r2.restore(snap)
    batch = r2.read_batch()
    np.testing.assert_array_equal(batch["x"], x[30:60])


def test_schema_mismatch_rejected(tmp_path):
    writer = DataCacheWriter(str(tmp_path / "c"))
    writer.append({"x": np.zeros((4, 3), np.float32)})
    with pytest.raises(ValueError):
        writer.append({"x": np.zeros((4, 5), np.float32)})  # wrong row shape
    with pytest.raises(ValueError):
        writer.append({"z": np.zeros((4, 3), np.float32)})  # wrong name


def test_append_after_finish_rejected(tmp_path):
    writer = DataCacheWriter(str(tmp_path / "c"))
    writer.append({"x": np.zeros((4, 3), np.float32)})
    writer.finish()
    with pytest.raises(RuntimeError):
        writer.append({"x": np.zeros((4, 3), np.float32)})


def test_snapshot_path_mode(tmp_path):
    segments, x, _ = _write_cache(tmp_path / "cache")
    snap_dir = str(tmp_path / "snap")
    DataCacheSnapshot.write(segments, snap_dir, embed=False, cursor=42)
    recovered, cursor = DataCacheSnapshot.recover(snap_dir)
    assert cursor == 42
    reader = DataCacheReader(recovered, batch_rows=100)
    np.testing.assert_array_equal(reader.read_batch()["x"], x)


def test_snapshot_embed_mode(tmp_path):
    # embed=True copies bytes into the snapshot; recovery rebuilds segments
    # in a NEW directory and the original cache can be deleted
    # (DataCacheSnapshot.java:82-111 embedded mode).
    import shutil

    cache_dir = tmp_path / "cache"
    segments, x, _ = _write_cache(cache_dir)
    snap_dir = str(tmp_path / "snap")
    DataCacheSnapshot.write(segments, snap_dir, embed=True, cursor=7)
    shutil.rmtree(cache_dir)

    restored, cursor = DataCacheSnapshot.recover(
        snap_dir, restore_dir=str(tmp_path / "restored"))
    assert cursor == 7
    reader = DataCacheReader(restored, batch_rows=1000)
    np.testing.assert_array_equal(reader.read_batch()["x"], x)


def test_native_library_loads_and_prefetch(tmp_path):
    lib = _native_lib()
    assert lib is not None, "native datacache library failed to build/load"
    segments, x, _ = _write_cache(tmp_path / "cache")
    # prefetch path exercises the native thread pool
    reader = DataCacheReader(segments, batch_rows=10, prefetch=True)
    for _ in range(3):
        reader.read_batch()
    lib.dc_prefetch_drain()
    assert lib.dc_prefetch_pending() == 0


def test_native_write_read_agree_with_fallback(tmp_path):
    # Force the fallback path and compare byte-for-byte with native output.
    import flink_ml_tpu.data.datacache as dc

    segments_native, x, y = _write_cache(tmp_path / "native")
    lib = dc._LIB
    try:
        dc._LIB = None
        segments_py, x2, y2 = _write_cache(tmp_path / "fallback")
    finally:
        dc._LIB = lib
    for sn, sp in zip(segments_native, segments_py):
        for name in sn.schema:
            with open(sn.column_path(name), "rb") as f1, \
                 open(sp.column_path(name), "rb") as f2:
                assert f1.read() == f2.read()


def test_empty_cache_rejected(tmp_path):
    writer = DataCacheWriter(str(tmp_path / "c"))
    segments = writer.finish()
    with pytest.raises(ValueError):
        DataCacheReader(segments, batch_rows=10)


def test_iterate_integration(tmp_path):
    # The cache feeds iterate() as a streaming source with cursor checkpoints
    import jax.numpy as jnp

    from flink_ml_tpu.iteration import (IterationBodyResult, IterationConfig,
                                        iterate)

    segments, x, _ = _write_cache(tmp_path / "cache")
    reader = DataCacheReader(segments, batch_rows=25)

    def body(acc, epoch, batch):
        return IterationBodyResult(acc + jnp.sum(batch["x"]))

    res = iterate(body, jnp.asarray(0.0, jnp.float32),
                  iter(reader), config=IterationConfig(mode="hosted"))
    assert res.num_epochs == 4
    np.testing.assert_allclose(float(res.state), x.sum(), rtol=1e-5)


def test_dirty_directory_rejected(tmp_path):
    # Reusing a cache dir must fail loudly, not serve stale leading bytes.
    d = tmp_path / "cache"
    _write_cache(d)
    with pytest.raises(ValueError):
        DataCacheWriter(str(d))


def test_broken_writer_refuses_retry(tmp_path, monkeypatch):
    import flink_ml_tpu.data.datacache as dc

    writer = DataCacheWriter(str(tmp_path / "c"))
    writer.append({"x": np.zeros((4, 3), np.float32),
                   "y": np.zeros((4,), np.int64)})

    # make the second column's write fail mid-append
    real_open = open
    calls = {"n": 0}

    def failing_open(path, mode="r", *a, **k):
        if str(path).endswith(dc._col_filename("y")) and mode == "ab":
            raise IOError("disk full")
        return real_open(path, mode, *a, **k)

    lib = dc._LIB
    try:
        dc._LIB = None  # force the python write path
        monkeypatch.setattr("builtins.open", failing_open)
        with pytest.raises(IOError):
            writer.append({"x": np.ones((4, 3), np.float32),
                           "y": np.ones((4,), np.int64)})
        monkeypatch.undo()
        with pytest.raises(RuntimeError):  # broken: no silent retry
            writer.append({"x": np.ones((4, 3), np.float32),
                           "y": np.ones((4,), np.int64)})
    finally:
        dc._LIB = lib


def test_parallel_writer_matches_serial(tmp_path):
    """workers>1 writes whole segments on a pool; the reader's view must be
    identical (same rows, same order, same segment rotation)."""
    rng = np.random.default_rng(3)
    batches = [{"x": rng.normal(size=(n, 4)).astype(np.float32),
                "y": rng.integers(0, 9, size=n).astype(np.int32)}
               for n in (70, 1, 130, 64, 35)]

    from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter

    outs = []
    for workers in (1, 3):
        d = str(tmp_path / f"cache-w{workers}")
        w = DataCacheWriter(d, segment_rows=64, workers=workers)
        for b in batches:
            w.append(b)
        segs = w.finish()
        assert [s.rows for s in segs] == [64, 64, 64, 64, 44]
        got = list(DataCacheReader(d, batch_rows=50))
        outs.append({k: np.concatenate([b[k] for b in got])
                     for k in ("x", "y")})
    for k in ("x", "y"):
        np.testing.assert_array_equal(outs[0][k], outs[1][k])


def test_parallel_writer_borrow_batches(tmp_path):
    """borrow_batches=True skips the defensive copy; with a fresh-array
    producer the cache is identical to the copying path."""
    from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter

    outs = []
    for borrow in (False, True):
        d = str(tmp_path / f"cache-b{borrow}")
        w = DataCacheWriter(d, segment_rows=50, workers=2,
                            borrow_batches=borrow)
        r2 = np.random.default_rng(6)
        for n in (70, 30, 55):
            w.append({"x": r2.normal(size=(n, 3)).astype(np.float32)})
        w.finish()
        got = list(DataCacheReader(d, batch_rows=64))
        outs.append(np.concatenate([b["x"] for b in got]))
    np.testing.assert_array_equal(outs[0], outs[1])


# ------------------------------------------------- ShuffledCacheReader


def _shuffle_cache(tmp_path, rows=300):
    from flink_ml_tpu.data.datacache import DataCacheWriter

    d = str(tmp_path / "shufcache")
    w = DataCacheWriter(d, segment_rows=128)
    w.append({"x": np.arange(rows, dtype=np.float32).reshape(rows, 1)})
    w.finish()
    return d


def test_shuffled_reader_permutes_blocks_partial_last(tmp_path):
    from flink_ml_tpu.data.datacache import ShuffledCacheReader

    d = _shuffle_cache(tmp_path, rows=300)     # 4 full 64-blocks + 44 tail
    r = ShuffledCacheReader(d, batch_rows=64, seed=3, epoch=1)
    batches = list(r)
    assert [len(b["x"]) for b in batches[:-1]] == [64] * 4
    assert len(batches[-1]["x"]) == 44         # partial block always last
    np.testing.assert_array_equal(batches[-1]["x"][:, 0],
                                  np.arange(256, 300, dtype=np.float32))
    # same multiset of rows, not the sequential order
    got = np.sort(np.concatenate([b["x"][:, 0] for b in batches]))
    np.testing.assert_array_equal(got, np.arange(300, dtype=np.float32))


def test_shuffled_reader_deterministic_per_seed_epoch(tmp_path):
    from flink_ml_tpu.data.datacache import ShuffledCacheReader

    d = _shuffle_cache(tmp_path)

    def stream(seed, epoch):
        return np.concatenate(
            [b["x"][:, 0]
             for b in ShuffledCacheReader(d, batch_rows=64,
                                          seed=seed, epoch=epoch)])

    np.testing.assert_array_equal(stream(3, 0), stream(3, 0))
    assert not np.array_equal(stream(3, 0), stream(3, 1))
    assert not np.array_equal(stream(3, 0), stream(4, 0))


def test_shuffled_reader_seek_cursor_roundtrip(tmp_path):
    from flink_ml_tpu.data.datacache import ShuffledCacheReader

    d = _shuffle_cache(tmp_path)
    full = ShuffledCacheReader(d, batch_rows=64, seed=5, epoch=2)
    want = [b["x"] for b in full]

    r = ShuffledCacheReader(d, batch_rows=64, seed=5, epoch=2)
    r.read_batch()
    r.read_batch()
    assert r.cursor == 128
    r2 = ShuffledCacheReader(d, batch_rows=64, seed=5, epoch=2)
    r2.seek(128)                                # resume at visit 2
    rest = [b["x"] for b in r2]
    assert len(rest) == len(want) - 2
    for a, b in zip(rest, want[2:]):
        np.testing.assert_array_equal(a, b)
    r2.seek(r2.total_rows)
    assert r2.read_batch() is None


def test_shuffled_reader_seek_rejects_non_boundary(tmp_path):
    """ShuffledCacheReader's cursor protocol only produces visit
    boundaries (or total_rows); an arbitrary row cursor used to be
    silently floored, losing up to batch_rows-1 rows (ADVICE r4)."""
    from flink_ml_tpu.data.datacache import (
        DataCacheWriter, ShuffledCacheReader)

    cache = str(tmp_path / "c")
    w = DataCacheWriter(cache, segment_rows=256)
    w.append({"x": np.arange(1000, dtype=np.float32)})
    w.finish()
    r = ShuffledCacheReader(cache, batch_rows=256, seed=3)
    r.seek(512)                    # visit boundary: fine
    assert r.cursor == 512
    r.seek(1000)                   # total_rows (ragged end): fine
    with pytest.raises(ValueError, match="visit boundary"):
        r.seek(300)
