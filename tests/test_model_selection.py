"""Model selection (`api/model_selection.py`): ParamGridBuilder grids,
CrossValidator fold mechanics + best-candidate selection + full-table
refit, TrainValidationSplit, metric direction, error probes."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.api.model_selection import (
    CrossValidator,
    CrossValidatorModel,
    ParamGridBuilder,
    TrainValidationSplit,
)
from flink_ml_tpu.models.classification import LogisticRegression
from flink_ml_tpu.models.evaluation.binary_evaluator import (
    BinaryClassificationEvaluator,
)


def _data(n=400, d=4, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d))
    y = (X[:, 0] - 0.5 * X[:, 1] > 0).astype(np.float64)
    return Table({"features": X, "label": y})


def _lr():
    return (LogisticRegression().set_max_iter(15).set_learning_rate(0.5)
            .set_global_batch_size(128))


def _auc_eval():
    return (BinaryClassificationEvaluator()
            .set_raw_prediction_col("rawPrediction")
            .set_metrics("areaUnderROC"))


class TestParamGridBuilder:
    def test_cartesian_product(self):
        grid = (ParamGridBuilder()
                .add_grid(LogisticRegression.REG, [0.0, 0.1])
                .add_grid(LogisticRegression.MAX_ITER, [5, 10, 20])
                .build())
        assert len(grid) == 6
        regs = {g[LogisticRegression.REG] for g in grid}
        assert regs == {0.0, 0.1}

    def test_empty_builder_is_single_default(self):
        assert ParamGridBuilder().build() == [{}]

    def test_rejects_non_param(self):
        with pytest.raises(TypeError):
            ParamGridBuilder().add_grid("reg", [1])
        with pytest.raises(ValueError):
            ParamGridBuilder().add_grid(LogisticRegression.REG, [])


class TestCrossValidator:
    def test_selects_sane_candidate_and_refits(self):
        t = _data()
        # candidate 0 is crippled (1 iteration, tiny lr); candidate 1 real
        grid = [
            {LogisticRegression.MAX_ITER: 1,
             LogisticRegression.LEARNING_RATE: 1e-4},
            {LogisticRegression.MAX_ITER: 20,
             LogisticRegression.LEARNING_RATE: 0.5},
        ]
        cv = (CrossValidator(_lr(), _auc_eval(), grid)
              .set_num_folds(3).set_seed(7))
        model = cv.fit(t)
        assert isinstance(model, CrossValidatorModel)
        assert model.best_index == 1
        assert len(model.avg_metrics) == 2
        assert model.avg_metrics[1] > model.avg_metrics[0]
        # refit-on-all-rows model predicts well
        pred = np.asarray(model.transform(t)[0]["prediction"]).ravel()
        assert (pred == np.asarray(t["label"])).mean() > 0.9

    def test_fold_partition_is_exact(self):
        t = _data(n=103)
        cv = CrossValidator(_lr(), _auc_eval()).set_num_folds(4).set_seed(1)
        splits = cv._splits(t)
        assert len(splits) == 4
        val_rows = sum(v.num_rows for _, v in splits)
        assert val_rows == 103                       # folds cover all rows
        for train, val in splits:
            assert train.num_rows + val.num_rows == 103
        # validation folds are disjoint (feature rows unique per fold)
        seen = np.concatenate(
            [np.asarray(v["features"])[:, 0] for _, v in splits])
        assert len(np.unique(seen)) == 103

    def test_minimizing_metric_direction(self):
        # with largerIsBetter=false the crippled candidate "wins"
        t = _data()
        grid = [
            {LogisticRegression.MAX_ITER: 1,
             LogisticRegression.LEARNING_RATE: 1e-4},
            {LogisticRegression.MAX_ITER: 20,
             LogisticRegression.LEARNING_RATE: 0.5},
        ]
        cv = (CrossValidator(_lr(), _auc_eval(), grid)
              .set_num_folds(2).set_larger_is_better(False))
        assert cv.fit(t).best_index == 0

    def test_too_few_rows_rejected(self):
        cv = CrossValidator(_lr(), _auc_eval()).set_num_folds(5)
        with pytest.raises(ValueError, match="folds"):
            cv.fit(_data(n=3))

    def test_missing_pieces_rejected(self):
        with pytest.raises(ValueError, match="set_estimator"):
            CrossValidator().fit(_data())

    def test_model_save_delegates_to_best(self, tmp_path):
        from flink_ml_tpu.models.classification import (
            LogisticRegressionModel)

        t = _data()
        model = CrossValidator(_lr(), _auc_eval()).set_num_folds(2).fit(t)
        path = str(tmp_path / "best")
        model.save(path)
        loaded = LogisticRegressionModel.load(path)
        np.testing.assert_array_equal(
            np.asarray(loaded.transform(t)[0]["prediction"]),
            np.asarray(model.transform(t)[0]["prediction"]))


class TestTrainValidationSplit:
    def test_single_split_selection(self):
        t = _data()
        grid = [
            {LogisticRegression.MAX_ITER: 1,
             LogisticRegression.LEARNING_RATE: 1e-4},
            {LogisticRegression.MAX_ITER: 20,
             LogisticRegression.LEARNING_RATE: 0.5},
        ]
        tvs = (TrainValidationSplit(_lr(), _auc_eval(), grid)
               .set_train_ratio(0.7).set_seed(3))
        model = tvs.fit(t)
        assert model.best_index == 1
        (train, val), = tvs._splits(t)
        assert train.num_rows == 280 and val.num_rows == 120

    def test_degenerate_ratio_rejected(self):
        tvs = (TrainValidationSplit(_lr(), _auc_eval())
               .set_train_ratio(0.001))
        with pytest.raises(ValueError, match="empty split"):
            tvs.fit(_data(n=10))

def test_root_exports_and_bool_param():
    import flink_ml_tpu as fm

    assert fm.CrossValidator is CrossValidator
    assert fm.ParamGridBuilder is ParamGridBuilder
    cv = CrossValidator().set(CrossValidator.LARGER_IS_BETTER, False)
    assert cv.get(CrossValidator.LARGER_IS_BETTER) is False


def test_add_grid_repeated_param_replaces():
    grid = (ParamGridBuilder()
            .add_grid(LogisticRegression.REG, [0.0, 1.0])
            .add_grid(LogisticRegression.REG, [2.0, 3.0])
            .build())
    assert [g[LogisticRegression.REG] for g in grid] == [2.0, 3.0]


def test_cv_over_pipeline_clones_children():
    from flink_ml_tpu import Pipeline
    from flink_ml_tpu.models.feature.scalers import StandardScaler

    t = _data()
    grid = (ParamGridBuilder()
            .add_grid(LogisticRegression.MAX_ITER, [1, 20])
            .build())
    pipe = Pipeline([StandardScaler().set_output_col("features"),
                     _lr()])
    cv = (CrossValidator(pipe, _auc_eval(), grid)
          .set_num_folds(2).set_seed(2))
    model = cv.fit(t)
    assert model.best_params[LogisticRegression.MAX_ITER] == 20
    pred = np.asarray(model.transform(t)[0]["prediction"]).ravel()
    assert (pred == np.asarray(t["label"])).mean() > 0.9
    # the original pipeline's children are untouched by candidate fits
    assert pipe.stages[1].get_max_iter() == 15


def test_cv_pipeline_unknown_grid_param_rejected():
    from flink_ml_tpu import Pipeline
    from flink_ml_tpu.models.clustering.kmeans import KMeansParams

    pipe = Pipeline([_lr()])
    cv = CrossValidator(pipe, _auc_eval(),
                        [{KMeansParams.K: 4}]).set_num_folds(2)
    with pytest.raises(ValueError, match="matches no pipeline stage"):
        cv.fit(_data())


def test_cv_pipeline_nested_and_shared_mixin_binding():
    from flink_ml_tpu import Pipeline
    from flink_ml_tpu.models.feature.scalers import StandardScaler

    t = _data()
    # nested pipeline; maxIter (HasMaxIter mixin) binds into the inner LR
    inner = Pipeline([_lr()])
    pipe = Pipeline([StandardScaler().set_output_col("features"), inner])
    grid = (ParamGridBuilder()
            .add_grid(LogisticRegression.MAX_ITER, [1, 20]).build())
    model = (CrossValidator(pipe, _auc_eval(), grid)
             .set_num_folds(2).set_seed(4).fit(t))
    assert model.best_params[LogisticRegression.MAX_ITER] == 20


def test_cv_pipeline_tuple_key_pins_one_child():
    from flink_ml_tpu import Pipeline
    from flink_ml_tpu.models.feature.scalers import StandardScaler
    from flink_ml_tpu.params.shared import HasFeaturesCol

    # featuresCol is a SHARED mixin param: a bare key would hit both
    # children; the tuple key pins it to the LR child only
    t = _data().with_column("feat2", np.asarray(_data()["features"]))
    pipe = Pipeline([StandardScaler().set_output_col("scaled"), _lr()])
    grid = [{(1, HasFeaturesCol.FEATURES_COL): "scaled"}]
    model = (CrossValidator(pipe, _auc_eval(), grid)
             .set_num_folds(2).fit(t))
    # the scaler child still reads the raw column (params untouched)
    assert pipe.stages[0].get_features_col() == "features"
    pred = np.asarray(model.transform(t)[0]["prediction"]).ravel()
    assert (pred == np.asarray(t["label"])).mean() > 0.9


def test_cv_pipeline_reuses_transformer_children():
    from flink_ml_tpu import Pipeline
    from flink_ml_tpu.models.feature.scalers import StandardScaler

    t = _data()
    # a FITTED model child must pass through with its model data intact
    scaler_model = (StandardScaler().set_output_col("features").fit(t))
    pipe = Pipeline([scaler_model, _lr()])
    grid = (ParamGridBuilder()
            .add_grid(LogisticRegression.MAX_ITER, [1, 20]).build())
    model = (CrossValidator(pipe, _auc_eval(), grid)
             .set_num_folds(2).fit(t))
    assert model.best_params[LogisticRegression.MAX_ITER] == 20


def test_cv_pipeline_transformer_grid_param_does_not_mutate_original():
    """ADVICE r3: a grid key targeting a plain TRANSFORMER child must
    bind on a per-candidate clone — never on the caller's original stage
    (and candidates must not share one mutable transformer)."""
    from flink_ml_tpu import Pipeline
    from flink_ml_tpu.api.model_selection import _clone_with
    from flink_ml_tpu.models.feature.transforms import Normalizer

    t = _data()
    norm = Normalizer().set_p(2.0).set_output_col("features")
    pipe = Pipeline([norm, _lr()])
    grid = (ParamGridBuilder()
            .add_grid(Normalizer.P, [1.0, 3.0])
            .add_grid(LogisticRegression.MAX_ITER, [1, 20]).build())

    # direct clone surface: binding P must not touch the original
    c = _clone_with(pipe, {Normalizer.P: 1.0})
    assert c.stages[0].get_p() == 1.0
    assert norm.get_p() == 2.0
    assert c.stages[0] is not norm

    # nested pipeline: the same guarantee one level down
    outer = Pipeline([Pipeline([norm]), _lr()])
    c2 = _clone_with(outer, {Normalizer.P: 3.0})
    assert c2.stages[0].stages[0].get_p() == 3.0
    assert norm.get_p() == 2.0

    # full CV run leaves the original untouched too
    model = (CrossValidator(pipe, _auc_eval(), grid)
             .set_num_folds(2).set_seed(5).fit(t))
    assert norm.get_p() == 2.0
    assert pipe.stages[1].get_max_iter() == 15
    assert model.best_params[Normalizer.P] in (1.0, 3.0)


def test_cv_pipeline_fused_scoring_reuses_compiled_segments():
    """Pipeline candidates score through the fused chain (`api/chain.py`):
    fold metrics are identical to the stagewise path, and because the
    segment jit is plan-static with fold params as runtime device args,
    a whole repeat grid x fold sweep at the same shapes adds ZERO new
    XLA lowerings — fold models share one compiled program per
    (schema, bucket) instead of recompiling per fold."""
    from jax._src import test_util as jtu

    from flink_ml_tpu import Pipeline
    from flink_ml_tpu.api import chain
    from flink_ml_tpu.models.feature.scalers import StandardScaler

    t = _data(n=400)
    grid = (ParamGridBuilder()
            .add_grid(LogisticRegression.MAX_ITER, [2, 8]).build())

    def _cv():
        pipe = Pipeline([StandardScaler().set_output_col("features"),
                         _lr()])
        return (CrossValidator(pipe, _auc_eval(), grid)
                .set_num_folds(4).set_seed(6))

    with chain.chain_disabled():
        ref = _cv().fit(t)
    fused = _cv().fit(t)
    assert fused.avg_metrics == ref.avg_metrics   # fold metrics unchanged
    assert fused.best_index == ref.best_index

    # scoring-side compile reuse: one fitted pipeline per fold (distinct
    # fitted arrays, identical stage types / columns / shapes), fold 1
    # warms the (schema, bucket) segment compiles, every later fold's
    # scoring transform must hit them — the fit-side `sgd` compiles stay
    # outside the counter (they are per-fit and predate the chain)
    folds = []
    for train, val in _cv()._splits(t):
        pipe = Pipeline([StandardScaler().set_output_col("features"),
                         _lr().set_max_iter(2)])
        folds.append((pipe.fit(train), val))
    m0, v0 = folds[0]
    m0.transform(v0)                        # warm fold
    with jtu.count_jit_and_pmap_lowerings() as count:
        preds = [m.transform(v)[0] for m, v in folds]
    assert count[0] == 0, (
        f"{count[0]} new XLA lowerings across fold scoring — fold "
        "models are not sharing the plan-static segment compiles")
    for (m, v), pred in zip(folds, preds):
        with chain.chain_disabled():
            (sw,) = m.transform(v)
        for c in sw.column_names:
            assert np.array_equal(np.asarray(sw[c]), np.asarray(pred[c]))
