"""Pallas kernel tests — run in interpret mode on the CPU mesh (the kernels
compile natively on TPU; interpret mode is the portable correctness oracle)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_ml_tpu.ops.kmeans_pallas import (
    kmeans_assign_reduce,
    kmeans_update_stats,
    pad_correction,
    pick_block_n,
    supported,
    update_stats_sharded,
)


def _problem(n=512, d=16, k=8, n_pad=17, seed=0):
    """Points with ``n_pad`` trailing all-zero padding rows (the maskless
    kernel contract)."""
    rng = np.random.default_rng(seed)
    pts = rng.normal(size=(n, d)).astype(np.float32)
    pts[-n_pad:] = 0.0
    cents = pts[:k].copy()
    return jnp.asarray(pts), jnp.asarray(cents), n_pad


def _oracle(pts, cents, n_pad):
    """Numpy Lloyd's statistics over the real (non-padding) rows only."""
    pts = np.asarray(pts)[: pts.shape[0] - n_pad]
    cents = np.asarray(cents)
    d2 = ((pts[:, None, :] - cents[None, :, :]) ** 2).sum(-1)
    assign = d2.argmin(1)
    oh = np.zeros((pts.shape[0], cents.shape[0]), np.float32)
    oh[np.arange(pts.shape[0]), assign] = 1
    return assign, oh.T @ pts, oh.sum(0)


def _corrected_stats(pts, cents, n_pad, **kw):
    sums, counts = kmeans_update_stats(pts, cents, interpret=True, **kw)
    counts = pad_correction(counts, cents, n_pad)
    return sums, counts


def test_update_stats_matches_oracle():
    pts, cents, n_pad = _problem()
    _, exp_sums, exp_counts = _oracle(pts, cents, n_pad)
    for tie_policy in ("fast", "split"):
        sums, counts = _corrected_stats(pts, cents, n_pad, block_n=128,
                                        tie_policy=tie_policy)
        np.testing.assert_allclose(np.asarray(sums), exp_sums, atol=1e-3)
        np.testing.assert_allclose(np.asarray(counts), exp_counts, atol=1e-5)


def test_update_stats_bf16_dots_conserve_mass():
    # bf16 scores may flip boundary assignments vs the f32 oracle, so check
    # the invariants instead: with "split" ties every real row contributes
    # exactly once, so counts and coordinate mass are conserved.
    pts, cents, n_pad = _problem()
    sums, counts = _corrected_stats(pts, cents, n_pad, block_n=128,
                                    tie_policy="split",
                                    compute_dtype=jnp.bfloat16)
    np.testing.assert_allclose(np.asarray(counts).sum(), 512 - n_pad,
                               atol=1e-3)
    np.testing.assert_allclose(
        np.asarray(sums).sum(0),
        np.asarray(pts)[: 512 - n_pad].sum(0), atol=0.3)


def test_assign_reduce_matches_oracle():
    pts, cents, n_pad = _problem()
    assign, sums, counts = kmeans_assign_reduce(pts, cents, block_n=128,
                                                interpret=True)
    counts = pad_correction(counts, cents, n_pad, tie_policy="argmin")
    exp_assign, exp_sums, exp_counts = _oracle(pts, cents, n_pad)
    np.testing.assert_array_equal(np.asarray(assign)[: 512 - n_pad],
                                  exp_assign)
    np.testing.assert_allclose(np.asarray(sums), exp_sums, atol=1e-3)
    np.testing.assert_allclose(np.asarray(counts), exp_counts)


def test_split_ties_fractional():
    # Two identical centroids: "split" halves each point between them,
    # "fast" double-counts — both leave the centroid *means* identical.
    rng = np.random.default_rng(1)
    pts = rng.normal(size=(128, 8)).astype(np.float32)
    cents = np.stack([pts[0], pts[0]])  # exact duplicates -> every row ties
    split_sums, split_counts = kmeans_update_stats(
        jnp.asarray(pts), jnp.asarray(cents), block_n=128,
        tie_policy="split", interpret=True)
    fast_sums, fast_counts = kmeans_update_stats(
        jnp.asarray(pts), jnp.asarray(cents), block_n=128,
        tie_policy="fast", interpret=True)
    np.testing.assert_allclose(np.asarray(split_counts).sum(), 128)
    np.testing.assert_allclose(np.asarray(fast_counts).sum(), 256)
    for sums, counts in ((split_sums, split_counts), (fast_sums, fast_counts)):
        means = np.asarray(sums) / np.asarray(counts)[:, None]
        np.testing.assert_allclose(means[0], means[1], rtol=1e-5)
        np.testing.assert_allclose(means[0], pts.mean(0), rtol=1e-4)


def test_update_stats_sharded_matches_single(cpu_mesh_8):
    pts, cents, n_pad = _problem(n=1024, d=16, k=8)
    sharded_sums, sharded_counts = update_stats_sharded(
        pts, cents, cpu_mesh_8, block_n=128, interpret=True)
    sums, counts = kmeans_update_stats(pts, cents, block_n=128,
                                       interpret=True)
    np.testing.assert_allclose(np.asarray(sharded_sums), np.asarray(sums),
                               atol=1e-3)
    np.testing.assert_allclose(np.asarray(sharded_counts), np.asarray(counts),
                               atol=1e-5)


def test_pad_correction_only_touches_nearest_to_origin():
    cents = jnp.asarray(np.array([[3.0, 0.0], [0.5, 0.5], [2.0, 2.0]],
                                 np.float32))
    counts = jnp.asarray(np.array([10.0, 20.0, 30.0], np.float32))
    out = np.asarray(pad_correction(counts, cents, 7))
    np.testing.assert_allclose(out, [10.0, 13.0, 30.0])


def test_pad_correction_exact_under_min_norm_ties():
    # Two centroids tie for minimal norm (duplicated init): the kernel counts
    # padding on BOTH under "fast" and half-each under "split"; the
    # correction must mirror that, not subtract from the first only.
    rng = np.random.default_rng(5)
    n, n_pad = 128, 32
    pts = rng.normal(loc=5.0, size=(n, 8)).astype(np.float32)
    pts[-n_pad:] = 0.0
    dup = pts[0] * 0.01  # small-norm duplicate pair
    cents = jnp.asarray(np.stack([dup, dup, pts[1], pts[2]]))
    exp_counts = _oracle(jnp.asarray(pts), cents, n_pad)[2]
    for tie_policy, scale in (("fast", 2.0), ("split", 1.0)):
        _, counts = kmeans_update_stats(jnp.asarray(pts), cents, block_n=128,
                                        tie_policy=tie_policy, interpret=True)
        counts = np.asarray(pad_correction(counts, cents, n_pad,
                                           tie_policy=tie_policy))
        # real rows tie on the duplicate pair too, under the same policy
        np.testing.assert_allclose(counts[2:], exp_counts[2:], atol=1e-4)
        np.testing.assert_allclose(counts[:2].sum(),
                                   scale * exp_counts[:2].sum(), atol=1e-3)
        assert (counts >= -1e-4).all()
    # argmin kernel under the same min-norm tie: correction must subtract
    # from the FIRST tied index only (regression: 'fast' correction after
    # the argmin kernel drove counts negative)
    _, _, counts = kmeans_assign_reduce(jnp.asarray(pts), cents, block_n=128,
                                        interpret=True)
    counts = np.asarray(pad_correction(counts, cents, n_pad,
                                       tie_policy="argmin"))
    np.testing.assert_allclose(counts[2:], exp_counts[2:], atol=1e-4)
    assert (counts >= -1e-4).all()


def test_block_divisibility_enforced():
    pts, cents, _ = _problem(n=500, n_pad=3)
    with pytest.raises(ValueError):
        kmeans_update_stats(pts, cents, block_n=128, interpret=True)


def test_bad_tie_policy_rejected():
    pts, cents, _ = _problem()
    with pytest.raises(ValueError):
        kmeans_update_stats(pts, cents, block_n=128, tie_policy="nope",
                            interpret=True)


def test_supported_budget_and_block_pick():
    assert supported(64, 256)
    assert not supported(4096, 8192)
    assert pick_block_n(1_048_576, 64, 256) == 8192
    assert pick_block_n(640, 16, 8) == 128
    assert pick_block_n(100, 16, 8) is None


def test_pad_correction_exact_under_min_norm_ties_first():
    """'first' (the r4 fit default) with duplicated min-norm centroids:
    the kernel counts ALL padding on the first tied column, and
    pad_correction's argmin(c2) must name that same column — real rows
    tying on the duplicate pair land on its first index too, so counts
    match the single-assignment oracle exactly."""
    rng = np.random.default_rng(5)
    n, n_pad = 128, 32
    pts = rng.normal(loc=5.0, size=(n, 8)).astype(np.float32)
    pts[-n_pad:] = 0.0
    dup = pts[0] * 0.01  # small-norm duplicate pair -> tied c2
    cents = jnp.asarray(np.stack([dup, dup, pts[1], pts[2]]))
    exp_counts = _oracle(jnp.asarray(pts), cents, n_pad)[2]
    _, counts = kmeans_update_stats(jnp.asarray(pts), cents, block_n=128,
                                    tie_policy="first", interpret=True)
    counts = np.asarray(pad_correction(counts, cents, n_pad,
                                       tie_policy="first"))
    # single assignment: the whole tied mass sits on column 0
    np.testing.assert_allclose(counts[2:], exp_counts[2:], atol=1e-4)
    np.testing.assert_allclose(counts[0], exp_counts[:2].sum(), atol=1e-3)
    np.testing.assert_allclose(counts[1], 0.0, atol=1e-4)
    assert (counts >= -1e-4).all()
