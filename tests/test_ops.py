"""Pallas kernel tests — run in interpret mode on the CPU mesh (the kernels
compile natively on TPU; interpret mode is the portable correctness oracle)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_ml_tpu.ops.kmeans_pallas import (
    kmeans_assign_reduce,
    kmeans_update_stats,
    supported,
)


def _problem(n=512, d=16, k=8, seed=0):
    rng = np.random.default_rng(seed)
    pts = rng.normal(size=(n, d)).astype(np.float32)
    cents = pts[:k].copy()
    mask = np.ones((n,), np.float32)
    mask[-17:] = 0.0  # padding rows
    return jnp.asarray(pts), jnp.asarray(mask), jnp.asarray(cents)


def _oracle(pts, mask, cents):
    pts, mask, cents = map(np.asarray, (pts, mask, cents))
    d2 = ((pts[:, None, :] - cents[None, :, :]) ** 2).sum(-1)
    assign = d2.argmin(1)
    oh = np.zeros((pts.shape[0], cents.shape[0]), np.float32)
    oh[np.arange(pts.shape[0]), assign] = 1
    oh *= mask[:, None]
    return assign, oh.T @ pts, oh.sum(0)


def test_assign_reduce_matches_oracle():
    pts, mask, cents = _problem()
    assign, sums, counts = kmeans_assign_reduce(pts, mask, cents,
                                                block_n=128, interpret=True)
    exp_assign, exp_sums, exp_counts = _oracle(pts, mask, cents)
    np.testing.assert_array_equal(np.asarray(assign), exp_assign)
    np.testing.assert_allclose(np.asarray(sums), exp_sums, atol=1e-3)
    np.testing.assert_allclose(np.asarray(counts), exp_counts)


def test_update_stats_matches_oracle():
    pts, mask, cents = _problem()
    sums, counts = kmeans_update_stats(pts, mask, cents,
                                       block_n=128, interpret=True)
    _, exp_sums, exp_counts = _oracle(pts, mask, cents)
    np.testing.assert_allclose(np.asarray(sums), exp_sums, atol=1e-3)
    np.testing.assert_allclose(np.asarray(counts), exp_counts, atol=1e-5)


def test_mask_zeroes_padding_contribution():
    pts, mask, cents = _problem()
    # same points, but with padding rows replaced by huge values that would
    # corrupt sums if the mask leaked
    pts_np = np.asarray(pts).copy()
    pts_np[-17:] = 1e6
    sums, counts = kmeans_update_stats(jnp.asarray(pts_np), mask, cents,
                                       block_n=128, interpret=True)
    assert np.all(np.isfinite(np.asarray(sums)))
    assert float(np.asarray(counts).sum()) == pytest.approx(512 - 17)
    assert np.abs(np.asarray(sums)).max() < 1e4  # 1e6 rows never entered


def test_block_divisibility_enforced():
    pts, mask, cents = _problem(n=500)
    with pytest.raises(ValueError):
        kmeans_assign_reduce(pts, mask, cents, block_n=128, interpret=True)


def test_supported_budget():
    assert supported(64, 256)
    assert not supported(4096, 8192)
