"""Serving fleet failover tests (ISSUE 20): the chip-lease health table
(expiry on the injected clock, zombie-heartbeat suppression, seeded
chip_down/chip_flap translation with LIFO victims and deterministic flap
recovery), the FailoverDriver end to end (dispatch-boundary fault ->
lossless requeue -> CAS re-placement -> re-admission generation bump),
the SLO-aware brownout ladder with hysteresis, deadline-aware requeue
(DeadlineExceededError is fatal-not-retryable), N-way replication (a
replicated tenant's failover window is one dispatch, no re-warm), the
restore-after-hysteresis flap-thrash bound, and the metrics-tree
surface."""

import time

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.autoscale.placement import PlacementStore
from flink_ml_tpu.obs.tree import default_tree
from flink_ml_tpu.robustness.faults import (FaultPlan, InjectedChipDown,
                                            InjectedChipFlap)
from flink_ml_tpu.robustness.retry import (DeadlineExceededError,
                                           RetryPolicy, default_classify)
from flink_ml_tpu.serving import (
    CHIP_SCOPE,
    DISPATCH_SCOPE,
    SLO_BULK,
    SLO_CLASSES,
    SLO_INTERACTIVE,
    SLO_STANDARD,
    FailoverDriver,
    FleetHealth,
    ModelRegistry,
    ServingOverloadedError,
    SharedScheduler,
)
from flink_ml_tpu.serving.metrics import HEALTH_SERVING


# -- fixtures (the test_scheduler stubs, kept local) -------------------------

class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class _StubServable:
    """Echo servable: queue/placement mechanics without model fits."""

    ready = True
    warmup_report = None

    def __init__(self, model, example, **kwargs):
        self.model = model
        self.example = example
        self.max_batch_rows = kwargs.get("max_batch_rows", 256)
        self.min_bucket = kwargs.get("min_bucket", 8)
        self.output_cols = None

    def warm_up(self):
        return self

    def check_schema(self, table):
        pass

    def bucket_for(self, rows):
        return max(8, rows)

    def predict(self, table):
        return table


def _stub_scheduler(**kwargs):
    return SharedScheduler(ModelRegistry(servable_factory=_StubServable),
                           **kwargs)


def _feats(n=256, seed=1):
    rng = np.random.default_rng(seed)
    return Table({"features": rng.normal(size=(n, 8))})


def _drain(scheduler, max_batches=10_000):
    batches = 0
    while batches < max_batches:
        formed = scheduler._next_batch(timeout=0.0)
        if formed is None:
            return batches
        scheduler._dispatch(*formed)
        batches += 1
    raise AssertionError("drain did not converge")


def _fleet(chips, placements, tenants, *, clock=None, **driver_kw):
    """A scheduler + placement store + driver wired like production:
    tenants admitted, initial placement published, driver attached."""
    clock = clock or FakeClock()
    s = _stub_scheduler(max_batch_rows=8, max_wait_ms=0.0,
                        queue_capacity=4096)
    feats = _feats()
    for name, slo in tenants:
        s.add_tenant(name, object(), feats.take(2), slo=slo)
    store = PlacementStore(max(chips) + 1)
    store.publish(placements, 0)
    driver = FailoverDriver(s, store, chips=chips, clock=clock,
                            **driver_kw)
    return s, store, driver, clock


# -- FleetHealth: the chip lease table ---------------------------------------

def test_lease_expiry_detects_silent_death_on_injected_clock():
    """Chips that miss heartbeats past lease_timeout_s are reaped
    deterministically; a heartbeat keeps its chip alive."""
    clock = FakeClock()
    h = FleetHealth([0, 1, 2], lease_timeout_s=5.0, clock=clock)
    clock.advance(3.0)
    assert h.heartbeat(0)
    clock.advance(3.0)              # t=6: chips 1,2 lapsed at 5; 0 at 8
    assert h.expire() == [1, 2]
    assert h.live() == [0]
    assert h.down() == [1, 2]
    snap = h.snapshot()
    assert snap["expiries"] == 2 and snap["deaths"] == 2
    assert h.epoch == 2
    assert [k for k, _, _ in h.transitions] == ["expired", "expired"]


def test_heartbeat_from_declared_dead_chip_is_suppressed():
    """A zombie cannot out-race the reaper: its heartbeat is counted,
    not honored — it must come back through recover()."""
    h = FleetHealth([0, 1], clock=FakeClock())
    assert h.fail(1)
    assert not h.heartbeat(1)
    assert h.down() == [1]
    assert h.snapshot()["suppressed"] == 1
    assert not h.fail(1)            # already down: no double-death
    assert h.recover(1)
    assert h.live() == [0, 1]
    assert h.snapshot()["recoveries"] == 1


def test_poll_translates_seeded_chip_down_to_lifo_victim():
    """A seeded chip_down fires at a deterministic poll index and kills
    the newest lease; the whole transition log replays bit-identically
    under the same plan."""
    def run():
        h = FleetHealth([0, 1, 2], clock=FakeClock())
        with FaultPlan(seed=3).inject(CHIP_SCOPE, at=1, kind="chip_down"):
            events = [h.poll() for _ in range(3)]
        return h, events

    h, events = run()
    assert events == [[], [("down", 2)], []]
    assert h.down() == [2]
    assert h.transitions == [("down", 2, 1)]
    h2, events2 = run()
    assert events2 == events and h2.transitions == h.transitions


def test_chip_flap_recovers_after_scheduled_polls():
    """chip_flap schedules its own recovery a deterministic number of
    polls later — the flap model needs no wall clock at all."""
    h = FleetHealth([0, 1], clock=FakeClock(), flap_recovery_polls=2)
    with FaultPlan().inject(CHIP_SCOPE, at=0, kind="chip_flap"):
        assert h.poll() == [("down", 1)]
    assert h.down() == [1]
    assert h.poll() == [("up", 1)]
    assert h.live() == [0, 1]
    snap = h.snapshot()
    assert snap["flaps"] == 1 and snap["recoveries"] == 1
    assert [k for k, _, _ in h.transitions] == ["flap_down", "up"]


def test_fleet_health_validates_construction():
    with pytest.raises(ValueError):
        FleetHealth([])
    with pytest.raises(ValueError):
        FleetHealth([0], lease_timeout_s=0.0)
    with pytest.raises(ValueError):
        FleetHealth([0], flap_recovery_polls=0)


# -- the failover itself -----------------------------------------------------

def test_dispatch_chip_fault_is_lossless_and_replaces_tenants():
    """The core contract: an injected chip death at the dispatch
    boundary requeues the picked batch (futures intact -> every request
    answered), evicts the dead chip through one CAS publish on the
    shared generation stream, moves its sole-placement tenant to the
    least-loaded survivor with a registry generation bump (the
    re-anchor signal), and raises the brownout."""
    s, store, driver, _ = _fleet(
        [0, 1, 2, 3],
        {"inter": [0, 3], "std": [3], "bulk": [1]},
        [("inter", SLO_INTERACTIVE), ("std", SLO_STANDARD),
         ("bulk", SLO_BULK)])
    gen0 = store.generation
    std_gen = s.registry.current("std").generation
    inter_gen = s.registry.current("inter").generation
    feats = _feats()
    futures = []
    for i in range(4):
        futures.append(s.submit("inter", feats.slice(4 * i, 4 * i + 4)))
        futures.append(s.submit("std", feats.slice(32 + 4 * i,
                                                   36 + 4 * i)))
    with FaultPlan().inject(DISPATCH_SCOPE, at=0, kind="chip_down"):
        _drain(s)

    # zero drops: every future resolved with its own rows echoed back
    for fut in futures:
        assert fut.result(timeout=0).num_rows == 4
    assert len(driver.reports) == 1
    rep = driver.reports[0]
    assert rep.dead_chips == (3,)       # LIFO victim: newest lease
    assert rep.cause == "dispatch"
    assert rep.requeued > 0
    assert rep.conflicts == 0
    assert set(rep.replicated) == {"inter"}
    assert set(rep.moved) == {"std"}

    pmap = store.current()
    assert pmap.generation == gen0 + 1 == rep.generation
    assert set(pmap.chips_for("inter")) == {0}      # survivors kept
    assert 3 not in pmap.chips_for("std")
    assert len(pmap.chips_for("std")) == 1
    # re-admission stamped a fresh generation for the MOVED tenant only
    assert s.registry.current("std").generation == std_gen + 1
    assert s.registry.current("inter").generation == inter_gen
    # 1/4 of the fleet down -> brownout level 1: bulk shed at admission,
    # standard and interactive still admitted
    assert driver.brownout_level == 1 and s.brownout_level == 1
    with pytest.raises(ServingOverloadedError, match="brownout"):
        s.submit("bulk", feats.take(4))
    fut = s.submit("inter", feats.take(4))
    _drain(s)
    assert fut.result(timeout=0).num_rows == 4


def test_brownout_ladder_raises_immediately_lowers_with_hysteresis():
    """Level tracks the capacity deficit: raising is immediate on the
    tick that sees the loss, lowering dwells hysteresis_s of stable
    fleet — and the top class NEVER sheds at any rung."""
    s, store, driver, clock = _fleet(
        [0, 1, 2, 3], {"inter": [0], "std": [1], "bulk": [2]},
        [("inter", SLO_INTERACTIVE), ("std", SLO_STANDARD),
         ("bulk", SLO_BULK)],
        hysteresis_s=30.0)
    feats = _feats()
    assert driver.brownout_level == 0
    fut = s.submit("bulk", feats.take(4))
    _drain(s)
    assert fut.result(timeout=0).num_rows == 4

    driver.health.fail(3)
    driver.tick()                       # deficit 1/4 -> level 1
    assert driver.brownout_level == 1
    with pytest.raises(ServingOverloadedError):
        s.submit("bulk", feats.take(4))
    fut = s.submit("std", feats.take(4))
    _drain(s)
    assert fut.result(timeout=0).num_rows == 4

    driver.health.fail(2)
    driver.tick()                       # deficit 1/2 -> level 2
    assert driver.brownout_level == 2
    with pytest.raises(ServingOverloadedError):
        s.submit("std", feats.take(4))
    fut = s.submit("inter", feats.take(4))   # interactive: protected
    _drain(s)
    assert fut.result(timeout=0).num_rows == 4

    driver.health.recover(2)
    driver.health.recover(3)
    driver.tick()                       # target 0: starts the dwell
    assert driver.brownout_level == 2   # ... but holds through it
    clock.advance(30.0)
    driver.tick()
    assert driver.brownout_level == 0 and s.brownout_level == 0
    assert s.health == HEALTH_SERVING   # brownout end releases the heal


def test_set_brownout_clamps_to_protect_the_top_class():
    s = _stub_scheduler()
    assert s.set_brownout(99) == len(SLO_CLASSES) - 1
    assert s.set_brownout(-5) == 0
    assert s.brownout_level == 0


def test_driver_validates_brownout_rungs():
    s = _stub_scheduler()
    store = PlacementStore(2)
    store.publish({}, 0)
    with pytest.raises(ValueError, match="non-decreasing"):
        FailoverDriver(s, store, chips=[0, 1],
                       brownout_deficits=(0.5, 0.25))
    with pytest.raises(ValueError, match="rungs"):
        FailoverDriver(s, store, chips=[0, 1],
                       brownout_deficits=(0.1, 0.2, 0.3))


# -- deadline-aware requeue --------------------------------------------------

def test_requeue_within_deadline_is_lossless():
    """A requeued request inside its SLO deadline goes back to the
    FRONT of its tenant's queue and is served bit-identically."""
    s = _stub_scheduler(max_batch_rows=8, max_wait_ms=0.0,
                        request_deadline_ms=10_000.0)
    feats = _feats()
    s.add_tenant("t", object(), feats.take(2), slo=SLO_INTERACTIVE)
    fut = s.submit("t", feats.take(4))
    formed = s._next_batch(timeout=0.0)
    assert formed is not None
    assert s._requeue(formed[1]) == 1
    assert s.tenant("t").metrics.requeued.value == 1
    _drain(s)
    out = fut.result(timeout=0)
    assert np.array_equal(out["features"], feats.take(4)["features"])


def test_requeue_past_deadline_sheds_with_fatal_error():
    """A requeued request already past its deadline sheds with
    DeadlineExceededError instead of burning survivor capacity — and
    the classifier refuses to retry it even though it IS a
    TimeoutError."""
    s = _stub_scheduler(max_batch_rows=8, max_wait_ms=0.0,
                        request_deadline_ms=1.0)
    feats = _feats()
    s.add_tenant("t", object(), feats.take(2), slo=SLO_INTERACTIVE)
    fut = s.submit("t", feats.take(4))
    formed = s._next_batch(timeout=0.0)
    time.sleep(0.01)                    # blow the 1ms deadline
    assert s._requeue(formed[1]) == 0
    with pytest.raises(DeadlineExceededError) as ei:
        fut.result(timeout=0)
    assert default_classify(ei.value) is False
    assert isinstance(ei.value, TimeoutError)
    assert s._deadline_shed.value == 1
    assert s.shed_counts()[SLO_INTERACTIVE] == 1
    assert _drain(s) == 0               # queue is empty: truly shed


def test_scheduler_validates_request_deadline():
    with pytest.raises(ValueError):
        _stub_scheduler(request_deadline_ms=0.0)


# -- retry classification (ISSUE 20 satellite) -------------------------------

def test_deadline_exceeded_outranks_timeout_retryability():
    assert default_classify(TimeoutError("transient")) is True
    assert default_classify(DeadlineExceededError("past SLO")) is False

    class ForeignDeadline(Exception):
        deadline_exceeded = True        # the marker, not the class

    assert default_classify(ForeignDeadline()) is False


def test_retry_policy_never_resurrects_a_dead_deadline():
    policy = RetryPolicy(max_attempts=5, sleep=lambda s: None)
    calls = []

    def fn():
        calls.append(1)
        raise DeadlineExceededError("answer is worthless now")

    with pytest.raises(DeadlineExceededError):
        policy.call(fn)
    assert len(calls) == 1              # ONE attempt: fatal, no retries
    assert policy.retries == 0 and policy.slept == []


# -- replication -------------------------------------------------------------

def test_replicated_tenant_fails_over_in_one_dispatch():
    """ensure_replicas grows the placement to n distinct least-loaded
    chips; on a chip loss the replicated tenant keeps a survivor —
    no move, no re-admission, no registry generation bump (its failover
    window is one dispatch, not one re-warm)."""
    s, store, driver, _ = _fleet(
        [0, 1, 2], {"hot": [2], "cold": [0]},
        [("hot", SLO_INTERACTIVE), ("cold", SLO_STANDARD)])
    pmap = driver.ensure_replicas("hot", 2)
    assert set(pmap.chips_for("hot")) == {1, 2}     # least-loaded added
    gen_after_replicas = store.generation
    assert driver.ensure_replicas("hot", 2) is store.current()
    assert store.generation == gen_after_replicas   # idempotent: no publish
    hot_gen = s.registry.current("hot").generation

    rep = driver.on_chip_fault(InjectedChipDown("injected chip death"))
    assert rep is not None
    assert rep.dead_chips == (2,)
    assert rep.replicated == ("hot",) and rep.moved == ()
    assert set(store.current().chips_for("hot")) == {1}
    assert set(store.current().chips_for("cold")) == {0}
    # the whole point of replication: NO re-admission happened
    assert s.registry.current("hot").generation == hot_gen


def test_ensure_replicas_validates_count():
    s, store, driver, _ = _fleet(
        [0, 1], {"t": [0]}, [("t", SLO_INTERACTIVE)])
    with pytest.raises(ValueError):
        driver.ensure_replicas("t", 0)


# -- flap thrash bound + restore ---------------------------------------------

def test_flap_costs_one_move_per_stability_window_then_restores():
    """A flapping chip: ONE eviction publish when it dies, ZERO restores
    while it is unstable, one restore publish once it has stayed live
    hysteresis_s — and the brownout settles back to 0 with it."""
    clock = FakeClock()
    s, store, driver, _ = _fleet(
        [0, 1, 2], {"a": [2], "b": [0]},
        [("a", SLO_INTERACTIVE), ("b", SLO_STANDARD)],
        clock=clock, hysteresis_s=20.0, flap_recovery_polls=2)
    with FaultPlan().inject(CHIP_SCOPE, at=0, kind="chip_flap"):
        rep = driver.tick()
    assert rep is not None and rep.dead_chips == (2,)
    assert rep.moved == ("a",)
    gen_evict = store.generation
    assert set(store.current().chips_for("a")) == {1}
    assert driver.brownout_level == 1

    assert driver.tick() is None        # flap recovery: chip 2 rejoins
    assert driver.health.live() == [0, 1, 2]
    assert store.generation == gen_evict            # no restore yet
    clock.advance(10.0)
    driver.tick()
    assert store.generation == gen_evict            # still dwelling
    assert driver.brownout_level == 1               # lowering dwells too
    clock.advance(10.0)
    driver.tick()                       # 20s stable: restore + level 0
    assert store.generation == gen_evict + 1
    assert set(store.current().chips_for("a")) == {2}
    assert driver.snapshot()["restores"] == 1
    assert driver.brownout_level == 0
    assert driver.snapshot()["evicted_chips_pending_restore"] == 0


# -- observability -----------------------------------------------------------

def test_default_tree_exposes_failover_fleet_view():
    s, store, driver, _ = _fleet(
        [0, 1, 2], {"t": [0]}, [("t", SLO_INTERACTIVE)])
    tree = default_tree(failover=driver)
    snap = tree.snapshot()
    assert snap["failover"]["chips_live"] == 3
    assert snap["failover"]["chips_down"] == 0
    assert snap["failover"]["brownout_level"] == 0
    driver.on_chip_fault(InjectedChipFlap("injected flap"))
    snap = tree.snapshot()
    assert snap["failover"]["chips_live"] == 2
    assert snap["failover"]["chips_down"] == 1
    assert snap["failover"]["failovers"] == 1
    assert snap["failover"]["chips_lost"] == 1
    assert snap["failover"]["last_failover_wall_s"] >= 0.0
