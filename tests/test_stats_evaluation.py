"""ChiSqTest + MulticlassClassificationEvaluator."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.evaluation import MulticlassClassificationEvaluator
from flink_ml_tpu.models.stats import ChiSqTest


def test_chisq_independent_vs_dependent():
    rng = np.random.default_rng(0)
    n = 2000
    y = rng.integers(0, 2, n)
    dependent = y.copy()
    dependent[rng.random(n) < 0.05] ^= 1        # strongly associated
    independent = rng.integers(0, 2, n)          # unrelated
    X = np.stack([dependent, independent], axis=1).astype(np.float64)
    out = ChiSqTest().transform(Table({"features": X, "label": y}))[0]
    p = np.asarray(out["pValue"])
    assert p[0] < 1e-10          # dependent column: reject independence
    assert p[1] > 0.01           # independent column: no evidence
    np.testing.assert_array_equal(np.asarray(out["degreesOfFreedom"]),
                                  [1, 1])


def test_chisq_matches_scipy_formula():
    # hand-checkable 2x2: observed [[10, 20], [20, 10]]
    x = np.repeat([0, 0, 1, 1], [10, 20, 20, 10])
    y = np.concatenate([np.zeros(10), np.ones(20), np.zeros(20), np.ones(10)])
    out = ChiSqTest().transform(Table({
        "features": x[:, None].astype(np.float64), "label": y}))[0]
    stat = float(np.asarray(out["statistic"])[0])
    # chi2 = sum (O-E)^2/E with E=15 everywhere: 4 * 25/15 = 6.6667
    assert stat == pytest.approx(20 / 3, rel=1e-5)
    p = float(np.asarray(out["pValue"])[0])
    assert p == pytest.approx(0.00982, abs=2e-4)  # 1 - chi2.cdf(6.667, 1)


def test_chisq_multi_level_dof():
    rng = np.random.default_rng(1)
    X = rng.integers(0, 4, size=(500, 1)).astype(np.float64)
    y = rng.integers(0, 3, 500)
    out = ChiSqTest().transform(Table({"features": X, "label": y}))[0]
    assert int(np.asarray(out["degreesOfFreedom"])[0]) == (4 - 1) * (3 - 1)


def test_multiclass_evaluator_perfect_and_known():
    y = np.asarray([0, 0, 1, 1, 2, 2])
    perfect = (MulticlassClassificationEvaluator()
               .set_metrics("accuracy", "weightedFMeasure")
               .transform(Table({"label": y, "prediction": y}))[0])
    assert float(np.asarray(perfect["accuracy"])[0]) == 1.0
    assert float(np.asarray(perfect["weightedFMeasure"])[0]) == 1.0

    pred = np.asarray([0, 1, 1, 1, 2, 0])  # 4/6 correct
    out = (MulticlassClassificationEvaluator()
           .set_metrics("accuracy", "weightedPrecision", "weightedRecall")
           .transform(Table({"label": y, "prediction": pred}))[0])
    assert float(np.asarray(out["accuracy"])[0]) == pytest.approx(4 / 6)
    # recall per class: 1/2, 2/2, 1/2 -> weighted = (0.5+1+0.5)/3
    assert float(np.asarray(out["weightedRecall"])[0]) == pytest.approx(2 / 3)


def test_multiclass_evaluator_prediction_outside_label_space():
    y = np.asarray([0, 0, 1])
    pred = np.asarray([0, 7, 1])  # class 7 never appears in labels
    out = (MulticlassClassificationEvaluator().set_metrics("accuracy")
           .transform(Table({"label": y, "prediction": pred}))[0])
    assert float(np.asarray(out["accuracy"])[0]) == pytest.approx(2 / 3)


def test_multiclass_evaluator_string_labels():
    y = np.asarray(["cat", "dog", "cat"])
    pred = np.asarray(["cat", "dog", "dog"])
    out = (MulticlassClassificationEvaluator().set_metrics("accuracy")
           .transform(Table({"label": y, "prediction": pred}))[0])
    assert float(np.asarray(out["accuracy"])[0]) == pytest.approx(2 / 3)


# ---------------------------------------------------- RegressionEvaluator


def test_regression_evaluator_hand_computed():
    from flink_ml_tpu.models.evaluation import RegressionEvaluator

    y = np.asarray([1.0, 2.0, 3.0, 4.0])
    pred = np.asarray([1.5, 2.0, 2.0, 5.0])
    # errors: .5, 0, -1, 1 -> mse = (.25+0+1+1)/4 = .5625; mae = 2.5/4
    t = Table({"label": y, "prediction": pred})
    out = (RegressionEvaluator().set_metrics("rmse", "mse", "mae", "r2")
           .transform(t)[0])
    np.testing.assert_allclose(float(out["mse"][0]), 0.5625)
    np.testing.assert_allclose(float(out["rmse"][0]), np.sqrt(0.5625))
    np.testing.assert_allclose(float(out["mae"][0]), 0.625)
    # ss_tot = sum((y - 2.5)^2) = 5 -> r2 = 1 - 2.25/5
    np.testing.assert_allclose(float(out["r2"][0]), 1 - 2.25 / 5)


def test_regression_evaluator_weighted_and_degenerate():
    from flink_ml_tpu.models.evaluation import RegressionEvaluator

    t = Table({"label": np.asarray([0.0, 10.0]),
               "prediction": np.asarray([1.0, 10.0]),
               "w": np.asarray([1.0, 3.0])})
    out = (RegressionEvaluator().set_metrics("mse").set_weight_col("w")
           .transform(t)[0])
    np.testing.assert_allclose(float(out["mse"][0]), 0.25)  # (1*1+3*0)/4

    # constant labels: perfect fit -> r2 = 1; any error -> 0
    const = Table({"label": np.ones(3), "prediction": np.ones(3)})
    out = RegressionEvaluator().set_metrics("r2").transform(const)[0]
    assert float(out["r2"][0]) == 1.0
    off = Table({"label": np.ones(3), "prediction": np.zeros(3)})
    assert float(RegressionEvaluator().set_metrics("r2")
                 .transform(off)[0]["r2"][0]) == 0.0


def test_regression_evaluator_validates():
    from flink_ml_tpu.models.evaluation import RegressionEvaluator

    with pytest.raises(ValueError, match="at least one"):
        RegressionEvaluator().transform(
            Table({"label": np.zeros(0), "prediction": np.zeros(0)}))


# ---------------------------------------------------- ClusteringEvaluator


def test_silhouette_matches_sklearn_formula(rng):
    """Hand-verified against the definition on a small fixture (and equal to
    sklearn.metrics.silhouette_score on the same input)."""
    from flink_ml_tpu.models.evaluation import ClusteringEvaluator

    X = rng.normal(size=(60, 3))
    labels = rng.integers(0, 3, size=60)
    t = Table({"features": X, "prediction": labels})
    got = float(ClusteringEvaluator().transform(t)[0]["silhouette"][0])

    # reference implementation straight from the definition
    from scipy.spatial.distance import cdist

    D = cdist(X, X)
    s_vals = []
    for i in range(len(X)):
        own = labels == labels[i]
        a = D[i, own].sum() / max(own.sum() - 1, 1)
        b = min(D[i, labels == c].mean()
                for c in np.unique(labels) if c != labels[i])
        s_vals.append((b - a) / max(a, b) if own.sum() > 1 else 0.0)
    np.testing.assert_allclose(got, np.mean(s_vals), atol=1e-5)


def test_silhouette_separated_blobs_near_one(rng):
    from flink_ml_tpu.models.evaluation import ClusteringEvaluator

    X = np.concatenate([rng.normal(size=(40, 2)) * 0.1,
                        rng.normal(size=(40, 2)) * 0.1 + 50.0])
    labels = np.repeat([0, 1], 40)
    t = Table({"features": X, "prediction": labels})
    got = float(ClusteringEvaluator().transform(t)[0]["silhouette"][0])
    assert got > 0.98


def test_silhouette_singletons_and_validation(rng):
    from flink_ml_tpu.models.evaluation import ClusteringEvaluator

    # one singleton cluster scores 0 by convention, pulling the mean down
    X = np.asarray([[0.0, 0], [0.1, 0], [9.0, 9]])
    t = Table({"features": X, "prediction": np.asarray([0, 0, 1])})
    got = float(ClusteringEvaluator().transform(t)[0]["silhouette"][0])
    assert 0.0 < got < 1.0

    with pytest.raises(ValueError, match="2 rows"):
        ClusteringEvaluator().transform(
            Table({"features": np.zeros((1, 2)),
                   "prediction": np.zeros(1)}))
