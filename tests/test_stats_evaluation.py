"""ChiSqTest + MulticlassClassificationEvaluator."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.evaluation import MulticlassClassificationEvaluator
from flink_ml_tpu.models.stats import ChiSqTest


def test_chisq_independent_vs_dependent():
    rng = np.random.default_rng(0)
    n = 2000
    y = rng.integers(0, 2, n)
    dependent = y.copy()
    dependent[rng.random(n) < 0.05] ^= 1        # strongly associated
    independent = rng.integers(0, 2, n)          # unrelated
    X = np.stack([dependent, independent], axis=1).astype(np.float64)
    out = ChiSqTest().transform(Table({"features": X, "label": y}))[0]
    p = np.asarray(out["pValue"])
    assert p[0] < 1e-10          # dependent column: reject independence
    assert p[1] > 0.01           # independent column: no evidence
    np.testing.assert_array_equal(np.asarray(out["degreesOfFreedom"]),
                                  [1, 1])


def test_chisq_matches_scipy_formula():
    # hand-checkable 2x2: observed [[10, 20], [20, 10]]
    x = np.repeat([0, 0, 1, 1], [10, 20, 20, 10])
    y = np.concatenate([np.zeros(10), np.ones(20), np.zeros(20), np.ones(10)])
    out = ChiSqTest().transform(Table({
        "features": x[:, None].astype(np.float64), "label": y}))[0]
    stat = float(np.asarray(out["statistic"])[0])
    # chi2 = sum (O-E)^2/E with E=15 everywhere: 4 * 25/15 = 6.6667
    assert stat == pytest.approx(20 / 3, rel=1e-5)
    p = float(np.asarray(out["pValue"])[0])
    assert p == pytest.approx(0.00982, abs=2e-4)  # 1 - chi2.cdf(6.667, 1)


def test_chisq_multi_level_dof():
    rng = np.random.default_rng(1)
    X = rng.integers(0, 4, size=(500, 1)).astype(np.float64)
    y = rng.integers(0, 3, 500)
    out = ChiSqTest().transform(Table({"features": X, "label": y}))[0]
    assert int(np.asarray(out["degreesOfFreedom"])[0]) == (4 - 1) * (3 - 1)


def test_multiclass_evaluator_perfect_and_known():
    y = np.asarray([0, 0, 1, 1, 2, 2])
    perfect = (MulticlassClassificationEvaluator()
               .set_metrics("accuracy", "weightedFMeasure")
               .transform(Table({"label": y, "prediction": y}))[0])
    assert float(np.asarray(perfect["accuracy"])[0]) == 1.0
    assert float(np.asarray(perfect["weightedFMeasure"])[0]) == 1.0

    pred = np.asarray([0, 1, 1, 1, 2, 0])  # 4/6 correct
    out = (MulticlassClassificationEvaluator()
           .set_metrics("accuracy", "weightedPrecision", "weightedRecall")
           .transform(Table({"label": y, "prediction": pred}))[0])
    assert float(np.asarray(out["accuracy"])[0]) == pytest.approx(4 / 6)
    # recall per class: 1/2, 2/2, 1/2 -> weighted = (0.5+1+0.5)/3
    assert float(np.asarray(out["weightedRecall"])[0]) == pytest.approx(2 / 3)


def test_multiclass_evaluator_prediction_outside_label_space():
    y = np.asarray([0, 0, 1])
    pred = np.asarray([0, 7, 1])  # class 7 never appears in labels
    out = (MulticlassClassificationEvaluator().set_metrics("accuracy")
           .transform(Table({"label": y, "prediction": pred}))[0])
    assert float(np.asarray(out["accuracy"])[0]) == pytest.approx(2 / 3)


def test_multiclass_evaluator_string_labels():
    y = np.asarray(["cat", "dog", "cat"])
    pred = np.asarray(["cat", "dog", "dog"])
    out = (MulticlassClassificationEvaluator().set_metrics("accuracy")
           .transform(Table({"label": y, "prediction": pred}))[0])
    assert float(np.asarray(out["accuracy"])[0]) == pytest.approx(2 / 3)
