"""Out-of-core training path: host->device prefetch + streaming SGD from the
data cache (the Criteo-scale input shape, BASELINE.md north star).  Runs on
the virtual 8-device mesh like everything else."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter
from flink_ml_tpu.data.prefetch import prefetch_to_device
from flink_ml_tpu.models.classification.logisticregression import (
    LogisticRegression,
)
from flink_ml_tpu.models.common.sgd import SGDConfig, sgd_fit_outofcore
from flink_ml_tpu.models.common.losses import logistic_loss
from flink_ml_tpu.data.table import Table


# ------------------------------------------------------------- prefetch


def test_prefetch_preserves_order_and_values():
    batches = [np.full((4,), i, np.float32) for i in range(10)]
    out = list(prefetch_to_device(iter(batches), depth=2))
    assert len(out) == 10
    for i, b in enumerate(out):
        assert isinstance(b, jax.Array)
        np.testing.assert_array_equal(np.asarray(b), batches[i])


def test_prefetch_transform_runs_on_worker_thread():
    main = threading.get_ident()
    seen = []

    def transform(b):
        seen.append(threading.get_ident())
        return b * 2

    out = list(prefetch_to_device(iter([np.ones(2), np.ones(2)]),
                                  transform=transform))
    assert all(t != main for t in seen)
    np.testing.assert_array_equal(np.asarray(out[0]), [2.0, 2.0])


def test_prefetch_propagates_source_exception():
    def bad_source():
        yield np.ones(2)
        raise RuntimeError("disk on fire")

    it = prefetch_to_device(bad_source(), depth=1)
    next(it)
    with pytest.raises(RuntimeError, match="disk on fire"):
        next(it)


def test_prefetch_put_workers_order_and_values():
    """Parallel putters must reassemble source order exactly, for every
    (workers, put_workers) topology, including an empty stream."""
    batches = [np.full((4,), i, np.float32) for i in range(17)]
    for w, pw in [(1, 3), (2, 2), (3, 4)]:
        out = list(prefetch_to_device(iter(batches), depth=2,
                                      workers=w, put_workers=pw))
        assert len(out) == 17
        for i, b in enumerate(out):
            np.testing.assert_array_equal(np.asarray(b), batches[i])
    assert list(prefetch_to_device(iter([]), put_workers=3)) == []


def test_prefetch_put_workers_propagates_exceptions():
    def bad_source():
        yield np.ones(2)
        yield np.ones(2)
        raise RuntimeError("disk on fire")

    with pytest.raises(RuntimeError, match="disk on fire"):
        list(prefetch_to_device(bad_source(), depth=1, put_workers=3))

    def bad_transform(b):
        raise ValueError("decode exploded")

    with pytest.raises(ValueError, match="decode exploded"):
        list(prefetch_to_device(iter([np.ones(2)]), transform=bad_transform,
                                put_workers=2))


def test_prefetch_errors_delivered_in_stream_order():
    """Every batch read before the failure reaches the consumer BEFORE
    the exception, at any worker topology — callers that checkpoint from
    the last consumed batch rely on it."""
    def bad_source():
        for i in range(12):
            yield np.full((2,), i, np.float32)
        raise RuntimeError("disk on fire")

    for w, pw in [(1, 1), (2, 1), (2, 3)]:
        got = []
        with pytest.raises(RuntimeError, match="disk on fire"):
            for b in prefetch_to_device(bad_source(), depth=2,
                                        workers=w, put_workers=pw):
                got.append(int(np.asarray(b)[0]))
        assert got == list(range(12)), (w, pw, got)


def test_prefetch_put_workers_validated():
    with pytest.raises(ValueError, match="put_workers"):
        list(prefetch_to_device(iter([]), put_workers=0))


def test_prefetch_depth_validated():
    with pytest.raises(ValueError, match="depth"):
        list(prefetch_to_device(iter([]), depth=0))


def test_prefetch_applies_sharding():
    from flink_ml_tpu.parallel.mesh import device_mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = device_mesh({"data": 8})
    sh = NamedSharding(mesh, P("data"))
    (out,) = list(prefetch_to_device(iter([np.arange(16, dtype=np.float32)]),
                                     sharding=sh))
    assert out.sharding == sh


def test_prefetch_early_abandon_does_not_hang():
    it = prefetch_to_device((np.ones(2) for _ in range(1000)), depth=2)
    next(it)
    it.close()  # generator close must stop the worker


# -------------------------------------------------------- streaming SGD


def _write_lr_cache(tmp_path, n=4096, d=16, seed=0):
    """Linearly-separable data cached on disk; returns (dir, true_w)."""
    rng = np.random.default_rng(seed)
    true_w = rng.normal(size=(d,))
    cache = str(tmp_path / "cache")
    writer = DataCacheWriter(cache, segment_rows=1024)
    for start in range(0, n, 512):
        X = rng.normal(size=(512, d)).astype(np.float32)
        y = (X @ true_w > 0).astype(np.float32)
        writer.append({"features": X, "label": y})
    writer.finish()
    return cache, true_w


def test_sgd_outofcore_converges(tmp_path):
    cache, true_w = _write_lr_cache(tmp_path)

    def make_reader():
        return iter(DataCacheReader(cache, batch_rows=256))

    state, loss_log = sgd_fit_outofcore(
        logistic_loss, make_reader, num_features=16,
        config=SGDConfig(learning_rate=0.5, max_epochs=8, tol=0.0))
    assert len(loss_log) == 8
    assert loss_log[-1] < loss_log[0] * 0.5
    # direction of the recovered separator matches the generator
    cos = (state.coefficients @ true_w) / (
        np.linalg.norm(state.coefficients) * np.linalg.norm(true_w))
    assert cos > 0.97


def test_sgd_outofcore_partial_final_batch(tmp_path):
    cache, _ = _write_lr_cache(tmp_path, n=4096)

    def make_reader():
        # 4096 % 384 != 0 -> final partial batch exercises padding
        return iter(DataCacheReader(cache, batch_rows=384))

    state, loss_log = sgd_fit_outofcore(
        logistic_loss, make_reader, num_features=16,
        config=SGDConfig(learning_rate=0.5, max_epochs=3, tol=0.0))
    assert np.all(np.isfinite(state.coefficients))
    assert loss_log[-1] < loss_log[0]


def test_sgd_outofcore_empty_reader_rejected():
    with pytest.raises(ValueError, match="empty epoch"):
        sgd_fit_outofcore(
            logistic_loss, lambda: iter([]), num_features=4,
            config=SGDConfig(max_epochs=2))


def _write_sparse_cache(tmp_path, n=2048, d=1 << 18, nnz=6, seed=0):
    """Hashed-pair rows on disk (the Criteo ingest shape)."""
    rng = np.random.default_rng(seed)
    cache = str(tmp_path / "sparse_cache")
    writer = DataCacheWriter(cache, segment_rows=1024)
    for start in range(0, n, 512):
        idx = rng.integers(4, d, size=(512, nnz)).astype(np.int32)
        y = rng.integers(0, 2, size=512).astype(np.float32)
        idx[:, 0] = np.where(y == 1, 1, 2)  # marker slots
        writer.append({"features_indices": idx,
                       "features_values": np.ones((512, nnz), np.float32),
                       "label": y})
    writer.finish()
    return cache


def test_sgd_outofcore_sparse_converges(tmp_path):
    cache = _write_sparse_cache(tmp_path)
    d = 1 << 18

    state, loss_log = sgd_fit_outofcore(
        logistic_loss,
        lambda: DataCacheReader(cache, batch_rows=256),
        num_features=d,
        indices_key="features_indices", values_key="features_values",
        config=SGDConfig(learning_rate=1.0, max_epochs=5, tol=0.0))
    assert state.coefficients.shape == (d,)
    assert loss_log[-1] < loss_log[0] * 0.5
    assert state.coefficients[1] > 0 > state.coefficients[2]


def test_estimator_fit_outofcore_sparse(tmp_path):
    cache = _write_sparse_cache(tmp_path, n=1024)
    d = 1 << 18
    model = (LogisticRegression().set_learning_rate(1.0).set_max_iter(4)
             .set_tol(0.0)
             .fit_outofcore(
                 lambda: DataCacheReader(cache, batch_rows=256),
                 num_features=d, sparse=True))
    reader = DataCacheReader(cache, batch_rows=1024)
    batch = reader.read_batch()
    t = Table(batch)
    pred = np.asarray(model.transform(t)[0]["prediction"])
    assert (pred == batch["label"]).mean() > 0.95


def test_estimator_fit_outofcore_matches_inmemory_quality(tmp_path):
    cache, _ = _write_lr_cache(tmp_path, n=2048)
    reader = DataCacheReader(cache, batch_rows=256)
    # materialize for the in-memory comparison + eval table
    batches = list(reader)
    X = np.concatenate([b["features"] for b in batches])
    y = np.concatenate([b["label"] for b in batches])
    table = Table({"features": X, "label": y})

    est = (LogisticRegression().set_learning_rate(0.5).set_max_iter(6)
           .set_tol(0.0))
    model_stream = est.fit_outofcore(
        lambda: iter(DataCacheReader(cache, batch_rows=256)),
        num_features=16)
    model_mem = est.fit(table)

    def acc(model):
        pred = np.asarray(model.transform(table)[0]["prediction"])
        return np.mean(pred == y)

    a_stream, a_mem = acc(model_stream), acc(model_mem)
    assert a_stream > 0.95
    assert abs(a_stream - a_mem) < 0.03


def test_prefetch_workers_ordered_and_stats():
    """Multi-worker decode must preserve source order; stats must account
    the pipeline stages."""
    import time as _time

    from flink_ml_tpu.data.prefetch import PrefetchStats, prefetch_to_device

    def slow_transform(x):
        # odd batches decode slower: out-of-order completion is forced
        _time.sleep(0.01 if x % 2 else 0.001)
        return np.full((4,), x, np.float32)

    stats = PrefetchStats()
    got = [int(b[0]) for b in prefetch_to_device(
        range(20), transform=slow_transform, workers=3, stats=stats)]
    assert got == list(range(20))
    assert stats.batches == 20
    assert stats.transform_s > 0
    d = stats.as_dict()
    assert set(d) == {"read_s", "transform_s", "put_s", "consumer_wait_s",
                      "batches"}


def test_prefetch_workers_propagates_transform_error():
    from flink_ml_tpu.data.prefetch import prefetch_to_device

    def bad(x):
        if x == 3:
            raise ValueError("boom at 3")
        return np.zeros(2, np.float32)

    out = []
    with pytest.raises(ValueError, match="boom at 3"):
        for b in prefetch_to_device(range(10), transform=bad, workers=2):
            out.append(b)
    assert len(out) <= 3


def test_streaming_ell_path_matches_xla(tmp_path, monkeypatch):
    """The out-of-core mixed trainer's ELL streaming path (per-batch
    layouts built in the decode workers) must reproduce the plain XLA
    path exactly.  CPU resolves the registry's XLA backend, so this exercises the
    batch assembly + fixed-cap layouts end to end."""
    from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter
    from flink_ml_tpu.models.common import sgd
    from flink_ml_tpu.models.common.losses import logistic_loss

    rng = np.random.default_rng(4)
    n, nd, nc, d = 3000, 4, 6, 128 * 128
    dense = rng.normal(size=(n, nd)).astype(np.float32)
    cat = rng.integers(0, d, size=(n, nc)).astype(np.int32)
    cat[:, 0] = 777                    # heavy hitter every row
    y = rng.integers(0, 2, size=n).astype(np.float32)

    cache = str(tmp_path / "cache")
    w = DataCacheWriter(cache, segment_rows=1024)
    w.append({"d": dense, "c": cat, "label": y})
    w.finish()

    cfg = sgd.SGDConfig(learning_rate=0.4, max_epochs=3, tol=0)

    def fit(force_ell):
        if force_ell:
            monkeypatch.setattr(sgd, "plan_mixed_impl",
                                lambda *a, **k: "ell")
        else:
            monkeypatch.setattr(sgd, "plan_mixed_impl",
                                lambda *a, **k: "xla")
        state, log = sgd.sgd_fit_outofcore(
            logistic_loss,
            lambda: DataCacheReader(cache, batch_rows=640),
            num_features=d, config=cfg, dense_key="d", indices_key="c",
            prefetch_workers=2)
        return state, log

    s_ell, log_ell = fit(True)
    s_xla, log_xla = fit(False)
    # the 8-device default mesh takes the SHARDED streaming route
    assert s_ell.planned_impl == "ell-stream"
    assert s_xla.planned_impl == "xla-stream"
    np.testing.assert_allclose(s_ell.coefficients, s_xla.coefficients,
                               atol=1e-5)
    np.testing.assert_allclose(log_ell, log_xla, rtol=1e-6)


def test_streaming_ell_cap_exceeded_raises(tmp_path, monkeypatch):
    from flink_ml_tpu.data.datacache import DataCacheReader, DataCacheWriter
    from flink_ml_tpu.models.common import sgd
    from flink_ml_tpu.models.common.losses import logistic_loss

    rng = np.random.default_rng(5)
    n, d = 600, 128 * 128
    dense = rng.normal(size=(n, 2)).astype(np.float32)
    # every row hits idx 300 and 301: both overflow ELL (not heavy at
    # threshold 512... 600 > 512 -> heavy actually; use two sub-heavy)
    cat = np.stack([np.full(n, 300), np.full(n, 301),
                    rng.integers(0, d, size=n)], axis=1).astype(np.int32)
    y = rng.integers(0, 2, size=n).astype(np.float32)
    cache = str(tmp_path / "cache")
    w = DataCacheWriter(cache, segment_rows=1024)
    w.append({"d": dense, "c": cat, "label": y})
    w.finish()

    import jax as _jax

    from flink_ml_tpu.parallel.mesh import device_mesh

    monkeypatch.setattr(sgd, "plan_mixed_impl", lambda *a, **k: "ell")
    # single-device grid: the full 600-deep runs are heavy; cap of 1 must
    # fail loudly (on the sharded mesh each 75-row shard absorbs this
    # load legally, so the mesh is pinned)
    with pytest.raises(ValueError, match="heavy indices > forced cap"):
        sgd.sgd_fit_outofcore(
            logistic_loss, lambda: DataCacheReader(cache, batch_rows=600),
            num_features=d, config=sgd.SGDConfig(max_epochs=1, tol=0),
            dense_key="d", indices_key="c", ell_heavy_cap=1,
            mesh=device_mesh({"data": 1}, devices=_jax.devices()[:1]))

    # sharded streaming (default 8-device mesh): per-shard overflow caps
    # are enforced the same way — 600/8-row shards spill row 2 past a
    # forced tiny cap
    with pytest.raises(ValueError, match="overflow needs"):
        sgd.sgd_fit_outofcore(
            logistic_loss, lambda: DataCacheReader(cache, batch_rows=600),
            num_features=d, config=sgd.SGDConfig(max_epochs=1, tol=0),
            dense_key="d", indices_key="c", ell_ovf_cap=4)


# --------------------------------------- per-epoch shuffled streaming


def test_epoch_aware_make_reader_receives_epoch(tmp_path):
    """A factory accepting ``epoch=`` is called with the actual epoch
    number each epoch."""
    cache, _ = _write_lr_cache(tmp_path, n=1024)
    seen = []

    def make_reader(epoch):
        seen.append(epoch)
        return DataCacheReader(cache, batch_rows=256)

    sgd_fit_outofcore(
        logistic_loss, make_reader, num_features=16,
        config=SGDConfig(learning_rate=0.5, max_epochs=3, tol=0.0),
        cache_decoded=False)
    assert seen == [0, 1, 2]


def test_shuffled_stream_trains_and_differs_from_sequential(tmp_path):
    """Per-epoch shuffled streaming: converges, and the visit order
    actually differs from the sequential reader (different SGD path =>
    different parameters)."""
    from flink_ml_tpu.data.datacache import ShuffledCacheReader

    cache, true_w = _write_lr_cache(tmp_path)
    cfg = SGDConfig(learning_rate=0.5, max_epochs=4, tol=0.0)

    state_seq, _ = sgd_fit_outofcore(
        logistic_loss, lambda: DataCacheReader(cache, batch_rows=256),
        num_features=16, config=cfg)
    state_shuf, log = sgd_fit_outofcore(
        logistic_loss,
        lambda epoch: ShuffledCacheReader(cache, batch_rows=256,
                                          seed=11, epoch=epoch),
        num_features=16, config=cfg)

    assert log[-1] < log[0] * 0.5
    cos = (state_shuf.coefficients @ true_w) / (
        np.linalg.norm(state_shuf.coefficients) * np.linalg.norm(true_w))
    assert cos > 0.97
    assert not np.array_equal(state_shuf.coefficients,
                              state_seq.coefficients)


def test_shuffled_stream_epochs_vary_and_use_block_cache(tmp_path):
    """Each epoch visits a different permutation; because the reader is
    block-addressable the decode cache engages in BLOCK-keyed mode (the
    positional record/replay machinery — whose one-batch guard cannot
    prove a permutation identical — stays out)."""
    from flink_ml_tpu.data.datacache import ShuffledCacheReader

    cache, _ = _write_lr_cache(tmp_path)
    orders = []

    def make_reader(epoch):
        r = ShuffledCacheReader(cache, batch_rows=256, seed=2, epoch=epoch)
        orders.append(tuple(r._order.tolist()))
        return r

    info = {}
    sgd_fit_outofcore(
        logistic_loss, make_reader, num_features=16,
        config=SGDConfig(learning_rate=0.5, max_epochs=3, tol=0.0),
        stream_info=info)
    assert len(set(orders)) == 3          # one distinct permutation/epoch
    assert info["decoded_cache_mode"] == "block"
    assert info["decoded_cache_batches"] == 16   # 4096 rows / 256


def test_kwargs_factory_not_force_fed_epoch(tmp_path):
    """A **kwargs factory that merely forwards its kwargs must be called
    with no arguments — feeding it epoch= would crash readers that do
    not take one."""
    cache, _ = _write_lr_cache(tmp_path, n=1024)

    def make_reader(**kw):
        return DataCacheReader(cache, batch_rows=256, **kw)

    state, _ = sgd_fit_outofcore(
        logistic_loss, make_reader, num_features=16,
        config=SGDConfig(learning_rate=0.5, max_epochs=2, tol=0.0))
    assert np.all(np.isfinite(state.coefficients))
