"""Table substrate + linalg + distance tests."""

import numpy as np
import pytest

from flink_ml_tpu import DenseVector, DistanceMeasure, Table, Vectors


def test_table_basics():
    t = Table({"a": [1, 2, 3], "b": np.ones((3, 4))})
    assert t.num_rows == 3
    assert t.column_names == ["a", "b"]
    assert t["b"].shape == (3, 4)
    with pytest.raises(ValueError):
        Table({"a": [1, 2], "b": [1, 2, 3]})
    with pytest.raises(KeyError):
        t.column("nope")


def test_table_from_rows():
    t = Table.from_rows([(1, "x"), (2, "y")], ["id", "name"])
    np.testing.assert_array_equal(t["id"], [1, 2])
    assert list(t["name"]) == ["x", "y"]
    assert list(t.rows()) == [(1, "x"), (2, "y")]


def test_table_ops():
    t = Table({"a": [1, 2, 3, 4], "b": [10, 20, 30, 40]})
    assert t.select("a").column_names == ["a"]
    assert t.drop("a").column_names == ["b"]
    assert t.rename({"a": "z"}).column_names == ["z", "b"]
    np.testing.assert_array_equal(t.with_column("c", t["a"] * 2)["c"], [2, 4, 6, 8])
    np.testing.assert_array_equal(t.slice(1, 3)["a"], [2, 3])
    merged = t.concat(t)
    assert merged.num_rows == 8
    shuffled = t.shuffle(seed=1)
    assert sorted(shuffled["a"].tolist()) == [1, 2, 3, 4]


def test_table_batches_and_padding():
    t = Table({"a": np.arange(10)})
    batches = list(t.batches(4))
    assert [b.num_rows for b in batches] == [4, 4, 2]
    batches = list(t.batches(4, drop_remainder=True))
    assert [b.num_rows for b in batches] == [4, 4]
    padded, mask = t.pad_to_multiple(8)
    assert padded.num_rows == 16
    assert mask.sum() == 10
    same, mask = Table({"a": np.arange(8)}).pad_to_multiple(8)
    assert same.num_rows == 8 and mask.sum() == 8


def test_dense_vector():
    v = Vectors.dense(1.0, 2.0, 3.0)
    assert v.size() == 3
    assert v.get(1) == 2.0
    np.testing.assert_array_equal(v.to_array(), [1.0, 2.0, 3.0])
    assert v == DenseVector([1, 2, 3])
    assert Vectors.dense([4.0, 5.0]) == DenseVector([4, 5])


def test_sparse_vector():
    v = Vectors.sparse(5, [1, 3], [2.0, 4.0])
    assert v.size() == 5
    assert v.get(3) == 4.0 and v.get(0) == 0.0
    np.testing.assert_array_equal(v.to_array(), [0, 2, 0, 4, 0])


def test_distance_registry():
    m = DistanceMeasure.get_instance("euclidean")
    assert m.distance(Vectors.dense(0, 0), Vectors.dense(3, 4)) == pytest.approx(5.0)
    with pytest.raises(ValueError):
        DistanceMeasure.get_instance("nope")


def test_pairwise_distances():
    m = DistanceMeasure.get_instance("euclidean")
    pts = np.array([[0.0, 0.0], [1.0, 1.0]])
    cents = np.array([[0.0, 0.0], [3.0, 4.0]])
    d = np.asarray(m.pairwise(pts, cents))
    np.testing.assert_allclose(d[0], [0.0, 5.0], atol=1e-5)
    np.testing.assert_allclose(d[1, 0], np.sqrt(2), atol=1e-5)

    man = DistanceMeasure.get_instance("manhattan")
    d = np.asarray(man.pairwise(pts, cents))
    np.testing.assert_allclose(d[1], [2.0, 5.0], atol=1e-5)

    cos = DistanceMeasure.get_instance("cosine")
    d = np.asarray(cos.pairwise(np.array([[1.0, 0.0]]), np.array([[0.0, 2.0], [2.0, 0.0]])))
    np.testing.assert_allclose(d[0], [1.0, 0.0], atol=1e-5)


def test_batches_rejects_nonpositive():
    t = Table({"a": np.arange(4)})
    with pytest.raises(ValueError):
        list(t.batches(0))
    with pytest.raises(ValueError):
        list(t.batches(-1))


def test_stack_vectors_shapes():
    from flink_ml_tpu.linalg import stack_vectors
    # 1-D numeric column = n scalar samples -> (n, 1)
    assert stack_vectors(np.arange(5.0)).shape == (5, 1)
    assert stack_vectors(np.ones((3, 4))).shape == (3, 4)
    assert stack_vectors([DenseVector([1, 2]), DenseVector([3, 4])]).shape == (2, 2)
