"""Tier-1 wiring for scripts/check_bench_schema.py: the live repo must be
drift-free, and the checker must actually CATCH the drift modes it exists
for (a version bumped in bench.py but not BENCH_SCHEMA.md, and an emitted
key the schema doc never documents)."""

import importlib.util
import os
import re

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "check_bench_schema", os.path.join(_REPO, "scripts",
                                       "check_bench_schema.py"))
check = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(check)


def test_repo_bench_schema_is_drift_free():
    assert check.check_versions() == []
    assert check.main([]) == 0


def test_version_bump_without_doc_update_is_caught(tmp_path, monkeypatch):
    src = open(check.BENCH).read()
    # bump whatever comm version the live bench carries (version-agnostic:
    # the r11 2-bump broke the old literal form of this test)
    cur = int(check.bench_metric_versions(src)["comm_metric_version"])
    bumped = src.replace(f'"comm_metric_version": {cur},',
                         f'"comm_metric_version": {cur + 1},')
    assert bumped != src
    fake = tmp_path / "bench.py"
    fake.write_text(bumped)
    monkeypatch.setattr(check, "BENCH", str(fake))
    problems = check.check_versions()
    assert any("comm_metric_version" in p and "bump both" in p
               for p in problems)


def test_new_version_key_without_doc_entry_is_caught(tmp_path, monkeypatch):
    fake = tmp_path / "bench.py"
    fake.write_text(open(check.BENCH).read()
                    + '\nX = {"shiny_metric_version": 1}\n')
    monkeypatch.setattr(check, "BENCH", str(fake))
    problems = check.check_versions()
    assert any("shiny_metric_version" in p for p in problems)


def test_undocumented_emitted_key_is_caught(tmp_path):
    line = ('{"metric": "logreg_epochs_per_sec", "value": 1.0, '
            '"unit": "epochs/s", "vs_baseline": 1.0, '
            '"totally_new_series": 7}')
    path = tmp_path / "BENCH_new.json"
    path.write_text(line + "\n")
    documented = check.schema_documented_keys(open(check.SCHEMA).read())
    problems = check.check_json(str(path), documented)
    assert any("totally_new_series" in p for p in problems)
    # documented + summary + *_error keys pass
    ok = ('{"metric": "m", "value": 1, "rows_per_sec": 2, '
          '"bench_gbt_error": "x", "notes": {}}')
    path.write_text(ok + "\n")
    assert check.check_json(str(path), documented) == []


def test_metric_version_regexes_cover_both_assignment_forms():
    found = check.bench_metric_versions(
        'a = {"outofcore_metric_version": 4}\n'
        'results["notes"]["kmeans_metric_version"] = 6\n')
    assert found == {"outofcore_metric_version": 4,
                     "kmeans_metric_version": 6}


def test_all_bench_version_literals_reach_the_table():
    """The regex harvest from the real bench.py must be non-trivial (it
    would silently pass if the patterns rotted)."""
    found = check.bench_metric_versions(open(check.BENCH).read())
    assert {"metric_version", "outofcore_metric_version",
            "kmeans_metric_version", "serving_metric_version",
            "comm_metric_version"} <= set(found)
    # and the doc table carries exactly the same names
    doc = check.schema_metric_versions(open(check.SCHEMA).read())
    assert set(doc) == set(found)


def test_documented_key_extraction_handles_dotted_names():
    keys = check.schema_documented_keys("see `notes.comm` and `a_b`")
    assert {"notes.comm", "notes", "a_b"} <= keys
