"""PCA — oracle vs numpy SVD, variance ordering, persistence."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature import PCA, PCAModel


def _t(X):
    return Table({"features": np.asarray(X, np.float64)})


def _anisotropic(rng, n=500):
    """Data with a known dominant direction."""
    base = rng.normal(size=(n, 3)) * np.asarray([5.0, 1.0, 0.2])
    rot, _ = np.linalg.qr(rng.normal(size=(3, 3)))
    return base @ rot.T, rot


def test_components_match_numpy_svd_oracle():
    rng = np.random.default_rng(0)
    X, _ = _anisotropic(rng)
    model = PCA().set_k(3).fit(_t(X))

    Xc = X - X.mean(axis=0)
    _, _, vt = np.linalg.svd(Xc, full_matrices=False)
    for row, oracle in zip(model._components, vt):
        # eigenvectors match up to sign
        assert min(np.abs(row - oracle).max(),
                   np.abs(row + oracle).max()) < 1e-4


def test_explained_variance_ordering_and_ratio():
    rng = np.random.default_rng(1)
    X, _ = _anisotropic(rng)
    model = PCA().set_k(3).fit(_t(X))
    v = model._variance
    assert v[0] > v[1] > v[2] > 0
    ratio = model.explained_variance_ratio
    np.testing.assert_allclose(ratio.sum(), 1.0, atol=1e-5)
    assert ratio[0] > 0.8        # the 5x direction dominates


def test_projection_decorrelates_and_centers():
    rng = np.random.default_rng(2)
    X, _ = _anisotropic(rng)
    out = np.asarray(PCA().set_k(2).fit(_t(X)).transform(_t(X))[0]["output"])
    assert out.shape == (len(X), 2)
    np.testing.assert_allclose(out.mean(axis=0), 0.0, atol=1e-3)
    corr = np.corrcoef(out.T)
    assert abs(corr[0, 1]) < 0.05


def test_deterministic_sign_across_refits():
    rng = np.random.default_rng(3)
    X, _ = _anisotropic(rng)
    a = PCA().set_k(2).fit(_t(X))._components
    b = PCA().set_k(2).fit(_t(X))._components
    np.testing.assert_array_equal(a, b)
    # pivot coordinate positive
    for row in a:
        assert row[np.argmax(np.abs(row))] > 0


def test_k_validation():
    with pytest.raises(ValueError, match="exceeds"):
        PCA().set_k(5).fit(_t(np.zeros((4, 3))))
    with pytest.raises(ValueError, match="invalid value"):
        PCA().set_k(0)


def test_save_load_roundtrip(tmp_path):
    rng = np.random.default_rng(4)
    X, _ = _anisotropic(rng)
    model = PCA().set_k(2).fit(_t(X))
    before = np.asarray(model.transform(_t(X))[0]["output"])
    path = str(tmp_path / "pca")
    model.save(path)
    loaded = PCAModel.load(path)
    np.testing.assert_allclose(
        np.asarray(loaded.transform(_t(X))[0]["output"]), before,
        atol=1e-6)
    np.testing.assert_allclose(loaded.explained_variance_ratio,
                               model.explained_variance_ratio)


def test_model_data_roundtrip():
    """The generic set_model_data(*get_model_data()) contract every
    sibling model honors."""
    rng = np.random.default_rng(5)
    X, _ = _anisotropic(rng)
    model = PCA().set_k(2).fit(_t(X))
    clone = PCAModel().set_model_data(*model.get_model_data())
    clone.copy_params_from(model)
    np.testing.assert_allclose(
        np.asarray(clone.transform(_t(X))[0]["output"]),
        np.asarray(model.transform(_t(X))[0]["output"]), atol=1e-6)
