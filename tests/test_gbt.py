"""Gradient-boosted trees: trainer, classifier, regressor."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.classification import GBTClassifier, GBTClassifierModel
from flink_ml_tpu.models.regression import GBTRegressor, GBTRegressorModel


def _xor_table(n=800, seed=0):
    """Nonlinear target a linear model cannot fit."""
    rng = np.random.default_rng(seed)
    X = rng.uniform(-1, 1, size=(n, 2))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(np.int64)
    return Table({"features": X, "label": y}), X, y


def _friedman(n=2000, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(size=(n, 5))
    y = (10 * np.sin(np.pi * X[:, 0] * X[:, 1]) + 20 * (X[:, 2] - 0.5) ** 2
         + 10 * X[:, 3] + 5 * X[:, 4])
    return Table({"features": X, "label": y}), X, y


def test_classifier_learns_xor():
    table, X, y = _xor_table()
    model = (GBTClassifier().set_max_iter(30).set_max_depth(3)
             .set_learning_rate(0.3).fit(table))
    out = model.transform(table)[0]
    pred = np.asarray(out["prediction"])
    assert (pred == y).mean() > 0.97
    probs = np.asarray(out["rawPrediction"])
    assert ((probs > 0.5) == (pred == 1)).all()
    assert probs.min() >= 0 and probs.max() <= 1


def test_classifier_label_values_preserved():
    table, X, y = _xor_table(n=400)
    relabeled = Table({"features": X, "label": np.where(y == 1, "yes", "no")})
    model = GBTClassifier().set_max_iter(20).set_max_depth(3).fit(relabeled)
    pred = np.asarray(model.transform(relabeled)[0]["prediction"])
    assert set(np.unique(pred)) <= {"yes", "no"}
    assert (pred == np.where(y == 1, "yes", "no")).mean() > 0.9


def test_classifier_rejects_multiclass():
    table = Table({"features": np.zeros((3, 2)), "label": np.asarray([0, 1, 2])})
    with pytest.raises(ValueError, match="binary"):
        GBTClassifier().fit(table)


def test_regressor_beats_linear_on_friedman():
    table, X, y = _friedman()
    model = (GBTRegressor().set_max_iter(40).set_max_depth(4)
             .set_learning_rate(0.2).fit(table))
    pred = np.asarray(model.transform(table)[0]["prediction"])
    rmse = np.sqrt(np.mean((pred - y) ** 2))
    # linear least squares on the same data
    A = np.concatenate([X, np.ones((len(X), 1))], axis=1)
    lin = A @ np.linalg.lstsq(A, y, rcond=None)[0]
    lin_rmse = np.sqrt(np.mean((lin - y) ** 2))
    assert rmse < 0.5 * lin_rmse, (rmse, lin_rmse)


def test_regressor_monotone_improvement_with_trees():
    table, X, y = _friedman(n=500, seed=1)

    def rmse(trees):
        m = (GBTRegressor().set_max_iter(trees).set_max_depth(3)
             .set_learning_rate(0.3).fit(table))
        p = np.asarray(m.transform(table)[0]["prediction"])
        return np.sqrt(np.mean((p - y) ** 2))

    assert rmse(30) < rmse(5) < rmse(1)


def test_constant_labels_yield_constant_prediction():
    X = np.random.default_rng(0).normal(size=(50, 3))
    table = Table({"features": X, "label": np.full(50, 7.0)})
    model = GBTRegressor().set_max_iter(5).fit(table)
    pred = np.asarray(model.transform(table)[0]["prediction"])
    np.testing.assert_allclose(pred, 7.0, atol=1e-3)


def test_save_load_round_trip(tmp_path):
    table, X, y = _xor_table(n=300)
    model = GBTClassifier().set_max_iter(10).set_max_depth(3).fit(table)
    p1 = np.asarray(model.transform(table)[0]["prediction"])
    model.save(str(tmp_path / "c"))
    re = GBTClassifierModel.load(str(tmp_path / "c"))
    p2 = np.asarray(re.transform(table)[0]["prediction"])
    np.testing.assert_array_equal(p1, p2)

    rtable, _, ry = _friedman(n=300)
    rmodel = GBTRegressor().set_max_iter(8).fit(rtable)
    r1 = np.asarray(rmodel.transform(rtable)[0]["prediction"])
    rmodel.save(str(tmp_path / "r"))
    rre = GBTRegressorModel.load(str(tmp_path / "r"))
    np.testing.assert_allclose(
        np.asarray(rre.transform(rtable)[0]["prediction"]), r1)


def test_model_data_round_trip():
    table, X, y = _xor_table(n=200)
    model = GBTClassifier().set_max_iter(5).set_max_depth(2).fit(table)
    rebuilt = GBTClassifierModel().set_model_data(*model.get_model_data())
    rebuilt.copy_params_from(model)
    np.testing.assert_array_equal(
        np.asarray(rebuilt.transform(table)[0]["prediction"]),
        np.asarray(model.transform(table)[0]["prediction"]))


def test_unseen_data_generalizes():
    table, X, y = _xor_table(n=1000, seed=2)
    model = (GBTClassifier().set_max_iter(30).set_max_depth(3)
             .set_learning_rate(0.3).fit(table))
    _, X2, y2 = _xor_table(n=500, seed=99)
    pred = np.asarray(model.transform(Table({"features": X2}))[0]["prediction"])
    assert (pred == y2).mean() > 0.95


def test_empty_fit_rejected():
    with pytest.raises(ValueError):
        GBTRegressor().fit(Table({"features": np.zeros((0, 2)),
                                  "label": np.zeros(0)}))
