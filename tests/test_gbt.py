"""Gradient-boosted trees: trainer, classifier, regressor."""

import numpy as np
import pytest

from flink_ml_tpu import Table
from flink_ml_tpu.models.classification import GBTClassifier, GBTClassifierModel
from flink_ml_tpu.models.regression import GBTRegressor, GBTRegressorModel


def _xor_table(n=800, seed=0):
    """Nonlinear target a linear model cannot fit."""
    rng = np.random.default_rng(seed)
    X = rng.uniform(-1, 1, size=(n, 2))
    y = ((X[:, 0] > 0) ^ (X[:, 1] > 0)).astype(np.int64)
    return Table({"features": X, "label": y}), X, y


def _friedman(n=2000, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(size=(n, 5))
    y = (10 * np.sin(np.pi * X[:, 0] * X[:, 1]) + 20 * (X[:, 2] - 0.5) ** 2
         + 10 * X[:, 3] + 5 * X[:, 4])
    return Table({"features": X, "label": y}), X, y


def test_classifier_learns_xor():
    table, X, y = _xor_table()
    model = (GBTClassifier().set_max_iter(30).set_max_depth(3)
             .set_learning_rate(0.3).fit(table))
    out = model.transform(table)[0]
    pred = np.asarray(out["prediction"])
    assert (pred == y).mean() > 0.97
    probs = np.asarray(out["rawPrediction"])
    assert ((probs > 0.5) == (pred == 1)).all()
    assert probs.min() >= 0 and probs.max() <= 1


def test_classifier_label_values_preserved():
    table, X, y = _xor_table(n=400)
    relabeled = Table({"features": X, "label": np.where(y == 1, "yes", "no")})
    model = GBTClassifier().set_max_iter(20).set_max_depth(3).fit(relabeled)
    pred = np.asarray(model.transform(relabeled)[0]["prediction"])
    assert set(np.unique(pred)) <= {"yes", "no"}
    assert (pred == np.where(y == 1, "yes", "no")).mean() > 0.9


def test_classifier_routes_three_labels_to_softmax_path():
    table = Table({"features": np.random.default_rng(0).normal(size=(30, 2)),
                   "label": np.asarray([0, 1, 2] * 10)})
    model = GBTClassifier().set_max_iter(2).fit(table)
    assert model._soft is not None and model._soft.n_classes == 3


def test_regressor_beats_linear_on_friedman():
    table, X, y = _friedman()
    model = (GBTRegressor().set_max_iter(40).set_max_depth(4)
             .set_learning_rate(0.2).fit(table))
    pred = np.asarray(model.transform(table)[0]["prediction"])
    rmse = np.sqrt(np.mean((pred - y) ** 2))
    # linear least squares on the same data
    A = np.concatenate([X, np.ones((len(X), 1))], axis=1)
    lin = A @ np.linalg.lstsq(A, y, rcond=None)[0]
    lin_rmse = np.sqrt(np.mean((lin - y) ** 2))
    assert rmse < 0.5 * lin_rmse, (rmse, lin_rmse)


def test_regressor_monotone_improvement_with_trees():
    table, X, y = _friedman(n=500, seed=1)

    def rmse(trees):
        m = (GBTRegressor().set_max_iter(trees).set_max_depth(3)
             .set_learning_rate(0.3).fit(table))
        p = np.asarray(m.transform(table)[0]["prediction"])
        return np.sqrt(np.mean((p - y) ** 2))

    assert rmse(30) < rmse(5) < rmse(1)


def test_constant_labels_yield_constant_prediction():
    X = np.random.default_rng(0).normal(size=(50, 3))
    table = Table({"features": X, "label": np.full(50, 7.0)})
    model = GBTRegressor().set_max_iter(5).fit(table)
    pred = np.asarray(model.transform(table)[0]["prediction"])
    np.testing.assert_allclose(pred, 7.0, atol=1e-3)


def test_save_load_round_trip(tmp_path):
    table, X, y = _xor_table(n=300)
    model = GBTClassifier().set_max_iter(10).set_max_depth(3).fit(table)
    p1 = np.asarray(model.transform(table)[0]["prediction"])
    model.save(str(tmp_path / "c"))
    re = GBTClassifierModel.load(str(tmp_path / "c"))
    p2 = np.asarray(re.transform(table)[0]["prediction"])
    np.testing.assert_array_equal(p1, p2)

    rtable, _, ry = _friedman(n=300)
    rmodel = GBTRegressor().set_max_iter(8).fit(rtable)
    r1 = np.asarray(rmodel.transform(rtable)[0]["prediction"])
    rmodel.save(str(tmp_path / "r"))
    rre = GBTRegressorModel.load(str(tmp_path / "r"))
    np.testing.assert_allclose(
        np.asarray(rre.transform(rtable)[0]["prediction"]), r1)


def test_model_data_round_trip():
    table, X, y = _xor_table(n=200)
    model = GBTClassifier().set_max_iter(5).set_max_depth(2).fit(table)
    rebuilt = GBTClassifierModel().set_model_data(*model.get_model_data())
    rebuilt.copy_params_from(model)
    np.testing.assert_array_equal(
        np.asarray(rebuilt.transform(table)[0]["prediction"]),
        np.asarray(model.transform(table)[0]["prediction"]))


def test_unseen_data_generalizes():
    table, X, y = _xor_table(n=1000, seed=2)
    model = (GBTClassifier().set_max_iter(30).set_max_depth(3)
             .set_learning_rate(0.3).fit(table))
    _, X2, y2 = _xor_table(n=500, seed=99)
    pred = np.asarray(model.transform(Table({"features": X2}))[0]["prediction"])
    assert (pred == y2).mean() > 0.95


def test_empty_fit_rejected():
    with pytest.raises(ValueError):
        GBTRegressor().fit(Table({"features": np.zeros((0, 2)),
                                  "label": np.zeros(0)}))


# ------------------------------------------------------------- multiclass


def test_gbt_multiclass_three_rings(rng):
    """3 well-separated blobs; softmax GBT must classify near-perfectly."""
    from flink_ml_tpu.models.classification import GBTClassifier

    n = 120
    centers = np.asarray([[0.0, 0.0], [6.0, 0.0], [0.0, 6.0]])
    X = np.concatenate([rng.normal(size=(n, 2)) * 0.5 + c for c in centers])
    y = np.repeat(["alpha", "beta", "gamma"], n)
    t = Table({"features": X, "label": y})
    model = (GBTClassifier().set_max_iter(10).set_max_depth(3)
             .set_learning_rate(0.3).fit(t))
    out = model.transform(t)[0]
    pred = np.asarray(out["prediction"])
    assert (pred == y).mean() > 0.98
    probs = np.asarray(out["rawPrediction"])
    assert probs.shape == (3 * n, 3)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-9)


def test_gbt_multiclass_save_load_and_model_data(tmp_path, rng):
    from flink_ml_tpu.models.classification import (
        GBTClassifier,
        GBTClassifierModel,
    )

    X = rng.normal(size=(90, 3))
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)  # 3 classes
    t = Table({"features": X, "label": y})
    model = GBTClassifier().set_max_iter(4).set_max_depth(3).fit(t)
    pred = np.asarray(model.transform(t)[0]["prediction"])

    model.save(str(tmp_path / "m"))
    re = GBTClassifierModel.load(str(tmp_path / "m"))
    np.testing.assert_array_equal(
        np.asarray(re.transform(t)[0]["prediction"]), pred)

    # model-data round trip through Tables
    fresh = GBTClassifierModel().set_model_data(*model.get_model_data())
    fresh.copy_params_from(model)
    np.testing.assert_array_equal(
        np.asarray(fresh.transform(t)[0]["prediction"]), pred)


def test_gbt_binary_still_binary(rng):
    """2-label input keeps the logistic path (scalar margins)."""
    from flink_ml_tpu.models.classification import GBTClassifier

    X = rng.normal(size=(80, 2))
    y = (X[:, 0] > 0).astype(int)
    model = (GBTClassifier().set_max_iter(5)
             .fit(Table({"features": X, "label": y})))
    assert model._soft is None
    probs = np.asarray(model.transform(
        Table({"features": X, "label": y}))[0]["rawPrediction"])
    assert probs.ndim == 1


def test_set_model_data_replaces_representation(rng):
    """Installing binary model data on a model that held a multiclass forest
    (or vice versa) fully replaces it — no stale forest answers."""
    from flink_ml_tpu.models.classification import GBTClassifier

    X = rng.normal(size=(90, 2))
    t3 = Table({"features": X,
                "label": (X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)})
    t2 = Table({"features": X, "label": (X[:, 0] > 0).astype(int)})
    m3 = GBTClassifier().set_max_iter(3).fit(t3)
    m2 = GBTClassifier().set_max_iter(3).fit(t2)

    m3.set_model_data(*m2.get_model_data())
    assert m3._soft is None
    pred = np.asarray(m3.transform(t2)[0]["prediction"])
    np.testing.assert_array_equal(pred,
                                  np.asarray(m2.transform(t2)[0]["prediction"]))

    m2.set_model_data(*GBTClassifier().set_max_iter(3).fit(t3)
                      .get_model_data())
    assert m2._soft is not None and m2._forest is None
    assert set(np.asarray(m2.transform(t3)[0]["prediction"])) <= {0, 1, 2}


class TestOutOfCore:
    """train_forest_outofcore == train_forest on the same rows (VERDICT r2
    task 9): identical tree STRUCTURE (exact int match on features and
    thresholds), allclose values/predictions."""

    def _data(self, n=3000, d=6):
        rng = np.random.default_rng(11)
        X = rng.normal(size=(n, d))
        y = ((X[:, 0] + 0.5 * X[:, 1] ** 2 + 0.1 * rng.normal(size=n))
             > 0.4).astype(np.float64)
        return X, y

    def test_matches_incore_forest(self, tmp_path):
        from flink_ml_tpu.models.common.gbt import (
            GBTConfig, predict_forest, train_forest,
            train_forest_outofcore)

        X, y = self._data()
        cfg = GBTConfig(num_trees=5, max_depth=3, max_bins=32)

        def grad_hess(yv, pred):
            p = 1.0 / (1.0 + np.exp(-pred))
            return p - yv, np.maximum(p * (1.0 - p), 1e-12)

        incore = train_forest(X, y, grad_hess, 0.0, cfg)

        def make_reader(batch=700):
            def gen():
                for s in range(0, len(X), batch):
                    yield {"features": X[s:s + batch],
                           "label": y[s:s + batch]}
            return gen()

        ooc = train_forest_outofcore(
            make_reader, grad_hess, 0.0, cfg,
            work_dir=str(tmp_path / "w"), sample_rows=len(X))

        np.testing.assert_array_equal(ooc.feature, incore.feature)
        np.testing.assert_array_equal(ooc.threshold, incore.threshold)
        np.testing.assert_allclose(ooc.value, incore.value,
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(predict_forest(X, ooc),
                                   predict_forest(X, incore),
                                   rtol=1e-4, atol=1e-5)

    def test_estimator_fit_outofcore(self, tmp_path):
        from flink_ml_tpu.data.table import Table
        from flink_ml_tpu.models.classification.gbtclassifier import (
            GBTClassifier)

        X, y = self._data(n=2000)
        t = Table({"features": X, "label": y})

        def make_reader():
            def gen():
                for s in range(0, len(X), 500):
                    yield {"features": X[s:s + 500], "label": y[s:s + 500]}
            return gen()

        est = (GBTClassifier().set_max_iter(5).set_max_depth(3)
               .set_max_bins(32))
        m_ooc = est.fit_outofcore(make_reader,
                                  work_dir=str(tmp_path / "w2"))
        m_in = est.fit(t)
        pred_ooc = np.asarray(
            m_ooc.transform(t)[0][est.get_prediction_col()]).ravel()
        pred_in = np.asarray(
            m_in.transform(t)[0][est.get_prediction_col()]).ravel()
        np.testing.assert_array_equal(pred_ooc, pred_in)
        acc = (pred_ooc == y).mean()
        assert acc > 0.9, acc

    def test_streaming_rejects_arbitrary_labels(self, tmp_path):
        from flink_ml_tpu.models.classification.gbtclassifier import (
            GBTClassifier)

        X, _ = self._data(n=100)
        y = np.where(X[:, 0] > 0, 3.0, 7.0)

        def make_reader():
            return iter([{"features": X, "label": y}])

        with pytest.raises(ValueError, match="0/1 labels"):
            GBTClassifier().fit_outofcore(make_reader,
                                          work_dir=str(tmp_path / "w3"))

    def test_device_binning_matches_host(self):
        import jax.numpy as jnp

        from flink_ml_tpu.models.common.gbt import (
            apply_bins, apply_bins_device, bin_features)

        rng = np.random.default_rng(3)
        X = rng.normal(size=(500, 4))
        X[:, 2] = np.round(X[:, 2])          # ties on edges
        _, edges = bin_features(X, 16)
        host = apply_bins(X.astype(np.float32), edges)
        dev = np.asarray(apply_bins_device(
            jnp.asarray(X, jnp.float32), jnp.asarray(edges, jnp.float32)))
        np.testing.assert_array_equal(host, dev)


def test_device_binning_nan_matches_host():
    import jax.numpy as jnp

    from flink_ml_tpu.models.common.gbt import (
        apply_bins, apply_bins_device, quantile_edges)

    rng = np.random.default_rng(4)
    X = rng.normal(size=(200, 3))
    edges = quantile_edges(X, 8)
    X[5, 0] = np.nan
    X[17, 2] = np.nan
    host = apply_bins(X, edges)
    dev = np.asarray(apply_bins_device(
        jnp.asarray(X, jnp.float32), jnp.asarray(edges, jnp.float32)))
    np.testing.assert_array_equal(host, dev)


def test_outofcore_workdir_reusable_and_cleaned(tmp_path):
    from flink_ml_tpu.models.common.gbt import (
        GBTConfig, train_forest_outofcore)

    rng = np.random.default_rng(5)
    X = rng.normal(size=(300, 3))
    y = (X[:, 0] > 0).astype(np.float64)

    def grad_hess(yv, pred):
        p = 1.0 / (1.0 + np.exp(-pred))
        return p - yv, np.maximum(p * (1.0 - p), 1e-12)

    def make_reader():
        return iter([{"features": X, "label": y}])

    wd = str(tmp_path / "work")
    cfg = GBTConfig(num_trees=2, max_depth=2, max_bins=8)
    for _ in range(2):   # same work_dir twice must not collide
        train_forest_outofcore(make_reader, grad_hess, 0.0, cfg,
                               work_dir=wd)
    import os
    assert os.listdir(wd) == []   # run dirs removed on return


def test_mxu_histograms_match_segsum():
    """The MXU double-one-hot histogram must equal the segment_sum form
    (f32 summation order aside) — including dead rows (-1) and empty
    nodes — and produce identical trees end-to-end."""
    import jax.numpy as jnp

    from flink_ml_tpu.models.common import gbt

    rng = np.random.default_rng(21)
    n, d, bins, n_nodes = 512, 5, 16, 4
    binned = jnp.asarray(rng.integers(0, bins, size=(n, d)), jnp.int32)
    ids = jnp.asarray(
        np.where(rng.random(n) < 0.2, -1,
                 rng.integers(0, n_nodes, size=n)), jnp.int32)
    g = jnp.asarray(rng.normal(size=n), jnp.float32)
    h = jnp.asarray(rng.random(n) + 0.1, jnp.float32)
    gs, hs = gbt._level_histograms_segsum(binned, ids, g, h, n_nodes, d,
                                          bins)
    gm, hm = gbt._level_histograms_mxu(binned, ids, g, h, n_nodes, d,
                                       bins)
    np.testing.assert_allclose(np.asarray(gm), np.asarray(gs),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hm), np.asarray(hs),
                               rtol=1e-5, atol=1e-5)

    # end-to-end: the two impls grow the same forest
    X = rng.normal(size=(1024, 4))
    y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)

    def gh_fn(y, pred):
        p = 1.0 / (1.0 + np.exp(-pred))
        return (p - y), np.maximum(p * (1.0 - p), 1e-16)

    cfg = gbt.GBTConfig(num_trees=3, max_depth=3)
    old = gbt.HIST_IMPL
    try:
        gbt.HIST_IMPL = "segsum"
        f1 = gbt.train_forest(X, y, gh_fn, 0.0, cfg)
        gbt.HIST_IMPL = "mxu"
        f2 = gbt.train_forest(X, y, gh_fn, 0.0, cfg)
    finally:
        gbt.HIST_IMPL = old
    # prediction-space equivalence, not exact trees: near-tie argmax
    # splits may legitimately differ under f32 summation order
    np.testing.assert_allclose(gbt.predict_forest(X, f1),
                               gbt.predict_forest(X, f2),
                               rtol=1e-3, atol=1e-3)
    # unknown impl names fail loudly, never silently fall back
    try:
        gbt.HIST_IMPL = "typo"
        with pytest.raises(KeyError):
            gbt._level_histograms(binned, ids, g, h, n_nodes, d, bins)
    finally:
        gbt.HIST_IMPL = old
